"""Pod-level systolic streaming: plan stages sharded over a 'stage' mesh
axis, row-band tiles flowing device-to-device on the ICI ppermute path.

PR 14's megakernels apply the software-systolic model WITHIN one chip
(stage intermediates live in VMEM). This module applies the same model
ACROSS chips: a fused-stage pipeline is cut into contiguous stage
groups, each group owned by one device on a dedicated 1-D ``'stage'``
mesh axis, and the image streams through as fixed-height row bands —
device g runs its stages on tile k while device g-1 runs its stages on
tile k+1, the classic systolic wavefront. Between steps one
``lax.ppermute`` shifts every in-flight band to its successor stage
owner, so a band crosses each stage boundary exactly once and HBM sees
one u8 read + one u8 write per stage GROUP instead of per stage — the
Casper move (compute goes to where the data is) expressed on the ICI
ring instead of the memory hierarchy.

Bit-exactness is inherited, not re-proven: inside a group the walk is
`plan/exec.walk_stage` under the sharded edge convention (context always
materialised, out-of-image rows rewritten per op by ``_fix_edge_axis``
BEFORE each stencil reads them — the exact `parallel/api._plan_walk`
fixture), every stage materialises u8 between stages exactly as
`run_stage_full` does, and the carry is the f32 exact-integer contract
from `ops.spec` — so the device-boundary handoff moves u8 values that
are bit-identical to the pinned path's stage intermediates.

Geometry: every band rides in a fixed (E, W[, C]) u8 buffer with
``E = tile_rows + 2 * total_halo``; group g's live region sits at the
STATIC offset ``off_g`` (the halo consumed by all prior groups), so one
traced program serves every (tile, device) pair — injection at device 0
and collection at device n-1 are data-dependent selects, never shape
changes, and an arbitrarily tall image compiles exactly once.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mpi_cuda_imagemanipulation_tpu.ops.spec import U8, Op, exact_f32
from mpi_cuda_imagemanipulation_tpu.parallel.api import _fix_edge_axis
from mpi_cuda_imagemanipulation_tpu.parallel.mesh import shard_map_compat
from mpi_cuda_imagemanipulation_tpu.stream.tiles import (
    StreamabilityError,
    out_channels,
    validate_stream_ops,
)

STAGE = "stage"

# closed vocabulary of sharded-eligibility refusals (tests pin it; the
# fabric lane folds any of these into its own "ineligible" fallback)
ELIGIBILITY_REASONS = (
    "not-streamable",  # geometric/global op in the chain
    "channel-changing",  # stage in/out channel counts differ (switch
    #                      branches need one buffer aval)
    "halo-exceeds-tile",  # chain halo > tile_rows (seam spans bands)
    "too-few-stages",  # fewer plan stages than 2 (nothing to shard)
)


def systolic_eligible(
    ops: tuple[Op, ...], *, channels: int = 3, tile_rows: int
) -> str | None:
    """``None`` when the chain can run stage-sharded, else the refusal
    reason (one of ELIGIBILITY_REASONS)."""
    try:
        halo = validate_stream_ops(ops)
    except StreamabilityError:
        return "not-streamable"
    try:
        if out_channels(ops, channels) != channels:
            return "channel-changing"
    except ValueError:
        return "channel-changing"
    for op in ops:
        if op.out_channels and op.out_channels != channels:
            return "channel-changing"
    if halo > tile_rows:
        return "halo-exceeds-tile"
    if len(ops) < 2:
        return "too-few-stages"
    return None


def make_stage_mesh(n: int, *, devices=None) -> Mesh:
    """A 1-D mesh of `n` devices named 'stage' — its own axis (not the
    'rows' data axis) because the decomposition is by pipeline DEPTH."""
    if devices is None:
        devices = jax.devices()
    if n < 2:
        raise ValueError(f"systolic mesh needs >= 2 devices, got {n}")
    if n > len(devices):
        raise ValueError(
            f"systolic mesh wants {n} devices, only {len(devices)} present"
        )
    import numpy as np

    return Mesh(np.asarray(devices[:n]), (STAGE,))


def stage_weights(plan, *, channels: int = 3, ledger=None) -> list[float]:
    """Per-stage balancer weight in bytes/pixel: the one-u8-read +
    one-u8-write analytical guess, scaled by the cost ledger's measured
    drift ratio when a record with this plan fingerprint + stage label
    exists (the PR 15 measured feed; analytical stays the fallback)."""
    if ledger is None:
        from mpi_cuda_imagemanipulation_tpu.obs.cost import cost_ledger

        ledger = cost_ledger
    weights = []
    for i, stage in enumerate(plan.stages):
        w = float(2 * channels)
        drift = ledger.drift("plan", plan.fingerprint, f"s{i}/{stage.kind}")
        if drift is not None and drift > 0:
            w *= float(drift)
        weights.append(w)
    return weights


@dataclasses.dataclass(frozen=True)
class SystolicBuild:
    """A compiled-shape sharded executor plus its static structure.

    The counters are STRUCTURAL — fixed by (geometry, grouping) at build
    time, which is what lets the smoke/bench lanes assert "exactly one
    exchange per stage boundary" against the compiled HLO instead of
    sampling runtime behaviour."""

    fn: object  # jitted (H, W[, C]) u8 -> (H, W[, C]) u8
    ranges: tuple[tuple[int, int], ...]  # stage index ranges per device
    n_tiles: int
    tile_rows: int
    buf_rows: int  # E = tile_rows + 2 * total_halo
    n_steps: int  # wavefront length: n_tiles + n_groups - 1
    tiles_forwarded: int  # n_tiles * (n_groups - 1): boundary crossings
    exchange_bytes: int  # u8 payload bytes crossing stage boundaries

    @property
    def n_groups(self) -> int:
        return len(self.ranges)

    @property
    def n_exchanges(self) -> int:
        """ppermute count in the compiled program: one per wavefront
        step except the last. With n_tiles == 1 this equals
        n_groups - 1 — exactly one exchange per stage boundary, the
        structural form the acceptance test counts in HLO."""
        return self.n_steps - 1


def systolic_callable(
    plan,
    *,
    height: int,
    width: int,
    channels: int = 3,
    tile_rows: int,
    n_devices: int | None = None,
    mesh: Mesh | None = None,
    impl: str = "xla",
    ledger=None,
) -> SystolicBuild:
    """Build the stage-sharded streaming executor for one image shape.

    Stages are grouped contiguously over `n_devices` by the same
    linear-partition balancer the fabric placement pass uses
    (`graph.compile.partition_weights` over modelled-or-measured
    bytes/pixel), then the wavefront runs ``n_tiles + n_groups - 1``
    steps: device 0 injects band t, every device runs its group on the
    band it holds, one ppermute shifts all bands down the chain, device
    n-1 collects finished rows. Returns the jitted callable plus the
    build's static exchange structure."""
    from mpi_cuda_imagemanipulation_tpu.graph.compile import partition_weights
    from mpi_cuda_imagemanipulation_tpu.plan.exec import (
        acc_fns_for,
        walk_stage,
    )

    reason = systolic_eligible(
        plan.ops, channels=channels, tile_rows=tile_rows
    )
    if reason is not None:
        raise StreamabilityError(f"chain not systolic-eligible: {reason}")
    if mesh is None:
        mesh = make_stage_mesh(n_devices or 2)
    n = mesh.shape[STAGE]
    stages = plan.stages
    n_use = min(n, len(stages))
    if n_use < 2:
        raise StreamabilityError(
            f"plan has {len(stages)} stage(s); systolic needs >= 2"
        )
    if n_use < n:
        raise ValueError(
            f"mesh has {n} devices but the plan only has {len(stages)} "
            "stages — build the mesh with n <= n_stages"
        )
    ranges = partition_weights(
        stage_weights(plan, channels=channels, ledger=ledger), n
    )
    group_halos = [
        sum(stages[i].halo for i in range(lo, hi)) for lo, hi in ranges
    ]
    h_total = sum(group_halos)
    assert h_total == plan.total_halo
    # static offset of group g's live region inside the E-row buffer:
    # the context consumed by every earlier group
    offs = [0]
    for gh in group_halos:
        offs.append(offs[-1] + gh)
    e_rows = tile_rows + 2 * h_total
    n_tiles = math.ceil(height / tile_rows)
    n_steps = n_tiles + n - 1

    acc_fns = {}
    for stage in stages:
        acc_fns.update(acc_fns_for(stage.ops, impl, width))

    has_c = channels > 1
    buf_shape = (e_rows, width, channels) if has_c else (e_rows, width)

    def fix(cur, op, row_lo):
        return _fix_edge_axis(cur, op, row_lo + op.halo, height, 0)

    def run_group(g: int, buf: jnp.ndarray, y0: jnp.ndarray) -> jnp.ndarray:
        """Group g's stages over its live region; result re-embedded at
        the next group's static offset so every branch of the switch
        yields one (E, W[, C]) u8 aval."""
        lo, hi = ranges[g]
        off = offs[g]
        cur = buf[off : e_rows - off] if off else buf
        y_lo = y0 + off
        for si in range(lo, hi):
            stage = stages[si]
            cur, y_lo, _, _ = walk_stage(
                stage.ops,
                exact_f32(cur),
                y_lo=y_lo,
                lead_rem=stage.halo,
                tail_rem=stage.halo,
                global_h=height,
                global_w=width,
                acc_fns=acc_fns,
                edge_fix=fix,
            )
            # per-stage u8 materialisation: the pinned path's stage
            # boundary contract, so cross-device handoff is bit-exact
            cur = cur.astype(U8)
        off_next = offs[g + 1]
        out = jnp.zeros(buf_shape, U8)
        return out.at[off_next : e_rows - off_next].set(cur)

    # stacked extended bands, gathered host-side of the shard_map with
    # clipped row indices (out-of-image rows carry clipped copies; the
    # per-op edge_fix rewrites them before any stencil reads them)
    def stack_tiles(img: jnp.ndarray) -> jnp.ndarray:
        rows = (
            jnp.arange(n_tiles)[:, None] * tile_rows
            - h_total
            + jnp.arange(e_rows)[None, :]
        )
        return jnp.take(img, jnp.clip(rows, 0, height - 1), axis=0)

    y0s = jnp.asarray(
        [k * tile_rows - h_total for k in range(n_tiles)], jnp.int32
    )
    fwd = [(i, i + 1) for i in range(n - 1)]
    branches = [
        (lambda b, y, g=g: run_group(g, b, y)) for g in range(n)
    ]

    def shard_body(tiles: jnp.ndarray, y0v: jnp.ndarray) -> jnp.ndarray:
        me = lax.axis_index(STAGE)
        buf = jnp.zeros(buf_shape, U8)
        outs = jnp.zeros(
            (n_tiles, tile_rows) + buf_shape[1:], U8
        )
        for t in range(n_steps):
            # device 0 injects band t (clipped index keeps the gather
            # in-bounds after the wavefront passes the last band; the
            # re-injected copy is never collected)
            k_in = min(t, n_tiles - 1)
            buf = jnp.where(me == 0, tiles[k_in], buf)
            # band held here this step: k = t - me (clipped for the y0
            # lookup; out-of-range holdings produce garbage that a
            # later real band overwrites before collection)
            k = jnp.clip(t - me, 0, n_tiles - 1)
            buf = lax.switch(me, branches, buf, y0v[k])
            valid = (t - me >= 0) & (t - me < n_tiles)
            done = jnp.where(
                valid & (me == n - 1),
                buf[h_total : e_rows - h_total],
                jax.lax.dynamic_index_in_dim(outs, k, keepdims=False),
            )
            outs = jax.lax.dynamic_update_index_in_dim(outs, done, k, 0)
            if t < n_steps - 1:
                with jax.named_scope(f"systolic_exchange_t{t}"):
                    buf = lax.ppermute(buf, STAGE, fwd)
        return outs

    sharded = shard_map_compat(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=P(STAGE),
    )

    def run(img: jnp.ndarray) -> jnp.ndarray:
        stacked = sharded(stack_tiles(img), y0s)
        # out_specs=P('stage') concatenates per-device blocks on axis 0;
        # only the last device's block holds collected bands
        final = stacked[(n - 1) * n_tiles :]
        out = final.reshape((n_tiles * tile_rows,) + buf_shape[1:])
        return out[:height]

    px = e_rows * width * channels
    tiles_forwarded = n_tiles * (n - 1)
    return SystolicBuild(
        fn=jax.jit(run),
        ranges=ranges,
        n_tiles=n_tiles,
        tile_rows=tile_rows,
        buf_rows=e_rows,
        n_steps=n_steps,
        tiles_forwarded=tiles_forwarded,
        exchange_bytes=tiles_forwarded * px,
    )
