"""2-D sharded pipeline execution: shard_map over a ('rows', 'cols') mesh.

Extends the 1-D row decomposition (parallel/api.py — the reference's
MPI_Scatter row blocks, SURVEY.md §2.3) to a full 2-D tile decomposition:
the image is split over both mesh axes, every stencil tile is extended with
ghost zones on all four sides, and corners arrive without any diagonal
communication via the standard two-phase exchange — the vertical ppermute
runs first, then the horizontal ppermute carries the *vertically extended*
edge strips, so each tile's corner ghosts are its diagonal neighbour's data
relayed through the shared row/column neighbour. Two ring hops per axis,
exactly the collectives a 2-D jax mesh maps onto ICI.

The compute per tile is the ops' own golden tile functions (ops/spec.py
`valid`/`finalize` thread (y0, x0) global offsets and were 2-D-aware from
the start), so 2-D sharded output is bit-identical to the unsharded golden
path — the same invariant the 1-D runner carries
(tests/test_sharded2d.py). Global-statistics ops psum over BOTH axes.

Scope: the tile compute is XLA (fused elementwise + stencil per tile). The
fused-ghost Pallas streaming kernel assumes full-width rows and is the 1-D
path's specialty; a width-split tile would need horizontal ghost columns
inside the kernel's lane dimension, which buys nothing at these tile sizes
(see BASELINE.md's element-ceiling analysis — the kernels are I/O-bound, and
a 2-D split only shrinks the per-chip tile).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    F32,
    GlobalOp,
    PointwiseOp,
    StencilOp,
)
from mpi_cuda_imagemanipulation_tpu.parallel.api import HALO_MODES, _fix_edge_axis
from mpi_cuda_imagemanipulation_tpu.parallel.halo import exchange_halo
from mpi_cuda_imagemanipulation_tpu.parallel.mesh import (
    COLS,
    ROWS,
    shard_map_compat,
)


def _apply_stencil_2d(
    op: StencilOp,
    tile: jnp.ndarray,
    y0: jnp.ndarray,
    x0: jnp.ndarray,
    global_h: int,
    global_w: int,
    n_r: int,
    n_c: int,
) -> jnp.ndarray:
    """Two-phase exchange + fixup, then the op's golden valid/finalize."""
    h = op.halo
    # phase 1: vertical ghosts + vertical edge fix (on the raw tile)
    ext = _fix_edge_axis(
        exchange_halo(tile, h, n_r, axis_name=ROWS, axis=0),
        op, y0, global_h, 0,
    )
    # phase 2: horizontal ghosts carry the vertically-extended strips, so
    # corner ghosts arrive via the shared neighbour; then horizontal fix
    ext = _fix_edge_axis(
        exchange_halo(ext, h, n_c, axis_name=COLS, axis=1),
        op, x0, global_w, 1,
    )
    if tile.ndim == 3:
        return jnp.stack(
            [
                op.finalize(
                    op.valid(ext[..., c].astype(F32)),
                    tile[..., c],
                    y0,
                    x0,
                    global_h,
                    global_w,
                )
                for c in range(tile.shape[2])
            ],
            axis=-1,
        )
    return op.finalize(op.valid(ext.astype(F32)), tile, y0, x0, global_h, global_w)


def _overlap_ok_2d(
    op, pad_h: int, pad_w: int, local_h: int, local_w: int
) -> bool:
    """2-D interior-first gate: a real halo, no pad rows/cols inside the
    tile, and a non-empty interior along both axes (same reasoning as the
    1-D _overlap_ok, applied per axis)."""
    return (
        isinstance(op, StencilOp)
        and op.halo >= 1
        and pad_h == 0
        and pad_w == 0
        and local_h > 2 * op.halo
        and local_w > 2 * op.halo
    )


def _apply_stencil_2d_overlap(
    op: StencilOp,
    tile: jnp.ndarray,
    y0: jnp.ndarray,
    x0: jnp.ndarray,
    global_h: int,
    global_w: int,
    n_r: int,
    n_c: int,
    gi: int,
) -> jnp.ndarray:
    """Interior-first execution of one stencil on a 2-D tile.

    The (local_h - 2h) x (local_w - 2h) interior computes from the raw
    tile with no data dependence on either exchange phase, so it runs
    while all four ring ppermutes are in flight. The h-thick frame —
    full-width top/bottom bands (whose corners use the two-phase
    corner-carrying ghosts) and the left/right middle bands — computes
    from the fully extended tile once the ghosts land. Every band's valid
    windows slice the same values the serial path's whole-tile valid
    sees, so the stitched output is bit-identical."""
    h = op.halo
    local_h, local_w = tile.shape[0], tile.shape[1]
    with jax.named_scope(f"halo_exchange_g{gi}"):
        vext = exchange_halo(tile, h, n_r, axis_name=ROWS, axis=0)
    vext = _fix_edge_axis(vext, op, y0, global_h, 0)
    with jax.named_scope(f"halo_exchange_g{gi}"):
        ext = exchange_halo(vext, h, n_c, axis_name=COLS, axis=1)
    ext = _fix_edge_axis(ext, op, x0, global_w, 1)

    def plane(extp, tilep):
        def band(rows, cols, orig, yb, xb):
            acc = op.valid(extp[rows, cols].astype(F32))
            return op.finalize(acc, orig, yb, xb, global_h, global_w)

        with jax.named_scope(f"halo_overlap_interior_g{gi}"):
            acc = op.valid(tilep.astype(F32))
            interior = op.finalize(
                acc, tilep[h:-h, h:-h], y0 + h, x0 + h, global_h, global_w
            )
        with jax.named_scope(f"halo_overlap_boundary_g{gi}"):
            # ext row r holds input row r - h (likewise columns)
            top = band(
                slice(0, 3 * h), slice(None), tilep[:h], y0, x0
            )
            bottom = band(
                slice(local_h - h, local_h + 2 * h), slice(None),
                tilep[local_h - h :], y0 + local_h - h, x0,
            )
            left = band(
                slice(h, local_h + h), slice(0, 3 * h),
                tilep[h:-h, :h], y0 + h, x0,
            )
            right = band(
                slice(h, local_h + h), slice(local_w - h, local_w + 2 * h),
                tilep[h:-h, local_w - h :], y0 + h, x0 + local_w - h,
            )
        mid = jnp.concatenate([left, interior, right], axis=1)
        return jnp.concatenate([top, mid, bottom], axis=0)

    if tile.ndim == 3:
        return jnp.stack(
            [plane(ext[..., c], tile[..., c]) for c in range(tile.shape[2])],
            axis=-1,
        )
    return plane(ext, tile)


def _min_local(pad: int, halo: int) -> int:
    """Static feasibility of local edge fixups, per axis (same reasoning as
    the 1-D runner): every reflect/pad source index must live on-tile."""
    return max(2 * pad + 1, pad + halo, halo, 1)


# --------------------------------------------------------------------------
# Plan-fused stage forms (plan/): temporal blocking over BOTH mesh axes
# --------------------------------------------------------------------------


def _plan_stage_fused_ok_2d(
    stage, pad_h: int, pad_w: int, local_h: int, local_w: int
) -> bool:
    """Whether one fused stage can run temporally blocked on this 2-D
    decomposition: no pad rows/cols inside the tile (the per-op dynamic
    edge fix gathers only from real data) and enough local extent on
    BOTH axes to source the stage-halo strips — the 1-D serial gate
    applied per axis. Static, so every shard decides identically."""
    H = stage.halo
    if H < 1:
        return True  # halo-0 stages fuse with no exchange at all
    return (
        pad_h == 0 and pad_w == 0 and local_h > H and local_w > H
    )


def _plan_walk_2d(stage, ext, y0, x0, global_h: int, global_w: int):
    """One fused stage over a (local_h + 2H, local_w + 2H[, C]) tile
    whose four-sided context was materialised by the stage's single
    two-phase exchange. The walk is plan/exec.walk_stage's sharded
    convention generalized to both axes: each stencil REWRITES the
    out-of-image rows then columns of the carry per its own edge mode
    (`_fix_edge_axis`, row fix before column fix — the column fix's
    sources are then row-fixed values, so global corners resolve to the
    separable reflect-of-reflect the golden pad2d produces), runs its
    golden `valid` over the doubly-extended carry, and finalizes at
    global (y, x) offsets. The carry stays f32 exact-integer between
    member ops; u8 materialises once at the stage boundary."""
    from mpi_cuda_imagemanipulation_tpu.ops.spec import U8, exact_f32
    from mpi_cuda_imagemanipulation_tpu.plan.exec import apply_pointwise_f32

    H = stage.halo
    cur = exact_f32(ext)
    off = 0
    for op in stage.ops:
        if not isinstance(op, StencilOp):
            cur = apply_pointwise_f32(op, cur)
            continue
        h = op.halo
        # global coordinates of the carry's first row/col
        row0 = y0 - (H - off)
        col0 = x0 - (H - off)
        if h:
            cur = _fix_edge_axis(cur, op, row0 + h, global_h, 0)
            cur = _fix_edge_axis(cur, op, col0 + h, global_w, 1)
        rows, cols = cur.shape[0], cur.shape[1]

        def plane(p, op=op, h=h, rows=rows, cols=cols, row0=row0, col0=col0):
            acc = op.valid(p)
            orig = p[h : rows - h, h : cols - h]
            return op.finalize_f32(
                acc, orig, row0 + h, col0 + h, global_h, global_w
            )

        if cur.ndim == 3:
            cur = jnp.stack(
                [plane(cur[..., c]) for c in range(cur.shape[2])], axis=-1
            )
        else:
            cur = plane(cur)
        off += h
    return cur.astype(U8)


def _apply_stage_serial_2d(
    stage, tile, y0, x0, global_h, global_w, n_r, n_c, si
):
    """Temporally blocked execution of one fused stage on a 2-D tile:
    ONE two-phase corner-carrying exchange sized to the stage's grown
    halo — the vertical ppermute pair first, then the horizontal pair
    carrying the vertically-extended strips so corner ghosts arrive via
    the shared neighbour — then the whole stage walks the extended tile.
    Where the per-op path pays one exchange round per stencil, a fused
    stage pays one total (the `plan_exchange2d_s<i>` scope is what the
    structural HLO test counts: exactly 4 collective-permutes per
    halo-carrying fused stage)."""
    H = stage.halo
    if H == 0:
        return _plan_walk_2d(stage, tile, y0, x0, global_h, global_w)
    with jax.named_scope(f"plan_exchange2d_s{si}"):
        vext = exchange_halo(tile, H, n_r, axis_name=ROWS, axis=0)
        ext = exchange_halo(vext, H, n_c, axis_name=COLS, axis=1)
    with jax.named_scope(f"plan_stage2d_s{si}"):
        return _plan_walk_2d(stage, ext, y0, x0, global_h, global_w)


def _run_segment_2d(
    ops, mesh, img: jnp.ndarray, halo_mode: str = "serial", plan=None
):
    n_r, n_c = mesh.shape[ROWS], mesh.shape[COLS]
    max_halo = max((op.halo for op in ops), default=0)
    global_h, global_w = img.shape[0], img.shape[1]
    padded_h = -(-global_h // n_r) * n_r
    padded_w = -(-global_w // n_c) * n_c
    pad_h, pad_w = padded_h - global_h, padded_w - global_w
    local_h, local_w = padded_h // n_r, padded_w // n_c
    for size, pad, n, name in (
        (local_h, pad_h, n_r, "rows"),
        (local_w, pad_w, n_c, "cols"),
    ):
        if size < _min_local(pad, max_halo):
            raise ValueError(
                f"image {global_h}x{global_w} over a {n_r}x{n_c} mesh gives "
                f"{size} {name}/shard, below the minimum "
                f"{_min_local(pad, max_halo)} for halo {max_halo} and "
                f"padding {pad}; use a smaller mesh"
            )
    if pad_h or pad_w:
        img_p = jnp.pad(
            img, ((0, pad_h), (0, pad_w)) + ((0, 0),) * (img.ndim - 2)
        )
    else:
        img_p = img

    def tile_fn(tile):
        y0 = lax.axis_index(ROWS) * local_h
        x0 = lax.axis_index(COLS) * local_w
        if plan is not None:
            for si, stage in enumerate(plan.stages):
                if stage.kind == "global":
                    op = stage.ops[0]
                    rows = y0 + lax.iota(jnp.int32, tile.shape[0])
                    cols = x0 + lax.iota(jnp.int32, tile.shape[1])
                    valid = (rows < global_h)[:, None] & (
                        cols < global_w
                    )[None, :]
                    valid = valid.reshape(
                        valid.shape + (1,) * (tile.ndim - 2)
                    )
                    stats = lax.psum(op.stats(tile, valid), (ROWS, COLS))
                    tile = op.apply(tile, stats)
                elif _plan_stage_fused_ok_2d(
                    stage, pad_h, pad_w, local_h, local_w
                ):
                    tile = _apply_stage_serial_2d(
                        stage, tile, y0, x0, global_h, global_w,
                        n_r, n_c, si,
                    )
                else:
                    # per-op fallback for this stage only (pad rows /
                    # sub-halo tiles) — the golden contract the fused
                    # path is gated against, same rule as the 1-D runner
                    for op in stage.ops:
                        if isinstance(op, PointwiseOp):
                            tile = op.fn(tile)
                        else:
                            tile = _apply_stencil_2d(
                                op, tile, y0, x0, global_h, global_w,
                                n_r, n_c,
                            )
            return tile
        gi = 0
        for op in ops:
            if isinstance(op, PointwiseOp):
                tile = op.fn(tile)
            elif isinstance(op, GlobalOp):
                # additive statistic over valid (non-padding) pixels,
                # combined across the WHOLE mesh with one two-axis psum
                rows = y0 + lax.iota(jnp.int32, tile.shape[0])
                cols = x0 + lax.iota(jnp.int32, tile.shape[1])
                valid = (rows < global_h)[:, None] & (cols < global_w)[None, :]
                valid = valid.reshape(valid.shape + (1,) * (tile.ndim - 2))
                stats = lax.psum(op.stats(tile, valid), (ROWS, COLS))
                tile = op.apply(tile, stats)
            else:
                if halo_mode == "overlap" and _overlap_ok_2d(
                    op, pad_h, pad_w, local_h, local_w
                ):
                    tile = _apply_stencil_2d_overlap(
                        op, tile, y0, x0, global_h, global_w, n_r, n_c, gi
                    )
                else:
                    tile = _apply_stencil_2d(
                        op, tile, y0, x0, global_h, global_w, n_r, n_c
                    )
                gi += 1
        return tile

    def seq(x):
        for op in ops:
            x = op(x)
        return x

    out_shape = jax.eval_shape(seq, img_p)
    in_spec = P(ROWS, COLS, *([None] * (img.ndim - 2)))
    out_spec = P(ROWS, COLS, *([None] * (len(out_shape.shape) - 2)))
    out = shard_map_compat(
        tile_fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec
    )(img_p)
    return out[:global_h, :global_w]


def sharded_pipeline_2d(pipe, mesh, halo_mode: str = "serial",
                        plan: str = "auto"):
    """Compile `pipe` to run tile-sharded over a ('rows', 'cols') mesh.

    Returns a jitted (H, W[, 3]) uint8 -> uint8 function, bit-identical to
    the unsharded golden path. Geometric (shape-changing) ops run between
    shard_map segments at the jit level under a 2-D sharding constraint,
    same recipe as the 1-D runner. `halo_mode='overlap'` computes each
    eligible stencil's interior while the four ring ppermutes are in
    flight (_apply_stencil_2d_overlap); ineligible stencils (pad
    rows/cols, halo 0, tiny tiles) stay serial, output unchanged.

    `plan` engages the fusion planner's stage forms: a fused stage pays
    ONE two-phase corner-carrying exchange round (its grown halo, both
    axes) instead of one round per stencil op. The 2-D tile compute is
    XLA, so 'fused-pallas' executes its (identical) stage partition
    through the same walker — the megakernel is the 1-D/full-image
    specialty (see parallel/api2d scope note). `halo_mode='overlap'`
    keeps PR 1's measured per-op interior-first structure unless a plan
    is explicitly requested — under an explicit plan the stage forms run
    serial (the stage exchange subsumes the per-op prefetch)."""
    from mpi_cuda_imagemanipulation_tpu.parallel.api import _split_segments
    from mpi_cuda_imagemanipulation_tpu.plan import (
        build_plan,
        resolve_plan_mode,
    )

    if halo_mode not in HALO_MODES:
        raise ValueError(
            f"unknown halo_mode {halo_mode!r}; known: {HALO_MODES}"
        )
    plan_mode = resolve_plan_mode(pipe.ops, plan, backend="xla")
    if plan_mode != "off" and halo_mode == "overlap" and plan in (
        "auto", None, "",
    ):
        plan_mode = "off"  # same rule as the 1-D runner (PR 1 structure)
    segments = _split_segments(pipe.ops)
    seg_plans = [
        build_plan(ops, plan_mode)
        if kind == "shard_map" and plan_mode != "off"
        else None
        for kind, ops in segments
    ]

    def run(img: jnp.ndarray) -> jnp.ndarray:
        from jax.sharding import NamedSharding

        for (kind, ops), seg_plan in zip(segments, seg_plans):
            if kind == "xla":
                img = ops[0].fn(img)
                img = lax.with_sharding_constraint(
                    img,
                    NamedSharding(
                        mesh, P(ROWS, COLS, *([None] * (img.ndim - 2)))
                    ),
                )
            else:
                img = _run_segment_2d(
                    ops, mesh, img, halo_mode=halo_mode, plan=seg_plan
                )
        return img

    return jax.jit(run)
