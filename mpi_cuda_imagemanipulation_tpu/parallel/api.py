"""Sharded pipeline execution: shard_map over a ('rows',) mesh.

Replaces the reference's entire distribution layer (SURVEY.md §2.3) with one
compiled XLA program:

  MPI_Scatter row blocks (kern.cpp:55)   -> in_specs P('rows', ...) sharding
  (missing) ghost-row exchange           -> lax.ppermute halos (halo.py)
  MPI_Gather (kern.cpp:81-83)            -> out_specs + jax.device_get
  rows % size silently dropped (ku:117)  -> pad-to-multiple + crop (exact)
  per-slice seams (kernel.cu:83)         -> global-coordinate interior masks

Every op runs on its local tile with the op's *own* tile functions
(ops/spec.py), so sharded output is bit-identical to the unsharded golden
path — the seam/race detector invariant of SURVEY.md §4.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    F32,
    GeometricOp,
    GlobalOp,
    PointwiseOp,
    StencilOp,
    edge_slices,
    interior_slice,
    pad2d,
)
from mpi_cuda_imagemanipulation_tpu.parallel.halo import (
    exchange_edge_strips,
    exchange_halo,
    exchange_halo_strips,
)
from mpi_cuda_imagemanipulation_tpu.parallel.mesh import ROWS, shard_map_compat

# Halo execution modes for the sharded stencil runners. 'serial' exchanges
# ghost strips and only then runs each stencil group (every group gates on
# two ring ppermutes); 'overlap' restructures the dataflow so interior rows
# — which need no ghost data — compute while the strips are in flight, and
# the next group's exchange is issued from the previous group's boundary
# outputs (cross-group prefetch). Output is bit-identical either way.
HALO_MODES = ("serial", "overlap")


def _reflect101_index(g: jnp.ndarray, size: int) -> jnp.ndarray:
    """Map any (possibly out-of-range) global row index to its reflect-101
    source inside [0, size): ... 2 1 | 0 1 2 ... n-1 | n-2 n-3 ..."""
    a = jnp.abs(g)
    return (size - 1) - jnp.abs((size - 1) - a)


def _fix_edge_axis(
    ext: jnp.ndarray,
    op: StencilOp,
    off: jnp.ndarray,
    global_size: int,
    axis: int,
) -> jnp.ndarray:
    """Overwrite ghost/padding slices along `axis` whose global index falls
    outside the real image with the op's edge extension.

    Slices needing fixes are (a) ring-wrapped halos on the first/last shard
    and (b) the pad-to-multiple slices at the global end. Sources are
    gathered from within this shard's extended tile — feasibility is
    checked statically by the segment runners. Axis-general: the 1-D row
    runner fixes axis 0; the 2-D tile runner (parallel/api2d) applies it
    per axis (reflect-101 is separable, so row fix before the column
    exchange plus column fix after yields golden corner values).
    """
    ext_sz = ext.shape[axis]
    h = op.halo
    g = off - h + lax.iota(jnp.int32, ext_sz)
    outside = (g < 0) | (g >= global_size)
    bshape = [1] * ext.ndim
    bshape[axis] = ext_sz
    outside_b = outside.reshape(bshape)
    if op.edge_mode in ("interior", "zero"):
        # zero out-of-image slices; 'interior' never reads them (masked),
        # but zeroing keeps tile values identical to the golden zero-padded
        # path.
        return jnp.where(outside_b, jnp.zeros_like(ext), ext)
    if op.edge_mode == "reflect101":
        src_g = _reflect101_index(g, global_size)
    elif op.edge_mode == "edge":
        src_g = jnp.clip(g, 0, global_size - 1)
    else:  # pragma: no cover
        raise ValueError(f"unknown edge mode {op.edge_mode!r}")
    src_local = jnp.clip(src_g - (off - h), 0, ext_sz - 1)
    gathered = jnp.take(ext, src_local, axis=axis)
    return jnp.where(outside_b, gathered, ext)


def _fix_edge_rows(
    ext: jnp.ndarray,
    op: StencilOp,
    y0: jnp.ndarray,
    global_h: int,
) -> jnp.ndarray:
    """Row-axis form of _fix_edge_axis (the 1-D runner's call shape)."""
    return _fix_edge_axis(ext, op, y0, global_h, 0)


def _fix_edge_strips(
    top: jnp.ndarray,
    bottom: jnp.ndarray,
    tile: jnp.ndarray,
    op: StencilOp,
    y0: jnp.ndarray,
    global_h: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Strip-level global-edge fixup for the fused-ghost path.

    With no pad rows inside the tile (caller-gated) and local_h > halo, a
    strip is either fully inside the image (middle shards — ppermuted rows
    are already correct) or fully outside (first shard's top / last shard's
    bottom), so the fix is a whole-strip select of the op's edge extension
    synthesised from the tile's own static row slices.
    """
    h = op.halo
    local_h = tile.shape[0]
    mode = op.edge_mode
    if mode in ("interior", "zero"):
        synth_top = jnp.zeros_like(top)
        synth_bot = jnp.zeros_like(bottom)
    elif mode == "reflect101":
        # global row -k reflects to row k; row H-1+k reflects to H-1-k
        synth_top = jnp.flip(tile[1 : h + 1], axis=0)
        synth_bot = jnp.flip(tile[local_h - 1 - h : local_h - 1], axis=0)
    elif mode == "edge":
        synth_top = jnp.broadcast_to(tile[:1], top.shape)
        synth_bot = jnp.broadcast_to(tile[local_h - 1 :], bottom.shape)
    else:  # pragma: no cover
        raise ValueError(f"unknown edge mode {mode!r}")
    is_first = y0 == 0
    is_last = y0 + local_h == global_h
    return (
        jnp.where(is_first, synth_top, top),
        jnp.where(is_last, synth_bot, bottom),
    )


def _prefer_swar() -> bool:
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import prefer_swar

    return prefer_swar()


def _resolve_backend(op: StencilOp, backend: str, width: int | None = None) -> str:
    if backend == "mxu":
        # explicit MXU backend: eligible ops take the banded-matmul path,
        # everything else falls back to the u8 Pallas tile kernel — the
        # same per-op always-correct contract as impl='swar'
        from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import mxu_eligible

        return "mxu" if mxu_eligible(op) else "pallas"
    if backend != "auto":
        return backend
    from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
        use_mxu_for_stencil,
    )
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        use_pallas_for_stencil,
    )

    # MXU routing first (mirrors pipeline_auto): fires only behind a
    # measured per-device-kind calibration win or MCIM_PREFER_MXU, and
    # never off-TPU
    if use_mxu_for_stencil(op, width) is not None:
        return "mxu"
    # the sharded ext path runs the stencil kernel per channel plane,
    # hence group_in_channels=1
    return "pallas" if use_pallas_for_stencil(op, 1) else "xla"


def _apply_stencil(
    op: StencilOp,
    tile: jnp.ndarray,
    y0: jnp.ndarray,
    global_h: int,
    global_w: int,
    n_shards: int,
    backend: str = "xla",
) -> jnp.ndarray:
    """Materialised-ext stencil path (pad-to-multiple tiles, halo-0 ops,
    and the XLA backend). The Pallas fast path is the fused-ghost group in
    _apply_group_fused, selected by _run_segment's group walker."""
    h = op.halo
    backend = _resolve_backend(op, backend, global_w)
    if backend == "swar":
        # the materialised-ext fallback has no swar variant (it exists for
        # pad rows / tiny tiles where throughput is moot); use the u8
        # Pallas tile kernel
        backend = "pallas"
    # halo exchange + global-edge fixup once on the full tile (2-D or HWC) —
    # on uint8 (dtype-generic gather/where), so colour images pay two
    # ppermutes total, not two per channel, and Pallas HBM traffic stays u8
    ext = _fix_edge_rows(exchange_halo(tile, h, n_shards), op, y0, global_h)
    if tile.ndim == 3:  # colour: filter each channel plane independently
        return jnp.stack(
            [
                _stencil_on_ext(
                    op, ext[..., c], tile[..., c], y0, global_h, global_w, backend
                )
                for c in range(tile.shape[2])
            ],
            axis=-1,
        )
    return _stencil_on_ext(op, ext, tile, y0, global_h, global_w, backend)


def _overlap_ok(op, n: int, local_h: int, global_h: int) -> bool:
    """Whether one stencil group can take the interior-first overlap path:
    a real halo (halo-0 groups have no exchange to hide), no pad rows
    inside the tile (strip-level edge synthesis is whole-strip — the same
    gate as the fused-ghost path), and a non-empty interior. Static, so
    the walker and the cross-group prefetch lookahead always agree."""
    return (
        isinstance(op, StencilOp)
        and op.halo >= 1
        and n * local_h == global_h
        and local_h > 2 * op.halo
    )


def _piece_edge_rows(pieces, k: int):
    """First/last `k` rows of a stitched (top, interior, bottom) piece
    list WITHOUT concatenating the tile first: slices are taken from the
    individual pieces, so the next group's ppermute payload depends only
    on the pieces that actually contain edge rows — for k <= halo just
    the boundary strips — never on the whole interior computation. This
    is what lets the cross-group prefetch ppermute issue as soon as the
    previous group's boundary rows are final."""
    first, need = [], k
    # mcim: allow(tracer-control-flow: pieces is a Python list of per-piece arrays; its length and shapes are static at trace time)
    for p in pieces:
        take = min(need, p.shape[0])
        if take:
            first.append(p[:take])
            need -= take
        if not need:
            break
    last, need = [], k
    for p in reversed(pieces):
        take = min(need, p.shape[0])
        if take:
            last.insert(0, p[p.shape[0] - take :])
            need -= take
        if not need:
            break

    def cat(xs):
        return xs[0] if len(xs) == 1 else jnp.concatenate(xs, axis=0)

    return cat(first), cat(last)


def _next_stencil_group(ops, i: int):
    """(next stencil op, intervening pointwise chain) looking forward from
    ops[i], or (None, []) when anything but a PointwiseOp intervenes (a
    GlobalOp's psum is itself a sync point, so prefetching past one buys
    nothing; geometric ops end the segment)."""
    chain: list = []
    for op in ops[i:]:
        if isinstance(op, PointwiseOp):
            chain.append(op)
        elif isinstance(op, StencilOp):
            return op, chain
        else:
            return None, []
    return None, []


def _apply_stencil_overlap(
    op: StencilOp,
    tile: jnp.ndarray,
    strips: tuple[jnp.ndarray, jnp.ndarray],
    y0: jnp.ndarray,
    global_h: int,
    global_w: int,
    backend: str,
    gi: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Interior-first execution of one stencil group.

    The interior rows — everything a halo-h stencil can produce from the
    local tile alone — are computed with NO data dependence on the
    ppermuted ghost strips, so XLA's scheduler can run them while the ICI
    transfers are in flight; only the two h-row boundary strips wait for
    `strips` to land. Stitching top/interior/bottom with per-slice global
    row offsets reproduces the serial path's windows exactly, so output
    stays bit-identical (the invariant tests/test_sharded.py asserts over
    both halo modes).

    Returns the (top, interior, bottom) pieces unconcatenated so the
    caller can slice the next group's prefetch payload from the boundary
    pieces alone (_piece_edge_rows). Named scopes tag the interior and
    boundary computations per group; tests/test_halo_overlap.py asserts
    from the lowered module that `halo_overlap_interior_g<gi>` has no
    path from any collective-permute of group >= gi.
    """
    h = op.halo
    local_h = tile.shape[0]
    backend = _resolve_backend(op, backend, global_w)
    if backend == "swar":
        backend = "pallas"  # same mapping as the materialised-ext path
    top, bottom = _fix_edge_strips(strips[0], strips[1], tile, op, y0, global_h)

    def run(ext, orig, yoff, be):
        if ext.ndim == 3:  # colour: filter each channel plane independently
            return jnp.stack(
                [
                    _stencil_on_ext(
                        op, ext[..., c], orig[..., c], yoff, global_h,
                        global_w, be,
                    )
                    for c in range(ext.shape[2])
                ],
                axis=-1,
            )
        return _stencil_on_ext(op, ext, orig, yoff, global_h, global_w, be)

    with jax.named_scope(f"halo_overlap_interior_g{gi}"):
        interior = run(tile, interior_slice(tile, h), y0 + h, backend)
    # boundary strips: h output rows each, from (3h, W) extended bands —
    # XLA compute (a Pallas launch for h rows costs more than it saves)
    with jax.named_scope(f"halo_overlap_boundary_g{gi}"):
        head, tail = edge_slices(tile, 2 * h)
        top_out = run(
            jnp.concatenate([top, head], axis=0), tile[:h], y0, "xla"
        )
        bottom_out = run(
            jnp.concatenate([tail, bottom], axis=0),
            tile[local_h - h :],
            y0 + local_h - h,
            "xla",
        )
    return top_out, interior, bottom_out


def _swar_group_ok(pointwise, op: StencilOp, tile, n: int, local_h: int,
                   global_h: int) -> bool:
    """Whether one [pointwise*, stencil] group can take the quarter-strip
    SWAR ghost path on this tile: single u8 plane the op is shape-eligible
    on, no pad rows inside the tile (strip edge synthesis is whole-strip),
    every buffered pointwise fits an exact affine chain, and (zero mode
    only) the composed chain fixes 0 so chain and padding commute."""
    from mpi_cuda_imagemanipulation_tpu.ops.swar_kernels import (
        _chain_fixes_zero,
        swar_any_eligible,
        swar_fusable,
    )

    return (
        tile.ndim == 2
        and n * local_h == global_h
        and local_h > op.halo
        and swar_any_eligible(op, (local_h, tile.shape[1]))
        and all(swar_fusable(p) is not None for p in pointwise)
        and (op.edge_mode != "zero" or _chain_fixes_zero(pointwise))
    )


def _apply_group_swar(
    pointwise,
    stencil: StencilOp,
    tile: jnp.ndarray,
    y0: jnp.ndarray,
    global_h: int,
    n_shards: int,
    post=(),
) -> jnp.ndarray:
    """Run one [pointwise*, stencil, pointwise*] group as a single
    quarter-strip SWAR kernel (ops/swar_kernels.py ghost mode): ghost
    strips are exchanged raw — per-pixel chains commute with strip
    selection — and the fitted pointwise chains run inside the kernel, so
    the sharded tile streams exactly like the unsharded SWAR path,
    suffix chains included. Caller gates with _swar_group_ok."""
    from mpi_cuda_imagemanipulation_tpu.ops.swar_kernels import swar_stencil

    h = stencil.halo
    top, bottom = exchange_halo_strips(tile, h, n_shards)
    top, bottom = _fix_edge_strips(top, bottom, tile, stencil, y0, global_h)
    return swar_stencil(
        stencil,
        tile,
        pre_ops=tuple(pointwise),
        post_ops=tuple(post),
        ghosts=(top, bottom),
        # interior-guard corr2d masks follow global coordinates (the
        # seam-removal property, spec.interior_mask); harmless otherwise
        y0=y0,
        global_h=global_h,
    )


def _apply_group_fused(
    pointwise,
    stencil: StencilOp,
    tile: jnp.ndarray,
    y0: jnp.ndarray,
    global_h: int,
    global_w: int,
    n_shards: int,
) -> jnp.ndarray:
    """Run one [pointwise*, stencil] group as a single ghost-mode Pallas
    call: the raw pre-pointwise tile streams through the kernel once, the
    (halo, W) ghost strips (exchanged raw — pointwise ops are per-pixel, so
    they commute with strip selection and are applied to the strips inside
    the kernel) ride along in VMEM, and no intermediate pointwise output is
    ever materialised in HBM.
    """
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import run_group

    h = stencil.halo
    top, bottom = exchange_halo_strips(tile, h, n_shards)
    # Edge synthesis on the raw tile is exact for reflect101/edge (row
    # selections commute with per-pixel ops). For interior mode the strip
    # values on the first/last shard never reach an unmasked output, so the
    # raw zeros are fine (mask passes those outputs through).
    top, bottom = _fix_edge_strips(top, bottom, tile, stencil, y0, global_h)
    if tile.ndim == 3:
        planes = [tile[..., c] for c in range(tile.shape[2])]
        tops = [top[..., c] for c in range(tile.shape[2])]
        bots = [bottom[..., c] for c in range(tile.shape[2])]
    else:
        planes, tops, bots = [tile], [top], [bottom]
    outs = run_group(
        list(pointwise),
        stencil,
        planes,
        ghosts=(tops, bots),
        y0=y0,
        image_h=global_h,
        image_w=global_w,
    )
    if len(outs) == 1:
        return outs[0]
    return jnp.stack(outs, axis=-1)


def _stencil_on_ext(
    op: StencilOp,
    ext: jnp.ndarray,
    tile: jnp.ndarray,
    y0: jnp.ndarray,
    global_h: int,
    global_w: int,
    backend: str,
) -> jnp.ndarray:
    """Run one stencil over a single (local_h + 2h, W) pre-exchanged plane."""
    h = op.halo
    if backend == "mxu":
        # banded-matmul accumulation on the (row-exchanged) tile: pad the
        # width per the op's edge mode (row halo is already materialised),
        # contract on the MXU, replay the golden finalize at global
        # coordinates — bit-identical to the XLA branch below
        from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import mxu_valid

        xpad = pad2d(ext.astype(F32), op.edge_mode, 0, 0, h, h)
        acc = mxu_valid(op, xpad)
        return op.finalize(acc, tile, y0, 0, global_h, global_w)
    if backend == "pallas":
        from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
            stencil_tile_pallas,
        )

        q = stencil_tile_pallas(op, ext)
        if op.edge_mode != "interior":
            return q
        mask = op.interior_mask(q.shape, y0, 0, global_h, global_w)
        return jnp.where(mask, q, tile)
    xpad = pad2d(ext.astype(F32), op.edge_mode, 0, 0, h, h)  # width halo is local
    acc = op.valid(xpad)
    return op.finalize(acc, tile, y0, 0, global_h, global_w)


# --------------------------------------------------------------------------
# Plan-fused segment execution (plan/): temporally blocked stages
# --------------------------------------------------------------------------


def _plan_stage_fused_ok(stage, n: int, local_h: int, global_h: int,
                         overlap: bool) -> bool:
    """Whether one fused stage can run temporally blocked on this
    decomposition: a real stage halo, no pad-to-multiple rows inside the
    tile (the per-op dynamic edge fix gathers only from real rows — the
    same gate as the fused-ghost and overlap paths), and enough local
    rows to slice the stage-halo strips (overlap additionally needs a
    non-empty interior after consuming 2H context rows). Static, so the
    fallback decision is identical on every shard."""
    H = stage.halo
    if H < 1 or n * local_h != global_h:
        return H == 0  # halo-0 stages always "fuse" (no exchange at all)
    if overlap:
        return local_h > 2 * H
    return local_h > H


def _plan_walk(stage, ext, y_lo, global_h: int, global_w: int, impl: str):
    """One fused stage over a materialised extended tile: the shared
    stage walker (plan/exec.walk_stage) with the sharded edge
    convention — context rows are always present (the stage's single
    exchange), and out-of-image rows are rewritten per op by
    _fix_edge_axis BEFORE that op reads them, so ring-wrapped strips and
    global-edge extension resolve exactly as the per-op serial path's
    fixups do, one op at a time (no commuting assumption between an op's
    output and the next op's border)."""
    from mpi_cuda_imagemanipulation_tpu.ops.spec import U8, exact_f32
    from mpi_cuda_imagemanipulation_tpu.plan.exec import acc_fns_for, walk_stage

    acc_fns = acc_fns_for(stage.ops, impl, global_w)

    def fix(cur, op, row_lo):
        return _fix_edge_axis(cur, op, row_lo + op.halo, global_h, 0)

    cur, _, _, _ = walk_stage(
        stage.ops,
        exact_f32(ext),
        y_lo=y_lo,
        lead_rem=stage.halo,
        tail_rem=stage.halo,
        global_h=global_h,
        global_w=global_w,
        acc_fns=acc_fns,
        edge_fix=fix,
    )
    return cur.astype(U8)


def _apply_stage_serial(stage, tile, y0, global_h, global_w, n, impl, si):
    """Temporally blocked serial execution of one fused stage: ONE
    ppermute ghost-strip pair sized to the stage's grown halo
    (`Stage.halo` = chain_halo of the member stencils), then the whole
    stage walks the extended tile — where the per-op serial path pays
    one exchange per stencil, a fused stage pays one total. The
    `plan_exchange_s<si>` scope is what the structural HLO test counts:
    exactly one collective-permute pair per fused stage."""
    H = stage.halo
    if H == 0:
        return _plan_walk(stage, tile, y0, global_h, global_w, impl)
    with jax.named_scope(f"plan_exchange_s{si}"):
        top, bottom = exchange_halo_strips(tile, H, n)
    ext = jnp.concatenate([top, tile, bottom], axis=0)
    with jax.named_scope(f"plan_stage_s{si}"):
        return _plan_walk(stage, ext, y0 - H, global_h, global_w, impl)


def _apply_stage_overlap(stage, tile, y0, global_h, global_w, n, impl, si):
    """Stage-granular interior-first execution (the PR-1 overlap
    machinery lifted from per-op groups to fused stages): the stage's
    single exchange is issued first, the interior — every output row the
    local tile can produce alone, i.e. all but H per side — walks the
    stage with NO data dependence on the strips, and two 3H-row boundary
    bands stitch once they land. Output is bit-identical to the serial
    stage (the walker is the same; only the region decomposition
    differs)."""
    H = stage.halo
    local_h = tile.shape[0]
    with jax.named_scope(f"plan_exchange_s{si}"):
        top, bottom = exchange_halo_strips(tile, H, n)
    with jax.named_scope(f"plan_overlap_interior_s{si}"):
        interior = _plan_walk(stage, tile, y0, global_h, global_w, impl)
    with jax.named_scope(f"plan_overlap_boundary_s{si}"):
        top_out = _plan_walk(
            stage,
            jnp.concatenate([top, tile[: 2 * H]], axis=0),
            y0 - H, global_h, global_w, impl,
        )
        bottom_out = _plan_walk(
            stage,
            jnp.concatenate([tile[local_h - 2 * H :], bottom], axis=0),
            y0 + local_h - 2 * H, global_h, global_w, impl,
        )
    return jnp.concatenate([top_out, interior, bottom_out], axis=0)


def _apply_stage_megakernel(
    stage, tile, y0, global_h, global_w, n, si, mxu_stage=None
):
    """Fused-pallas execution of one stage on a shard: the stage's ONE
    ppermute ghost-strip pair (identical wire structure to
    _apply_stage_serial — the HLO test counts the same
    `plan_exchange_s<i>` scopes), then the ghost-mode megakernel streams
    the pre-exchanged tile with every member-op intermediate resident in
    VMEM (ops/pallas_kernels.fused_stage_call). Strips ride RAW: ring-
    wrapped rows on the edge shards are rewritten per op inside the
    kernel (keyed on the traced y0), the same reachability contract the
    full-image mode documents."""
    from mpi_cuda_imagemanipulation_tpu.plan.pallas_exec import (
        run_stage_pallas_ext,
    )

    H = stage.halo
    with jax.named_scope(f"plan_exchange_s{si}"):
        top, bottom = exchange_halo_strips(tile, H, n)
    ext = jnp.concatenate([top, tile, bottom], axis=0)
    with jax.named_scope(f"plan_stage_pallas_s{si}"):
        return run_stage_pallas_ext(
            stage, ext, y0=y0, image_h=global_h, image_w=global_w,
            mxu_stage=mxu_stage,
        )


def _run_segment_planned(
    plan, mesh, impl: str, img: jnp.ndarray, halo_mode: str,
    mega: bool = False, mxu_stage: str | None = None,
):
    """One shard_map region executed stage-by-stage from a fused plan.
    Stages the decomposition gate rejects (pad rows in the tile,
    sub-halo tiles) fall back to the per-op materialised-ext path inside
    the same region, so the output contract is unchanged. `mega` (plan
    mode 'fused-pallas') additionally routes eligible fused stages
    through the ghost-mode megakernel — one pallas_call consuming the
    stage's single pre-exchanged halo — with the XLA stage walker as the
    per-stage fallback."""
    n = mesh.shape[ROWS]
    ops = plan.ops
    # feasibility bounds come from the PER-OP fallback (legacy rule): a
    # stage whose grown halo outsizes the tile falls back to per-op
    # execution instead of failing the build
    max_halo = max((op.halo for op in ops), default=0)
    global_h, global_w = img.shape[0], img.shape[1]
    padded_h = -(-global_h // n) * n
    pad = padded_h - global_h
    local_h = padded_h // n
    min_local = max(2 * pad + 1, pad + max_halo, max_halo)
    if local_h < min_local:
        raise ValueError(
            f"image height {global_h} over {n} shards gives {local_h} "
            f"rows/shard, below the minimum {min_local} for halo "
            f"{max_halo} and padding {pad}; use fewer shards"
        )
    img_p = (
        jnp.pad(img, ((0, pad),) + ((0, 0),) * (img.ndim - 1)) if pad else img
    )
    overlap = halo_mode == "overlap"
    # static per-stage megakernel eligibility (identical on every shard):
    # the decomposition gate at overlap strength (local_h > 2H — the
    # in-kernel edge synthesis bound) plus the Pallas eligibility matrix
    mega_stages: set[int] = set()
    if mega and not overlap:
        from mpi_cuda_imagemanipulation_tpu.plan.metrics import plan_metrics
        from mpi_cuda_imagemanipulation_tpu.plan.pallas_exec import (
            stage_pallas_reject,
        )

        ch = img.shape[2] if img.ndim == 3 else 1
        for si, stage in enumerate(plan.stages):
            if stage.kind != "fused" or stage.halo < 1:
                continue
            if not _plan_stage_fused_ok(
                stage, n, local_h, global_h, overlap=True
            ):
                plan_metrics.pallas_fallbacks.inc(reason="image-too-small")
                continue
            reason = stage_pallas_reject(stage, local_h, global_w, ch)
            if reason is None:
                plan_metrics.pallas_stages.inc()
                mega_stages.add(si)
            else:
                plan_metrics.pallas_fallbacks.inc(reason=reason)

    def tile_fn(tile):
        y0 = lax.axis_index(ROWS) * local_h
        for si, stage in enumerate(plan.stages):
            if stage.kind == "global":
                op = stage.ops[0]
                rows = y0 + lax.broadcasted_iota(
                    jnp.int32, (tile.shape[0], 1), 0
                )
                valid = (rows < global_h).reshape(
                    (tile.shape[0],) + (1,) * (tile.ndim - 1)
                )
                stats = lax.psum(op.stats(tile, valid), ROWS)
                tile = op.apply(tile, stats)
            elif si in mega_stages:
                tile = _apply_stage_megakernel(
                    stage, tile, y0, global_h, global_w, n, si,
                    mxu_stage=mxu_stage,
                )
            elif _plan_stage_fused_ok(stage, n, local_h, global_h, overlap):
                if overlap and stage.halo >= 1:
                    tile = _apply_stage_overlap(
                        stage, tile, y0, global_h, global_w, n, impl, si
                    )
                else:
                    tile = _apply_stage_serial(
                        stage, tile, y0, global_h, global_w, n, impl, si
                    )
            else:
                # fallback: per-op execution for this stage only (the
                # golden contract the fused path is gated against)
                for op in stage.ops:
                    if isinstance(op, PointwiseOp):
                        tile = op.fn(tile)
                    else:
                        tile = _apply_stencil(
                            op, tile, y0, global_h, global_w, n,
                            backend="xla" if impl == "auto" else impl,
                        )
        return tile

    def seq(x):
        for op in ops:
            x = op(x)
        return x

    out_shape = jax.eval_shape(seq, img_p)
    in_spec = P(ROWS, *([None] * (img.ndim - 1)))
    out_spec = P(ROWS, *([None] * (len(out_shape.shape) - 1)))
    out = shard_map_compat(
        tile_fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
        # the walker paths are pure XLA (+ MXU einsums); megakernel
        # stages are pallas_calls, whose outputs carry no vma annotations
        check_vma=not mega_stages,
    )(img_p)
    return out[:global_h]


def _split_segments(ops):
    """Partition an op sequence into shard_map segments separated by
    geometric (shape-changing) steps.

    Pointwise, stencil and global ops run *inside* shard_map on local tiles
    (stencils with ppermute halos, global ops with a psum of their masked
    statistics). Geometric ops are pure data movement with data-dependent
    output shapes; they run between segments at the jit level under a
    row-sharding constraint — the scaling-book recipe: annotate the
    sharding, let XLA insert the collective permutes/gathers it needs.
    """
    segments: list[tuple[str, tuple]] = []
    cur: list = []
    for op in ops:
        if isinstance(op, GeometricOp):
            if cur:
                segments.append(("shard_map", tuple(cur)))
                cur = []
            segments.append(("xla", (op,)))
        else:
            cur.append(op)
    if cur:
        segments.append(("shard_map", tuple(cur)))
    return segments


def _run_segment(
    ops,
    mesh,
    backend: str,
    any_pallas: bool,
    img: jnp.ndarray,
    try_swar: bool = False,
    halo_mode: str = "serial",
):
    """One shard_map region: pad-to-multiple, halo-exchanged local compute,
    crop. Fixes the reference's silent `rows / size` truncation
    (kernel.cu:117) by padding and cropping instead of dropping rows."""
    n = mesh.shape[ROWS]
    max_halo = max((op.halo for op in ops), default=0)
    global_h, global_w = img.shape[0], img.shape[1]
    padded_h = -(-global_h // n) * n
    pad = padded_h - global_h
    local_h = padded_h // n
    # Static feasibility of local edge fixups: every reflect/pad source row
    # must live on-shard.
    min_local = max(2 * pad + 1, pad + max_halo, max_halo)
    if local_h < min_local:
        raise ValueError(
            f"image height {global_h} over {n} shards gives {local_h} "
            f"rows/shard, below the minimum {min_local} for halo "
            f"{max_halo} and padding {pad}; use fewer shards"
        )
    if pad:
        img_p = jnp.pad(img, ((0, pad),) + ((0, 0),) * (img.ndim - 1))
    else:
        img_p = img

    def tile_fn(tile):
        y0 = lax.axis_index(ROWS) * local_h
        # kernel-safe pointwise ops buffer until the next op decides their
        # fate: fused into a ghost-mode Pallas stencil group (one HBM pass
        # for the whole [pointwise*, stencil] chain) or flushed as XLA
        # steps (which XLA fuses into one elementwise pass anyway)
        pending: list[PointwiseOp] = []

        def flush(t):
            for p in pending:
                t = p.fn(t)
            pending.clear()
            return t

        i = 0
        gi = 0  # stencil-group index (overlap scoping + prefetch pairing)
        # ghost strips already in flight for the next overlap group:
        # (top, bottom, halo) issued from the previous group's boundary
        # outputs (cross-group prefetch — the ICI rings stay busy while
        # this group's interior computes)
        prefetch = None
        while i < len(ops):
            op = ops[i]
            i += 1
            if isinstance(op, PointwiseOp):
                if op.kernel_safe:
                    pending.append(op)
                else:
                    tile = flush(tile)
                    tile = op.fn(tile)
            elif isinstance(op, GlobalOp):
                tile = flush(tile)
                # additive statistic over valid (non-padding) rows, combined
                # across shards with one psum — the MPI_Allreduce analogue
                rows = y0 + lax.broadcasted_iota(jnp.int32, (tile.shape[0], 1), 0)
                valid = (rows < global_h).reshape(
                    (tile.shape[0],) + (1,) * (tile.ndim - 1)
                )
                stats = lax.psum(op.stats(tile, valid), ROWS)
                tile = op.apply(tile, stats)
            else:
                # Interior-first overlapped halo path: eligible stencil
                # groups compute their interior while the ghost strips are
                # in flight; boundary strips stitch once they land. Takes
                # priority over the swar/fused serial paths — the knob is
                # an explicit execution-structure request.
                if halo_mode == "overlap" and _overlap_ok(
                    op, n, local_h, global_h
                ):
                    tile = flush(tile)
                    if prefetch is not None and prefetch[2] == op.halo:
                        strips = (prefetch[0], prefetch[1])
                    else:
                        with jax.named_scope(f"halo_exchange_g{gi}"):
                            strips = exchange_halo_strips(tile, op.halo, n)
                    prefetch = None
                    pieces = _apply_stencil_overlap(
                        op, tile, strips, y0, global_h, global_w, backend, gi
                    )
                    nxt, chain = _next_stencil_group(ops, i)
                    if nxt is not None and _overlap_ok(
                        nxt, n, local_h, global_h
                    ):
                        # issue the NEXT group's exchange now, from this
                        # group's boundary pieces (pointwise chains commute
                        # with row slicing, so applying them to the edge
                        # rows alone matches slicing the post-chain tile)
                        first, last = _piece_edge_rows(pieces, nxt.halo)
                        for p in chain:
                            first, last = p.fn(first), p.fn(last)
                        with jax.named_scope(f"halo_exchange_g{gi + 1}"):
                            pre = exchange_edge_strips(first, last, n)
                        prefetch = (pre[0], pre[1], nxt.halo)
                    tile = jnp.concatenate(pieces, axis=0)
                    gi += 1
                    continue
                gi += 1
                # Quarter-strip SWAR ghost path (backend='swar', or 'auto'
                # under the MCIM_PREFER_SWAR promotion switch, snapshotted
                # at build time): a single-chip SWAR win carries to
                # multi-chip unchanged (VERDICT r4 #3). Ineligible groups
                # fall through to the u8 paths below, the same per-op
                # fallback contract as the unsharded pipeline_swar.
                if try_swar:
                    if _swar_group_ok(
                        pending, op, tile, n, local_h, global_h
                    ):
                        group = list(pending)
                        pending.clear()
                        # a trailing fusable run becomes this group's
                        # post-chain unless another eligible stencil
                        # follows it (then it serves as that group's
                        # pre-chain) — same policy as pipeline_swar
                        from mpi_cuda_imagemanipulation_tpu.ops.swar_kernels import (
                            swar_any_eligible,
                            swar_fusable,
                        )

                        j = i
                        run = []
                        while j < len(ops) and (
                            isinstance(ops[j], PointwiseOp)
                            and swar_fusable(ops[j]) is not None
                        ):
                            run.append(ops[j])
                            j += 1
                        post: list = []
                        if not (
                            j < len(ops) and swar_any_eligible(ops[j])
                        ):
                            post = run
                            i = j
                        tile = _apply_group_swar(
                            group, op, tile, y0, global_h, n, post=post
                        )
                        continue
                # Fused-ghost fast path: no pad rows inside the tile
                # (pad-to-multiple needs position-dependent edge fixes),
                # halo >= 1, a mode the streaming kernel supports, and
                # enough local rows for strip synthesis. Auto mode judges
                # the whole group (the buffered prologue's channel count
                # matters: a 3-channel prologue forces planar form, where
                # XLA measured faster for cheap halo-1 stencils).
                if backend == "auto":
                    from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
                        use_mxu_for_stencil,
                    )
                    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
                        use_pallas_for_stencil,
                    )

                    group_in = tile.shape[2] if tile.ndim == 3 else 1
                    use_pallas = use_pallas_for_stencil(op, group_in)
                    if use_mxu_for_stencil(op, global_w) is not None:
                        # calibration-won MXU group: skip the fused-ghost
                        # Pallas path so the materialised-ext runner below
                        # resolves auto -> mxu (the flushed pointwise
                        # prologue stays XLA and fuses into the same
                        # program as the banded contraction)
                        use_pallas = False
                else:
                    use_pallas = backend in ("pallas", "swar")
                fusible = (
                    use_pallas
                    and op.halo >= 1
                    and op.edge_mode != "zero"  # run_group rejects zero mode
                    and n * local_h == global_h
                    and local_h > op.halo
                )
                if fusible:
                    group = list(pending)
                    pending.clear()
                    tile = _apply_group_fused(
                        group, op, tile, y0, global_h, global_w, n
                    )
                else:
                    tile = flush(tile)
                    tile = _apply_stencil(
                        op, tile, y0, global_h, global_w, n, backend=backend
                    )
        return flush(tile)

    def seq(x):
        for op in ops:
            x = op(x)
        return x

    out_shape = jax.eval_shape(seq, img_p)
    in_spec = P(ROWS, *([None] * (img.ndim - 1)))
    out_spec = P(ROWS, *([None] * (len(out_shape.shape) - 1)))
    out = shard_map_compat(
        tile_fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
        check_vma=not any_pallas,
    )(img_p)
    return out[:global_h]


def sharded_pipeline(
    pipe, mesh, backend: str = "xla", halo_mode: str = "serial",
    plan: str = "auto",
):
    """Compile `pipe` to run row-sharded over `mesh` with halo exchange.

    Returns a jitted (H, W[, 3]) uint8 -> uint8 function, bit-identical to
    the unsharded golden path (tests/test_sharded.py).

    `halo_mode='overlap'` restructures each eligible stencil group so the
    interior rows compute while the ppermute ghost strips are in flight
    (see HALO_MODES); groups the overlap gate rejects (halo 0, pad rows,
    sub-2*halo tiles) fall back to the serial paths, so the output
    contract is unchanged.

    `plan` engages the fusion planner (plan/): a fused plan exchanges ONE
    stage-halo ghost-strip pair per fused stage — temporal blocking over
    the wire — instead of one per stencil op. 'auto' resolves to fused
    for the pure-XLA/MXU backends under halo_mode='serial' (the measured
    overlap prefetch structure is preserved unless a plan is explicitly
    requested); resolution and bit-exactness contracts are
    plan/planner.resolve_plan_mode's.
    """
    if backend not in ("xla", "pallas", "swar", "mxu", "auto"):
        raise ValueError(f"unknown backend {backend!r}")
    if halo_mode not in HALO_MODES:
        raise ValueError(
            f"unknown halo_mode {halo_mode!r}; known: {HALO_MODES}"
        )
    from mpi_cuda_imagemanipulation_tpu.plan import (
        build_plan,
        resolve_plan_mode,
    )

    plan_mode = resolve_plan_mode(pipe.ops, plan, backend=backend)
    if plan_mode != "off" and halo_mode == "overlap" and plan in (
        "auto", None, "",
    ):
        # overlap's per-group interior-first prefetch is a measured
        # structure (PR 1); only an EXPLICIT plan request restructures it
        plan_mode = "off"
    if plan_mode != "off":
        segments = _split_segments(pipe.ops)
        seg_plans = [
            build_plan(ops, plan_mode) if kind == "shard_map" else None
            for kind, ops in segments
        ]
        impl = backend  # 'xla' | 'mxu' | 'auto' (resolver guarantees)
        mega = plan_mode in ("fused-pallas", "fused-pallas-mxu")
        mxu_stage = "on" if plan_mode == "fused-pallas-mxu" else None

        def run_planned(img: jnp.ndarray) -> jnp.ndarray:
            from jax.sharding import NamedSharding

            for (kind, seg_ops), seg_plan in zip(segments, seg_plans):
                if kind == "xla":
                    img = seg_ops[0].fn(img)
                    img = lax.with_sharding_constraint(
                        img,
                        NamedSharding(
                            mesh, P(ROWS, *([None] * (img.ndim - 1)))
                        ),
                    )
                else:
                    img = _run_segment_planned(
                        seg_plan, mesh, impl, img, halo_mode, mega=mega,
                        mxu_stage=mxu_stage,
                    )
            return img

        return jax.jit(run_planned)
    # The MCIM_PREFER_SWAR promotion switch is snapshotted ONCE here:
    # routing and the vma-checker decision below must agree, and a
    # mid-session env change between build and a retrace must not split
    # them (review finding).
    try_swar = backend == "swar" or (backend == "auto" and _prefer_swar())
    # Static per-op auto decisions, so the vma checker stays on whenever no
    # Pallas tile can run (pallas_call outputs carry no vma annotations).
    if backend == "auto":
        from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
            use_pallas_for_stencil,
        )

        # under try_swar, eligible groups take the quarter-strip SWAR
        # ghost path inside _run_segment (a single-chip SWAR win carries
        # to multi-chip); the swar kernels are pallas_calls too
        any_pallas = try_swar or any(
            isinstance(op, StencilOp) and use_pallas_for_stencil(op, 1)
            for op in pipe.ops
        )
    elif backend == "mxu":
        # the MXU path itself is pure XLA (vma checker can stay on), but
        # ineligible stencils fall back to the u8 Pallas tile kernel
        from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
            mxu_eligible,
        )

        any_pallas = any(
            isinstance(op, StencilOp) and not mxu_eligible(op)
            for op in pipe.ops
        )
    else:
        any_pallas = backend in ("pallas", "swar")
    segments = _split_segments(pipe.ops)

    def run(img: jnp.ndarray) -> jnp.ndarray:
        from jax.sharding import NamedSharding

        for kind, ops in segments:
            if kind == "xla":
                img = ops[0].fn(img)
                img = lax.with_sharding_constraint(
                    img,
                    NamedSharding(mesh, P(ROWS, *([None] * (img.ndim - 1)))),
                )
            else:
                img = _run_segment(
                    ops, mesh, backend, any_pallas, img,
                    try_swar=try_swar, halo_mode=halo_mode,
                )
        return img

    return jax.jit(run)
