"""Device mesh construction + multi-host process-group setup.

Replaces the reference's MPI world management (MPI_Init/rank/size,
kern.cpp:25-28; kernel.cu:104-107): process identity becomes
`jax.process_index()`, and the communicator becomes a named 1-D
`jax.sharding.Mesh` over the 'rows' axis — the image-height domain
decomposition the reference implements with MPI_Scatter row blocks
(SURVEY.md §2.3). Collectives ride ICI within a slice and DCN across hosts,
inserted by XLA from sharding annotations rather than hand-written.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

ROWS = "rows"
COLS = "cols"


_distributed_initialized = False


def distributed_init() -> None:
    """Initialise the multi-host process group when launched as one process
    per host (the `mpirun` analogue). No-op for single-process runs.

    Must be called before any other JAX API (jax.distributed.initialize
    refuses to run once the XLA backend exists), so the guard is a module
    flag plus the coordinator env var — never a jax.* query.

    Config comes from JAX_COORDINATOR_ADDRESS (+ JAX_NUM_PROCESSES /
    JAX_PROCESS_ID) when set — jax's own cluster auto-detection only knows
    managed launchers (OMPI/SLURM/TPU pods), so plain `mpirun`-style manual
    launches need the explicit triple. With only auto-detectable launchers
    (OMPI's env present) the bare initialize() path still works.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if not addr:
        return
    num = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    if num is not None and pid is not None:
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=int(num),
            process_id=int(pid),
        )
    elif num is not None or pid is not None:
        raise RuntimeError(
            "set both JAX_NUM_PROCESSES and JAX_PROCESS_ID (or neither, "
            "under a managed launcher like OMPI/SLURM) — only one is set"
        )
    else:  # managed launcher: let cluster auto-detection fill the rest
        jax.distributed.initialize(coordinator_address=addr)
    _distributed_initialized = True


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across jax versions.

    Newer jax exposes the stable `jax.shard_map` (replication checking via
    `check_vma`); older versions only have the experimental entry point,
    where the same checker is named `check_rep`. Both runners route through
    here so the sharded path works on either.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(n_shards: int | None = None, *, devices=None) -> Mesh:
    """A 1-D mesh over `n_shards` devices on the ('rows',) axis.

    `n_shards=None` uses every visible device — the analogue of
    `mpirun -np <world>` with MPI_Comm_size (kernel.cu:107).
    """
    if devices is None:
        devices = jax.devices()
    if n_shards is None:
        n_shards = len(devices)
    if n_shards > len(devices):
        raise ValueError(
            f"requested {n_shards} shards but only {len(devices)} devices are visible"
        )
    return Mesh(np.asarray(devices[:n_shards]), (ROWS,))


def make_mesh_2d(n_rows: int, n_cols: int, *, devices=None) -> Mesh:
    """A 2-D mesh over n_rows x n_cols devices on ('rows', 'cols') axes —
    the full tile decomposition (parallel/api2d.py). On real hardware, lay
    the axes out so both ride ICI (a (4, 2) slice maps directly)."""
    if devices is None:
        devices = jax.devices()
    need = n_rows * n_cols
    if need > len(devices):
        raise ValueError(
            f"requested a {n_rows}x{n_cols} mesh but only {len(devices)} "
            "devices are visible"
        )
    return Mesh(
        np.asarray(devices[:need]).reshape(n_rows, n_cols), (ROWS, COLS)
    )


def parse_shards(spec) -> tuple[int, int | None]:
    """Parse a CLI shard spec: '4' -> (4, None) (1-D row mesh), '2x4' ->
    (2, 4) (2-D rows x cols mesh). Ints pass through as 1-D."""
    if isinstance(spec, int):
        return spec, None
    s = str(spec).lower().strip()
    if "x" in s:
        r, _, c = s.partition("x")
        try:
            n_r, n_c = int(r), int(c)
        except ValueError:
            raise ValueError(
                f"invalid --shards spec {spec!r}: expected N (1-D row mesh) "
                "or RxC (2-D rows x cols mesh), e.g. '8' or '2x4'"
            ) from None
        if n_r < 1 or n_c < 1:
            raise ValueError(f"shard counts must be >= 1, got {spec!r}")
        return n_r, n_c
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"invalid --shards spec {spec!r}: expected N (1-D row mesh) "
            "or RxC (2-D rows x cols mesh), e.g. '8' or '2x4'"
        ) from None
    if n < 1:
        raise ValueError(f"shard count must be >= 1, got {spec!r}")
    return n, None


def mesh_from_shards(spec) -> Mesh | None:
    """Mesh for a CLI shard spec, or None when it means 'unsharded' ('1').
    'RxC' builds a 2-D mesh even for '1x8'/'8x1' (explicit 2-D request);
    a bare count builds the 1-D row mesh."""
    n_r, n_c = parse_shards(spec)
    if n_c is not None:
        return make_mesh_2d(n_r, n_c)
    return make_mesh(n_r) if n_r > 1 else None


def row_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """NamedSharding splitting axis 0 (image rows) over the mesh — the
    declarative replacement for MPI_Scatter of contiguous row blocks
    (kern.cpp:55, kernel.cu:137)."""
    return NamedSharding(mesh, PartitionSpec(ROWS, *([None] * (ndim - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
