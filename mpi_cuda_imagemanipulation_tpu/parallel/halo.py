"""Ghost-row halo exchange over the ('rows',) mesh axis via lax.ppermute.

This is the component the reference *lacks* (SURVEY.md §2.3 last row): its
MPI row-scatter runs stencils on each slice independently, producing visible
seams every H/N rows (kernel.cu:83 guard skips slice-edge rows). Here every
stencil tile is extended with real neighbour rows moved over ICI by two ring
shifts — the same ring communication pattern ring-attention uses, applied to
image rows — before the stencil runs, so the sharded result equals the
unsharded result bit-exactly (the invariant tests/test_sharded.py asserts).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from mpi_cuda_imagemanipulation_tpu.parallel.mesh import ROWS


def exchange_halo(tile: jnp.ndarray, halo: int, n_shards: int) -> jnp.ndarray:
    """Return `tile` extended with `halo` ghost rows on top and bottom.

    Two ring ppermutes over the 'rows' axis: the "down" ring carries each
    shard's last rows to its south neighbour (becoming that neighbour's top
    halo); the "up" ring carries first rows north. Rings are full
    permutations (XLA requires a bijection), so shard 0's top halo and shard
    n-1's bottom halo arrive wrapped from the opposite end of the image —
    callers mask or overwrite them with the op's edge extension
    (ops never read unfixed wrapped rows; see parallel.api._apply_stencil).
    """
    if halo == 0:
        return tile
    if n_shards == 1:
        zeros = jnp.zeros((halo, *tile.shape[1:]), tile.dtype)
        return jnp.concatenate([zeros, tile, zeros], axis=0)
    down = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    up = [(i, (i - 1) % n_shards) for i in range(n_shards)]
    top = lax.ppermute(tile[-halo:], ROWS, down)
    bottom = lax.ppermute(tile[:halo], ROWS, up)
    return jnp.concatenate([top, tile, bottom], axis=0)
