"""Ghost-row halo exchange over the ('rows',) mesh axis via lax.ppermute.

This is the component the reference *lacks* (SURVEY.md §2.3 last row): its
MPI row-scatter runs stencils on each slice independently, producing visible
seams every H/N rows (kernel.cu:83 guard skips slice-edge rows). Here every
stencil tile is extended with real neighbour rows moved over ICI by two ring
shifts — the same ring communication pattern ring-attention uses, applied to
image rows — before the stencil runs, so the sharded result equals the
unsharded result bit-exactly (the invariant tests/test_sharded.py asserts).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from mpi_cuda_imagemanipulation_tpu.parallel.mesh import ROWS


def exchange_edge_strips(
    first: jnp.ndarray,
    last: jnp.ndarray,
    n_shards: int,
    *,
    axis_name: str = ROWS,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Ring-exchange pre-sliced edge strips: `first`/`last` are each
    shard's leading/trailing `halo` slices along the exchanged axis,
    already cut out by the caller.

    This is the primitive under exchange_halo_strips, exposed so the
    overlapped-halo pipeline can ppermute a *derived* strip — e.g. the
    next stencil group's edge rows assembled from the previous group's
    boundary outputs (cross-group prefetch) — without the exchange being
    data-dependent on a full materialised tile. Ring wrap semantics are
    identical to exchange_halo_strips: callers overwrite wrapped strips
    with the op's edge extension before use.
    """
    if n_shards == 1:
        return jnp.zeros_like(last), jnp.zeros_like(first)
    down = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    up = [(i, (i - 1) % n_shards) for i in range(n_shards)]
    before = lax.ppermute(last, axis_name, down)
    after = lax.ppermute(first, axis_name, up)
    return before, after


def exchange_halo_strips(
    tile: jnp.ndarray,
    halo: int,
    n_shards: int,
    *,
    axis_name: str = ROWS,
    axis: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Return the (before, after) ghost strips for `tile` along `axis`,
    each `halo` slices thick.

    Two ring ppermutes over the mesh axis `axis_name`: the "down" ring
    carries each shard's last slices to its successor (becoming that
    neighbour's leading halo); the "up" ring carries first slices back.
    Rings are full permutations (XLA requires a bijection), so shard 0's
    leading strip and shard n-1's trailing strip arrive wrapped from the
    opposite end of the image — callers mask or overwrite them with the
    op's edge extension (ops never read unfixed wrapped slices; see
    parallel.api._apply_stencil / parallel.api2d._fix side). With
    n_shards == 1 the strips are zeros, overwritten the same way.

    Defaults cover the 1-D 'rows' decomposition; the 2-D tile runner
    (parallel/api2d) calls it per axis.
    """
    idx = [slice(None)] * tile.ndim
    idx[axis] = slice(None, halo)
    first = tile[tuple(idx)]
    idx[axis] = slice(tile.shape[axis] - halo, None)
    last = tile[tuple(idx)]
    return exchange_edge_strips(first, last, n_shards, axis_name=axis_name)


def host_edge_strips(
    tile: np.ndarray, halo: int, *, axis: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """(leading, trailing) ``halo``-thick strips of a HOST-resident tile.

    The same slicing convention as the device-side exchanges above,
    generalized from device boundaries to tile boundaries: the streaming
    tile engine (stream/) keeps these strips from tile k to extend tile
    k+1 instead of re-reading neighbour rows from the decoder — the
    Casper seam-reuse move, with a host copy standing in for the
    ppermute. Copies (not views) so the donor tile's buffer can be
    released while the strip is still pending."""
    lead = np.take(tile, range(halo), axis=axis)
    n = tile.shape[axis]
    tail = np.take(tile, range(n - halo, n), axis=axis)
    return np.ascontiguousarray(lead), np.ascontiguousarray(tail)


def stitch_tile(
    before: np.ndarray | None,
    tile: np.ndarray,
    after: np.ndarray | None,
    *,
    axis: int = 0,
) -> np.ndarray:
    """Concatenate a host tile with its neighbour seam strips — the
    host-memory analogue of ``exchange_halo``'s concatenated device tile.
    ``None`` strips mean a global image edge: nothing is stitched there
    and the op-level edge extension (pad2d, asymmetric) takes over,
    exactly as the sharded runner fixes ring-wrapped strips."""
    parts = [p for p in (before, tile, after) if p is not None]
    if len(parts) == 1:
        return tile
    return np.concatenate(parts, axis=axis)


def exchange_halo(
    tile: jnp.ndarray,
    halo: int,
    n_shards: int,
    *,
    axis_name: str = ROWS,
    axis: int = 0,
) -> jnp.ndarray:
    """Return `tile` extended with `halo` ghost slices on both sides of
    `axis` (see exchange_halo_strips; this materialises the concatenated
    tile for the XLA stencil paths)."""
    if halo == 0:
        return tile
    before, after = exchange_halo_strips(
        tile, halo, n_shards, axis_name=axis_name, axis=axis
    )
    return jnp.concatenate([before, tile, after], axis=axis)
