"""Ghost-row halo exchange over the ('rows',) mesh axis via lax.ppermute.

This is the component the reference *lacks* (SURVEY.md §2.3 last row): its
MPI row-scatter runs stencils on each slice independently, producing visible
seams every H/N rows (kernel.cu:83 guard skips slice-edge rows). Here every
stencil tile is extended with real neighbour rows moved over ICI by two ring
shifts — the same ring communication pattern ring-attention uses, applied to
image rows — before the stencil runs, so the sharded result equals the
unsharded result bit-exactly (the invariant tests/test_sharded.py asserts).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from mpi_cuda_imagemanipulation_tpu.parallel.mesh import ROWS


def exchange_halo_strips(
    tile: jnp.ndarray, halo: int, n_shards: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Return the (top, bottom) ghost-row strips for `tile`, each (halo, ...).

    Two ring ppermutes over the 'rows' axis: the "down" ring carries each
    shard's last rows to its south neighbour (becoming that neighbour's top
    halo); the "up" ring carries first rows north. Rings are full
    permutations (XLA requires a bijection), so shard 0's top strip and shard
    n-1's bottom strip arrive wrapped from the opposite end of the image —
    callers mask or overwrite them with the op's edge extension
    (ops never read unfixed wrapped rows; see parallel.api._apply_stencil).
    """
    if n_shards == 1:
        zeros = jnp.zeros((halo, *tile.shape[1:]), tile.dtype)
        return zeros, zeros
    down = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    up = [(i, (i - 1) % n_shards) for i in range(n_shards)]
    top = lax.ppermute(tile[-halo:], ROWS, down)
    bottom = lax.ppermute(tile[:halo], ROWS, up)
    return top, bottom


def exchange_halo(tile: jnp.ndarray, halo: int, n_shards: int) -> jnp.ndarray:
    """Return `tile` extended with `halo` ghost rows on top and bottom
    (see exchange_halo_strips; this materialises the concatenated tile for
    the XLA stencil path)."""
    if halo == 0:
        return tile
    top, bottom = exchange_halo_strips(tile, halo, n_shards)
    return jnp.concatenate([top, tile, bottom], axis=0)
