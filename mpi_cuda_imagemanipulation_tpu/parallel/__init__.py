from mpi_cuda_imagemanipulation_tpu.parallel.mesh import (
    ROWS,
    distributed_init,
    make_mesh,
    replicated_sharding,
    row_sharding,
)

__all__ = [
    "ROWS",
    "distributed_init",
    "make_mesh",
    "replicated_sharding",
    "row_sharding",
]
