"""Versioned JSON pipeline-spec schema + the closed error taxonomy.

A pipeline spec is the wire form of a `PipelineGraph` (graph/ir.py):

    {
      "version": 1,
      "name": "unsharp",                       # optional, display only
      "nodes": [
        {"id": "src",  "kind": "source"},
        {"id": "blur", "kind": "op", "op": "gaussian:5", "input": "src"},
        {"id": "mask", "kind": "merge", "merge": "subtract",
         "inputs": ["src", "blur"]}
      ],
      "outputs": {"image": "mask", "histogram": "mask", "stats": "mask"}
    }

  * exactly one `source` node (the request image);
  * `op` nodes name an `ops/registry` spec string (``name[:arg]``) and one
    input — fan-out taps are implicit (any node with >1 consumer);
  * `merge` nodes join exactly two branches with a combinator from
    `graph/ir.MERGE_COMBINATORS` (``alpha_composite`` takes an ``alpha``
    in [0, 1], quantized to k/256 so the arithmetic stays exact — see
    ir.py);
  * `outputs` maps output names (``image`` required; ``histogram`` /
    ``stats`` optional side outputs computed in the SAME dispatch) to
    node ids.

**The closed error taxonomy.** Every way a spec (or a graph request) can
be refused has a code in `TAXONOMY`, and every rejection path raises
`SpecError(code, message)` with a literal code — machine-checked by the
``graph-taxonomy-unknown`` rule (analysis/rules_obs.py), exactly like the
failpoint-site registry. The HTTP layer maps SpecError onto 4xx-class
structured JSON ({code, error, trace_id}); a hostile or malformed spec
can therefore never surface as a 500 (the fuzz tests in
tests/test_graph.py hammer this).
"""

from __future__ import annotations

import json
import re

from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

SPEC_VERSION = 1

ENV_MAX_NODES = "MCIM_GRAPH_MAX_NODES"

# code -> one-line meaning. CLOSED vocabulary: a rejection path may only
# name a code registered here (analysis/rules_obs.py graph-taxonomy-*
# rules), so clients can switch on codes without chasing free-form text.
TAXONOMY = {
    # -- spec shape ---------------------------------------------------------
    "bad-json": "the body is not valid JSON",
    "bad-root": "the spec root is not a JSON object",
    "bad-version": "missing/unsupported `version` (this server speaks 1)",
    "unknown-field": "an object carries a field the schema does not define",
    "bad-name": "`name` is not a short string",
    "bad-nodes": "`nodes` is not a non-empty list of objects",
    "too-large": "node count exceeds MCIM_GRAPH_MAX_NODES",
    # -- nodes --------------------------------------------------------------
    "bad-node-id": "a node id is not a short [A-Za-z0-9_-] string",
    "duplicate-node": "two nodes share one id",
    "unknown-kind": "node `kind` is not source/op/merge",
    "no-source": "the graph has no source node",
    "multi-source": "the graph has more than one source node",
    "unknown-op": "`op` names nothing in ops/registry",
    "bad-op-arg": "the op factory rejected its argument",
    "unservable-op": "the op cannot run in a graph (shape-changing)",
    "unknown-merge": "`merge` is not a registered combinator",
    "bad-merge-arity": "`inputs` is not a list of exactly two node ids",
    "bad-merge-arg": "the merge parameter (e.g. alpha) is out of range",
    # -- wiring -------------------------------------------------------------
    "unknown-input": "a node/output references an id that does not exist",
    "graph-cycle": "the node references are not acyclic",
    "dangling-node": "a node feeds no output (dead subgraph)",
    "channel-mismatch": "channel counts cannot chain along an edge/merge",
    "no-output": "`outputs` does not map `image` to a node",
    "unknown-output": "`outputs` names an output kind the service lacks",
    # -- registration / request admission (graph/service.py) ---------------
    "unknown-tenant": "the tenant id has never registered here",
    "unknown-pipeline": "the pipeline id is not registered for this tenant",
    "bad-tenant-id": "the tenant id is not a short [A-Za-z0-9_-] string",
    "tenant-limit": "the tenant registry is at MCIM_GRAPH_MAX_TENANTS",
    "bad-qos": "the QoS class is not a registered admission class",
    "bad-quota": "a quota field is not a non-negative number",
    "bad-image": "the request image cannot feed this graph",
    "unknown-route": "no handler at this path",
}

OUTPUT_KINDS = ("image", "histogram", "stats")

_ID_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")

_NODE_FIELDS = {
    "source": {"id", "kind"},
    "op": {"id", "kind", "op", "input"},
    "merge": {"id", "kind", "merge", "inputs", "alpha"},
}


class SpecError(ValueError):
    """A spec/request rejection with a closed-taxonomy code. The HTTP
    layer maps it onto 4xx structured JSON — never a 500."""

    def __init__(self, code: str, message: str):
        if code not in TAXONOMY:  # pragma: no cover - taxonomy bug
            raise KeyError(
                f"SpecError code {code!r} is not in graph.spec.TAXONOMY"
            )
        super().__init__(message)
        self.code = code


def max_nodes() -> int:
    return int(env_registry.get(ENV_MAX_NODES))


def parse_spec(raw):
    """bytes/str/dict -> validated `PipelineGraph` (graph/ir.py). Every
    refusal is a SpecError with a TAXONOMY code; anything else escaping
    this function is a bug (the fuzz tests assert it cannot happen)."""
    from mpi_cuda_imagemanipulation_tpu.graph import ir

    if isinstance(raw, (bytes, bytearray, memoryview)):
        try:
            raw = bytes(raw).decode("utf-8")
        except UnicodeDecodeError as e:
            raise SpecError("bad-json", f"body is not UTF-8: {e}") from None
    if isinstance(raw, str):
        try:
            raw = json.loads(raw)
        except ValueError as e:
            raise SpecError("bad-json", f"body is not JSON: {e}") from None
    if not isinstance(raw, dict):
        raise SpecError(
            "bad-root", f"spec root must be an object, got {type(raw).__name__}"
        )
    unknown = set(raw) - {"version", "name", "nodes", "outputs"}
    if unknown:
        raise SpecError(
            "unknown-field", f"unknown spec fields {sorted(unknown)}"
        )
    if raw.get("version") != SPEC_VERSION:
        raise SpecError(
            "bad-version",
            f"spec version must be {SPEC_VERSION}, got {raw.get('version')!r}",
        )
    name = raw.get("name", "")
    if not isinstance(name, str) or len(name) > 128:
        raise SpecError("bad-name", "`name` must be a short string")

    nodes_raw = raw.get("nodes")
    if not isinstance(nodes_raw, list) or not nodes_raw:
        raise SpecError("bad-nodes", "`nodes` must be a non-empty list")
    cap = max_nodes()
    if len(nodes_raw) > cap:
        raise SpecError(
            "too-large", f"{len(nodes_raw)} nodes exceed the cap of {cap}"
        )

    nodes: dict[str, object] = {}
    for nd in nodes_raw:
        nodes.update(_parse_node(nd, nodes))
    source_ids = [
        nid for nid, n in nodes.items() if isinstance(n, ir.SourceNode)
    ]
    if not source_ids:
        raise SpecError("no-source", "the graph declares no source node")
    if len(source_ids) > 1:
        raise SpecError(
            "multi-source", f"multiple source nodes {sorted(source_ids)}"
        )

    outputs = _parse_outputs(raw.get("outputs"), nodes)
    return ir.build_graph(
        name=name, nodes=nodes, source_id=source_ids[0], outputs=outputs
    )


def _parse_node(nd, seen: dict) -> dict:
    from mpi_cuda_imagemanipulation_tpu.graph import ir
    from mpi_cuda_imagemanipulation_tpu.ops.registry import (
        REGISTRY,
        make_op,
        op_family,
    )

    if not isinstance(nd, dict):
        raise SpecError(
            "bad-nodes", f"node entries must be objects, got {type(nd).__name__}"
        )
    nid = nd.get("id")
    if not isinstance(nid, str) or not _ID_RE.match(nid):
        raise SpecError("bad-node-id", f"bad node id {nid!r}")
    if nid in seen:
        raise SpecError("duplicate-node", f"duplicate node id {nid!r}")
    kind = nd.get("kind")
    if not isinstance(kind, str) or kind not in _NODE_FIELDS:
        raise SpecError(
            "unknown-kind",
            f"node {nid!r}: kind must be source/op/merge, got {kind!r}",
        )
    unknown = set(nd) - _NODE_FIELDS[kind]
    if unknown:
        raise SpecError(
            "unknown-field", f"node {nid!r} has unknown fields {sorted(unknown)}"
        )
    if kind == "source":
        return {nid: ir.SourceNode(id=nid)}
    if kind == "op":
        spec_str = nd.get("op")
        if not isinstance(spec_str, str) or not spec_str:
            raise SpecError(
                "unknown-op", f"node {nid!r}: `op` must be a spec string"
            )
        op_name = spec_str.partition(":")[0].strip().lower()
        if op_name not in REGISTRY:
            raise SpecError(
                "unknown-op", f"node {nid!r}: unknown op {op_name!r}"
            )
        try:
            op = make_op(spec_str)
        except SpecError:
            raise
        except Exception as e:
            # the registry factory refused the argument (ValueError for
            # every documented misuse; anything else is still the same
            # client error class — a bad argument, not a server fault)
            raise SpecError(
                "bad-op-arg", f"node {nid!r}: {type(e).__name__}: {e}"
            ) from None
        if op_family(op) == "geometric":
            raise SpecError(
                "unservable-op",
                f"node {nid!r}: geometric op {op.name!r} changes the image "
                "shape; graphs serve shape-preserving ops only",
            )
        inp = nd.get("input")
        if not isinstance(inp, str) or not inp:
            raise SpecError(
                "unknown-input", f"node {nid!r}: `input` must be a node id"
            )
        return {nid: ir.OpNode(id=nid, op=op, input=inp)}
    # merge
    comb = nd.get("merge")
    if not isinstance(comb, str) or comb not in ir.MERGE_COMBINATORS:
        raise SpecError(
            "unknown-merge",
            f"node {nid!r}: unknown combinator {comb!r} "
            f"(known: {sorted(ir.MERGE_COMBINATORS)})",
        )
    inputs = nd.get("inputs")
    if (
        not isinstance(inputs, list)
        or len(inputs) != 2
        or not all(isinstance(i, str) for i in inputs)
    ):
        raise SpecError(
            "bad-merge-arity",
            f"node {nid!r}: `inputs` must list exactly two node ids",
        )
    alpha_k = 256  # only read by alpha_composite
    if comb == "alpha_composite":
        alpha = nd.get("alpha", 0.5)
        if not isinstance(alpha, (int, float)) or not 0.0 <= alpha <= 1.0:
            raise SpecError(
                "bad-merge-arg",
                f"node {nid!r}: alpha must be a number in [0, 1], "
                f"got {alpha!r}",
            )
        # quantize to k/256 so the merge arithmetic is an exact integer
        # MAC + one power-of-two scale (graph/ir.py) — deterministic on
        # every backend, immune to fma contraction
        alpha_k = int(round(float(alpha) * 256.0))
    elif "alpha" in nd:
        raise SpecError(
            "bad-merge-arg",
            f"node {nid!r}: `alpha` only applies to alpha_composite",
        )
    return {
        nid: ir.MergeNode(
            id=nid, combinator=comb, inputs=(inputs[0], inputs[1]),
            alpha_k=alpha_k,
        )
    }


def _parse_outputs(raw, nodes: dict) -> dict[str, str]:
    if raw is None:
        raise SpecError("no-output", "`outputs` must map `image` to a node")
    if not isinstance(raw, dict):
        raise SpecError("no-output", "`outputs` must be an object")
    out: dict[str, str] = {}
    for kind, nid in raw.items():
        if kind not in OUTPUT_KINDS:
            raise SpecError(
                "unknown-output",
                f"unknown output kind {kind!r} (known: {OUTPUT_KINDS})",
            )
        if not isinstance(nid, str) or nid not in nodes:
            raise SpecError(
                "unknown-input", f"output {kind!r} references unknown node "
                f"{nid!r}"
            )
        out[kind] = nid
    if "image" not in out:
        raise SpecError("no-output", "`outputs` must include `image`")
    return out


def chain_as_spec(ops_spec: str, *, name: str = "") -> dict:
    """Render a CLI chain string (``grayscale,contrast:3.5,...``) as its
    degenerate linear-DAG spec dict — the bridge the bit-exactness gates
    and the loadgen lane use to drive the SAME workload down both paths."""
    nodes = [{"id": "src", "kind": "source"}]
    prev = "src"
    for i, tok in enumerate(s for s in ops_spec.split(",") if s.strip()):
        nid = f"n{i}"
        nodes.append(
            {"id": nid, "kind": "op", "op": tok.strip(), "input": prev}
        )
        prev = nid
    return {
        "version": SPEC_VERSION,
        "name": name or ops_spec,
        "nodes": nodes,
        "outputs": {"image": prev},
    }
