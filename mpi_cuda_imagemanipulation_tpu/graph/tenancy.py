"""Multi-tenant admission: tenant registry, quotas, QoS classes, bounded
per-tenant compile-cache namespaces.

A *tenant* is the unit of isolation the pipeline service admits by:

  * **registered specs** — each tenant registers its own pipeline specs
    (idempotent; the id is the spec's `dag_fingerprint`, so two tenants
    registering the same spec get the same id but separate namespaces);
  * **compile-cache namespace** — compiled graph executables live in a
    per-tenant LRU bounded at `MCIM_GRAPH_CACHE_CAP` entries (the PR 8
    bucket-cardinality-cap discipline: a tenant registering pipelines
    without bound recycles ITS OWN cache slots — evictions are counted,
    nothing grows with tenant behavior);
  * **quotas** — fixed-window request/byte budgets
    (`quota_requests`/`quota_bytes` per `window_s`); an exhausted window
    SHEDS with Retry-After = the window remainder (an explicit
    "come back later", counted as shed, never an error);
  * **QoS admission class** — interactive / standard / batch. Under load
    the LOW class sheds first: a class admits only while the load
    fraction is below its admit threshold (batch: the
    `MCIM_GRAPH_QOS_SHED_FRAC` shed threshold; standard: halfway between
    that and 1; interactive: full capacity). The serving scheduler
    honors the same ladder for chain traffic
    (serve/scheduler.submit(qos=...)).

The registry itself is bounded (`MCIM_GRAPH_MAX_TENANTS`): tenant ids
are also metric labels, and an unbounded tenant set would be an
unbounded label set.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict

from mpi_cuda_imagemanipulation_tpu.graph.spec import _ID_RE, SpecError
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

ENV_MAX_TENANTS = "MCIM_GRAPH_MAX_TENANTS"
ENV_CACHE_CAP = "MCIM_GRAPH_CACHE_CAP"
ENV_QOS_SHED_FRAC = "MCIM_GRAPH_QOS_SHED_FRAC"
ENV_QUOTA_WINDOW_S = "MCIM_GRAPH_QUOTA_WINDOW_S"

# admission classes, best first. The scheduler and the graph service
# share this ladder so "low QoS sheds first" means the same thing on
# both the chain and the graph paths.
QOS_CLASSES = ("interactive", "standard", "batch")


def qos_admit_frac(qos: str, shed_frac: float | None = None) -> float:
    """The load fraction below which `qos` still admits: interactive
    rides to full capacity, batch stops at the shed threshold, standard
    halfway between — so as load climbs past the threshold the classes
    shed strictly low-first."""
    if shed_frac is None:
        shed_frac = float(env_registry.get(ENV_QOS_SHED_FRAC))
    return {
        "interactive": 1.0,
        "standard": (1.0 + shed_frac) / 2.0,
        "batch": shed_frac,
    }[qos]


class GraphShed(Exception):
    """An explicit shed (quota window exhausted or QoS class over the
    load threshold): HTTP 503 + Retry-After, counted as shed."""

    def __init__(self, reason: str, message: str, retry_after_s: float):
        super().__init__(message)
        self.reason = reason  # 'quota' | 'qos' | 'inflight'
        self.retry_after_s = max(retry_after_s, 0.05)


@dataclasses.dataclass
class TenantConfig:
    tenant_id: str
    qos: str = "standard"
    quota_requests: int | None = None  # per window; None = unlimited
    quota_bytes: int | None = None
    window_s: float | None = None  # None: MCIM_GRAPH_QUOTA_WINDOW_S

    def __post_init__(self):
        if not isinstance(self.tenant_id, str) or not _ID_RE.match(
            self.tenant_id
        ):
            raise SpecError(
                "bad-tenant-id", f"bad tenant id {self.tenant_id!r}"
            )
        if self.qos not in QOS_CLASSES:
            raise SpecError(
                "bad-qos",
                f"unknown QoS class {self.qos!r} (known: {QOS_CLASSES})",
            )
        for field in ("quota_requests", "quota_bytes"):
            v = getattr(self, field)
            if v is not None and (
                not isinstance(v, (int, float)) or v < 0
            ):
                raise SpecError(
                    "bad-quota", f"{field} must be a non-negative number"
                )
        if self.window_s is None:
            self.window_s = float(env_registry.get(ENV_QUOTA_WINDOW_S))


class TenantState:
    """One tenant's live state: registered programs, its compile-cache
    namespace (LRU, capped), and the current quota window."""

    def __init__(self, config: TenantConfig, cache_cap: int):
        self.config = config
        self.cache_cap = cache_cap
        # pipeline id -> (PipelineGraph, canonical spec dict)
        self.pipelines: dict[str, tuple] = {}
        # the compile-cache namespace: pipeline id -> jitted executable;
        # its own leaf lock (dict bookkeeping only — compiles happen
        # off-lock in the service, serve/cache.py discipline)
        self._cache_lock = threading.Lock()
        self.cache: OrderedDict[str, object] = OrderedDict()
        self.cache_evictions = 0
        # fixed quota window
        self.window_start = 0.0
        self.window_requests = 0
        self.window_bytes = 0
        # lifetime accounting (metrics/stats)
        self.requests_ok = 0
        self.requests_shed = 0

    def cache_put(self, key: str, fn) -> None:
        with self._cache_lock:
            self.cache[key] = fn
            self.cache.move_to_end(key)
            while len(self.cache) > self.cache_cap:
                self.cache.popitem(last=False)
                self.cache_evictions += 1

    def cache_get(self, key: str):
        with self._cache_lock:
            fn = self.cache.get(key)
            if fn is not None:
                self.cache.move_to_end(key)
            return fn


class TenantRegistry:
    """The bounded tenant table. `ensure` creates with defaults (a spec
    registration is enough to become a tenant); `configure` overwrites
    QoS/quotas. All mutation is under one lock; dispatch-path reads take
    the same lock briefly (dict lookups, no compiles — compiles happen
    off-lock in the service, same discipline as serve/cache.py)."""

    def __init__(self, *, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: dict[str, TenantState] = {}
        self.max_tenants = int(env_registry.get(ENV_MAX_TENANTS))
        self.cache_cap = int(env_registry.get(ENV_CACHE_CAP))
        self.qos_shed_frac = float(env_registry.get(ENV_QOS_SHED_FRAC))

    def ensure(self, tenant_id: str) -> TenantState:
        with self._lock:
            st = self._tenants.get(tenant_id)
            if st is not None:
                return st
            if len(self._tenants) >= self.max_tenants:
                raise SpecError(
                    "tenant-limit",
                    f"tenant registry is at its cap of {self.max_tenants}",
                )
            st = TenantState(TenantConfig(tenant_id), self.cache_cap)
            self._tenants[tenant_id] = st
            return st

    def configure(self, config: TenantConfig) -> TenantState:
        st = self.ensure(config.tenant_id)
        with self._lock:
            st.config = config
        return st

    def get(self, tenant_id: str) -> TenantState:
        with self._lock:
            st = self._tenants.get(tenant_id)
        if st is None:
            raise SpecError(
                "unknown-tenant", f"unknown tenant {tenant_id!r}"
            )
        return st

    def tenants(self) -> list[TenantState]:
        with self._lock:
            return list(self._tenants.values())

    # -- admission ---------------------------------------------------------

    def admit(
        self, st: TenantState, nbytes: int, load_frac: float
    ) -> None:
        """One request's quota + QoS gate; raises GraphShed on refusal.
        Quota windows are fixed (reset at each boundary) — deterministic
        under a fake clock, O(1) per request."""
        now = self._clock()
        cfg = st.config
        with self._lock:
            if now - st.window_start >= cfg.window_s:
                st.window_start = now
                st.window_requests = 0
                st.window_bytes = 0
            remain = cfg.window_s - (now - st.window_start)
            if (
                cfg.quota_requests is not None
                and st.window_requests + 1 > cfg.quota_requests
            ):
                st.requests_shed += 1
                raise GraphShed(
                    "quota",
                    f"tenant {cfg.tenant_id!r} exceeded its "
                    f"{cfg.quota_requests}-request window",
                    remain,
                )
            if (
                cfg.quota_bytes is not None
                and st.window_bytes + nbytes > cfg.quota_bytes
            ):
                st.requests_shed += 1
                raise GraphShed(
                    "quota",
                    f"tenant {cfg.tenant_id!r} exceeded its "
                    f"{cfg.quota_bytes}-byte window",
                    remain,
                )
            if load_frac >= qos_admit_frac(cfg.qos, self.qos_shed_frac):
                st.requests_shed += 1
                raise GraphShed(
                    "qos",
                    f"load {load_frac:.2f} sheds QoS class "
                    f"{cfg.qos!r} (admits below "
                    f"{qos_admit_frac(cfg.qos, self.qos_shed_frac):.2f})",
                    1.0,
                )
            st.window_requests += 1
            st.window_bytes += nbytes

    def stats(self) -> dict:
        with self._lock:
            return {
                "tenants": {
                    tid: {
                        "qos": st.config.qos,
                        "quota_requests": st.config.quota_requests,
                        "quota_bytes": st.config.quota_bytes,
                        "window_s": st.config.window_s,
                        "pipelines": sorted(st.pipelines),
                        "cache_entries": len(st.cache),
                        "cache_evictions": st.cache_evictions,
                        "ok": st.requests_ok,
                        "shed": st.requests_shed,
                    }
                    for tid, st in self._tenants.items()
                },
                "max_tenants": self.max_tenants,
                "cache_cap": self.cache_cap,
                "qos_shed_frac": self.qos_shed_frac,
            }
