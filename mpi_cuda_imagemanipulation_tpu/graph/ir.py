"""The DAG IR: nodes, merge-combinator golden semantics, fingerprints.

A `PipelineGraph` is the validated in-memory form of a pipeline spec
(graph/spec.py): one source, op nodes (each consuming one input), and
merge nodes joining exactly two branches. Fan-out taps are implicit —
any node with more than one consumer is materialized once and read by
every consumer (the executor's env is the memo table, so shared prefixes
are computed once by construction; tests/test_graph.py asserts it via
the trace-time stage counter).

**Merge combinators** follow ops/spec.py's golden-semantics discipline:
each core maps exact u8 integer values held in f32 to exact u8 integer
values, using only arithmetic that is deterministic and fma-immune on
every backend:

  * ``subtract``        — ``trunc_clip(a - b)``: exact integer difference,
                          clamped. ``subtract(source, blurred)`` IS the
                          classic unsharp mask.
  * ``blend``           — ``rint_clip((a + b) * 0.5)``: the sum (<= 510)
                          and the power-of-two halving are both exact in
                          f32; rint is one correctly-rounded op.
  * ``alpha_composite`` — ``rint_clip((a*k + b*(256-k)) / 256)`` with
                          ``k = round(alpha * 256)``: an integer
                          multiply-accumulate (<= 255*256 < 2^24, exact
                          in f32, immune to fma contraction/reordering —
                          the sepia-matrix trick, ops/registry.py) and a
                          single exact power-of-two scale.

**Fingerprints.** ``dag_fingerprint`` extends ``pipeline_fingerprint``
(plan/ir.py): a graph that is a degenerate linear chain fingerprints as
EXACTLY that chain's ``pipeline_fingerprint``, so the calibration store
and every serve-cache key carry over unchanged between "the chain" and
"the chain written as a DAG"; true DAGs hash their full topology under a
``dag-`` prefix.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable

import jax.numpy as jnp
import numpy as np

from mpi_cuda_imagemanipulation_tpu.ops.registry import op_family
from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    Op,
    rint_clip_f32,
    trunc_clip_f32,
)
from mpi_cuda_imagemanipulation_tpu.plan.ir import pipeline_fingerprint


@dataclasses.dataclass(frozen=True)
class SourceNode:
    id: str


@dataclasses.dataclass(frozen=True)
class OpNode:
    id: str
    op: Op
    input: str


@dataclasses.dataclass(frozen=True)
class MergeNode:
    id: str
    combinator: str
    inputs: tuple[str, str]
    alpha_k: int = 256  # alpha quantized to k/256 (alpha_composite only)


Node = SourceNode | OpNode | MergeNode


def _merge_subtract(a: jnp.ndarray, b: jnp.ndarray, k: int) -> jnp.ndarray:
    return trunc_clip_f32(a - b)


def _merge_blend(a: jnp.ndarray, b: jnp.ndarray, k: int) -> jnp.ndarray:
    return rint_clip_f32((a + b) * np.float32(0.5))


def _merge_alpha(a: jnp.ndarray, b: jnp.ndarray, k: int) -> jnp.ndarray:
    acc = a * np.float32(k) + b * np.float32(256 - k)
    return rint_clip_f32(acc * np.float32(1.0 / 256.0))


# combinator name -> (a_f32, b_f32, alpha_k) -> f32; exact u8 integer
# values in, exact u8 integer values out (the fused-stage carry contract)
MERGE_COMBINATORS: dict[str, Callable] = {
    "subtract": _merge_subtract,
    "blend": _merge_blend,
    "alpha_composite": _merge_alpha,
}


def merge_core(node: MergeNode, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Apply one merge on the f32 exact-integer carry."""
    return MERGE_COMBINATORS[node.combinator](a, b, node.alpha_k)


@dataclasses.dataclass(frozen=True)
class PipelineGraph:
    """One validated pipeline DAG, nodes in a fixed topological order."""

    name: str
    nodes: tuple[Node, ...]  # topological order, source first
    source_id: str
    outputs: dict[str, str]  # output kind -> node id ('image' guaranteed)

    @property
    def by_id(self) -> dict[str, Node]:
        return {n.id: n for n in self.nodes}

    @property
    def consumers(self) -> dict[str, int]:
        """node id -> reference count (edges in + output refs)."""
        count = {n.id: 0 for n in self.nodes}
        for n in self.nodes:
            if isinstance(n, OpNode):
                count[n.input] += 1
            elif isinstance(n, MergeNode):
                for i in n.inputs:
                    count[i] += 1
        for nid in self.outputs.values():
            count[nid] += 1
        return count

    @property
    def ops(self) -> tuple[Op, ...]:
        return tuple(n.op for n in self.nodes if isinstance(n, OpNode))

    @property
    def max_halo(self) -> int:
        return max((op.halo for op in self.ops), default=0)

    @property
    def min_true_dim(self) -> int:
        """Smallest image dimension the graph can take (reflect-101
        border extension needs dim >= halo + 1, serve/padded.py)."""
        return self.max_halo + 1

    def as_linear_chain(self) -> tuple[Op, ...] | None:
        """The op chain when this graph is degenerate — a single
        source -> op -> ... -> op path with image-only output — else
        None. The fingerprint and the serving path use this to make
        "the chain written as a DAG" indistinguishable from the chain."""
        if set(self.outputs) != {"image"}:
            return None
        consumers = self.consumers
        chain: list[Op] = []
        cur = self.source_id
        for _ in range(len(self.nodes) - 1):
            nxt = [
                n for n in self.nodes
                if isinstance(n, OpNode) and n.input == cur
            ]
            if len(nxt) != 1 or consumers[cur] != 1:
                return None
            chain.append(nxt[0].op)
            cur = nxt[0].id
        if cur != self.outputs["image"] or consumers[cur] != 1:
            return None
        return tuple(chain)

    def check_channels(self, channels: int) -> None:
        """Validate that a `channels`-channel source feeds every edge and
        merge (raised as the closed `channel-mismatch`/`bad-image` codes
        so a bad request can never become a trace-time 500)."""
        from mpi_cuda_imagemanipulation_tpu.graph.spec import SpecError

        ch: dict[str, int] = {self.source_id: channels}
        for n in self.nodes:
            if isinstance(n, OpNode):
                got = ch[n.input]
                if n.op.in_channels and got and n.op.in_channels != got:
                    raise SpecError(
                        "bad-image",
                        f"node {n.id!r}: op {n.op.name!r} expects "
                        f"{n.op.in_channels} channels, gets {got}",
                    )
                ch[n.id] = n.op.out_channels or got
            elif isinstance(n, MergeNode):
                a, b = (ch[i] for i in n.inputs)
                if a and b and a != b:
                    raise SpecError(
                        "bad-image",
                        f"merge {n.id!r} joins {a}-channel and {b}-channel "
                        "branches",
                    )
                ch[n.id] = a or b

    def describe(self) -> str:
        rows = [f"graph {self.name or '<unnamed>'}: {len(self.nodes)} nodes"]
        consumers = self.consumers
        for n in self.nodes:
            if isinstance(n, SourceNode):
                desc = "source"
            elif isinstance(n, OpNode):
                desc = f"op {n.op.name} <- {n.input}"
            else:
                desc = f"merge {n.combinator} <- {n.inputs[0]},{n.inputs[1]}"
            tap = f" (tap x{consumers[n.id]})" if consumers[n.id] > 1 else ""
            rows.append(f"  {n.id}: {desc}{tap}")
        rows.append(
            "  outputs: "
            + ", ".join(f"{k}={v}" for k, v in sorted(self.outputs.items()))
        )
        return "\n".join(rows)


def build_graph(
    *, name: str, nodes: dict[str, Node], source_id: str,
    outputs: dict[str, str],
) -> PipelineGraph:
    """Wire + order a parsed node set: resolve references, topo-sort
    (cycle refusal), prune-check dangling nodes, chain channels. All
    refusals are closed-taxonomy SpecErrors (graph/spec.py)."""
    from mpi_cuda_imagemanipulation_tpu.graph.spec import SpecError

    def deps(n: Node) -> tuple[str, ...]:
        if isinstance(n, OpNode):
            return (n.input,)
        if isinstance(n, MergeNode):
            return n.inputs
        return ()

    for n in nodes.values():
        for d in deps(n):
            if d not in nodes:
                raise SpecError(
                    "unknown-input",
                    f"node {n.id!r} references unknown node {d!r}",
                )

    # Kahn topo sort; leftovers = a cycle
    indeg = {nid: len(deps(n)) for nid, n in nodes.items()}
    ready = sorted(nid for nid, d in indeg.items() if d == 0)
    rdeps: dict[str, list[str]] = {nid: [] for nid in nodes}
    for n in nodes.values():
        for d in deps(n):
            rdeps[d].append(n.id)
    order: list[str] = []
    while ready:
        nid = ready.pop(0)
        order.append(nid)
        for r in sorted(rdeps[nid]):
            indeg[r] -= 1
            if indeg[r] == 0:
                ready.append(r)
    if len(order) != len(nodes):
        cyclic = sorted(set(nodes) - set(order))
        raise SpecError("graph-cycle", f"cyclic node references {cyclic}")

    # reachability: every node must feed some output
    needed: set[str] = set(outputs.values())
    frontier = list(needed)
    while frontier:
        nid = frontier.pop()
        for d in deps(nodes[nid]):
            if d not in needed:
                needed.add(d)
                frontier.append(d)
    dangling = sorted(set(nodes) - needed)
    if dangling:
        raise SpecError(
            "dangling-node", f"nodes {dangling} feed no output"
        )

    g = PipelineGraph(
        name=name,
        nodes=tuple(nodes[nid] for nid in order),
        source_id=source_id,
        outputs=dict(outputs),
    )
    _check_static_channels(g)
    return g


def _check_static_channels(g: PipelineGraph) -> None:
    """Registration-time channel chaining with the source count unknown:
    propagate the symbolic source count, constraining it at the first op
    that demands a concrete one (make_pipeline_ops' rule, lifted to the
    DAG). A contradiction between two branches is a spec bug — caught
    here with the closed `channel-mismatch` code, not at request time."""
    from mpi_cuda_imagemanipulation_tpu.graph.spec import SpecError

    source_ch: list[int] = [0]  # 0 = unconstrained

    def resolve(v: int | str) -> int:
        return source_ch[0] if v == "S" else int(v)

    ch: dict[str, int | str] = {g.source_id: "S"}
    for n in g.nodes:
        if isinstance(n, OpNode):
            want = n.op.in_channels
            got = ch[n.input]
            if want:
                if got == "S" or resolve(got) == 0:
                    if got == "S":
                        if source_ch[0] and source_ch[0] != want:
                            raise SpecError(
                                "channel-mismatch",
                                f"node {n.id!r} needs a {want}-channel "
                                f"source but another branch fixed it at "
                                f"{source_ch[0]}",
                            )
                        source_ch[0] = want
                elif resolve(got) != want:
                    raise SpecError(
                        "channel-mismatch",
                        f"node {n.id!r}: op {n.op.name!r} expects "
                        f"{want} channels but its input produces "
                        f"{resolve(got)}",
                    )
            ch[n.id] = n.op.out_channels or got
        elif isinstance(n, MergeNode):
            a, b = (ch[i] for i in n.inputs)
            ra = source_ch[0] if a == "S" else int(a)
            rb = source_ch[0] if b == "S" else int(b)
            if ra and rb and ra != rb:
                raise SpecError(
                    "channel-mismatch",
                    f"merge {n.id!r} joins a {ra}-channel branch with a "
                    f"{rb}-channel branch",
                )
            ch[n.id] = a if (a == b or not rb) else b


def dag_fingerprint(g: PipelineGraph) -> str:
    """Stable identity of the DAG's execution structure. Degenerate
    linear chains fingerprint as the chain itself (pipeline_fingerprint)
    so every existing calibration/serve-cache key carries over; real
    DAGs hash their topology + combinator params + outputs."""
    chain = g.as_linear_chain()
    if chain is not None:
        return pipeline_fingerprint(chain)
    parts = []
    for n in g.nodes:
        if isinstance(n, SourceNode):
            parts.append(f"src:{n.id}")
        elif isinstance(n, OpNode):
            parts.append(
                f"op:{n.id}<{n.input}:{n.op.name}/{op_family(n.op)}"
                f"/h{n.op.halo}"
            )
        else:
            parts.append(
                f"mg:{n.id}<{n.inputs[0]},{n.inputs[1]}:{n.combinator}"
                f"/k{n.alpha_k}"
            )
    parts.append(
        "out:" + ",".join(f"{k}={v}" for k, v in sorted(g.outputs.items()))
    )
    key = "|".join(parts)
    return "dag-" + hashlib.sha256(key.encode()).hexdigest()[:16]
