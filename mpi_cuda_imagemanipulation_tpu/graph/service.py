"""The pipeline service: registration + tenant-admitted graph dispatch.

`GraphService` is the engine behind the HTTP surface (serve/server.py):

    register(tenant, spec)   validate (closed taxonomy, graph/spec.py),
                             compile-plan the DAG, store under the
                             tenant; returns the pipeline id — the
                             spec's `dag_fingerprint`, so registration
                             is idempotent and two tenants registering
                             one spec agree on the id.
    process(tenant, id, img) admission (quota + QoS ladder,
                             graph/tenancy.py) -> per-tenant compile
                             cache -> ONE jitted dispatch producing
                             image + any declared side outputs.

Wire surface (shared with the fabric router, which forwards these
headers and keys warm affinity on (tenant, pipeline id, bucket)):

    POST /v1/pipelines                  {"tenant": ..., "spec": {...}}
    POST /v1/tenants                    {"tenant": ..., "qos": ...,
                                         "quota_requests"/"quota_bytes"}
    POST /v1/process?pipeline=<id>      X-MCIM-Tenant / X-MCIM-Pipeline
                                        headers work too

Failure posture: every refusal is a `SpecError` (4xx-class structured
JSON with the taxonomy code) or a `GraphShed` (503 + Retry-After,
counted as shed) — a hostile spec or request can never 500. The
`graph.dispatch` failpoint injects the one genuine 500 class (a device
dispatch failure) so the error path stays testable.
"""

from __future__ import annotations

import threading
import time

import jax
import numpy as np

from mpi_cuda_imagemanipulation_tpu.graph.compile import (
    compile_graph,
    graph_callable,
    graph_sub_callable,
    split_for_placement,
)
from mpi_cuda_imagemanipulation_tpu.graph.spec import SpecError, parse_spec
from mpi_cuda_imagemanipulation_tpu.graph.tenancy import (
    GraphShed,
    TenantConfig,
    TenantRegistry,
)
from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry
from mpi_cuda_imagemanipulation_tpu.resilience import (
    deadline as deadline_mod,
)
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

ENV_MAX_INFLIGHT = "MCIM_GRAPH_MAX_INFLIGHT"

# the graph wire headers the fabric router forwards verbatim
HDR_TENANT = "X-MCIM-Tenant"
HDR_PIPELINE = "X-MCIM-Pipeline"
HDR_HISTOGRAM = "X-MCIM-Histogram"
HDR_STATS = "X-MCIM-Stats"
PIPELINES_PATH = "/v1/pipelines"
TENANTS_PATH = "/v1/tenants"

# bounded terminal-status label set of mcim_graph_requests_total
STATUSES = ("ok", "shed", "rejected", "error")


def _graph_modeled_bytes(program, backend: str, args) -> float:
    """The DAG's boundary model for cost attribution (obs/cost): the u8
    source in, the DECLARED outputs out (image + histogram/stats side
    outputs) — shared prefixes, merge joins and fused segments are
    in-executable structure and must add nothing at the boundary. The
    output avals come from eval_shape (spec-determined: the callable
    returns exactly the spec's `outputs` mapping), never from the
    compiled artifact itself."""
    img = args[0]
    aval = jax.ShapeDtypeStruct(tuple(img.shape), np.uint8)
    out = jax.eval_shape(graph_callable(program, impl=backend), aval)
    total = int(np.prod(aval.shape, dtype=np.int64))
    for leaf in jax.tree_util.tree_leaves(out):
        total += int(
            np.prod(leaf.shape, dtype=np.int64)
        ) * leaf.dtype.itemsize
    return float(total)


class GraphService:
    def __init__(
        self,
        *,
        registry: Registry | None = None,
        backend: str = "xla",
        plan: str = "auto",
        systolic: bool = False,
        load_frac=None,
        coalescer=None,
        clock=time.monotonic,
    ):
        self.registry = registry or Registry()
        self.backend = backend
        self.plan = plan
        # serve/scheduler.MicroBatchScheduler (or None): when attached,
        # admitted graph dispatches ride the chain path's coalescing
        # queue as group lanes keyed (dag fingerprint, true shape) — one
        # vmapped executable per (pipeline, batch bucket) instead of one
        # jit per request shape per request
        self.coalescer = coalescer
        # stage-sharded execution across replicas (graph/systolic.py);
        # advertised in heartbeats so the router only places stages on
        # replicas that will accept /v1/systolic hops
        self.systolic = systolic
        self.tenants = TenantRegistry(clock=clock)
        # external load signal (the serving scheduler's queue fill); the
        # QoS ladder sheds on max(external, own-inflight fraction)
        self._load_frac = load_frac
        self.max_inflight = int(env_registry.get(ENV_MAX_INFLIGHT))
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._clock = clock
        self._log = get_logger()
        r = self.registry
        self._m_requests = r.counter(
            "mcim_graph_requests_total",
            "Graph-pipeline requests by terminal status "
            "(ok/shed/rejected/error).",
            labels=("status",),
        )
        self._m_rejections = r.counter(
            "mcim_graph_rejections_total",
            "Spec/request refusals by closed-taxonomy code "
            "(graph/spec.TAXONOMY — a bounded label set by construction).",
            labels=("code",),
        )
        self._m_shed = r.counter(
            "mcim_graph_shed_total",
            "Explicit sheds by reason (quota window / qos ladder / "
            "inflight cap).",
            labels=("reason",),
        )
        self._m_registrations = r.counter(
            "mcim_graph_registrations_total",
            "Accepted pipeline-spec registrations (idempotent re-posts "
            "count — the wire cost is real either way).",
        )
        self._m_deadline = deadline_mod.expired_counter(r)
        self._m_dispatch_s = r.histogram(
            "mcim_graph_dispatch_seconds",
            "Device+host time per graph dispatch.",
        )
        self._m_compiles = r.counter(
            "mcim_graph_compiles_total",
            "Graph executables built into a tenant cache namespace.",
        )
        self._m_coalesced = r.counter(
            "mcim_graph_coalesced_total",
            "Graph dispatches routed through the serving scheduler's "
            "group lanes, by outcome (batched = answered by the lane; "
            "fallback = lane refused, answered by the solo golden path "
            "— a bounded two-label set).",
            labels=("outcome",),
        )
        # replica-side systolic accounting (the router holds the
        # placement/fallback families; these live where the bytes move)
        self._m_sys_tiles = r.counter(
            "mcim_systolic_tiles_forwarded_total",
            "Live-env handoffs forwarded to the next stage owner "
            "(one per stage boundary per request — the fabric mirror "
            "of the sharded path's collective-permute count).",
        )
        self._m_sys_bytes = r.counter(
            "mcim_systolic_exchange_bytes_total",
            "u8 payload bytes crossing stage boundaries replica-to-"
            "replica (the traffic the systolic mode moves off the "
            "front door).",
        )
        r.gauge(
            "mcim_graph_tenants",
            "Tenants in the registry (bounded by MCIM_GRAPH_MAX_TENANTS).",
            fn=lambda: float(len(self.tenants.tenants())),
        )
        r.gauge(
            "mcim_graph_pipelines",
            "Registered (tenant, pipeline) pairs.",
            fn=lambda: float(
                sum(len(t.pipelines) for t in self.tenants.tenants())
            ),
        )
        r.gauge(
            "mcim_graph_cache_entries",
            "Compiled executables across all tenant cache namespaces "
            "(each namespace capped at MCIM_GRAPH_CACHE_CAP).",
            fn=lambda: float(
                sum(len(t.cache) for t in self.tenants.tenants())
            ),
        )
        r.gauge(
            "mcim_graph_cache_evictions",
            "Cumulative LRU evictions out of tenant cache namespaces.",
            fn=lambda: float(
                sum(t.cache_evictions for t in self.tenants.tenants())
            ),
        )

    # -- registration ------------------------------------------------------

    def on_reject(self, code: str) -> None:
        """Count one closed-taxonomy refusal (the HTTP layer calls this
        for refusals it maps itself, e.g. undecodable request bodies)."""
        self._m_requests.inc(status="rejected")
        self._m_rejections.inc(code=code)

    def register(self, tenant_id: str, spec_raw) -> dict:
        """Validate + store one spec under the tenant; idempotent.
        Raises SpecError (closed taxonomy) on any refusal."""
        try:
            graph = parse_spec(spec_raw)
            st = self.tenants.ensure(tenant_id)
        except SpecError as e:
            self._m_rejections.inc(code=e.code)
            raise
        program = compile_graph(
            graph, plan=self.plan, backend=self.backend
        )
        pid = program.dag_fp
        canonical = spec_raw if isinstance(spec_raw, dict) else None
        st.pipelines[pid] = (graph, canonical)
        self._m_registrations.inc()
        chain = graph.as_linear_chain()
        self._log.info(
            "graph: tenant %s registered %s (%s, %d nodes, %d segments)",
            tenant_id, pid, graph.name or "<unnamed>", len(graph.nodes),
            program.n_segments,
        )
        return {
            "pipeline": pid,
            "tenant": tenant_id,
            "name": graph.name,
            "nodes": len(graph.nodes),
            "segments": program.n_segments,
            "merges": program.n_merges,
            "outputs": sorted(graph.outputs),
            "linear_chain": (
                ",".join(op.name for op in chain) if chain else None
            ),
            "fingerprint": program.fingerprint,
        }

    def configure_tenant(self, body: dict) -> dict:
        """`POST /v1/tenants` body -> stored TenantConfig; SpecError on
        any refusal (bad-tenant-id / bad-qos / bad-quota)."""
        if not isinstance(body, dict):
            raise SpecError("bad-root", "tenant config must be an object")
        unknown = set(body) - {
            "tenant", "qos", "quota_requests", "quota_bytes", "window_s"
        }
        if unknown:
            raise SpecError(
                "unknown-field",
                f"unknown tenant fields {sorted(unknown)}",
            )
        cfg = TenantConfig(
            tenant_id=body.get("tenant", ""),
            qos=body.get("qos", "standard"),
            quota_requests=body.get("quota_requests"),
            quota_bytes=body.get("quota_bytes"),
            window_s=body.get("window_s"),
        )
        st = self.tenants.configure(cfg)
        return {
            "tenant": cfg.tenant_id,
            "qos": cfg.qos,
            "quota_requests": cfg.quota_requests,
            "quota_bytes": cfg.quota_bytes,
            "window_s": st.config.window_s,
        }

    # -- dispatch ----------------------------------------------------------

    def _current_load(self) -> float:
        own = self._inflight / max(1, self.max_inflight)
        ext = 0.0
        if self._load_frac is not None:
            try:
                ext = float(self._load_frac())
            except Exception:  # the signal must never fail a request
                ext = 0.0
        return max(own, ext)

    def process(
        self,
        tenant_id: str,
        pipeline_id: str,
        img: np.ndarray,
        *,
        nbytes: int | None = None,
        trace_id: str = "",
        deadline: deadline_mod.Deadline | None = None,
    ) -> dict:
        """One admitted graph dispatch -> {'image': np.uint8 array,
        'histogram'?: list[int], 'stats'?: dict}. Raises SpecError
        (rejected) / GraphShed (shed) / DeadlineExpired (the propagated
        budget died before dispatch) / anything else = a real error."""
        try:
            st = self.tenants.get(tenant_id)
            graph_entry = st.pipelines.get(pipeline_id)
            if graph_entry is None:
                raise SpecError(
                    "unknown-pipeline",
                    f"tenant {tenant_id!r} has no pipeline "
                    f"{pipeline_id!r}",
                )
            graph = graph_entry[0]
            self._validate_image(graph, img)
        except SpecError as e:
            self._m_requests.inc(status="rejected")
            self._m_rejections.inc(code=e.code)
            raise
        if deadline is not None and deadline.expired():
            # checked between validation and admission: a dead budget
            # must not charge the tenant's quota window, and certainly
            # not reach the compiled dispatch
            deadline_mod.count_expired(self._m_deadline, "graph")
            self._m_requests.inc(status="deadline_expired")
            raise deadline_mod.DeadlineExpired(
                "graph dispatch budget exhausted before admission"
            )
        try:
            self.tenants.admit(
                st, img.nbytes if nbytes is None else nbytes,
                self._current_load(),
            )
        except GraphShed as e:
            self._m_requests.inc(status="shed")
            self._m_shed.inc(reason=e.reason)
            raise
        with self._inflight_lock:
            if self._inflight >= self.max_inflight:
                self._m_requests.inc(status="shed")
                self._m_shed.inc(reason="inflight")
                raise GraphShed(
                    "inflight",
                    f"{self._inflight} graph dispatches already in "
                    f"flight (cap {self.max_inflight})",
                    0.5,
                )
            self._inflight += 1
        t0 = self._clock()
        try:
            failpoints.maybe_fail(
                "graph.dispatch", tenant=tenant_id, pipeline=pipeline_id
            )
            width = img.shape[1] if img.ndim >= 2 else None
            if self.coalescer is not None:
                out = self._coalesced(
                    st, pipeline_id, graph, img, width,
                    qos=st.config.qos, trace_id=trace_id,
                )
            else:
                out = self._pipeline_fn(st, pipeline_id, graph, width)(img)
            result: dict = {"image": np.asarray(out["image"])}
            if "histogram" in out:
                result["histogram"] = [
                    int(v) for v in np.asarray(out["histogram"])
                ]
            if "stats" in out:
                s = out["stats"]
                result["stats"] = {
                    "count": int(s["count"]),
                    "min": int(s["min"]),
                    "max": int(s["max"]),
                    "mean": round(float(s["mean"]), 4),
                }
        except Exception:
            self._m_requests.inc(status="error")
            raise
        finally:
            with self._inflight_lock:
                self._inflight -= 1
        self._m_dispatch_s.observe(
            self._clock() - t0, exemplar=trace_id or None
        )
        self._m_requests.inc(status="ok")
        st.requests_ok += 1
        return result

    # -- coalesced (group-lane) dispatch -----------------------------------

    def _pipeline_fn(self, st, pipeline_id: str, graph, width: int | None):
        """Cached jitted solo executor for the whole program (the
        uncoalesced path and the group lane's golden fallback)."""
        fn = st.cache_get(pipeline_id)
        if fn is None:
            # build + jit OFF the registry lock (serve/cache.py
            # discipline); a racing miss builds twice, cache_put keeps
            # the newest — correctness is unaffected (both are the same
            # program)
            program = compile_graph(
                graph, plan=self.plan, backend=self.backend, width=width
            )
            from mpi_cuda_imagemanipulation_tpu.obs import cost as obs_cost

            # cost attribution rides the insertion (obs/cost): each
            # request shape's first dispatch compiles AOT and lands its
            # measured cost in the ledger keyed by the program's
            # execution-structure fingerprint; the model is the DAG's
            # boundary — source in, declared outputs out, shared
            # prefixes and fused segments adding nothing
            fn = obs_cost.wrap_cache_fn(
                "graph",
                program.fingerprint,
                jax.jit(graph_callable(program, impl=self.backend)),
                modeled_fn=lambda args, p=program: (
                    _graph_modeled_bytes(p, self.backend, args)
                ),
            )
            st.cache_put(pipeline_id, fn)
            self._m_compiles.inc()
        return fn

    def _batched_fn(
        self, st, pipeline_id: str, graph, width: int | None, nb: int
    ):
        """Cached jitted vmapped executor for nb-stacked group-lane
        dispatch, cached as f"{pipeline_id}@b{nb}" in the same tenant
        LRU namespace (the '@' separator cannot appear in a pipeline
        id). vmap over the program is value-preserving: every op is
        per-image elementwise/stencil/reduction and the histogram is a
        fixed-length bincount, so batched and solo dispatch are
        bit-exact — the group lane's correctness premise."""
        key = f"{pipeline_id}@b{nb}"
        fn = st.cache_get(key)
        if fn is None:
            program = compile_graph(
                graph, plan=self.plan, backend=self.backend, width=width
            )
            from mpi_cuda_imagemanipulation_tpu.obs import cost as obs_cost

            fn = obs_cost.wrap_cache_fn(
                "graph",
                f"{program.fingerprint}@b{nb}",
                jax.jit(
                    jax.vmap(graph_callable(program, impl=self.backend))
                ),
                modeled_fn=lambda args, p=program, n=nb: n * (
                    _graph_modeled_bytes(p, self.backend, (args[0][0],))
                ),
            )
            st.cache_put(key, fn)
            self._m_compiles.inc()
        return fn

    def _coalesced(
        self, st, pipeline_id: str, graph, img, width: int | None,
        *, qos: str, trace_id: str,
    ):
        """One dispatch through the serving scheduler's group lane,
        keyed (dag fingerprint, true shape) so same-program same-shape
        requests share one vmapped executable per batch bucket.
        Coalescing is a pure optimisation: any lane-level refusal
        (queue at depth, lane quarantined, scheduler stopping) falls
        back to the solo golden path — tenant admission already passed,
        so the request must still be answered, and solo output is
        bit-exact with batched by construction."""
        from mpi_cuda_imagemanipulation_tpu.serve.scheduler import GroupSpec

        ch = img.shape[2] if img.ndim == 3 else 1
        spec = GroupSpec(
            key=("graph", pipeline_id, img.shape[0], img.shape[1], ch),
            get_fn=lambda nb: self._batched_fn(
                st, pipeline_id, graph, width, nb
            ),
            fallback=lambda im: self._pipeline_fn(
                st, pipeline_id, graph, width
            )(im),
        )
        req = self.coalescer.submit_group(
            img, spec, trace_id=trace_id or None, qos=qos
        )
        try:
            out = req.wait()
        except Exception:
            self._m_coalesced.inc(outcome="fallback")
            return self._pipeline_fn(st, pipeline_id, graph, width)(img)
        self._m_coalesced.inc(outcome="batched")
        return out

    # -- systolic (stage-sharded) dispatch ---------------------------------

    def _sub_fn(self, st, pipeline_id: str, graph, lo: int, hi: int,
                width: int | None):
        """Cached jitted executor for the step subrange [lo, hi) — the
        same tenant LRU namespace as the pinned executable (the '#'
        cache-key separator cannot appear in a pipeline id), with cost
        attribution keyed by fingerprint + range so the ledger can
        tell a stage-owner's share from the whole program."""
        key = f"{pipeline_id}#r{lo}-{hi}"
        fn = st.cache_get(key)
        if fn is None:
            # the canonical systolic step form: plan='off' (per-op
            # stages, no calibration dependence) + stage-boundary
            # splitting, so every owner and the router derive the SAME
            # step indices from the spec with no shared state — and
            # bit-exactness holds because plan partitioning never
            # changes values (the repo's exact-integer premise)
            program = split_for_placement(
                compile_graph(
                    graph, plan="off", backend=self.backend, width=width
                )
            )
            sub = graph_sub_callable(program, lo, hi, impl=self.backend)
            from mpi_cuda_imagemanipulation_tpu.obs import cost as obs_cost

            def modeled(args, s=sub):
                env = args[0]
                total = 0
                for leaf in jax.tree_util.tree_leaves(env):
                    total += int(
                        np.prod(np.shape(leaf), dtype=np.int64)
                    ) * np.asarray(leaf).dtype.itemsize
                out = jax.eval_shape(s, env)
                for leaf in jax.tree_util.tree_leaves(out):
                    total += int(
                        np.prod(leaf.shape, dtype=np.int64)
                    ) * leaf.dtype.itemsize
                return float(total)

            fn = obs_cost.wrap_cache_fn(
                "graph",
                f"{program.fingerprint}:r{lo}-{hi}",
                jax.jit(sub),
                modeled_fn=modeled,
            )
            st.cache_put(key, fn)
            self._m_compiles.inc()
        return fn

    def count_forward(self, nbytes: int) -> None:
        """One live-env handoff left this replica (the HTTP layer calls
        this after a successful peer POST)."""
        self._m_sys_tiles.inc()
        self._m_sys_bytes.inc(nbytes)

    def systolic_process(
        self,
        placement: dict,
        idx: int,
        payload,
        *,
        nbytes: int | None = None,
        trace_id: str = "",
    ):
        """Run this replica's step range of a placed program.

        `idx` is this replica's index in placement['ranges']. At the
        entry owner (idx 0) `payload` is the decoded u8 image and the
        FULL admission path runs (validation, quota/QoS, inflight cap) —
        a refusal here is the request's real refusal, relayed verbatim.
        At interior owners `payload` is the live env decoded from the
        handoff frame; the request was already admitted, so a hop never
        sheds (shedding mid-chain would break accepted => answered).

        Returns ``("env", env)`` with the [hi) boundary env to forward,
        or ``("result", result)`` at the final owner — `result` in the
        exact `process()` shape, counted as the request's one terminal
        'ok' (fleet-wide the request still counts once)."""
        tenant_id = placement["tenant"]
        pipeline_id = placement["pipeline"]
        ranges = placement["ranges"]
        lo, hi = ranges[idx]
        entry = idx == 0
        final = idx == len(ranges) - 1
        try:
            st = self.tenants.get(tenant_id)
            graph_entry = st.pipelines.get(pipeline_id)
            if graph_entry is None:
                raise SpecError(
                    "unknown-pipeline",
                    f"tenant {tenant_id!r} has no pipeline "
                    f"{pipeline_id!r}",
                )
            graph = graph_entry[0]
            if entry:
                self._validate_image(graph, payload)
        except SpecError as e:
            self._m_requests.inc(status="rejected")
            self._m_rejections.inc(code=e.code)
            raise
        if entry:
            try:
                self.tenants.admit(
                    st, payload.nbytes if nbytes is None else nbytes,
                    self._current_load(),
                )
            except GraphShed as e:
                self._m_requests.inc(status="shed")
                self._m_shed.inc(reason=e.reason)
                raise
            with self._inflight_lock:
                if self._inflight >= self.max_inflight:
                    self._m_requests.inc(status="shed")
                    self._m_shed.inc(reason="inflight")
                    raise GraphShed(
                        "inflight",
                        f"{self._inflight} graph dispatches already in "
                        f"flight (cap {self.max_inflight})",
                        0.5,
                    )
                self._inflight += 1
            env = {graph.source_id: payload}
            width = payload.shape[1] if payload.ndim >= 2 else None
        else:
            env = {k: np.asarray(v) for k, v in payload.items()}
            any_leaf = next(iter(env.values()))
            width = any_leaf.shape[1] if any_leaf.ndim >= 2 else None
        t0 = self._clock()
        try:
            if entry:
                failpoints.maybe_fail(
                    "graph.dispatch", tenant=tenant_id,
                    pipeline=pipeline_id,
                )
            fn = self._sub_fn(st, pipeline_id, graph, lo, hi, width)
            out = fn(env)
        except Exception:
            self._m_requests.inc(status="error")
            raise
        finally:
            if entry:
                with self._inflight_lock:
                    self._inflight -= 1
        self._m_dispatch_s.observe(
            self._clock() - t0, exemplar=trace_id or None
        )
        if not final:
            return "env", {k: np.asarray(v) for k, v in out.items()}
        result: dict = {"image": np.asarray(out["~image"])}
        if "~histogram" in out:
            result["histogram"] = [
                int(v) for v in np.asarray(out["~histogram"])
            ]
        if "~stats" in out:
            s = out["~stats"]
            result["stats"] = {
                "count": int(s["count"]),
                "min": int(s["min"]),
                "max": int(s["max"]),
                "mean": round(float(s["mean"]), 4),
            }
        self._m_requests.inc(status="ok")
        st.requests_ok += 1
        return "result", result

    def _validate_image(self, graph, img: np.ndarray) -> None:
        if (
            not isinstance(img, np.ndarray)
            or img.dtype != np.uint8
            or img.ndim not in (2, 3)
        ):
            raise SpecError(
                "bad-image",
                "graphs take (H, W[, C]) uint8 images",
            )
        if min(img.shape[:2]) < graph.min_true_dim:
            raise SpecError(
                "bad-image",
                f"image {img.shape[0]}x{img.shape[1]} is below the "
                f"graph's minimum dimension {graph.min_true_dim} "
                "(stencil border extension)",
            )
        ch = img.shape[2] if img.ndim == 3 else 1
        graph.check_channels(ch)

    def pipeline_ids(self) -> list[str]:
        """Every registered pipeline id across tenants — the replica
        heartbeat's `pipelines` field (the router re-pushes specs to
        replicas whose beat lacks one)."""
        ids: set[str] = set()
        for st in self.tenants.tenants():
            ids.update(st.pipelines)
        return sorted(ids)

    def stats(self) -> dict:
        return {
            "backend": self.backend,
            "plan": self.plan,
            "systolic": self.systolic,
            "max_inflight": self.max_inflight,
            "inflight": self._inflight,
            **self.tenants.stats(),
        }
