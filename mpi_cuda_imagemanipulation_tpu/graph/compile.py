"""Graph compilation: topo-sort + stage partition, generalizing `plan/`'s
chain fusion to fan-out/fan-in.

The compiler cuts the DAG at its *materialization boundaries* — the
source, every merge, every fan-out tap (a node with more than one
consumer), and every node a spec output names. Between boundaries each
maximal linear op run becomes one `RunSegment`, compiled by the SAME
`plan/planner.build_plan` stage rules the chain path uses (pointwise
absorption + temporal blocking; the per-segment plan mode resolves
through `resolve_plan_mode`, whose calibration lookup keys on the
segment's `pipeline_fingerprint` — so a DAG branch that equals a
calibrated chain reuses its measured plan choice unchanged). Merges are
join barriers: both inputs are materialized env values before the
combinator core runs.

Shared prefixes are computed ONCE by construction: the executor
evaluates steps in topological order into an environment keyed by node
id, so a tap's value is produced by exactly one step no matter how many
branches read it (the `on_stage` trace-time hook lets tests count this —
tests/test_graph.py's dispatch-count assertion).

Side outputs ride the same dispatch: `histogram` is the 256-bin int32
count of the named node's u8 value (`ops/histogram.histogram_stats` —
the exact additive statistic the global-stat ops psum), and `stats`
(count/min/max/mean) derives from that histogram, so one device program
produces image + histogram + stats with no second pass over the pixels.
"""

from __future__ import annotations

import dataclasses
import hashlib

import jax.numpy as jnp

from mpi_cuda_imagemanipulation_tpu.graph.ir import (
    MergeNode,
    OpNode,
    PipelineGraph,
    SourceNode,
    dag_fingerprint,
    merge_core,
)
from mpi_cuda_imagemanipulation_tpu.ops.histogram import histogram_stats
from mpi_cuda_imagemanipulation_tpu.ops.spec import U8, exact_f32
from mpi_cuda_imagemanipulation_tpu.plan.ir import Plan
from mpi_cuda_imagemanipulation_tpu.plan.planner import (
    build_plan,
    resolve_plan_mode,
)


@dataclasses.dataclass(frozen=True)
class RunSegment:
    """One maximal linear op run between materialization boundaries,
    compiled into fused stages by the chain planner."""

    dst: str  # node id whose value this segment produces
    src: str  # env key the segment reads
    plan: Plan

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(op.name for op in self.plan.ops)


@dataclasses.dataclass(frozen=True)
class MergeStep:
    """A join barrier: both inputs are materialized env values."""

    dst: str
    node: MergeNode


Step = RunSegment | MergeStep


@dataclasses.dataclass(frozen=True)
class GraphProgram:
    """A compiled graph: executable steps in topological order."""

    graph: PipelineGraph
    steps: tuple[Step, ...]
    mode: str  # the resolved build mode segments were fused with

    @property
    def dag_fp(self) -> str:
        return dag_fingerprint(self.graph)

    @property
    def n_segments(self) -> int:
        return sum(1 for s in self.steps if isinstance(s, RunSegment))

    @property
    def n_merges(self) -> int:
        return sum(1 for s in self.steps if isinstance(s, MergeStep))

    @property
    def hbm_passes(self) -> int:
        return sum(
            s.plan.hbm_passes for s in self.steps if isinstance(s, RunSegment)
        ) + self.n_merges

    @property
    def hbm_passes_unfused(self) -> int:
        return sum(
            s.plan.hbm_passes_unfused
            for s in self.steps
            if isinstance(s, RunSegment)
        ) + self.n_merges

    @property
    def fingerprint(self) -> str:
        """Execution-structure identity: the DAG fingerprint plus every
        segment's resolved stage partition — the graph compile-cache key
        component, exactly the role plan.Plan.fingerprint plays for the
        chain serve cache."""
        key = self.dag_fp + "|" + self.mode + "|" + ";".join(
            f"{s.dst}<{s.src}:{s.plan.fingerprint}"
            if isinstance(s, RunSegment)
            else f"{s.dst}<{s.node.inputs[0]},{s.node.inputs[1]}:"
            f"{s.node.combinator}/k{s.node.alpha_k}"
            for s in self.steps
        )
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def describe(self) -> str:
        rows = [
            f"graph program {self.graph.name or self.dag_fp}: "
            f"{self.n_segments} segments + {self.n_merges} merges "
            f"(mode={self.mode}, hbm passes "
            f"{self.hbm_passes_unfused} -> {self.hbm_passes})"
        ]
        for s in self.steps:
            if isinstance(s, RunSegment):
                rows.append(
                    f"  seg {s.dst} <- {s.src}: {'+'.join(s.names)} "
                    f"({len(s.plan.stages)} stages)"
                )
            else:
                rows.append(
                    f"  merge {s.dst} <- {s.node.inputs[0]} "
                    f"{s.node.combinator} {s.node.inputs[1]}"
                )
        return "\n".join(rows)


def compile_graph(
    graph: PipelineGraph,
    *,
    plan: str = "auto",
    backend: str = "xla",
    width: int | None = None,
) -> GraphProgram:
    """Partition the DAG into steps; each linear segment's fusion mode
    resolves through the chain planner's calibration-aware resolution
    (per-segment `pipeline_fingerprint` lookup — chain keys carry over)."""
    consumers = graph.consumers
    out_refs = set(graph.outputs.values())
    by_id = graph.by_id

    def is_boundary(nid: str) -> bool:
        """A node whose value must materialize into the env."""
        if consumers[nid] != 1 or nid in out_refs:
            return True
        (consumer,) = (
            n for n in graph.nodes
            if (isinstance(n, OpNode) and n.input == nid)
            or (isinstance(n, MergeNode) and nid in n.inputs)
        )
        return not isinstance(consumer, OpNode)

    steps: list[Step] = []
    # op node id -> (segment source env key, ops so far) while the run is
    # still open (its nodes are interior — single-consumer, op-fed)
    open_seg: dict[str, tuple[str, list]] = {}
    resolved_mode: str | None = None
    for node in graph.nodes:
        if isinstance(node, SourceNode):
            continue
        if isinstance(node, MergeNode):
            steps.append(MergeStep(dst=node.id, node=node))
            continue
        src, ops = open_seg.pop(node.input, (node.input, []))
        ops = ops + [node.op]
        if is_boundary(node.id):
            mode = resolve_plan_mode(
                tuple(ops), plan, backend=backend, width=width
            )
            resolved_mode = resolved_mode or mode
            steps.append(
                RunSegment(
                    dst=node.id, src=src, plan=build_plan(tuple(ops), mode)
                )
            )
        else:
            open_seg[node.id] = (src, ops)
    assert not open_seg, f"unterminated segments {sorted(open_seg)}"
    # a graph of only merges/source still needs a mode label
    return GraphProgram(
        graph=graph, steps=tuple(steps), mode=resolved_mode or "off"
    )


def _stats_from_hist(hist: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """count/min/max/mean from the integer histogram — derived, so the
    whole side-output family costs one pixels pass. The mean is f32 over
    exact integer counts: deterministic (same replicated arithmetic as
    ops/histogram's Otsu moments)."""
    bins = jnp.arange(256, dtype=jnp.int32)
    total = jnp.sum(hist)
    occupied = hist > 0
    lo = jnp.min(jnp.where(occupied, bins, 256))
    hi = jnp.max(jnp.where(occupied, bins, -1))
    s = jnp.sum(hist.astype(jnp.float32) * bins.astype(jnp.float32))
    mean = s / jnp.maximum(total, 1).astype(jnp.float32)
    return {"count": total, "min": lo, "max": hi, "mean": mean}


def graph_callable(program: GraphProgram, *, impl: str = "xla", on_stage=None):
    """The full-image executor: a u8 image -> {output kind: array}
    function (jit it like any backend callable; outputs are `image` u8
    plus any declared `histogram` int32[256] / `stats` scalars).

    `on_stage(step)` fires at trace time once per executed step — the
    computed-once evidence for shared prefixes (a tap's segment appears
    exactly once in the traced program no matter how many branches read
    it)."""
    from mpi_cuda_imagemanipulation_tpu.plan.exec import run_stage_full

    graph = program.graph

    def run(img: jnp.ndarray):
        env: dict[str, jnp.ndarray] = {graph.source_id: img}
        for step in program.steps:
            if on_stage is not None:
                on_stage(step)  # python side effect => once per (re)trace
            if isinstance(step, RunSegment):
                x = env[step.src]
                for stage in step.plan.stages:
                    if stage.kind == "global":
                        x = stage.ops[0](x)
                    else:
                        x = run_stage_full(stage, x, impl)
                env[step.dst] = x
            else:
                a, b = (env[i] for i in step.node.inputs)
                env[step.dst] = merge_core(
                    step.node, exact_f32(a), exact_f32(b)
                ).astype(U8)
        out: dict[str, jnp.ndarray] = {
            "image": env[graph.outputs["image"]]
        }
        hist_node = graph.outputs.get("histogram")
        stats_node = graph.outputs.get("stats")
        # one histogram serves both side outputs when they name one node
        hists: dict[str, jnp.ndarray] = {}
        for nid in {n for n in (hist_node, stats_node) if n}:
            hists[nid] = histogram_stats(env[nid], None)
        if hist_node:
            out["histogram"] = hists[hist_node]
        if stats_node:
            out["stats"] = _stats_from_hist(hists[stats_node])
        return out

    return run
