"""Graph compilation: topo-sort + stage partition, generalizing `plan/`'s
chain fusion to fan-out/fan-in.

The compiler cuts the DAG at its *materialization boundaries* — the
source, every merge, every fan-out tap (a node with more than one
consumer), and every node a spec output names. Between boundaries each
maximal linear op run becomes one `RunSegment`, compiled by the SAME
`plan/planner.build_plan` stage rules the chain path uses (pointwise
absorption + temporal blocking; the per-segment plan mode resolves
through `resolve_plan_mode`, whose calibration lookup keys on the
segment's `pipeline_fingerprint` — so a DAG branch that equals a
calibrated chain reuses its measured plan choice unchanged). Merges are
join barriers: both inputs are materialized env values before the
combinator core runs.

Shared prefixes are computed ONCE by construction: the executor
evaluates steps in topological order into an environment keyed by node
id, so a tap's value is produced by exactly one step no matter how many
branches read it (the `on_stage` trace-time hook lets tests count this —
tests/test_graph.py's dispatch-count assertion).

Side outputs ride the same dispatch: `histogram` is the 256-bin int32
count of the named node's u8 value (`ops/histogram.histogram_stats` —
the exact additive statistic the global-stat ops psum), and `stats`
(count/min/max/mean) derives from that histogram, so one device program
produces image + histogram + stats with no second pass over the pixels.
"""

from __future__ import annotations

import dataclasses
import hashlib

import jax.numpy as jnp

from mpi_cuda_imagemanipulation_tpu.graph.ir import (
    MergeNode,
    OpNode,
    PipelineGraph,
    SourceNode,
    dag_fingerprint,
    merge_core,
)
from mpi_cuda_imagemanipulation_tpu.ops.histogram import histogram_stats
from mpi_cuda_imagemanipulation_tpu.ops.spec import U8, exact_f32
from mpi_cuda_imagemanipulation_tpu.plan.ir import Plan
from mpi_cuda_imagemanipulation_tpu.plan.planner import (
    build_plan,
    resolve_plan_mode,
)


@dataclasses.dataclass(frozen=True)
class RunSegment:
    """One maximal linear op run between materialization boundaries,
    compiled into fused stages by the chain planner."""

    dst: str  # node id whose value this segment produces
    src: str  # env key the segment reads
    plan: Plan

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(op.name for op in self.plan.ops)


@dataclasses.dataclass(frozen=True)
class MergeStep:
    """A join barrier: both inputs are materialized env values."""

    dst: str
    node: MergeNode


Step = RunSegment | MergeStep


@dataclasses.dataclass(frozen=True)
class GraphProgram:
    """A compiled graph: executable steps in topological order."""

    graph: PipelineGraph
    steps: tuple[Step, ...]
    mode: str  # the resolved build mode segments were fused with

    @property
    def dag_fp(self) -> str:
        return dag_fingerprint(self.graph)

    @property
    def n_segments(self) -> int:
        return sum(1 for s in self.steps if isinstance(s, RunSegment))

    @property
    def n_merges(self) -> int:
        return sum(1 for s in self.steps if isinstance(s, MergeStep))

    @property
    def hbm_passes(self) -> int:
        return sum(
            s.plan.hbm_passes for s in self.steps if isinstance(s, RunSegment)
        ) + self.n_merges

    @property
    def hbm_passes_unfused(self) -> int:
        return sum(
            s.plan.hbm_passes_unfused
            for s in self.steps
            if isinstance(s, RunSegment)
        ) + self.n_merges

    @property
    def fingerprint(self) -> str:
        """Execution-structure identity: the DAG fingerprint plus every
        segment's resolved stage partition — the graph compile-cache key
        component, exactly the role plan.Plan.fingerprint plays for the
        chain serve cache."""
        key = self.dag_fp + "|" + self.mode + "|" + ";".join(
            f"{s.dst}<{s.src}:{s.plan.fingerprint}"
            if isinstance(s, RunSegment)
            else f"{s.dst}<{s.node.inputs[0]},{s.node.inputs[1]}:"
            f"{s.node.combinator}/k{s.node.alpha_k}"
            for s in self.steps
        )
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def describe(self) -> str:
        rows = [
            f"graph program {self.graph.name or self.dag_fp}: "
            f"{self.n_segments} segments + {self.n_merges} merges "
            f"(mode={self.mode}, hbm passes "
            f"{self.hbm_passes_unfused} -> {self.hbm_passes})"
        ]
        for s in self.steps:
            if isinstance(s, RunSegment):
                rows.append(
                    f"  seg {s.dst} <- {s.src}: {'+'.join(s.names)} "
                    f"({len(s.plan.stages)} stages)"
                )
            else:
                rows.append(
                    f"  merge {s.dst} <- {s.node.inputs[0]} "
                    f"{s.node.combinator} {s.node.inputs[1]}"
                )
        return "\n".join(rows)


def compile_graph(
    graph: PipelineGraph,
    *,
    plan: str = "auto",
    backend: str = "xla",
    width: int | None = None,
) -> GraphProgram:
    """Partition the DAG into steps; each linear segment's fusion mode
    resolves through the chain planner's calibration-aware resolution
    (per-segment `pipeline_fingerprint` lookup — chain keys carry over)."""
    consumers = graph.consumers
    out_refs = set(graph.outputs.values())
    by_id = graph.by_id

    def is_boundary(nid: str) -> bool:
        """A node whose value must materialize into the env."""
        if consumers[nid] != 1 or nid in out_refs:
            return True
        (consumer,) = (
            n for n in graph.nodes
            if (isinstance(n, OpNode) and n.input == nid)
            or (isinstance(n, MergeNode) and nid in n.inputs)
        )
        return not isinstance(consumer, OpNode)

    steps: list[Step] = []
    # op node id -> (segment source env key, ops so far) while the run is
    # still open (its nodes are interior — single-consumer, op-fed)
    open_seg: dict[str, tuple[str, list]] = {}
    resolved_mode: str | None = None
    for node in graph.nodes:
        if isinstance(node, SourceNode):
            continue
        if isinstance(node, MergeNode):
            steps.append(MergeStep(dst=node.id, node=node))
            continue
        src, ops = open_seg.pop(node.input, (node.input, []))
        ops = ops + [node.op]
        if is_boundary(node.id):
            mode = resolve_plan_mode(
                tuple(ops), plan, backend=backend, width=width
            )
            resolved_mode = resolved_mode or mode
            steps.append(
                RunSegment(
                    dst=node.id, src=src, plan=build_plan(tuple(ops), mode)
                )
            )
        else:
            open_seg[node.id] = (src, ops)
    assert not open_seg, f"unterminated segments {sorted(open_seg)}"
    # a graph of only merges/source still needs a mode label
    return GraphProgram(
        graph=graph, steps=tuple(steps), mode=resolved_mode or "off"
    )


# --------------------------------------------------------------------------
# Stage placement (pod-level systolic execution)
# --------------------------------------------------------------------------


def split_for_placement(program: GraphProgram) -> GraphProgram:
    """The program with every multi-stage RunSegment split into one
    segment per plan stage — the canonical systolic step form.

    A linear chain compiles to ONE RunSegment (no interior
    materialization boundary), which would leave the placement pass
    nothing to cut; but the segment's plan stages each materialize u8
    anyway (`_run_step` runs `run_stage_full` per stage), so promoting
    those stage boundaries to step boundaries changes no value — it only
    names the intermediates (`dst~i`; `~` cannot appear in a spec node
    id, so synthesized keys never collide) and makes them placeable.
    Both the router (placement) and the stage owners (subrange
    executables) derive this form from the same spec with `plan='off'`,
    so step indices agree across processes with no shared state."""
    steps: list[Step] = []
    for step in program.steps:
        if (
            not isinstance(step, RunSegment)
            or len(step.plan.stages) <= 1
        ):
            steps.append(step)
            continue
        src = step.src
        n = len(step.plan.stages)
        for i, stage in enumerate(step.plan.stages):
            dst = step.dst if i == n - 1 else f"{step.dst}~{i}"
            steps.append(
                RunSegment(
                    dst=dst,
                    src=src,
                    plan=Plan(stages=(stage,), mode=step.plan.mode),
                )
            )
            src = dst
    return dataclasses.replace(program, steps=tuple(steps))


@dataclasses.dataclass(frozen=True)
class StagePlacement:
    """Contiguous step-index ranges assigned to stage-owning replicas.

    Cuts land exactly at the materialization boundaries the step
    partition already produces (every step's `dst` is an env value), so
    a cut ships only live env arrays — u8, already materialized — and
    the cross-replica handoff inherits the exact-integer carry contract
    for free. Contiguity in topological order is also the merge-barrier
    guarantee: every input of a step in range k was produced in range
    <= k, so a merge never waits on a later-placed branch."""

    ranges: tuple[tuple[int, int], ...]  # [lo, hi) step indices, topo order
    weights: tuple[float, ...]  # per-step balancer weight (bytes/pixel)
    source: str  # "measured" when any ledger record fed a weight

    @property
    def n_ranges(self) -> int:
        return len(self.ranges)

    def owner_of(self, step_idx: int) -> int:
        for k, (lo, hi) in enumerate(self.ranges):
            if lo <= step_idx < hi:
                return k
        raise IndexError(f"step {step_idx} is outside every range")

    def range_weight(self, k: int) -> float:
        lo, hi = self.ranges[k]
        return float(sum(self.weights[lo:hi]))


def partition_weights(
    weights: list[float] | tuple[float, ...], n: int
) -> tuple[tuple[int, int], ...]:
    """Contiguous partition of `weights` into `n` non-empty ranges
    minimizing the maximum range sum — the classic linear-partition DP
    (step/stage counts are tiny, so O(n * k^2) is free). Returns [lo, hi)
    index pairs covering the whole list in order."""
    k = len(weights)
    if not 1 <= n <= k:
        raise ValueError(f"cannot cut {k} weights into {n} non-empty ranges")
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + float(w))

    # best[j][i] = minimal max-range-sum splitting weights[:i] into j ranges
    best = [[float("inf")] * (k + 1) for _ in range(n + 1)]
    cut = [[0] * (k + 1) for _ in range(n + 1)]
    for i in range(1, k + 1):
        best[1][i] = prefix[i]
    for j in range(2, n + 1):
        for i in range(j, k + 1):
            for m in range(j - 1, i):
                cand = max(best[j - 1][m], prefix[i] - prefix[m])
                if cand < best[j][i]:
                    best[j][i] = cand
                    cut[j][i] = m
    bounds = [k]
    j, i = n, k
    while j > 1:
        i = cut[j][i]
        bounds.append(i)
        j -= 1
    bounds.append(0)
    bounds.reverse()
    return tuple(
        (bounds[t], bounds[t + 1]) for t in range(len(bounds) - 1)
    )


def _segment_weight(
    seg: RunSegment, c_in: int, ledger
) -> tuple[float, int, bool]:
    """One RunSegment's balancer weight in bytes per source pixel: each
    fused stage reads its u8 input once and writes its u8 output once
    (the planner's one-read-one-write model), scaled by the measured
    drift ratio when the cost ledger holds a record for that stage of
    this segment's plan (site 'plan', key = plan fingerprint, stage
    label 's<i>/<kind>' — obs/cost.attribute_plan's keying). Returns
    (weight, out_channels, measured_any)."""
    from mpi_cuda_imagemanipulation_tpu.stream.tiles import out_channels

    weight = 0.0
    measured = False
    ch = c_in
    for i, stage in enumerate(seg.plan.stages):
        try:
            ch_out = out_channels(stage.ops, ch)
        except ValueError:
            ch_out = ch
        w = float(ch + ch_out)  # u8 in + u8 out, per pixel
        ratio = None
        if ledger is not None:
            ratio = ledger.drift(
                "plan", seg.plan.fingerprint, f"s{i}/{stage.kind}"
            )
        if ratio is None:
            # no live record — the online tuning store may hold one
            # persisted by another process (tune/store; same keying)
            from mpi_cuda_imagemanipulation_tpu.tune.store import (
                persisted_io_scale,
            )

            ratio = persisted_io_scale(
                seg.plan.fingerprint, f"s{i}/{stage.kind}"
            )
        if ratio is not None and ratio > 0:
            w *= ratio
            measured = True
        weight += w
        ch = ch_out
    return weight, ch, measured


def place_steps(
    program: GraphProgram,
    n_replicas: int,
    *,
    channels: int = 3,
    ledger=None,
) -> StagePlacement | None:
    """The stage-placement pass: assign contiguous step subsets of a
    compiled program to up to `n_replicas` replicas, balanced by
    per-step boundary bytes — the measured cost-ledger record when one
    matches the segment plan's stage fingerprint, the analytical
    one-u8-read-one-u8-write model otherwise.

    Returns None when the program cannot be split usefully (fewer than
    two steps, or fewer than two replicas) — callers fall back to
    pinned-replica execution."""
    if ledger is None:
        from mpi_cuda_imagemanipulation_tpu.obs.cost import cost_ledger

        ledger = cost_ledger
    n_steps = len(program.steps)
    n = min(int(n_replicas), n_steps)
    if n < 2:
        return None
    # channel counts per env key, walked in topo order (merges preserve
    # the channel count of their inputs by the static channel check)
    ch_of: dict[str, int] = {program.graph.source_id: channels}
    weights: list[float] = []
    measured_any = False
    for step in program.steps:
        if isinstance(step, RunSegment):
            w, ch_out, m = _segment_weight(
                step, ch_of.get(step.src, channels), ledger
            )
            measured_any = measured_any or m
            ch_of[step.dst] = ch_out
            weights.append(w)
        else:
            ch = ch_of.get(step.node.inputs[0], channels)
            ch_of[step.dst] = ch
            weights.append(float(3 * ch))  # two u8 reads + one u8 write
    return StagePlacement(
        ranges=partition_weights(weights, n),
        weights=tuple(weights),
        source="measured" if measured_any else "modeled",
    )


def _stats_from_hist(hist: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """count/min/max/mean from the integer histogram — derived, so the
    whole side-output family costs one pixels pass. The mean is f32 over
    exact integer counts: deterministic (same replicated arithmetic as
    ops/histogram's Otsu moments)."""
    bins = jnp.arange(256, dtype=jnp.int32)
    total = jnp.sum(hist)
    occupied = hist > 0
    lo = jnp.min(jnp.where(occupied, bins, 256))
    hi = jnp.max(jnp.where(occupied, bins, -1))
    s = jnp.sum(hist.astype(jnp.float32) * bins.astype(jnp.float32))
    mean = s / jnp.maximum(total, 1).astype(jnp.float32)
    return {"count": total, "min": lo, "max": hi, "mean": mean}


def _run_step(step: Step, env: dict, impl: str) -> None:
    """Execute one step against the env — the single step semantics every
    executor variant (full program, systolic subrange) shares, so a cut
    program cannot drift from the pinned one."""
    from mpi_cuda_imagemanipulation_tpu.plan.exec import run_stage_full

    if isinstance(step, RunSegment):
        x = env[step.src]
        for stage in step.plan.stages:
            if stage.kind == "global":
                x = stage.ops[0](x)
            else:
                x = run_stage_full(stage, x, impl)
        env[step.dst] = x
    else:
        a, b = (env[i] for i in step.node.inputs)
        env[step.dst] = merge_core(
            step.node, exact_f32(a), exact_f32(b)
        ).astype(U8)


def graph_callable(program: GraphProgram, *, impl: str = "xla", on_stage=None):
    """The full-image executor: a u8 image -> {output kind: array}
    function (jit it like any backend callable; outputs are `image` u8
    plus any declared `histogram` int32[256] / `stats` scalars).

    `on_stage(step)` fires at trace time once per executed step — the
    computed-once evidence for shared prefixes (a tap's segment appears
    exactly once in the traced program no matter how many branches read
    it)."""
    graph = program.graph

    def run(img: jnp.ndarray):
        env: dict[str, jnp.ndarray] = {graph.source_id: img}
        for step in program.steps:
            if on_stage is not None:
                on_stage(step)  # python side effect => once per (re)trace
            _run_step(step, env, impl)
        out: dict[str, jnp.ndarray] = {
            "image": env[graph.outputs["image"]]
        }
        hist_node = graph.outputs.get("histogram")
        stats_node = graph.outputs.get("stats")
        # one histogram serves both side outputs when they name one node
        hists: dict[str, jnp.ndarray] = {}
        for nid in {n for n in (hist_node, stats_node) if n}:
            hists[nid] = histogram_stats(env[nid], None)
        if hist_node:
            out["histogram"] = hists[hist_node]
        if stats_node:
            out["stats"] = _stats_from_hist(hists[stats_node])
        return out

    return run


def live_keys_at(program: GraphProgram, cut: int) -> tuple[str, ...]:
    """Env keys a cut at step index `cut` must ship downstream: values
    produced at or before the cut (the source included) that a step in
    [cut, n) still reads, or that a declared output names. This is
    exactly the systolic handoff payload — everything else is dead at
    the boundary and never crosses the wire."""
    produced = {program.graph.source_id}
    for step in program.steps[:cut]:
        produced.add(step.dst)
    needed: set[str] = set()
    for step in program.steps[cut:]:
        if isinstance(step, RunSegment):
            needed.add(step.src)
        else:
            needed.update(step.node.inputs)
    needed.update(program.graph.outputs.values())
    return tuple(sorted(needed & produced))


def graph_sub_callable(
    program: GraphProgram, lo: int, hi: int, *, impl: str = "xla"
):
    """Executor for the step subrange [lo, hi) — one stage-owning
    replica's share of a placed program. Takes the live env dict at the
    `lo` boundary (u8 arrays keyed by node id), returns the live env at
    the `hi` boundary; when `hi` is the final step the declared outputs
    ride along under the reserved keys the full executor produces
    (`~image` / `~histogram` / `~stats` — node ids cannot collide: the
    spec id regex has no `~`). Step semantics are `_run_step`'s, so a
    split execution is bit-identical to the pinned one at every env
    materialization point."""
    if not 0 <= lo < hi <= len(program.steps):
        raise ValueError(
            f"bad step range [{lo}, {hi}) for {len(program.steps)} steps"
        )
    graph = program.graph
    final = hi == len(program.steps)

    def run(env_in: dict):
        env = dict(env_in)
        for step in program.steps[lo:hi]:
            _run_step(step, env, impl)
        if not final:
            return {k: env[k] for k in live_keys_at(program, hi)}
        out = {"~image": env[graph.outputs["image"]]}
        hist_node = graph.outputs.get("histogram")
        stats_node = graph.outputs.get("stats")
        hists: dict[str, jnp.ndarray] = {}
        for nid in {n for n in (hist_node, stats_node) if n}:
            hists[nid] = histogram_stats(env[nid], None)
        if hist_node:
            out["~histogram"] = hists[hist_node]
        if stats_node:
            out["~stats"] = _stats_from_hist(hists[stats_node])
        return out

    return run
