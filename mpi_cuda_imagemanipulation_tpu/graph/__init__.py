"""Pipelines as data — the DAG op-graph IR and the pipeline service.

`graph/` generalizes the repo's execution model from "one op chain baked
into the CLI" to "a pipeline *service*": clients POST a versioned JSON
pipeline spec (graph/spec.py) describing a DAG of ops — branch taps,
merge combinators (blend / alpha_composite / subtract), side outputs
(image + histogram + stats in one dispatch) — validated against
`ops/registry` under a CLOSED error taxonomy (malformed specs are always
4xx-class, never 500), compiled into fused linear segments by the same
Stage rules `plan/` proved on chains (graph/compile.py), and served
per-tenant with quota + QoS admission and bounded compile-cache
namespaces (graph/tenancy.py, graph/service.py).

The bit-exactness contract is the gate everywhere: a DAG that happens to
be a linear chain produces output bit-identical to the `--plan` chain
path (its `dag_fingerprint` IS that chain's `pipeline_fingerprint`, so
calibration and cache keying carry over unchanged), and every merge
combinator has golden semantics in ops/spec.py style.
"""

from mpi_cuda_imagemanipulation_tpu.graph.compile import (
    GraphProgram,
    compile_graph,
    graph_callable,
)
from mpi_cuda_imagemanipulation_tpu.graph.ir import (
    MERGE_COMBINATORS,
    PipelineGraph,
    dag_fingerprint,
)
from mpi_cuda_imagemanipulation_tpu.graph.spec import (
    SPEC_VERSION,
    TAXONOMY,
    SpecError,
    parse_spec,
)

__all__ = [
    "MERGE_COMBINATORS",
    "SPEC_VERSION",
    "TAXONOMY",
    "GraphProgram",
    "PipelineGraph",
    "SpecError",
    "compile_graph",
    "dag_fingerprint",
    "graph_callable",
    "parse_spec",
]
