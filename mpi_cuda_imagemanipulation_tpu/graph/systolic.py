"""Fabric-level systolic execution: the wire protocol and the closed
fallback vocabulary.

The pod-scale form of parallel/systolic.py: instead of devices on one
chip's mesh, the stage owners are REPLICAS, and the "ppermute" is an
HTTP hop replica-to-replica carrying the live environment slice at a
step cut. The router computes a `graph.compile.place_steps` placement,
forwards the request to the stage-0 owner with the placement map in a
header, and each owner runs its contiguous step range
(`graph_sub_callable`) then forwards the live env to the next owner's
``/v1/systolic`` endpoint. The final owner renders the response (PNG +
side-output headers) and the reply chains back up through the nested
forwards — so the transport-forward count is structurally one per stage
boundary, the fabric-path mirror of the HLO collective-permute count.

Bit-exactness across the hop is free: env values are u8 arrays (the
graph IR materialises u8 at every step boundary), serialised raw —
there is no float in flight, so the handoff cannot perturb anything.

Everything here is deliberately dependency-light (json + numpy): both
router and replica import it, and the analysis rules read the closed
vocabularies below statically.
"""

from __future__ import annotations

import io
import json

import numpy as np

# ---------------------------------------------------------------------------
# Closed vocabularies + env/header surface
# ---------------------------------------------------------------------------

# Why a request fell back to the pinned-replica lane (never a wrong
# answer — fallback IS the correct result, just not stage-sharded).
# Closed vocabulary: analysis/rules_obs.py extracts this tuple and
# checks every count_fallback() call site passes a literal member, so
# dashboards can enumerate reasons without scraping live series.
#   off            systolic mode disabled (knob accounting: every graph
#                  request is attributed to exactly one lane)
#   replicas       fewer than 2 systolic-advertising routable replicas
#   ineligible     program not stage-shardable (placement returned None:
#                  too few steps, or non-streamable structure)
#   owner_down     forward to the stage-0 owner failed (death/drain
#                  between placement and dispatch)
#   forward_failed an inter-stage hop failed mid-chain (the owner
#                  answered 424 systolic-broken)
FALLBACK_REASONS = (
    "off",
    "replicas",
    "ineligible",
    "owner_down",
    "forward_failed",
)

HDR_PLAN = "X-MCIM-Systolic-Plan"
SYSTOLIC_PATH = "/v1/systolic"

ENV_SYSTOLIC = "MCIM_SYSTOLIC"
ENV_MIN_STEPS = "MCIM_SYSTOLIC_MIN_STEPS"
ENV_AB_JSON = "MCIM_SYSTOLIC_AB_JSON"


def count_fallback(counter, reason: str) -> None:
    """The one choke point for fallback accounting — raises on a reason
    outside the closed vocabulary so a typo becomes a loud failure, not
    an unbounded label set."""
    if reason not in FALLBACK_REASONS:
        raise ValueError(
            f"unknown systolic fallback reason {reason!r}; "
            f"known: {FALLBACK_REASONS}"
        )
    counter.inc(reason=reason)


# ---------------------------------------------------------------------------
# Placement wire form (router -> stage-0 owner, in HDR_PLAN)
# ---------------------------------------------------------------------------


def encode_placement(
    *,
    tenant: str,
    pipeline: str,
    ranges,
    addrs,
    trace_id: str,
) -> str:
    """The placement map as a compact JSON header value: step ranges in
    topo order and the owner base URL for each range (index k owns
    ranges[k]). Single-line by construction (headers)."""
    return json.dumps(
        {
            "tenant": tenant,
            "pipeline": pipeline,
            "ranges": [[int(lo), int(hi)] for lo, hi in ranges],
            "addrs": list(addrs),
            "trace_id": trace_id,
        },
        separators=(",", ":"),
    )


def decode_placement(header: str) -> dict:
    d = json.loads(header)
    for field in ("tenant", "pipeline", "ranges", "addrs", "trace_id"):
        if field not in d:
            raise ValueError(f"systolic placement missing {field!r}")
    if len(d["ranges"]) != len(d["addrs"]):
        raise ValueError("systolic placement ranges/addrs length mismatch")
    d["ranges"] = [(int(lo), int(hi)) for lo, hi in d["ranges"]]
    return d


# ---------------------------------------------------------------------------
# Inter-stage handoff wire form (owner k -> owner k+1, POST body)
# ---------------------------------------------------------------------------


def encode_handoff(meta: dict, env: dict) -> bytes:
    """One self-describing frame: a JSON header line {meta, arrays:
    [{key, shape, dtype}, ...]} then the raw array bytes concatenated in
    header order. u8 env values ride byte-for-byte (bit-exactness needs
    no float discipline on the wire — there are no floats)."""
    arrays = []
    bufs = []
    for key in sorted(env):
        a = np.ascontiguousarray(env[key])
        arrays.append(
            {"key": key, "shape": list(a.shape), "dtype": str(a.dtype)}
        )
        bufs.append(a.tobytes())
    head = json.dumps(
        {"meta": meta, "arrays": arrays}, separators=(",", ":")
    ).encode("utf-8")
    out = io.BytesIO()
    out.write(head)
    out.write(b"\n")
    for b in bufs:
        out.write(b)
    return out.getvalue()


def decode_handoff(body: bytes) -> tuple[dict, dict]:
    """Inverse of encode_handoff -> (meta, env of np arrays)."""
    nl = body.find(b"\n")
    if nl < 0:
        raise ValueError("systolic handoff missing header line")
    head = json.loads(body[:nl].decode("utf-8"))
    meta = head.get("meta")
    arrays = head.get("arrays")
    if not isinstance(meta, dict) or not isinstance(arrays, list):
        raise ValueError("systolic handoff header malformed")
    env = {}
    off = nl + 1
    for spec in arrays:
        shape = tuple(int(s) for s in spec["shape"])
        dtype = np.dtype(spec["dtype"])
        n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        chunk = body[off : off + n]
        if len(chunk) != n:
            raise ValueError(
                f"systolic handoff truncated at {spec['key']!r}"
            )
        env[spec["key"]] = np.frombuffer(chunk, dtype=dtype).reshape(shape)
        off += n
    if off != len(body):
        raise ValueError("systolic handoff has trailing bytes")
    return meta, env
