"""Engine instrumentation — per-stage latencies, in-flight depth, device idle.

The numbers that tell you whether the overlap is real:

  * ``device_idle_frac`` — fraction of the engine's active window (first
    dispatch → last completion) the device spent with NOTHING enqueued.
    The serial loop's idle fraction is ≈ (decode + encode) / total; a
    working double-buffered engine drives it toward 0. Measured on the
    completion thread: any wait for a new item that starts with zero
    unforced dispatches outstanding is, by definition, device idle.
  * ``inflight`` depth — outstanding (dispatched, not yet forced) batches,
    sampled at every submit; the peak proves the pipeline actually kept
    ``--inflight`` batches in the air rather than degenerating to serial.
  * stage latencies — host input build (``build``), H2D staging (``h2d``),
    async enqueue (``enqueue``), completion force = D2H + device wait
    (``force``), encode/write worker (``encode``) — percentiles via
    `utils.timing.percentiles` (the same quantile definition the serving
    metrics and the bench suite use).

Since the obs/ fabric landed, storage is an `obs.Registry`
(`mcim_engine_*` families, stage as a label on one histogram): the
serving scheduler passes its app's registry so `/metrics` exposes engine
and serving quantities in one scrape, and `snapshot()` — the `/stats`
engine section and batch summary — is a view over the same objects.
"""

from __future__ import annotations

import threading
from collections import deque

from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry

PERCENTILES = (50, 95, 99)

STAGES = ("build", "h2d", "enqueue", "force", "encode")


class EngineMetrics:
    def __init__(self, registry: Registry | None = None,
                 sample_cap: int = 65536):
        self.registry = registry or Registry()
        r = self.registry
        self._lock = threading.Lock()
        self._submitted = r.counter(
            "mcim_engine_submitted_total", "Batches submitted to the engine."
        )
        self._completed = r.counter(
            "mcim_engine_completed_total", "Batches whose on_done finished."
        )
        self._failed = r.counter(
            "mcim_engine_failed_total", "Batches routed to on_error."
        )
        self._inflight = r.gauge(
            "mcim_engine_inflight",
            "Dispatched-but-not-yet-forced batches (gauge).",
        )
        self._inflight_peak = r.gauge(
            "mcim_engine_inflight_peak", "High-water in-flight depth."
        )
        self._idle = r.counter(
            "mcim_engine_device_idle_seconds_total",
            "Device-idle seconds inside the engine's active window.",
        )
        self._stage = r.histogram(
            "mcim_engine_stage_seconds",
            "Per-stage engine latency (build/h2d/enqueue/force/encode).",
            labels=("stage",),
            sample_cap=sample_cap,
        )
        self.t_first_dispatch: float | None = None
        self.t_last_complete: float | None = None
        self._depth: deque = deque(maxlen=sample_cap)

    # -- registry-backed readers -------------------------------------------

    @property
    def submitted(self) -> int:
        return int(self._submitted.value())

    @property
    def inflight(self) -> int:
        return int(self._inflight.value())

    @property
    def inflight_peak(self) -> int:
        return int(self._inflight_peak.value())

    @property
    def idle_s(self) -> float:
        return self._idle.value()

    # -- recording ---------------------------------------------------------

    def on_submit(self, now: float) -> None:
        with self._lock:
            self._submitted.inc()
            self._inflight.inc()
            depth = self._inflight.value()
            self._inflight_peak.set_max(depth)
            self._depth.append(depth)
            if self.t_first_dispatch is None:
                self.t_first_dispatch = now

    def on_forced(self) -> None:
        with self._lock:
            self._inflight.dec()

    def unforced(self) -> int:
        """Dispatched-but-not-forced count (the completion thread's idle
        predicate: waiting while this is 0 means the device has nothing)."""
        with self._lock:
            return int(self._inflight.value())

    def on_idle(self, seconds: float) -> None:
        self._idle.inc(seconds)

    def on_complete(self, now: float) -> None:
        with self._lock:
            self._completed.inc()
            self.t_last_complete = now

    def on_failed(self, now: float) -> None:
        with self._lock:
            self._failed.inc()
            self.t_last_complete = now

    def on_stage(
        self, stage: str, seconds: float, exemplar: str | None = None
    ) -> None:
        # exemplar: the batch's trace id — a force/encode latency spike
        # in the exposition links straight to its trace (obs/metrics.py)
        self._stage.observe(seconds, stage=stage, exemplar=exemplar)

    # -- reporting ---------------------------------------------------------

    def active_window_s(self) -> float | None:
        with self._lock:
            if self.t_first_dispatch is None or self.t_last_complete is None:
                return None
            return max(self.t_last_complete - self.t_first_dispatch, 0.0)

    def device_idle_frac(self) -> float | None:
        window = self.active_window_s()
        if not window:
            return None
        return min(max(self._idle.value() / window, 0.0), 1.0)

    def snapshot(self) -> dict:
        idle = self.device_idle_frac()
        with self._lock:
            mean_depth = (
                sum(self._depth) / len(self._depth) if self._depth else None
            )
        return {
            "submitted": int(self._submitted.value()),
            "completed": int(self._completed.value()),
            "failed": int(self._failed.value()),
            "inflight": int(self._inflight.value()),
            "inflight_peak": int(self._inflight_peak.value()),
            "inflight_mean": mean_depth,
            "device_idle_frac": idle,
            "idle_s": self._idle.value(),
            "stages": {
                s: self._stage.percentiles_ms(PERCENTILES, stage=s)
                for s in STAGES
            },
        }

    def summary_line(self) -> str:
        s = self.snapshot()
        idle = s["device_idle_frac"]
        forced = s["stages"]["force"] or {}
        return (
            f"engine: {s['completed']}/{s['submitted']} batches "
            f"({s['failed']} failed), inflight peak {s['inflight_peak']}"
            + (f", device idle {idle * 100:.0f}%" if idle is not None else "")
            + (
                f", force p50 {forced['p50_ms']:.1f} ms"
                if forced
                else ""
            )
        )
