"""Engine instrumentation — per-stage latencies, in-flight depth, device idle.

The numbers that tell you whether the overlap is real:

  * ``device_idle_frac`` — fraction of the engine's active window (first
    dispatch → last completion) the device spent with NOTHING enqueued.
    The serial loop's idle fraction is ≈ (decode + encode) / total; a
    working double-buffered engine drives it toward 0. Measured on the
    completion thread: any wait for a new item that starts with zero
    unforced dispatches outstanding is, by definition, device idle.
  * ``inflight`` depth — outstanding (dispatched, not yet forced) batches,
    sampled at every submit; the peak proves the pipeline actually kept
    ``--inflight`` batches in the air rather than degenerating to serial.
  * stage latencies — host input build (``build``), H2D staging (``h2d``),
    async enqueue (``enqueue``), completion force = D2H + device wait
    (``force``), encode/write worker (``encode``) — percentiles via
    `utils.timing.percentiles` (the same quantile definition the serving
    metrics and the bench suite use).

Counters + bounded reservoirs behind one lock, `snapshot()` for /stats and
the batch summary — same conventions as serve/metrics.ServeMetrics.
"""

from __future__ import annotations

import threading
from collections import deque

from mpi_cuda_imagemanipulation_tpu.utils.timing import percentiles

PERCENTILES = (50, 95, 99)

STAGES = ("build", "h2d", "enqueue", "force", "encode")


class EngineMetrics:
    def __init__(self, sample_cap: int = 65536):
        self._lock = threading.Lock()
        self.submitted = 0  # batches submitted to the engine
        self.completed = 0  # batches whose on_done finished
        self.failed = 0  # batches routed to on_error
        self.inflight = 0  # gauge: dispatched, not yet forced
        self.inflight_peak = 0
        self.idle_s = 0.0  # device-idle seconds inside the active window
        self.t_first_dispatch: float | None = None
        self.t_last_complete: float | None = None
        self._stage: dict[str, deque] = {
            s: deque(maxlen=sample_cap) for s in STAGES
        }
        self._depth: deque = deque(maxlen=sample_cap)

    # -- recording ---------------------------------------------------------

    def on_submit(self, now: float) -> None:
        with self._lock:
            self.submitted += 1
            self.inflight += 1
            self.inflight_peak = max(self.inflight_peak, self.inflight)
            self._depth.append(self.inflight)
            if self.t_first_dispatch is None:
                self.t_first_dispatch = now

    def on_forced(self) -> None:
        with self._lock:
            self.inflight -= 1

    def unforced(self) -> int:
        """Dispatched-but-not-forced count (the completion thread's idle
        predicate: waiting while this is 0 means the device has nothing)."""
        with self._lock:
            return self.inflight

    def on_idle(self, seconds: float) -> None:
        with self._lock:
            self.idle_s += seconds

    def on_complete(self, now: float) -> None:
        with self._lock:
            self.completed += 1
            self.t_last_complete = now

    def on_failed(self, now: float) -> None:
        with self._lock:
            self.failed += 1
            self.t_last_complete = now

    def on_stage(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._stage[stage].append(seconds)

    # -- reporting ---------------------------------------------------------

    @staticmethod
    def _pcts(samples) -> dict[str, float] | None:
        if not samples:
            return None
        got = percentiles(samples, PERCENTILES)
        return {f"p{int(q)}_ms": got[q] * 1e3 for q in PERCENTILES}

    def active_window_s(self) -> float | None:
        with self._lock:
            if self.t_first_dispatch is None or self.t_last_complete is None:
                return None
            return max(self.t_last_complete - self.t_first_dispatch, 0.0)

    def device_idle_frac(self) -> float | None:
        window = self.active_window_s()
        if not window:
            return None
        with self._lock:
            return min(max(self.idle_s / window, 0.0), 1.0)

    def snapshot(self) -> dict:
        idle = self.device_idle_frac()
        with self._lock:
            mean_depth = (
                sum(self._depth) / len(self._depth) if self._depth else None
            )
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "inflight": self.inflight,
                "inflight_peak": self.inflight_peak,
                "inflight_mean": mean_depth,
                "device_idle_frac": idle,
                "idle_s": self.idle_s,
                "stages": {s: self._pcts(self._stage[s]) for s in STAGES},
            }

    def summary_line(self) -> str:
        s = self.snapshot()
        idle = s["device_idle_frac"]
        forced = s["stages"]["force"] or {}
        return (
            f"engine: {s['completed']}/{s['submitted']} batches "
            f"({s['failed']} failed), inflight peak {s['inflight_peak']}"
            + (f", device idle {idle * 100:.0f}%" if idle is not None else "")
            + (
                f", force p50 {forced['p50_ms']:.1f} ms"
                if forced
                else ""
            )
        )
