"""Asynchronous double-buffered execution engine (docs/design.md
"Execution engine & overlap"): bounded in-flight dispatch, in-order
completion draining, encode/write worker pool — shared by `batch
--inflight` (cli.py) and the serving scheduler (serve/scheduler.py)."""

from mpi_cuda_imagemanipulation_tpu.engine.core import (
    DEFAULT_INFLIGHT,
    DEFAULT_IO_THREADS,
    Engine,
)
from mpi_cuda_imagemanipulation_tpu.engine.metrics import EngineMetrics

__all__ = [
    "DEFAULT_INFLIGHT",
    "DEFAULT_IO_THREADS",
    "Engine",
    "EngineMetrics",
]
