"""Bounded-depth asynchronous execution engine — keep the device busy
while the host decodes, transfers, and encodes.

The serial offline/serving loops run decode → dispatch → force → encode
with the device idle during every host phase (the same structure as the
reference's per-launch MPI scatter/compute/gather round-trip,
kernel.cu:163,202). JAX dispatch is asynchronous: a jitted call returns a
future-like device array immediately, so the fix is structural, not a new
kernel — software-pipeline the stages over consecutive work items:

    caller thread            completion thread         encode pool
    ─────────────            ─────────────────         ───────────
    make_input (host build)
    stage      (H2D ahead)   ┌───────────────┐
    run        (async enq) ─►│ bounded FIFO  │─► force (D2H, in
                 ▲           │ (≤ inflight)  │   submission order)
                 │           └───────────────┘      │
                 └── blocks when full ◄─────────────┴─► on_done(key, out)
                     (backpressure)                     [≤ io_threads,
                                                         bounded backlog]

Invariants:

  * **Bounded everywhere.** At most ``inflight`` dispatches are
    outstanding: a dispatch slot is reserved before the computation
    enqueues and released when its result is forced, so acquiring it
    blocks the caller — the backpressure that keeps host decode from
    racing ahead of the device. The encode pool's backlog is capped by a
    semaphore so a slow writer stalls the completion thread rather than
    buffering results without bound.
  * **Completion in submission order.** The FIFO is drained in order:
    results are forced (and handed to the pool) exactly in submission
    order even though the device pipeline is deep. ``on_done`` callbacks
    for *different* items may interleave across pool workers
    (``io_threads=1`` serializes them); items are independent by contract.
    ``ordered_done=True`` — the tile-stream submission mode — instead
    gates pool delivery so ``on_done`` runs strictly in submission order
    (an incremental encoder can only append row band k after k-1);
    failed items advance the gate so a bad tile never wedges the stream.
  * **Results are bit-identical to the serial loop** — the engine changes
    *when* work happens, never *what* runs: same callable, same inputs.
  * **Failure is per-item.** A force (D2H) failure routes that one
    submission to ``on_error`` on the completion thread (where callers run
    their retry/quarantine machinery — serve/scheduler.py) and the
    pipeline keeps draining; an ``on_done`` failure (encode/write) routes
    to ``on_error`` on the pool worker. The armed ``engine.complete``
    failpoint (resilience/failpoints.py) injects exactly this class of
    fault for the tier-1 recovery tests.

Donation note: pair the engine with ``Pipeline.jit(donate=True)`` /
``Pipeline.batched(donate=True)`` so each dispatch's input buffer is
recycled into its output and steady state runs without per-batch HBM
allocation. Safe here by construction — every ``make_input`` builds (or
stages) a fresh buffer per submission; never donate a buffer you intend
to read again.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable

from mpi_cuda_imagemanipulation_tpu.engine.metrics import EngineMetrics
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

DEFAULT_INFLIGHT = 2
DEFAULT_IO_THREADS = 4

_SENTINEL = object()


@dataclass
class _InFlight:
    key: Any
    out: Any  # un-forced device result (JAX async dispatch future)
    on_done: Callable[[Any, Any, dict], None]
    on_error: Callable[[Any, BaseException], None]
    info: dict = field(default_factory=dict)
    seq: int = 0  # submission index (ordered_done delivery gate)


class Engine:
    """The shared async pipeline behind ``batch --inflight`` and the
    serving scheduler. One instance owns one completion thread and one
    encode pool; ``submit`` is single-producer by convention (the batch
    loop / the scheduler thread), completions fan out to the pool."""

    def __init__(
        self,
        *,
        inflight: int = DEFAULT_INFLIGHT,
        io_threads: int = DEFAULT_IO_THREADS,
        stage: Callable[[Any], Any] | None = None,
        metrics: EngineMetrics | None = None,
        name: str = "engine",
        ordered_done: bool = False,
    ):
        if inflight < 1:
            raise ValueError(f"inflight must be >= 1, got {inflight}")
        if io_threads < 1:
            raise ValueError(f"io_threads must be >= 1, got {io_threads}")
        self.inflight = inflight
        self.io_threads = io_threads
        # H2D staging hook (e.g. jax.device_put): runs on the caller thread
        # ahead of dispatch so the transfer is already in flight when the
        # computation enqueues. None = inputs go up with the dispatch
        # (sharded/data-parallel callables place their own inputs).
        self._stage = stage
        self.metrics = metrics or EngineMetrics()
        self.name = name
        # the in-flight bound: a dispatch slot is reserved BEFORE the
        # computation enqueues and released once its result is forced, so
        # at most `inflight` dispatches are ever outstanding on the device
        # (the completion FIFO itself never exceeds that)
        self._slots = threading.BoundedSemaphore(inflight)
        self._q: queue.Queue = queue.Queue()
        self._pool: ThreadPoolExecutor | None = None
        # encode backlog bound: a slow writer blocks the completion thread
        # (and transitively the submitter) instead of buffering results
        self._encode_slots = threading.BoundedSemaphore(
            max(2 * io_threads, inflight)
        )
        self._outstanding = 0  # submitted, not yet fully resolved
        self._cond = threading.Condition()
        self._thread: threading.Thread | None = None
        self._closed = False
        self._log = get_logger()
        # ordered_done: deliver on_done strictly in submission order (the
        # tile-stream mode — an incremental encoder can only append row
        # band k after k-1). Results are already FORCED in submission
        # order; this gate additionally serialises the pool's delivery.
        # Deadlock-free: the completion thread hands items to the FIFO
        # pool in order, so the lowest outstanding seq is always running
        # or queued ahead of every waiter.
        self._ordered = ordered_done
        self._seq = 0
        self._next_done = 0
        self._order_cond = threading.Condition()

    # -- lifecycle ---------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    def _ensure_started(self) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError(f"{self.name} is closed")
            if self._thread is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.io_threads,
                    thread_name_prefix=f"mcim-{self.name}-io",
                )
                self._thread = threading.Thread(
                    target=self._completion_loop,
                    name=f"mcim-{self.name}-complete",
                    daemon=True,
                )
                self._thread.start()

    def flush(self, timeout: float | None = None) -> bool:
        """Block until every submitted item has fully resolved (on_done or
        on_error returned). True on drained, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._outstanding > 0:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def close(self, timeout: float | None = None) -> None:
        """Drain, then stop the completion thread and the encode pool.
        Idempotent; safe to call with work in flight (it finishes first)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
        drained = self.flush(timeout)
        if self._thread is not None:
            self._q.put(_SENTINEL)
            self._thread.join(timeout=timeout)
        if self._pool is not None:
            # a timed-out drain must not hang interpreter exit on a wedged
            # writer; the pool threads are abandoned (daemonic teardown)
            self._pool.shutdown(wait=drained)
        if not drained:
            self._log.warning(
                "%s: close timed out with %d submissions unresolved",
                self.name, self._outstanding,
            )

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- dispatch stage (caller thread) ------------------------------------

    def submit(
        self,
        key: Any,
        make_input: Callable[[], Any],
        run: Callable[[Any], Any],
        *,
        on_done: Callable[[Any, Any, dict], None],
        on_error: Callable[[Any, BaseException], None],
    ) -> None:
        """Build + stage + asynchronously dispatch one work item.

        ``make_input()`` and ``run(staged_input)`` execute on the calling
        thread — ``run`` must only *enqueue* (a jitted call under JAX async
        dispatch); its exceptions (host-side dispatch failures, armed
        failpoints) propagate to the caller, which still owns retry policy
        at this stage. After a successful enqueue the item is handed to the
        completion thread; blocks while ``inflight`` items are outstanding.

        ``on_done(key, host_out, info)`` runs on the encode pool;
        ``on_error(key, exc)`` runs on the completion thread (force
        failures) or the pool worker (``on_done`` failures). ``info``
        carries the item's stage timings (seconds): build/h2d/enqueue at
        submit, queue_wait/force stamped at completion."""
        self._ensure_started()
        info: dict = {}
        t0 = time.perf_counter()
        x = make_input()
        t1 = time.perf_counter()
        if self._stage is not None:
            # H2D can start NOW even when every dispatch slot is taken —
            # the upload overlaps the in-flight compute
            x = self._stage(x)
        t2 = time.perf_counter()
        # backpressure: all `inflight` slots taken means the device already
        # has that many dispatches outstanding — stall the producer here,
        # before it enqueues (and before it decodes further upstream)
        self._slots.acquire()
        try:
            out = run(x)
        except BaseException:
            self._slots.release()
            raise
        t3 = time.perf_counter()
        info["build_s"] = t1 - t0
        info["h2d_s"] = t2 - t1
        info["enqueue_s"] = t3 - t2
        info["t_dispatch"] = t3
        # trace parentage hops threads with the item: the caller's active
        # span (the serving dispatch span / a batch root) anchors the
        # completion thread's force span and the pool's encode span
        info["trace"] = obs_trace.current_context()
        self.metrics.on_stage("build", info["build_s"])
        self.metrics.on_stage("h2d", info["h2d_s"])
        self.metrics.on_stage("enqueue", info["enqueue_s"])
        with self._cond:
            self._outstanding += 1
            seq = self._seq
            self._seq += 1
        self.metrics.on_submit(t3)
        self._q.put(_InFlight(key, out, on_done, on_error, info, seq))

    # -- completion stage (own thread) -------------------------------------

    def _completion_loop(self) -> None:
        while True:
            idle_from = (
                time.perf_counter()
                if self.metrics.unforced() == 0
                else None
            )
            item = self._q.get()
            if item is _SENTINEL:
                return
            if idle_from is not None and self.metrics.submitted > 0:
                # nothing was enqueued on the device while we waited: that
                # whole wait is device-idle time (the serial loop's decode/
                # encode stalls show up exactly here)
                self.metrics.on_idle(time.perf_counter() - idle_from)
            self._complete_one(item)

    def _complete_one(self, item: _InFlight) -> None:
        t0 = time.perf_counter()
        item.info["queue_wait_s"] = t0 - item.info["t_dispatch"]
        fspan = obs_trace.span(
            "engine.force", parent=item.info.get("trace")
        )
        try:
            # injected completion-stage fault (D2H/transfer class) — the
            # recovery paths behind it are the caller's on_error machinery
            failpoints.maybe_fail("engine.complete", key=item.key)
            host = self._force(item.out)
        except Exception as e:
            fspan.set(error=type(e).__name__)
            fspan.end()
            self.metrics.on_forced()
            self._slots.release()
            self.metrics.on_failed(time.perf_counter())
            # an item that dies before the pool must still advance the
            # ordered-delivery gate, or every later tile waits forever
            self._advance_order(item)
            self._resolve_error(item, e)
            return
        fspan.end()
        t1 = time.perf_counter()
        item.info["force_s"] = t1 - t0
        self.metrics.on_forced()
        self._slots.release()
        ctx = item.info.get("trace")
        self.metrics.on_stage(
            "force", item.info["force_s"],
            exemplar=ctx.trace_id if ctx is not None and ctx.sampled else None,
        )
        self._encode_slots.acquire()
        assert self._pool is not None
        try:
            self._pool.submit(self._encode_one, item, host)
        except BaseException:
            self._encode_slots.release()
            raise

    @staticmethod
    def _force(out):
        """Block for the device result and bring it to host memory (D2H).
        `jax.device_get` walks pytrees and passes numpy through, so `run`
        may return device arrays, tuples of them, or host arrays."""
        import jax

        return jax.device_get(out)

    # -- encode stage (worker pool) ----------------------------------------

    def _wait_turn(self, item: _InFlight) -> None:
        """Block until every earlier submission's on_done has resolved
        (ordered_done mode). Runs on a pool worker; the lock is released
        before on_done runs, so user callbacks never execute under it."""
        with self._order_cond:
            while item.seq != self._next_done:
                self._order_cond.wait()

    def _advance_order(self, item: _InFlight) -> None:
        if not self._ordered:
            return
        with self._order_cond:
            self._next_done = max(self._next_done, item.seq + 1)
            self._order_cond.notify_all()

    def _encode_one(self, item: _InFlight, host) -> None:
        if self._ordered:
            self._wait_turn(item)
        t0 = time.perf_counter()
        try:
            # entered (not just timed) so the caller's on_done — response
            # crop/resolve, file encode/write — nests under engine.encode
            with obs_trace.span(
                "engine.encode", parent=item.info.get("trace")
            ):
                item.on_done(item.key, host, item.info)
        except Exception as e:
            self.metrics.on_failed(time.perf_counter())
            self._resolve_error(item, e)
            return
        finally:
            self._advance_order(item)
            self._encode_slots.release()
            self.metrics.on_stage("encode", time.perf_counter() - t0)
        self.metrics.on_complete(time.perf_counter())
        self._mark_resolved()

    def _resolve_error(self, item: _InFlight, exc: BaseException) -> None:
        try:
            item.on_error(item.key, exc)
        except Exception:
            self._log.exception(
                "%s: on_error handler failed for %r", self.name, item.key
            )
        finally:
            self._mark_resolved()

    def _mark_resolved(self) -> None:
        with self._cond:
            self._outstanding -= 1
            self._cond.notify_all()
