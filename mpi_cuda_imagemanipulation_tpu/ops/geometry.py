"""Geometric ops: flip, rotate, transpose, crop, pad, resize.

The reference contains no geometric transforms (its only ops are the three
point/stencil kernels, kernel.cu:31-94); this module extends the framework
beyond parity with the standard image-geometry toolkit, built TPU-first:

  * flips / rotations / transpose / crop are pure data movement — XLA lowers
    them to layout changes and (under a sharded input) the minimal
    collective permutes, so they cost ~one HBM pass;
  * resize is 4-tap bilinear with 8-bit fixed-point weights precomputed
    host-side in float64 — chosen so every device-side f32 product and sum
    is an exact integer (< 2^24) and therefore identical on every platform,
    backend and sharding (see `_linear_taps`); the device work is four
    `jnp.take` gathers and one fused weighted sum.

Half-pixel center convention (``src = (dst + 0.5) * in/out - 0.5``), the
same sampling grid OpenCV's ``INTER_LINEAR`` and PIL's ``BILINEAR`` use;
edge taps clamp (edge-replicate). Nearest mode rounds the same grid down.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from mpi_cuda_imagemanipulation_tpu.ops.spec import F32, U8, GeometricOp, rint_clip_f32

# --------------------------------------------------------------------------
# Data-movement ops
# --------------------------------------------------------------------------

FLIP_H = GeometricOp("fliph", lambda img: img[:, ::-1])
FLIP_V = GeometricOp("flipv", lambda img: img[::-1])
TRANSPOSE = GeometricOp("transpose", lambda img: jnp.swapaxes(img, 0, 1))

# clockwise rotations, named by angle
ROT90 = GeometricOp("rot90", lambda img: jnp.swapaxes(img, 0, 1)[:, ::-1])
ROT180 = GeometricOp("rot180", lambda img: img[::-1, ::-1])
ROT270 = GeometricOp("rot270", lambda img: jnp.swapaxes(img, 0, 1)[::-1])


def make_crop(y0: int, x0: int, h: int, w: int) -> GeometricOp:
    if h <= 0 or w <= 0 or y0 < 0 or x0 < 0:
        raise ValueError(f"invalid crop y0={y0} x0={x0} h={h} w={w}")

    def fn(img: jnp.ndarray) -> jnp.ndarray:
        ih, iw = img.shape[0], img.shape[1]
        if y0 + h > ih or x0 + w > iw:
            raise ValueError(
                f"crop [{y0}:{y0 + h}, {x0}:{x0 + w}] exceeds image {ih}x{iw}"
            )
        return img[y0 : y0 + h, x0 : x0 + w]

    return GeometricOp(f"crop{y0}_{x0}_{h}_{w}", fn)


_PAD_NP_MODES = {"zero": "constant", "reflect101": "reflect", "edge": "edge"}


def make_pad(n: int, mode: str = "zero") -> GeometricOp:
    if n <= 0:
        raise ValueError(f"pad amount must be positive, got {n}")
    if mode not in _PAD_NP_MODES:
        raise ValueError(f"unknown pad mode {mode!r}; known: {sorted(_PAD_NP_MODES)}")

    def fn(img: jnp.ndarray) -> jnp.ndarray:
        pads = ((n, n), (n, n)) + ((0, 0),) * (img.ndim - 2)
        return jnp.pad(img, pads, mode=_PAD_NP_MODES[mode])

    return GeometricOp(f"pad{n}_{mode}", fn)


# --------------------------------------------------------------------------
# Resize
# --------------------------------------------------------------------------


WEIGHT_BITS = 8  # fixed-point lerp weight resolution (0..256)
_WEIGHT_ONE = float(1 << WEIGHT_BITS)


def _linear_taps(in_len: int, out_len: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Two-tap source indices (lo, hi) and the hi-tap weight for one axis,
    computed in float64 on the host (static constants under jit).

    Weights are quantized to 8-bit fixed point (w1 in 0..256, w0 = 256-w1),
    the OpenCV-style scheme, for a stronger reason than speed: with u8
    pixels, every product pixel·wy·wx <= 255·2^16 < 2^24 and the 4-tap sum
    <= 255·2^16 are *exactly representable* in f32, and the final scale is
    a power of two — so the whole interpolation incurs zero rounding until
    the last rint, making the result immune to FMA contraction (TPU fuses
    a+(b-a)·t into an FMA with different rounding than CPU; observed ±1
    diffs) and bit-identical on every platform and sharding."""
    centers = (np.arange(out_len, dtype=np.float64) + 0.5) * (in_len / out_len) - 0.5
    lo = np.floor(centers)
    w1 = np.rint((centers - lo) * _WEIGHT_ONE).astype(np.float32)
    lo_c = np.clip(lo, 0, in_len - 1).astype(np.int32)
    hi_c = np.clip(lo + 1, 0, in_len - 1).astype(np.int32)
    return lo_c, hi_c, w1


def _nearest_index(in_len: int, out_len: int) -> np.ndarray:
    centers = (np.arange(out_len, dtype=np.float64) + 0.5) * (in_len / out_len)
    return np.clip(np.floor(centers), 0, in_len - 1).astype(np.int32)


def _resize_fn(out_h: int, out_w: int, method: str):
    def fn(img: jnp.ndarray) -> jnp.ndarray:
        th = out_h
        tw = out_w
        if (th, tw) == img.shape[:2]:
            return img
        if method == "nearest":
            ys = jnp.asarray(_nearest_index(img.shape[0], th))
            xs = jnp.asarray(_nearest_index(img.shape[1], tw))
            return jnp.take(jnp.take(img, ys, axis=0), xs, axis=1)
        ylo, yhi, wy1 = _linear_taps(img.shape[0], th)
        xlo, xhi, wx1 = _linear_taps(img.shape[1], tw)
        xf = img.astype(F32)
        r0 = jnp.take(xf, jnp.asarray(ylo), axis=0)
        r1 = jnp.take(xf, jnp.asarray(yhi), axis=0)
        a00 = jnp.take(r0, jnp.asarray(xlo), axis=1)
        a01 = jnp.take(r0, jnp.asarray(xhi), axis=1)
        a10 = jnp.take(r1, jnp.asarray(xlo), axis=1)
        a11 = jnp.take(r1, jnp.asarray(xhi), axis=1)
        yshape = (th, 1) + (1,) * (img.ndim - 2)
        xshape = (1, tw) + (1,) * (img.ndim - 2)
        wy1_b = jnp.asarray(wy1).reshape(yshape)
        wx1_b = jnp.asarray(wx1).reshape(xshape)
        wy0_b = np.float32(_WEIGHT_ONE) - wy1_b
        wx0_b = np.float32(_WEIGHT_ONE) - wx1_b
        # every product and partial sum below is an exact f32 integer
        acc = (a00 * (wy0_b * wx0_b) + a01 * (wy0_b * wx1_b)) + (
            a10 * (wy1_b * wx0_b) + a11 * (wy1_b * wx1_b)
        )
        acc = acc * np.float32(1.0 / (_WEIGHT_ONE * _WEIGHT_ONE))
        return rint_clip_f32(acc).astype(U8)

    return fn


def make_resize(out_h: int, out_w: int, method: str = "bilinear") -> GeometricOp:
    if out_h <= 0 or out_w <= 0:
        raise ValueError(f"invalid resize target {out_h}x{out_w}")
    if method not in ("bilinear", "nearest"):
        raise ValueError(f"unknown resize method {method!r}")
    return GeometricOp(f"resize{out_h}x{out_w}_{method}", _resize_fn(out_h, out_w, method))


def make_scale(factor: float, method: str = "bilinear") -> GeometricOp:
    """Resize by a scale factor; the target shape is derived from the input
    inside `fn` (static under jit — shapes are trace-time constants)."""
    if factor <= 0:
        raise ValueError(f"scale factor must be positive, got {factor}")
    if method not in ("bilinear", "nearest"):
        raise ValueError(f"unknown resize method {method!r}")

    def fn(img: jnp.ndarray) -> jnp.ndarray:
        th = max(1, int(round(img.shape[0] * factor)))
        tw = max(1, int(round(img.shape[1] * factor)))
        return _resize_fn(th, tw, method)(img)

    return GeometricOp(f"scale{factor:g}_{method}", fn)


def make_rot90(angle: int) -> GeometricOp:
    ops = {90: ROT90, 180: ROT180, 270: ROT270}
    if angle not in ops:
        raise ValueError(f"rotation must be 90/180/270 degrees, got {angle}")
    return ops[angle]


def _rotate_maps(h: int, w: int, angle_deg: float, method: str):
    """Host-side sampling maps for a same-size rotation about the image
    centre (counter-clockwise positive, the OpenCV getRotationMatrix2D
    convention; out-of-image samples read the constant border 0, the
    warpAffine default). Returns static index/weight arrays; weights use
    the same 8-bit fixed-point scheme as _linear_taps, so every product and
    partial sum is an exact f32 integer and the result is bit-identical on
    every platform and sharding."""
    th = np.deg2rad(angle_deg)
    cos, sin = np.cos(th), np.sin(th)
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
    # inverse map: source position for each output pixel (ccw rotation of
    # the image = cw rotation of the sampling grid)
    dy, dx = yy - cy, xx - cx
    sy = cos * dy + sin * dx + cy
    sx = -sin * dy + cos * dx + cx
    if method == "nearest":
        iy = np.rint(sy).astype(np.int64)
        ix = np.rint(sx).astype(np.int64)
        inside = (iy >= 0) & (iy < h) & (ix >= 0) & (ix < w)
        flat = np.clip(iy, 0, h - 1) * w + np.clip(ix, 0, w - 1)
        return (flat.astype(np.int32), inside.astype(np.float32))
    ylo = np.floor(sy)
    xlo = np.floor(sx)
    wy1 = np.rint((sy - ylo) * _WEIGHT_ONE).astype(np.float32)
    wx1 = np.rint((sx - xlo) * _WEIGHT_ONE).astype(np.float32)
    taps = []
    for oy, wy in ((0, _WEIGHT_ONE - wy1), (1, wy1)):
        for ox, wx in ((0, _WEIGHT_ONE - wx1), (1, wx1)):
            ty, tx = ylo + oy, xlo + ox
            inside = (ty >= 0) & (ty < h) & (tx >= 0) & (tx < w)
            flat = np.clip(ty, 0, h - 1) * w + np.clip(tx, 0, w - 1)
            # border-0 samples: zero the tap weight instead of the value
            taps.append(
                (flat.astype(np.int32), (wy * wx * inside).astype(np.float32))
            )
    return taps


def make_rotate(angle_deg: float, method: str = "bilinear") -> GeometricOp:
    """Arbitrary-angle rotation (the cv2.warpAffine/getRotationMatrix2D
    analogue — beyond-parity; the reference has only the implicit identity).
    Same-size output about the centre, constant-0 border, counter-clockwise
    positive like PIL/OpenCV (rotate:90 therefore equals the ROT270 named
    op, whose name follows the transpose-flip construction, not PIL's
    convention). Data movement is 4 static flat gathers + an exact
    fixed-point lerp (see _rotate_maps), running at the jit level between
    shard_map segments like every geometric op."""
    if method not in ("bilinear", "nearest"):
        raise ValueError(f"unknown rotate method {method!r}")
    if not np.isfinite(angle_deg):
        raise ValueError(f"rotate angle must be finite, got {angle_deg}")

    def fn(img: jnp.ndarray) -> jnp.ndarray:
        h, w = img.shape[:2]
        if h * w >= 2**31:  # flat-index gather would wrap in int32
            raise ValueError(
                f"rotate supports images up to 2^31 pixels, got {h}x{w}"
            )
        flat = img.reshape((h * w,) + img.shape[2:]).astype(F32)
        maps = _rotate_maps(h, w, angle_deg, method)
        wshape = (h, w) + (1,) * (img.ndim - 2)
        if method == "nearest":
            idx, inside = maps
            vals = jnp.take(flat, jnp.asarray(idx).ravel(), axis=0)
            vals = vals.reshape((h, w) + img.shape[2:])
            return (vals * jnp.asarray(inside).reshape(wshape)).astype(U8)
        acc = None
        for idx, wt in maps:
            vals = jnp.take(flat, jnp.asarray(idx).ravel(), axis=0)
            vals = vals.reshape((h, w) + img.shape[2:])
            term = vals * jnp.asarray(wt).reshape(wshape)
            acc = term if acc is None else acc + term
        acc = acc * np.float32(1.0 / (_WEIGHT_ONE * _WEIGHT_ONE))
        return rint_clip_f32(acc).astype(U8)

    return GeometricOp(f"rotate{angle_deg:g}_{method}", fn)
