"""Production SWAR quarter-strip streaming backend (``impl='swar'``).

Promotion of the tools/swar_proto.py design into the framework, gated
behind an explicit backend choice (it joins ``auto`` routing only after an
on-chip win; see BASELINE.md round-4 pre-registered predictions).

Why this exists (the round-3 roofline result, BASELINE.md): u8 streaming on
v5e is element-rate-capped (~95-100 Ge/s) at ~1/4 of the f32 byte rate, and
the u8 production kernels already sit at ~94% of that ceiling — so the only
way past it is fewer, wider elements. The first packed attempt
(tools/packed_kernels.py, demoted round 5) moves u32 words but unpacks every word into four
f32 lane planes in-kernel, paying the full element count *plus* shift
overhead; it measured 3.2x slower. SWAR is the design that actually banks
the element saving:

1. **Quarter-strip (SoA) packing**: the padded row is split into 4 equal
   strips; byte k of word j is strip k's pixel j. A horizontal stencil tap
   is then a plain word-column shift for all four strips at once — no
   cross-lane byte algebra (the packed layout's fatal cost).
2. **16-bit SWAR fields**: each word splits once into two u32 arrays
   holding 2x16-bit fields (bytes 0,2 and 1,3). The separable correlation
   runs as integer mul/add on those fields — 2 pixels per 32-bit element,
   half the VPU element count of f32 compute — exactly: for integer taps
   with sum S, row accumulators are <= 255*S, so S <= 128 keeps every
   field (and its i32 view) in range. The column pass then runs in one of
   two modes (``_swar_mode``):

   * **narrow** (S a power of two, S <= 16 — the binomial Gaussians 3/5):
     column accumulators <= 255*S^2 <= 65280 stay inside the 16-bit
     fields, and the final x S^-2 with round-half-to-even is the integer
     identity q = (s + (S^2/2 - 1) + (q0 & 1)) >> k with q0 = s >> k,
     k = log2(S^2) — bit-identical to the golden ``rint_clip`` quantize
     (clipping is vacuous: a weighted mean of u8 values is in [0, 255]).
   * **wide** (everything else — gaussian:7 with S = 64, whose column
     sums overflow 16-bit fields, and the box family, whose S^2 is not a
     power of two): the row-passed fields widen to one pixel per i32
     lane for the column pass (row-pass element saving kept; column pass
     at full element count), and quantization REPLAYS the golden float
     ops on the exact integer sums — ``f32(s) * np.float32(scale)`` then
     ``rint_clip`` — so it is bit-exact by construction for ANY scale,
     power of two or not. Exactness needs the column sums representable
     in f32: 255*S^2 < 2^24 (S <= 128 satisfies it). The i32 shift/mask/
     convert idiom mirrors tools/packed_kernels.py's Mosaic-native lane
     algebra.

Separable eligibility (``swar_eligible``): single-plane u8 (H, W) with
W % 4 == 0, StencilOp with ``reduce='corr'``, ``combine='single'``, an
integer non-negative odd-length separable vector with sum 2 <= S <= 128,
``scale == 1/S^2``, ``quantize='rint_clip'``, and a real border extension.
In the registry that is the binomial Gaussians 3/5/7 and the odd box
filters.

A third kernel covers the non-separable integer family
(``swar_corr2d_eligible`` / ``make_swar_corr2d``): odd-square signed
integer kernels with scale 1.0 and sum|w| <= 128 — the emboss family
(INCLUDING the reference's interior-guard emboss:3/5, whose golden
passthrough masks run in quarter-strip space), sharpen, and the
laplacians. Signed taps accumulate as (bias + positives) - negatives over
a +255*sum(|w<0|) bias so packed fields never go negative; quantize is
clip(acc - bias) — exact, since integer sums make trunc and rint the
identity. With the pointwise fusion above, the reference pipeline's
contrast:3.5 -> emboss:3 tail (kernel.cu:192-195) runs as ONE
quarter-strip kernel.

A fourth kernel (``swar_corr2d_wide_eligible`` / ``make_swar_corr2d_wide``)
takes the REST of the correlation class: integer odd-square kernel(s)
with 255*sum(|w|) < 2^24, any scale, 'single' OR 'magnitude' combine,
either quantizer. The carried fields widen to one pixel per i32 lane in
the finalize step, accumulate SIGNED natively (no bias trick), and the
combine + scale + quantize replay the golden float sequence on the exact
integer sums — so sqrt gradient magnitudes (sobel/prewitt/scharr),
unsharp's 1/256 scale, and arbitrary integer custom filters are all
bit-exact. I/O stays packed; only finalize compute runs at full element
count. Net coverage: every correlation-class stencil in the registry
runs on the SWAR path; only rank/morphology (median/erode/dilate) and
gather-based LUT ops remain on the u8 kernels.

Ineligible ops fall back to the u8 streaming kernels per op, so
``impl='swar'`` is always-correct — the same contract as
``impl='packed'`` (tools/packed_kernels.py, demoted round 5).

The streaming kernels reuse the production scratch-carry structure
(ops/pallas_kernels.stencil_tile_pallas): ext-row blocks stream in
non-overlapping, the (row-passed, or for corr2d raw pre-chained) fields
of the previous block live in VMEM scratch, and output block i-1 is the
finalize pass over [scratch ; first 2h rows of block i]. Reference
analogue: the CUDA stencil paths (kernel.cu:64-94), minus the in-place
race and missing halo.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    _PAD_MODES,
    F32,
    Op,
    PointwiseOp,
    StencilOp,
    rint_clip_f32,
)
from mpi_cuda_imagemanipulation_tpu.utils import calibration
from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

# field masks as python ints (a pallas kernel body must not capture traced
# constants); & / + / * with a uint32 array stays uint32
_M_LO = 0x00FF00FF  # bytes 0,2 as 16-bit fields
_M_B = 0x00010001  # LSB of each field


def swar_eligible(op: Op, plane_shape: tuple[int, ...] | None = None) -> bool:
    """True iff `op` (on an optional (H, W) u8 plane shape) can run on the
    SWAR path. See module docstring for the exact conditions."""
    if not isinstance(op, StencilOp):
        return False
    if op.reduce != "corr" or op.combine != "single":
        return False
    if op.quantize != "rint_clip":
        return False
    if op.edge_mode == "interior" or op.edge_mode not in _PAD_MODES:
        return False
    taps = op.separable
    if taps is None:
        return False
    t = np.asarray(taps)
    if not np.all(t == np.floor(t)) or np.any(t < 0):
        return False
    s = int(t.sum())
    # S <= 128: row-pass fields <= 255*128 = 32640 fit 16 bits with the
    # sign bit clear (so the wide mode's i32 view of the carried fields
    # is exact), and wide-mode column sums 255*S^2 < 2^24 stay exactly
    # representable in f32 for the golden-replay quantize
    if s < 2 or s > 128:
        return False
    if abs(op.scale * s * s - 1.0) > 1e-12:
        return False
    # exact form (not (n-1)//2): make_swar_stencil assumes n - 1 == 2*halo,
    # and this also rejects even-length tap vectors, which would otherwise
    # pass a truncating check and crash in-kernel instead of falling back
    # (advisor round-4 finding)
    if len(t) - 1 != 2 * op.halo:
        return False
    if plane_shape is not None and not _shape_ok(op, plane_shape):
        return False
    return True


def _taps_shift(op: StencilOp) -> tuple[tuple[int, ...], int]:
    """(integer taps, k) with 2^k = S^2 — the field arithmetic constants."""
    t = tuple(int(v) for v in np.asarray(op.separable))
    s = sum(t)
    k = int(s * s).bit_length() - 1
    return t, k


def _swar_mode(taps: tuple[int, ...]) -> str:
    """'narrow' (16-bit-field column pass + shift normalisation) when S is
    a power of two <= 16; 'wide' (per-pixel i32 column pass + golden f32
    quantize) otherwise. See module docstring."""
    s = sum(taps)
    return "narrow" if s <= 16 and not (s & (s - 1)) else "wide"


# --------------------------------------------------------------------------
# Pointwise fusion: fitted affine u8 steps applied inside the stream
#
# An elementwise u8 op IS its 256-entry LUT; ops that publish a host-side
# LUT (PointwiseOp.lut_host) are fitted to the integer form
#
#     q(p) = min(max(A*x - C, 0) >> m, 255),  x = p  or  255 - p
#
# and fused into the SWAR stencil stream — before the row pass (pre-chain,
# on the unpacked 16-bit fields) or after the quantize (post-chain) —
# exactly when the fit reproduces EVERY LUT entry, so fusion is bit-exact
# by checked construction, never by assumption. In the registry this
# covers contrast (rounding-free factors: 3.5 = (7p - 640) >> 1),
# brightness and invert — the chains the reference pipeline composes
# around its stencils (kernel.cu:192-195). A fused pointwise costs a few
# VPU ops per field instead of its own HBM read+write pass.
# --------------------------------------------------------------------------


def _fit_affine_u8(lut_bytes: bytes) -> tuple[bool, int, int, int] | None:
    """Fit (neg, A, C, m) reproducing the 256-entry u8 LUT exactly, or
    None. Bounds keep every intermediate under 2^15 per 16-bit field:
    A <= 128 (so A*255 <= 32640 with the sign bit clear) and
    A*255 + max(-C, 0) <= 32767 (additive steps stay in range)."""
    lut = np.frombuffer(lut_bytes, dtype=np.uint8).astype(np.int64)
    p = np.arange(256, dtype=np.int64)
    interior = np.nonzero((lut > 0) & (lut < 255))[0]
    if interior.size < 2:
        return None  # constant/step tables are not usefully affine
    p1, p2 = int(interior[0]), int(interior[-1])
    for neg in (False, True):
        x = 255 - p if neg else p
        dx = int(x[p2]) - int(x[p1])
        if dx == 0:
            continue
        dl = int(lut[p2]) - int(lut[p1])
        for m in range(9):
            a_est = (dl << m) / dx
            A = int(round(a_est))
            if A < 1 or A > 128:
                continue
            # C from the anchor: (A*x[p1] - C) >> m == lut[p1] leaves
            # exactly 2^m integer candidates
            base = A * int(x[p1]) - (int(lut[p1]) << m)
            for C in range(base - (1 << m) + 1, base + 1):
                if abs(C) > 32767 or A * 255 + max(-C, 0) > 32767:
                    continue
                t = np.maximum(A * x - C, 0)
                if np.array_equal(np.minimum(t >> m, 255), lut):
                    return (bool(neg), A, int(C), m)
    return None


_FIT_CACHE: dict[bytes, tuple | None] = {}


def swar_fusable(op: Op) -> tuple[bool, int, int, int] | None:
    """The fitted in-field form of an elementwise pointwise op, or None
    when the op cannot fuse into a SWAR stream (no host LUT, channel
    structure, or no exact affine fit)."""
    if not isinstance(op, PointwiseOp) or not op.kernel_safe:
        return None
    if op.lut_host is None or op.core is None:
        return None
    if op.in_channels not in (0, 1) or op.out_channels not in (0, 1):
        return None
    lut = np.asarray(op.lut_host(), dtype=np.uint8)
    if lut.shape != (256,):
        return None
    key = lut.tobytes()
    if key not in _FIT_CACHE:
        _FIT_CACHE[key] = _fit_affine_u8(key)
    return _FIT_CACHE[key]


def _dt_const(F: jnp.ndarray, v: int):
    """Dtype-matched scalar with the u32 bit pattern `v` (an i32 view
    wraps to the same bits; add/sub/mul/bitwise are bit-identical in
    two's complement, which is what the field tricks rely on)."""
    if v >= 1 << 31 and F.dtype == jnp.int32:
        v -= 1 << 32
    return F.dtype.type(v)


def _field_sat_sub(T: jnp.ndarray, c: int) -> jnp.ndarray:
    """Per-16-bit-field max(T - c, 0) on packed field arrays.

    The classic SWAR sign-probe: with both operands < 2^15, (T | 0x8000)
    - c keeps fields independent (the injected bit absorbs any borrow)
    and its 0x8000 bit reads T >= c. Dtype-generic (u32 / i32: wraparound
    bit patterns are identical; the one arithmetic-shift smear on the
    probe extraction is masked off)."""
    H = _dt_const(T, 0x80008000)
    D = (T | H) - _dt_const(T, c * 0x00010001)
    ge = ((D & H) >> 15) & _dt_const(T, _M_B)  # 1 per field where T >= c
    mask = ge * _dt_const(T, 0xFFFF)
    return D & _dt_const(T, 0x7FFF7FFF) & mask


def _field_min255(T: jnp.ndarray) -> jnp.ndarray:
    """Per-16-bit-field min(T, 255) (same sign-probe; T < 2^15)."""
    H = _dt_const(T, 0x80008000)
    D = (T | H) - _dt_const(T, 256 * 0x00010001)
    ge = ((D & H) >> 15) & _dt_const(T, _M_B)
    mask = ge * _dt_const(T, 0xFFFF)
    return (T & ~mask) | (_dt_const(T, _M_LO) & mask)


def _apply_affine_fields(F: jnp.ndarray, chain) -> jnp.ndarray:
    """Apply fitted (neg, A, C, m) steps to two 16-bit fields per 32-bit
    element, each field holding a u8 value; returns fields holding the
    mapped u8 values. The fitter's bounds guarantee the < 2^15 invariant
    the sign-probe helpers need at every step."""
    if not chain:
        return F
    M255 = _dt_const(F, _M_LO)
    for neg, A, C, m in chain:
        if neg:
            F = M255 - F  # per-field 255 - v: borrow-free (v <= 255)
        T = F * _dt_const(F, A)  # <= 32640 per field
        if C > 0:
            T = _field_sat_sub(T, C)
        elif C < 0:
            T = T + _dt_const(F, (-C) * 0x00010001)  # <= 32767 per field
        if m:
            T = (T >> m) & _dt_const(F, (0xFFFF >> m) * 0x00010001)
        F = _field_min255(T)
    return F


def _apply_affine_lanes(x: jnp.ndarray, chain) -> jnp.ndarray:
    """Single-value-per-lane (i32 values 0..255) version of the chain —
    the wide-mode column lanes need no field tricks, just the plain
    integer form the fitter verified: min(max(A*x - C, 0) >> m, 255)."""
    for neg, A, C, m in chain:
        if neg:
            x = jnp.int32(255) - x
        t = jnp.maximum(x * jnp.int32(A) - jnp.int32(C), jnp.int32(0))
        if m:
            t = t >> m
        x = jnp.minimum(t, jnp.int32(255))
    return x


def pack_quarters(xpad: jnp.ndarray, halo: int) -> jnp.ndarray:
    """(H+2h, W+2h) u8 padded plane -> (H+2h, W/4+2h) u32 quarter-strip
    words: byte k of word j is strip k's padded pixel j. Each strip's ext
    covers [k*Ws, k*Ws + Ws + 2h) of the padded row, so every horizontal
    tap is word-local."""
    hp, wp2 = xpad.shape
    ws = (wp2 - 2 * halo) // 4
    strips = [xpad[:, k * ws : k * ws + ws + 2 * halo] for k in range(4)]
    stacked = jnp.stack(strips, axis=-1)  # (Hp, Ws+2h, 4) u8
    return jax.lax.bitcast_convert_type(stacked, jnp.uint32)


def unpack_quarters(words: jnp.ndarray) -> jnp.ndarray:
    """(H, Ws) u32 -> (H, 4*Ws) u8 by reassembling the quarter strips."""
    b = jax.lax.bitcast_convert_type(words, jnp.uint8)  # (H, Ws, 4)
    return jnp.concatenate([b[..., k] for k in range(4)], axis=1)


def _row_pass_fields(
    ext_block: jnp.ndarray, taps: tuple[int, ...], pre_chain: tuple = ()
):
    """(bh, Ws+2h) words -> two (bh, Ws) field arrays (bytes 0,2 and 1,3
    as 16-bit fields), row-correlated with `taps`. Dtype-generic: u32 in
    narrow mode, i32 in wide mode (the byte masks make the extraction
    identical under either shift semantics; weights match the input
    dtype so no promotion happens). `pre_chain` steps (fused pointwise
    prefix ops) map the u8 field values before the correlation."""
    n = len(taps)
    w8 = ext_block.dtype.type
    lo = ext_block & w8(_M_LO)
    hi = (ext_block >> w8(8)) & w8(_M_LO)
    if pre_chain:
        lo = _apply_affine_fields(lo, pre_chain)
        hi = _apply_affine_fields(hi, pre_chain)

    def row(a):
        w = a.shape[1] - (n - 1)
        acc = a[:, 0:w] * w8(taps[0])
        for t in range(1, n):
            acc = acc + a[:, t : w + t] * w8(taps[t])
        return acc

    return row(lo), row(hi)


def _col_finalize(
    lo_rows, hi_rows, taps: tuple[int, ...], k: int, post_chain: tuple = ()
):
    """(bh+2h, Ws) field arrays -> (bh, Ws) u32 output words: column pass +
    x 2^-k round-half-to-even + fused pointwise suffix + byte repack."""
    n = len(taps)
    half = (1 << (k - 1)) - 1
    m_half = (half << 16) | half

    def col(a):
        hgt = a.shape[0] - (n - 1)
        acc = a[0:hgt, :] * jnp.uint32(taps[0])
        for t in range(1, n):
            acc = acc + a[t : hgt + t, :] * jnp.uint32(taps[t])
        return acc

    def rnd(s):
        b = (s >> k) & _M_B
        q = ((s + m_half + b) >> k) & _M_LO
        return _apply_affine_fields(q, post_chain)

    return rnd(col(lo_rows)) | (rnd(col(hi_rows)) << 8)


def _col_finalize_wide(
    lo_rows,
    hi_rows,
    taps: tuple[int, ...],
    scale: float,
    post_chain: tuple = (),
):
    """Wide-mode column pass: (bh+2h, Ws) i32 packed-field arrays ->
    (bh, Ws) i32 output words.

    Each 16-bit field widens to its own i32 lane BEFORE accumulation (the
    narrow mode's packed column sums would overflow for S > 16), then
    quantization replays the golden float ops on the exact integer sums —
    ``f32(s) * np.float32(scale)``, ``rint``, clip — which is bit-exact
    against StencilOp.valid + rint_clip for any scale, including the box
    family's non-power-of-two 1/S^2 (same float sequence on the same
    values). Sums <= 255*S^2 < 2^24 are exact in f32 (swar_eligible)."""
    n = len(taps)
    m16 = jnp.int32(0xFFFF)

    def col(a):
        hgt = a.shape[0] - (n - 1)
        acc = a[0:hgt, :] * jnp.int32(taps[0])
        for t in range(1, n):
            acc = acc + a[t : hgt + t, :] * jnp.int32(taps[t])
        return acc

    def q(a):  # exact integer sums -> quantized bytes (golden replay)
        b = rint_clip_f32(a.astype(F32) * np.float32(scale)).astype(
            jnp.int32
        )
        return _apply_affine_lanes(b, post_chain)

    # field layout (pack_quarters): lo = bytes 0,2; hi = bytes 1,3 —
    # low field = the even byte, high field = the odd+2 byte
    b0 = q(col(lo_rows & m16))
    b2 = q(col((lo_rows >> 16) & m16))
    b1 = q(col(hi_rows & m16))
    b3 = q(col((hi_rows >> 16) & m16))
    return b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)


def _pick_swar_block_h(ws: int, halo: int, mode: str = "narrow") -> int:
    """VMEM-safe ext-row block height for the carry kernel.

    Working set per ext row: u32 input block (double-buffered) + two field
    scratch blocks + output block (double-buffered) + ~6 live u32 temps
    while the body runs — all Ws-wide words; wide mode adds the per-pixel
    widened column lanes (+ their f32 copies), ~12 more live temps.
    Budget mirrors the u8 kernels' 3/4 of the 64 MiB scoped-VMEM limit
    (ops/pallas_kernels.py)."""
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import _VMEM_LIMIT

    budget = 3 * _VMEM_LIMIT // 4
    live = {"narrow": 6, "wide": 18, "corr2d": 18, "corr2d_wide": 22}[mode]
    per_row = 4 * (ws + 2 * halo) * 2 + 4 * ws * (2 + 2 + live)
    bh = budget // max(per_row, 1)
    bh = int(max(2 * halo, min(512, bh)))
    # round to a multiple of 8 UP where rounding down would violate the
    # kernel's bh >= 2*halo precondition (reachable since wide mode admits
    # halos > 4 — review finding); the VMEM estimate is conservative
    # enough that +7 rows never matters
    min8 = -(-2 * halo // 8) * 8
    bh = max(8, min8, (bh // 8) * 8)
    calibrated = calibration.lookup_block_h(impl="swar", width=4 * ws)
    if calibrated is not None:
        bh = max(min8, 8, min(bh, (calibrated // 8) * 8))
    return bh


def make_swar_stencil(
    ext_shape: tuple[int, int],
    taps: tuple[int, ...],
    k: int,
    bh: int,
    *,
    mode: str = "narrow",
    scale: float = 0.0,
    pre_chain: tuple = (),
    post_chain: tuple = (),
    interpret: bool = False,
):
    """Streaming SWAR kernel over quarter-strip words with the production
    scratch-carry structure. `ext_shape` = (H+2h, Ws+2h) words; returns a
    function ext_words -> (ceil(H/bh)*bh, Ws) words (caller crops [:H]).
    Word dtype is u32 in narrow mode, i32 in wide mode (`_swar_mode`;
    `scale` is the op's 1/S^2, used by the wide quantize only).

    Ragged heights are fine: out rows >= H are garbage (OOB-padded input
    blocks / duplicated tail rows via the clamped index maps) and the
    caller crops — every real out row r reads ext rows [r, r+2h], which
    live in the scratch block and the next block's first 2h rows by
    construction."""
    halo = (len(taps) - 1) // 2
    hp, wsp = ext_shape
    height = hp - 2 * halo
    ws = wsp - 2 * halo
    if bh < 2 * halo:
        raise ValueError(f"block_h {bh} < 2*halo {2 * halo}")
    nb = -(-height // bh)
    nb_in = -(-hp // bh)  # last block holds the bottom halo rows
    dtype = jnp.uint32 if mode == "narrow" else jnp.int32

    def kernel(in_ref, out_ref, lo_ref, hi_ref):
        i = pl.program_id(0)
        rlo, rhi = _row_pass_fields(in_ref[:], taps, pre_chain)

        @pl.when(i >= 1)
        def _():
            lo_rows = jnp.concatenate([lo_ref[:], rlo[: 2 * halo]], axis=0)
            hi_rows = jnp.concatenate([hi_ref[:], rhi[: 2 * halo]], axis=0)
            if mode == "narrow":
                out_ref[:] = _col_finalize(
                    lo_rows, hi_rows, taps, k, post_chain
                )
            else:
                out_ref[:] = _col_finalize_wide(
                    lo_rows, hi_rows, taps, scale, post_chain
                )

        lo_ref[:] = rlo
        hi_ref[:] = rhi

    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        _COMPILER_PARAMS,
    )

    return pl.pallas_call(
        kernel,
        grid=(nb + 1,),
        in_specs=[
            pl.BlockSpec(
                (bh, wsp),
                lambda i: (jnp.minimum(i, nb_in - 1), 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (bh, ws),
            lambda i: (jnp.maximum(i - 1, 0), 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((nb * bh, ws), dtype),
        scratch_shapes=[
            pltpu.VMEM((bh, ws), dtype),
            pltpu.VMEM((bh, ws), dtype),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


def _shape_ok(op: StencilOp, plane_shape) -> bool:
    """The common (H, W) plane gate: single u8 plane, W a multiple of 4
    wide enough that every horizontal tap is word-local, H past the
    halo."""
    if len(plane_shape) != 2:
        return False
    h_img, w_img = plane_shape
    return not (
        w_img % 4 or w_img // 4 < 2 * op.halo + 1 or h_img <= op.halo
    )


def _kernel_geom_ok(w: "np.ndarray", halo: int) -> bool:
    """Shared corr2d kernel-geometry gate: odd square matching the op's
    halo, integer weights (both corr2d eligibility predicates use this —
    review finding: two drifting copies existed)."""
    if w.ndim != 2 or w.shape[0] != w.shape[1] or w.shape[0] % 2 == 0:
        return False
    if w.shape[0] != 2 * halo + 1:
        return False
    return bool(np.all(w == np.floor(w)))


def _corr2d_weights(op: StencilOp) -> tuple[tuple[int, ...], ...]:
    w = np.asarray(op.kernels[0])
    return tuple(tuple(int(v) for v in row) for row in w)


def swar_corr2d_eligible(
    op: Op, plane_shape: tuple[int, ...] | None = None
) -> bool:
    """Whether `op` can run on the SWAR 2-D correlation path: a single
    odd-square integer kernel (signed weights welcome — the kernel
    accumulates positive and negative taps separately over a +255*sum(|w<0|)
    bias so packed fields never go negative), scale exactly 1.0 (both
    quantizers are the identity-then-clip on integer sums), sum|w| <= 128
    so biased accumulators stay under the sign-probe helpers' 2^15 bound.
    Covers the emboss family (incl. the reference's interior-guard
    emboss:3/5, kernel.cu:64-94 — golden passthrough masks run in
    quarter-strip space), sharpen, and the laplacians."""
    if not isinstance(op, StencilOp):
        return False
    if op.reduce != "corr" or op.combine != "single":
        return False
    if len(op.kernels) != 1:
        return False
    if op.quantize not in ("trunc_clip", "rint_clip"):
        return False
    if op.scale != 1.0:
        return False
    if op.edge_mode not in _PAD_MODES:  # includes 'interior'
        return False
    w = np.asarray(op.kernels[0])
    if op.halo < 1 or not _kernel_geom_ok(w, op.halo):
        return False
    if int(np.abs(w).sum()) > 128 or not np.any(w):
        return False
    if plane_shape is not None and not _shape_ok(op, plane_shape):
        return False
    return True


def swar_corr2d_wide_eligible(
    op: Op, plane_shape: tuple[int, ...] | None = None
) -> bool:
    """Whether `op` can run on the WIDE 2-D correlation path: integer
    odd-square kernel(s) with 255*sum(|w|) < 2^24 (exact in f32 and in
    range for i32 lanes), any scale, 'single' or 'magnitude' combine,
    either quantizer. The correlation runs at one pixel per i32 lane
    with native signed accumulation (no bias trick needed) and the
    combine + scale + quantize REPLAY the golden float ops on the exact
    integer sums — bit-exact for sqrt magnitudes and arbitrary scales
    alike. Covers sobel/prewitt/scharr, unsharp, and integer custom
    filters; I/O still moves packed u32 words."""
    if not isinstance(op, StencilOp):
        return False
    if op.reduce != "corr":
        return False
    if op.combine not in ("single", "magnitude"):
        return False
    if op.combine == "magnitude" and len(op.kernels) != 2:
        return False
    if op.combine == "single" and len(op.kernels) != 1:
        return False
    from mpi_cuda_imagemanipulation_tpu.ops.spec import QUANTIZERS_F32

    if op.quantize not in QUANTIZERS_F32:
        return False
    if op.edge_mode not in _PAD_MODES:
        return False
    if op.halo < 1:
        return False
    for k in op.kernels:
        w = np.asarray(k)
        if not _kernel_geom_ok(w, op.halo):
            return False
        if 255 * int(np.abs(w).sum()) >= 1 << 24 or not np.any(w):
            return False
    if plane_shape is not None and not _shape_ok(op, plane_shape):
        return False
    return True


def swar_any_eligible(
    op: Op, plane_shape: tuple[int, ...] | None = None
) -> bool:
    """Combined predicate: any of the three SWAR kernels (separable,
    packed-field corr2d, wide-lane corr2d) can take this op (used by
    the pipeline walkers)."""
    return (
        swar_eligible(op, plane_shape)
        or swar_corr2d_eligible(op, plane_shape)
        or swar_corr2d_wide_eligible(op, plane_shape)
    )


def make_swar_corr2d_wide(
    ext_shape: tuple[int, int],
    kernels: tuple[tuple[tuple[int, ...], ...], ...],
    bh: int,
    *,
    combine: str,
    scale: float,
    quantize: str,
    interior: bool,
    global_h: int,
    pre_chain: tuple = (),
    post_chain: tuple = (),
    sharded_y0: bool = False,
    interpret: bool = False,
):
    """Wide-lane 2-D correlation kernel: packed u32 words stream in, the
    carried fields widen to one pixel per i32 lane in the finalize step,
    and the correlation accumulates SIGNED in i32 (no bias trick — each
    lane is its own value). combine/scale/quantize replay the golden
    float sequence on the exact integer sums (see
    swar_corr2d_wide_eligible), so sqrt-magnitude gradient ops and
    arbitrary scales stay bit-exact. I/O element saving is kept (words);
    finalize compute runs at full element count like the separable wide
    column mode."""
    from mpi_cuda_imagemanipulation_tpu.ops.spec import QUANTIZERS_F32

    n = len(kernels[0])
    halo = (n - 1) // 2
    hp, wsp = ext_shape
    height = hp - 2 * halo
    ws = wsp - 2 * halo
    if bh < 2 * halo:
        raise ValueError(f"block_h {bh} < 2*halo {2 * halo}")
    nb = -(-height // bh)
    nb_in = -(-hp // bh)
    o = halo
    quant = QUANTIZERS_F32[quantize]

    def corr(lane, weights):
        """(bh+2h, wsp) i32 lane -> (bh, ws) signed i32 sums."""
        acc = None
        for dy, row in enumerate(weights):
            for dx, w in enumerate(row):
                if w == 0:
                    continue
                win = lane[dy : dy + bh, dx : dx + ws]
                term = win if w == 1 else win * jnp.int32(w)
                acc = term if acc is None else acc + term
        return acc if acc is not None else jnp.zeros((bh, ws), jnp.int32)

    def q_lane(lane, i, y0, strip):
        """One widened (bh+2h, wsp) i32 lane -> quantized (bh, ws) i32."""
        accs = [corr(lane, k) for k in kernels]
        if combine == "single":
            acc = accs[0].astype(F32)
        else:  # magnitude — replay spec.StencilOp.valid exactly
            a0 = accs[0].astype(F32)
            a1 = accs[1].astype(F32)
            acc = jnp.sqrt(a0 * a0 + a1 * a1)
        if scale != 1.0:
            acc = acc * np.float32(scale)
        q = quant(acc).astype(jnp.int32)
        if interior:
            yy = (
                y0
                + (i - 1) * bh
                + jax.lax.broadcasted_iota(jnp.int32, (bh, ws), 0)
            )
            yc = (yy > o) & (yy <= global_h - 1 - o)
            jl = jax.lax.broadcasted_iota(jnp.int32, (bh, ws), 1)
            cond = yc
            if strip == 0:
                cond = cond & (jl > o)
            elif strip == 3:
                cond = cond & (jl < ws - o)
            centre = lane[halo : halo + bh, halo : halo + ws]
            q = jnp.where(cond, q, centre)
        return _apply_affine_lanes(q, post_chain)

    def kernel(*refs):
        if sharded_y0:
            y0_ref, in_ref, out_ref, lo_ref, hi_ref = refs
            y0 = y0_ref[0]
        else:
            in_ref, out_ref, lo_ref, hi_ref = refs
            y0 = jnp.int32(0)
        i = pl.program_id(0)
        ext = in_ref[:]
        w8 = ext.dtype.type
        lo = ext & w8(_M_LO)
        hi = (ext >> w8(8)) & w8(_M_LO)
        if pre_chain:
            lo = _apply_affine_fields(lo, pre_chain)
            hi = _apply_affine_fields(hi, pre_chain)

        @pl.when(i >= 1)
        def _():
            lo_rows = jnp.concatenate([lo_ref[:], lo[: 2 * halo]], axis=0)
            hi_rows = jnp.concatenate([hi_ref[:], hi[: 2 * halo]], axis=0)
            m16 = jnp.int32(0xFFFF)
            # widen: byte k of each word is strip k's pixel (pack_quarters)
            lo_i = lo_rows.astype(jnp.int32)
            hi_i = hi_rows.astype(jnp.int32)
            b0 = q_lane(lo_i & m16, i, y0, 0)  # strip 0
            b2 = q_lane((lo_i >> 16) & m16, i, y0, 2)  # strip 2
            b1 = q_lane(hi_i & m16, i, y0, 1)  # strip 1
            b3 = q_lane((hi_i >> 16) & m16, i, y0, 3)  # strip 3
            # stays i32 end-to-end (packed_kernels' Mosaic-native idiom);
            # the caller bitcasts the word array back to u32
            out_ref[:] = b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)

        lo_ref[:] = lo
        hi_ref[:] = hi

    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        _COMPILER_PARAMS,
    )

    in_specs = [
        pl.BlockSpec(
            (bh, wsp),
            lambda i: (jnp.minimum(i, nb_in - 1), 0),
            memory_space=pltpu.VMEM,
        )
    ]
    if sharded_y0:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
    return pl.pallas_call(
        kernel,
        grid=(nb + 1,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (bh, ws),
            lambda i: (jnp.maximum(i - 1, 0), 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((nb * bh, ws), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((bh, wsp), jnp.uint32),
            pltpu.VMEM((bh, wsp), jnp.uint32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


def make_swar_corr2d(
    ext_shape: tuple[int, int],
    weights: tuple[tuple[int, ...], ...],
    bh: int,
    *,
    interior: bool,
    global_h: int,
    pre_chain: tuple = (),
    post_chain: tuple = (),
    sharded_y0: bool = False,
    interpret: bool = False,
):
    """Streaming SWAR kernel for a non-separable integer 2-D correlation
    over quarter-strip words (scale 1.0; covers the reference emboss,
    kernel.cu:64-94, minus its in-place race).

    Same scratch-carry structure as the separable kernel, but the VMEM
    scratch holds the RAW (pre-chained) unpacked fields of the previous
    ext block — the 2-D correlation has no row/column factorisation, so
    all taps apply in the finalize step over [scratch ; next 2h rows].
    Signed weights: positive and negative taps accumulate separately and
    combine as (bias + P) - N with bias = 255*sum(|w<0|), which keeps
    every packed field non-negative (no cross-field borrow) and <= 2^15.
    Quantize is clip(acc - bias, 0, 255) — exact: integer sums make both
    trunc and rint the identity.

    `interior` replays the reference guard (kernel.cu:83): output fields
    outside the interior select the (pre-chained) centre pixel instead.
    The x-side masks live in quarter-strip space — only strips 0 and 3
    contain global edge columns. `sharded_y0` prepends a (1,) SMEM scalar
    carrying the tile's global row offset so the masks follow global
    coordinates, exactly like the u8 ghost kernels.
    """
    n = len(weights)
    halo = (n - 1) // 2
    hp, wsp = ext_shape
    height = hp - 2 * halo
    ws = wsp - 2 * halo
    if bh < 2 * halo:
        raise ValueError(f"block_h {bh} < 2*halo {2 * halo}")
    nb = -(-height // bh)
    nb_in = -(-hp // bh)
    bias = 255 * sum(-w for row in weights for w in row if w < 0)
    o = halo  # the reference guard's offset

    def corr(F):
        """(bh+2h, wsp) fields -> (bh, ws) biased accumulators."""
        w8 = F.dtype.type
        P = None
        N = None
        for dy, row in enumerate(weights):
            for dx, w in enumerate(row):
                if w == 0:
                    continue
                win = F[dy : dy + bh, dx : dx + ws]
                if w > 0:
                    term = win if w == 1 else win * w8(w)
                    P = term if P is None else P + term
                else:
                    term = win if w == -1 else win * w8(-w)
                    N = term if N is None else N + term
        acc = _dt_const(F, bias * 0x00010001)
        if P is not None:
            acc = acc + P
        if N is not None:
            acc = acc - N  # >= 0 per field by the bias bound
        return acc

    def finalize(lo_rows, hi_rows, i, y0):
        qlo = _field_min255(_field_sat_sub(corr(lo_rows), bias))
        qhi = _field_min255(_field_sat_sub(corr(hi_rows), bias))
        if interior:
            yy = (
                y0
                + (i - 1) * bh
                + jax.lax.broadcasted_iota(jnp.int32, (bh, ws), 0)
            )
            yc = (yy > o) & (yy <= global_h - 1 - o)
            jl = jax.lax.broadcasted_iota(jnp.int32, (bh, ws), 1)
            # global x per field: strip k covers x in [k*ws*... only
            # strips 0 (x = j) and 3 (x = 3*W/4 + j) hold edge columns
            xc0 = jl > o
            xc3 = jl < ws - o
            w8 = lo_rows.dtype.type

            def m(cond_f0, cond_f1):
                return (cond_f0.astype(lo_rows.dtype) * w8(0xFFFF)) | (
                    (cond_f1.astype(lo_rows.dtype) * w8(0xFFFF)) << 16
                )

            m_lo = m(yc & xc0, yc)  # fields: strip0, strip2
            m_hi = m(yc, yc & xc3)  # fields: strip1, strip3
            c_lo = lo_rows[halo : halo + bh, halo : halo + ws]
            c_hi = hi_rows[halo : halo + bh, halo : halo + ws]
            qlo = (qlo & m_lo) | (c_lo & ~m_lo)
            qhi = (qhi & m_hi) | (c_hi & ~m_hi)
        if post_chain:
            qlo = _apply_affine_fields(qlo, post_chain)
            qhi = _apply_affine_fields(qhi, post_chain)
        return qlo | (qhi << 8)

    def kernel(*refs):
        if sharded_y0:
            y0_ref, in_ref, out_ref, lo_ref, hi_ref = refs
            y0 = y0_ref[0]
        else:
            in_ref, out_ref, lo_ref, hi_ref = refs
            y0 = jnp.int32(0)
        i = pl.program_id(0)
        ext = in_ref[:]
        w8 = ext.dtype.type
        lo = ext & w8(_M_LO)
        hi = (ext >> w8(8)) & w8(_M_LO)
        if pre_chain:
            lo = _apply_affine_fields(lo, pre_chain)
            hi = _apply_affine_fields(hi, pre_chain)

        @pl.when(i >= 1)
        def _():
            lo_rows = jnp.concatenate([lo_ref[:], lo[: 2 * halo]], axis=0)
            hi_rows = jnp.concatenate([hi_ref[:], hi[: 2 * halo]], axis=0)
            out_ref[:] = finalize(lo_rows, hi_rows, i, y0)

        lo_ref[:] = lo
        hi_ref[:] = hi

    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        _COMPILER_PARAMS,
    )

    in_specs = [
        pl.BlockSpec(
            (bh, wsp),
            lambda i: (jnp.minimum(i, nb_in - 1), 0),
            memory_space=pltpu.VMEM,
        )
    ]
    if sharded_y0:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
    return pl.pallas_call(
        kernel,
        grid=(nb + 1,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (bh, ws),
            lambda i: (jnp.maximum(i - 1, 0), 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((nb * bh, ws), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((bh, wsp), jnp.uint32),
            pltpu.VMEM((bh, wsp), jnp.uint32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


def swar_stencil(
    op: StencilOp,
    img: jnp.ndarray,
    *,
    pre_ops: tuple = (),
    post_ops: tuple = (),
    ghosts: tuple[jnp.ndarray, jnp.ndarray] | None = None,
    y0=None,
    global_h: int | None = None,
    block_h: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One eligible StencilOp on a (H, W) u8 plane via the SWAR path —
    the separable kernel when ``swar_eligible``, else one of the two 2-D
    correlation kernels — packed-field where ``swar_corr2d_eligible``,
    wide-lane otherwise (caller guarantees ``swar_any_eligible``) — with
    optional
    fused pointwise prefix/suffix ops (each must satisfy ``swar_fusable``;
    their fitted chains run inside the same kernel, so the whole group
    costs one HBM read + one write).

    `ghosts` = (top, bottom) (halo, W) u8 strips supplied by the sharded
    runner (ppermute-exchanged + edge-synthesised, parallel/api.py): they
    replace the vertical self-padding, making this the quarter-strip
    ghost mode — the shard's tile streams through the same kernel as the
    unsharded path (the pack pass exists in both, so per-chip traffic
    matches unsharded SWAR). Strips are raw pixels; the pre-chain applies
    to them inside the kernel exactly as it does on-tile. Sharded
    interior-mode ops additionally pass `y0` (traced global row offset)
    and `global_h` so the guard masks follow global coordinates.

    `interpret=None` resolves like every other kernel entry point
    (compiled on TPU, interpreter elsewhere), so callers pass their own
    `interpret` straight through."""
    if interpret is None:
        interpret = not is_tpu_backend()
    pre_chain = tuple(_require_fusable(o) for o in pre_ops)
    post_chain = tuple(_require_fusable(o) for o in post_ops)
    halo = op.halo
    height, width = img.shape
    ws = width // 4
    if ghosts is not None:
        top, bottom = ghosts
        xv = jnp.concatenate([top, img, bottom], axis=0)
        # horizontal padding only — the vertical extension came from the
        # mesh neighbours (or edge synthesis at the global boundary)
        xpad = jnp.pad(
            xv, ((0, 0), (halo, halo)), mode=_PAD_MODES[op.edge_mode]
        )
    else:
        xpad = jnp.pad(
            img, ((halo, halo), (halo, halo)), mode=_PAD_MODES[op.edge_mode]
        )
    ext = pack_quarters(xpad, halo)

    if not swar_eligible(op):
        # 2-D correlation paths: packed-field kernel where the bias trick
        # fits (emboss family / sharpen / laplacian), wide-lane kernel
        # for the rest (gradient magnitudes, unsharp, custom filters)
        sharded_y0 = y0 is not None
        if swar_corr2d_eligible(op):
            bh = block_h or _pick_swar_block_h(ws, halo, "corr2d")
            fn = make_swar_corr2d(
                ext.shape,
                _corr2d_weights(op),
                bh,
                interior=op.edge_mode == "interior",
                global_h=global_h if global_h is not None else height,
                pre_chain=pre_chain,
                post_chain=post_chain,
                sharded_y0=sharded_y0,
                interpret=interpret,
            )
        else:
            bh = block_h or _pick_swar_block_h(ws, halo, "corr2d_wide")
            kernels = tuple(
                tuple(tuple(int(v) for v in row) for row in np.asarray(k))
                for k in op.kernels
            )
            wide = make_swar_corr2d_wide(
                ext.shape,
                kernels,
                bh,
                combine=op.combine,
                scale=float(op.scale),
                quantize=op.quantize,
                interior=op.edge_mode == "interior",
                global_h=global_h if global_h is not None else height,
                pre_chain=pre_chain,
                post_chain=post_chain,
                sharded_y0=sharded_y0,
                interpret=interpret,
            )

            def fn(*a):
                return jax.lax.bitcast_convert_type(wide(*a), jnp.uint32)

        if sharded_y0:
            outw = fn(jnp.asarray(y0, jnp.int32).reshape(1), ext)
        else:
            outw = fn(ext)
        return unpack_quarters(outw[:height])

    taps, k = _taps_shift(op)
    mode = _swar_mode(taps)
    if mode == "wide":
        # free same-width view: the wide kernel runs Mosaic-native i32
        # lane algebra end-to-end (all byte values, so no sign surprises)
        ext = jax.lax.bitcast_convert_type(ext, jnp.int32)
    bh = block_h or _pick_swar_block_h(ws, halo, mode)
    outw = make_swar_stencil(
        ext.shape, taps, k, bh, mode=mode, scale=float(op.scale),
        pre_chain=pre_chain, post_chain=post_chain, interpret=interpret,
    )(ext)
    if mode == "wide":
        outw = jax.lax.bitcast_convert_type(outw, jnp.uint32)
    return unpack_quarters(outw[:height])


def _require_fusable(op: Op) -> tuple[bool, int, int, int]:
    fit = swar_fusable(op)
    if fit is None:
        raise ValueError(f"op {op.name!r} is not SWAR-fusable")
    return fit


def pipeline_swar(
    ops: tuple[Op, ...],
    img: jnp.ndarray,
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
) -> jnp.ndarray:
    """Run a pipeline with eligible stencils on the SWAR path and every
    other op on the u8 streaming kernels (fallback keeps the backend
    always-correct, the ``impl='packed'`` contract).

    Fallback granularity is maximal runs, not single ops: consecutive
    ineligible ops go to pipeline_pallas as ONE call so its group fusion
    (pointwise chains folded into stencil streams) is preserved — per-op
    fallback would pay an extra HBM read+write per op (review finding).

    An explicit ``block_h`` applies to the SWAR kernels only; fallback
    flushes let the u8 path's own heuristic pick (advisor round-4
    finding: swar-granularity heights — multiples of 8, as low as 8 —
    would otherwise silently shape the u8 kernels, which are tuned at
    multiples of 32)."""
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        pipeline_pallas,
    )

    if interpret is None:
        interpret = not is_tpu_backend()

    pending: list[Op] = []

    def flush(im):
        if pending:
            im = pipeline_pallas(
                tuple(pending), im, interpret=interpret, block_h=None
            )
            pending.clear()
        return im

    def fusable(o):
        return swar_fusable(o) is not None

    n = len(ops)
    i = 0
    while i < n:
        # try to form a fused group starting here: [pre*] stencil [post*]
        j = i
        pre: list[Op] = []
        while j < n and fusable(ops[j]):
            pre.append(ops[j])
            j += 1
        if j < n and swar_any_eligible(ops[j]):
            st = ops[j]
            j += 1
            # a trailing fusable run becomes this group's post-chain
            # UNLESS another eligible stencil follows it — then it serves
            # as that group's pre-chain instead (same cost either way;
            # pre keeps groups maximal when chains sit between stencils)
            k2 = j
            run: list[Op] = []
            while k2 < n and fusable(ops[k2]):
                run.append(ops[k2])
                k2 += 1
            post: list[Op] = []
            if not (k2 < n and swar_any_eligible(ops[k2])):
                post = run
                j = k2
            # pre-chain + zero padding don't commute (golden pads AFTER
            # the pointwise ops with literal zeros; the fused kernel would
            # map the pad zeros through the chain) unless the composed
            # chain fixes 0 — reflect101/edge pads are image values, so
            # they always commute with elementwise maps
            pre_ok = not pre or st.edge_mode != "zero" or _chain_fixes_zero(
                pre
            )
            img = flush(img)  # shape gate needs the ACTUAL input
            if (
                pre_ok
                and img.dtype == jnp.uint8
                and img.ndim == 2
                and swar_any_eligible(st, tuple(img.shape))
            ):
                img = swar_stencil(
                    st,
                    img,
                    pre_ops=tuple(pre),
                    post_ops=tuple(post),
                    block_h=block_h,
                    interpret=interpret,
                )
            else:
                # whole group falls back as one run (keeps u8 group fusion)
                pending.extend(pre)
                pending.append(st)
                pending.extend(post)
            i = j
            continue
        # no eligible stencil follows this position: ops[i] joins the
        # fallback run (a later iteration re-tries from i+1)
        pending.append(ops[i])
        i += 1
    return flush(img)


def _chain_fixes_zero(pre_ops) -> bool:
    """Whether the composed pointwise prefix maps pixel value 0 to 0 (the
    condition for fusing under a zero-padded stencil)."""
    v = 0
    for o in pre_ops:
        v = int(np.asarray(o.lut_host(), dtype=np.uint8)[v])
    return v == 0
