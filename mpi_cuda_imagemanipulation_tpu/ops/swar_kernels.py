"""Production SWAR quarter-strip streaming backend (``impl='swar'``).

Promotion of the tools/swar_proto.py design into the framework, gated
behind an explicit backend choice (it joins ``auto`` routing only after an
on-chip win; see BASELINE.md round-4 pre-registered predictions).

Why this exists (the round-3 roofline result, BASELINE.md): u8 streaming on
v5e is element-rate-capped (~95-100 Ge/s) at ~1/4 of the f32 byte rate, and
the u8 production kernels already sit at ~94% of that ceiling — so the only
way past it is fewer, wider elements. The first packed attempt
(ops/packed_kernels.py) moves u32 words but unpacks every word into four
f32 lane planes in-kernel, paying the full element count *plus* shift
overhead; it measured 3.2x slower. SWAR is the design that actually banks
the element saving:

1. **Quarter-strip (SoA) packing**: the padded row is split into 4 equal
   strips; byte k of word j is strip k's pixel j. A horizontal stencil tap
   is then a plain word-column shift for all four strips at once — no
   cross-lane byte algebra (the packed layout's fatal cost).
2. **16-bit SWAR fields**: each word splits once into two u32 arrays
   holding 2x16-bit fields (bytes 0,2 and 1,3). The whole separable
   correlation runs as u32 mul/add on those fields — 2 pixels per 32-bit
   element, half the VPU element count of f32 compute — and stays exact:
   for integer taps with sum S, row accumulators are <= 255*S and column
   accumulators <= 255*S^2, so S^2 <= 257 (S <= 16) guarantees no field
   overflow. The final x S^-2 with round-half-to-even is the integer
   identity q = (s + (S^2/2 - 1) + (q0 & 1)) >> k with q0 = s >> k,
   k = log2(S^2) — bit-identical to the golden ``rint_clip`` quantize
   (clipping is vacuous: the weighted mean of u8 values is in [0, 255]).

Eligibility (``swar_eligible``): single-plane u8 (H, W) with W % 4 == 0,
StencilOp with ``reduce='corr'``, ``combine='single'``, an integer
non-negative separable vector whose sum S is a power of two with
2 <= S <= 16, ``scale == 1/S^2``, ``quantize='rint_clip'``, and a real
border extension (not the reference's ``interior`` guard). In the registry
that is exactly the binomial Gaussians 3 and 5 (gaussian:7 has S = 64:
its column pass would overflow 16-bit fields). Ineligible ops fall back to
the u8 streaming kernels per op, so ``impl='swar'`` is always-correct —
the same contract as ``impl='packed'`` (ops/packed_kernels.py).

The streaming kernel reuses the production scratch-carry structure
(ops/pallas_kernels.stencil_tile_pallas): ext-row blocks stream in
non-overlapping, the row-passed fields of the previous block live in VMEM
scratch, and output block i-1 is the column pass over
[scratch ; first 2h rows of block i]. Reference analogue: the CUDA 5x5
stencil path (kernel.cu:64-94), minus its in-place race and missing halo.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    _PAD_MODES,
    Op,
    StencilOp,
)
from mpi_cuda_imagemanipulation_tpu.utils import calibration
from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

# field masks as python ints (a pallas kernel body must not capture traced
# constants); & / + / * with a uint32 array stays uint32
_M_LO = 0x00FF00FF  # bytes 0,2 as 16-bit fields
_M_B = 0x00010001  # LSB of each field


def swar_eligible(op: Op, plane_shape: tuple[int, ...] | None = None) -> bool:
    """True iff `op` (on an optional (H, W) u8 plane shape) can run on the
    SWAR path. See module docstring for the exact conditions."""
    if not isinstance(op, StencilOp):
        return False
    if op.reduce != "corr" or op.combine != "single":
        return False
    if op.quantize != "rint_clip":
        return False
    if op.edge_mode == "interior" or op.edge_mode not in _PAD_MODES:
        return False
    taps = op.separable
    if taps is None:
        return False
    t = np.asarray(taps)
    if not np.all(t == np.floor(t)) or np.any(t < 0):
        return False
    s = int(t.sum())
    if s < 2 or s > 16 or (s & (s - 1)):
        return False
    if abs(op.scale * s * s - 1.0) > 1e-12:
        return False
    # exact form (not (n-1)//2): make_swar_stencil assumes n - 1 == 2*halo,
    # and this also rejects even-length tap vectors, which would otherwise
    # pass a truncating check and crash in-kernel instead of falling back
    # (advisor round-4 finding)
    if len(t) - 1 != 2 * op.halo:
        return False
    if plane_shape is not None:
        if len(plane_shape) != 2:
            return False
        h_img, w_img = plane_shape
        if w_img % 4 or w_img // 4 < 2 * op.halo + 1 or h_img <= op.halo:
            return False
    return True


def _taps_shift(op: StencilOp) -> tuple[tuple[int, ...], int]:
    """(integer taps, k) with 2^k = S^2 — the field arithmetic constants."""
    t = tuple(int(v) for v in np.asarray(op.separable))
    s = sum(t)
    k = int(s * s).bit_length() - 1
    return t, k


def pack_quarters(xpad: jnp.ndarray, halo: int) -> jnp.ndarray:
    """(H+2h, W+2h) u8 padded plane -> (H+2h, W/4+2h) u32 quarter-strip
    words: byte k of word j is strip k's padded pixel j. Each strip's ext
    covers [k*Ws, k*Ws + Ws + 2h) of the padded row, so every horizontal
    tap is word-local."""
    hp, wp2 = xpad.shape
    ws = (wp2 - 2 * halo) // 4
    strips = [xpad[:, k * ws : k * ws + ws + 2 * halo] for k in range(4)]
    stacked = jnp.stack(strips, axis=-1)  # (Hp, Ws+2h, 4) u8
    return jax.lax.bitcast_convert_type(stacked, jnp.uint32)


def unpack_quarters(words: jnp.ndarray) -> jnp.ndarray:
    """(H, Ws) u32 -> (H, 4*Ws) u8 by reassembling the quarter strips."""
    b = jax.lax.bitcast_convert_type(words, jnp.uint8)  # (H, Ws, 4)
    return jnp.concatenate([b[..., k] for k in range(4)], axis=1)


def _row_pass_fields(ext_block: jnp.ndarray, taps: tuple[int, ...]):
    """(bh, Ws+2h) u32 words -> two (bh, Ws) u32 field arrays (bytes 0,2
    and 1,3 as 16-bit fields), row-correlated with `taps`."""
    n = len(taps)
    lo = ext_block & _M_LO
    hi = (ext_block >> 8) & _M_LO

    def row(a):
        w = a.shape[1] - (n - 1)
        acc = a[:, 0:w] * jnp.uint32(taps[0])
        for t in range(1, n):
            acc = acc + a[:, t : w + t] * jnp.uint32(taps[t])
        return acc

    return row(lo), row(hi)


def _col_finalize(lo_rows, hi_rows, taps: tuple[int, ...], k: int):
    """(bh+2h, Ws) field arrays -> (bh, Ws) u32 output words: column pass +
    x 2^-k round-half-to-even + byte repack."""
    n = len(taps)
    half = (1 << (k - 1)) - 1
    m_half = (half << 16) | half

    def col(a):
        hgt = a.shape[0] - (n - 1)
        acc = a[0:hgt, :] * jnp.uint32(taps[0])
        for t in range(1, n):
            acc = acc + a[t : hgt + t, :] * jnp.uint32(taps[t])
        return acc

    def rnd(s):
        b = (s >> k) & _M_B
        return ((s + m_half + b) >> k) & _M_LO

    return rnd(col(lo_rows)) | (rnd(col(hi_rows)) << 8)


def _pick_swar_block_h(ws: int, halo: int) -> int:
    """VMEM-safe ext-row block height for the carry kernel.

    Working set per ext row: u32 input block (double-buffered) + two field
    scratch blocks + output block (double-buffered) + ~6 live u32 temps
    while the body runs — all Ws-wide words. Budget mirrors the u8 kernels'
    3/4 of the 64 MiB scoped-VMEM limit (ops/pallas_kernels.py)."""
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import _VMEM_LIMIT

    budget = 3 * _VMEM_LIMIT // 4
    per_row = 4 * (ws + 2 * halo) * 2 + 4 * ws * (2 + 2 + 6)
    bh = budget // max(per_row, 1)
    bh = int(max(2 * halo, min(512, bh)))
    bh = max(8, (bh // 8) * 8)
    calibrated = calibration.lookup_block_h(impl="swar", width=4 * ws)
    if calibrated is not None:
        bh = max(2 * halo, max(8, min(bh, (calibrated // 8) * 8)))
    return bh


def make_swar_stencil(
    ext_shape: tuple[int, int],
    taps: tuple[int, ...],
    k: int,
    bh: int,
    *,
    interpret: bool = False,
):
    """Streaming SWAR kernel over quarter-strip words with the production
    scratch-carry structure. `ext_shape` = (H+2h, Ws+2h) words; returns a
    function ext_words -> (ceil(H/bh)*bh, Ws) u32 (caller crops [:H]).

    Ragged heights are fine: out rows >= H are garbage (OOB-padded input
    blocks / duplicated tail rows via the clamped index maps) and the
    caller crops — every real out row r reads ext rows [r, r+2h], which
    live in the scratch block and the next block's first 2h rows by
    construction."""
    halo = (len(taps) - 1) // 2
    hp, wsp = ext_shape
    height = hp - 2 * halo
    ws = wsp - 2 * halo
    if bh < 2 * halo:
        raise ValueError(f"block_h {bh} < 2*halo {2 * halo}")
    nb = -(-height // bh)
    nb_in = -(-hp // bh)  # last block holds the bottom halo rows

    def kernel(in_ref, out_ref, lo_ref, hi_ref):
        i = pl.program_id(0)
        rlo, rhi = _row_pass_fields(in_ref[:], taps)

        @pl.when(i >= 1)
        def _():
            lo_rows = jnp.concatenate([lo_ref[:], rlo[: 2 * halo]], axis=0)
            hi_rows = jnp.concatenate([hi_ref[:], rhi[: 2 * halo]], axis=0)
            out_ref[:] = _col_finalize(lo_rows, hi_rows, taps, k)

        lo_ref[:] = rlo
        hi_ref[:] = rhi

    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        _COMPILER_PARAMS,
    )

    return pl.pallas_call(
        kernel,
        grid=(nb + 1,),
        in_specs=[
            pl.BlockSpec(
                (bh, wsp),
                lambda i: (jnp.minimum(i, nb_in - 1), 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (bh, ws),
            lambda i: (jnp.maximum(i - 1, 0), 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((nb * bh, ws), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((bh, ws), jnp.uint32),
            pltpu.VMEM((bh, ws), jnp.uint32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


def swar_stencil(
    op: StencilOp,
    img: jnp.ndarray,
    *,
    block_h: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One eligible StencilOp on a (H, W) u8 plane via the SWAR path.

    `interpret=None` resolves like every other kernel entry point
    (compiled on TPU, interpreter elsewhere), so callers pass their own
    `interpret` straight through."""
    if interpret is None:
        interpret = not is_tpu_backend()
    taps, k = _taps_shift(op)
    halo = op.halo
    height, width = img.shape
    ws = width // 4
    xpad = jnp.pad(
        img, ((halo, halo), (halo, halo)), mode=_PAD_MODES[op.edge_mode]
    )
    ext = pack_quarters(xpad, halo)
    bh = block_h or _pick_swar_block_h(ws, halo)
    outw = make_swar_stencil(
        ext.shape, taps, k, bh, interpret=interpret
    )(ext)
    return unpack_quarters(outw[:height])


def pipeline_swar(
    ops: tuple[Op, ...],
    img: jnp.ndarray,
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
) -> jnp.ndarray:
    """Run a pipeline with eligible stencils on the SWAR path and every
    other op on the u8 streaming kernels (fallback keeps the backend
    always-correct, the ``impl='packed'`` contract).

    Fallback granularity is maximal runs, not single ops: consecutive
    ineligible ops go to pipeline_pallas as ONE call so its group fusion
    (pointwise chains folded into stencil streams) is preserved — per-op
    fallback would pay an extra HBM read+write per op (review finding).

    An explicit ``block_h`` applies to the SWAR kernels only; fallback
    flushes let the u8 path's own heuristic pick (advisor round-4
    finding: swar-granularity heights — multiples of 8, as low as 8 —
    would otherwise silently shape the u8 kernels, which are tuned at
    multiples of 32)."""
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        pipeline_pallas,
    )

    if interpret is None:
        interpret = not is_tpu_backend()

    pending: list[Op] = []

    def flush(im):
        if pending:
            im = pipeline_pallas(
                tuple(pending), im, interpret=interpret, block_h=None
            )
            pending.clear()
        return im

    for op in ops:
        if swar_eligible(op):
            # op-qualifies; the shape gate needs the ACTUAL input to this
            # op, so flush the pending run first
            img = flush(img)
            if img.dtype == jnp.uint8 and swar_eligible(
                op, tuple(img.shape)
            ):
                img = swar_stencil(
                    op, img, block_h=block_h, interpret=interpret
                )
                continue
        pending.append(op)
    return flush(img)
