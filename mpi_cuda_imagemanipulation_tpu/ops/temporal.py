"""Temporal ops — the first cross-frame operators (stream/ video mode).

Every op elsewhere in ``ops/`` maps one image to one image; video adds
operators whose output at frame t depends on a bounded window of PAST
frames. They are deliberately host-side numpy over uint8 frames: the
per-frame spatial chain still runs through the compiled tile pipeline,
and the temporal combine is a cheap pointwise pass over the bounded
frame-history ring the stream runner maintains (stream/video.py) — the
ring, not the video, bounds memory, which is what makes hour-long
streams a constant-footprint workload.

Golden semantics (deterministic, integer-exact):

  * ``framediff`` — ``|f_t - f_{t-1}|`` per pixel (u8 absolute
    difference, computed in int16 so 255-0 doesn't wrap). Frame 0 has no
    predecessor and diffs against itself: an all-zeros first frame, the
    standard motion-mask convention.
  * ``tdenoise:K`` — temporal box denoise: round-to-nearest-even mean of
    the last K frames (fewer while the ring is still filling). Integer
    sums are exact in int32; the single divide + rint happens in
    float64 on the host, so the result is identical on every platform.

Temporal ops must lead the chain (``framediff,grayscale,gaussian:5``):
they consume raw frames from the ring, and everything after them is the
ordinary spatial pipeline. ``split_temporal`` enforces that."""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class TemporalOp:
    """One cross-frame operator.

    ``window`` is the ring capacity the op needs: how many frames of
    history (INCLUDING the current frame) ``fn`` may read. ``fn``
    receives the ring oldest-to-newest — at stream start it is shorter
    than ``window`` and the op must define its warm-up behaviour (both
    ops here do)."""

    name: str
    window: int
    fn: Callable[[Sequence[np.ndarray]], np.ndarray]

    def __call__(self, history: Sequence[np.ndarray]) -> np.ndarray:
        if not history:
            raise ValueError(f"temporal op {self.name!r}: empty history")
        return self.fn(history)


def _framediff(history: Sequence[np.ndarray]) -> np.ndarray:
    cur = history[-1]
    prev = history[-2] if len(history) > 1 else cur
    d = np.abs(cur.astype(np.int16) - prev.astype(np.int16))
    return d.astype(np.uint8)


def make_framediff() -> TemporalOp:
    return TemporalOp("framediff", window=2, fn=_framediff)


def make_tdenoise(k: int) -> TemporalOp:
    if k < 2:
        raise ValueError(f"tdenoise window must be >= 2, got {k}")

    def tdenoise(history: Sequence[np.ndarray]) -> np.ndarray:
        frames = list(history)[-k:]  # history may be a deque (no slicing)
        acc = np.zeros(frames[0].shape, dtype=np.int32)
        for f in frames:
            acc += f
        # exact integer sum, one host-side float64 divide + rint: the
        # same quantizer discipline as the spatial rint_clip ops
        return np.rint(acc / np.float64(len(frames))).astype(np.uint8)

    return TemporalOp(f"tdenoise{k}", window=k, fn=tdenoise)


# name -> factory(arg_str_or_None) — the video-mode counterpart of
# ops.registry.REGISTRY (kept separate: these are invalid in per-image
# pipelines, and Pipeline.parse must keep rejecting them loudly)
TEMPORAL_REGISTRY: dict[str, Callable[[str | None], TemporalOp]] = {
    "framediff": lambda a: make_framediff(),
    "tdenoise": lambda a: make_tdenoise(int(a) if a else 3),
}


def split_temporal(spec: str) -> tuple[tuple[TemporalOp, ...], str]:
    """Split a stream pipeline spec into its leading temporal ops and the
    trailing spatial spec (handed to ``Pipeline.parse``). Temporal ops
    after a spatial op are rejected: the ring holds raw input frames, so
    a mid-chain temporal op would need a second ring of intermediate
    frames per op — out of scope until a workload needs it."""
    temporal: list[TemporalOp] = []
    rest: list[str] = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, _, arg = tok.partition(":")
        factory = TEMPORAL_REGISTRY.get(name.strip().lower())
        if factory is not None:
            if rest:
                raise ValueError(
                    f"temporal op {tok!r} must precede every spatial op "
                    "(the frame ring holds raw inputs; see ops/temporal.py)"
                )
            temporal.append(factory(arg.strip() or None if arg else None))
        else:
            rest.append(tok)
    return tuple(temporal), ",".join(rest)
