"""Filter-bank definitions (correlation weights, `w[dy, dx]` indexing).

All stencil weights in this framework are *integers* stored as float32, with a
separate power-of-two (or single-multiply) normalisation scale. Integer
accumulation is exact in float32 (all partial sums < 2**24), so every backend
(golden jnp, Pallas tiles, sharded shard_map tiles) produces bit-identical
results regardless of accumulation order — the framework's cross-backend
bit-exactness guarantee rests on this.

Reference provenance:
  - EMBOSS3 / EMBOSS5: /root/reference/kernel.cu:71-82. The reference indexes
    `filter[fx][fy]` where `fx` is the *x* displacement (kernel.cu:86-88),
    i.e. it applies the transposed matrix; both matrices are symmetric so the
    transposition is unobservable, but we store the transposed ("as applied")
    orientation explicitly.
  - Gaussian / Sobel / box / sharpen: not present in the reference; mandated
    by BASELINE.json's benchmark configs and standard definitions.
"""

from __future__ import annotations

import numpy as np


def _w(rows) -> np.ndarray:
    a = np.asarray(rows, dtype=np.float32)
    assert a.ndim in (1, 2)
    return a


# Reference emboss 3x3 (kernel.cu:71-75), stored transposed (as applied —
# symmetric, so identical to the source matrix).
EMBOSS3 = _w([[-2, -1, 0], [-1, 1, 1], [0, 1, 2]]).T.copy()

# Reference emboss 5x5 (kernel.cu:76-82): diagonal {4, 4, 1, -4, -4}.
EMBOSS5 = _w(np.diag([4.0, 4.0, 1.0, -4.0, -4.0]).astype(np.float32)).T.copy()


def binomial_1d(size: int) -> np.ndarray:
    """Integer binomial (Pascal) row, e.g. size=5 -> [1, 4, 6, 4, 1]."""
    row = np.array([1.0], dtype=np.float64)
    for _ in range(size - 1):
        row = np.convolve(row, [1.0, 1.0])
    return row.astype(np.float32)


def gaussian_2d(size: int) -> tuple[np.ndarray, float]:
    """Integer 2-D binomial-Gaussian kernel and its power-of-two 1/norm."""
    row = binomial_1d(size)
    k2 = np.outer(row, row).astype(np.float32)
    norm = float(k2.sum())  # (2**(size-1))**2 — a power of two
    return k2, 1.0 / norm


SOBEL_GX = _w([[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]])
SOBEL_GY = SOBEL_GX.T.copy()

PREWITT_GX = _w([[-1, 0, 1], [-1, 0, 1], [-1, 0, 1]])
PREWITT_GY = PREWITT_GX.T.copy()

SCHARR_GX = _w([[-3, 0, 3], [-10, 0, 10], [-3, 0, 3]])
SCHARR_GY = SCHARR_GX.T.copy()

SHARPEN3 = _w([[0, -1, 0], [-1, 5, -1], [0, -1, 0]])

# 4- and 8-neighbour Laplacians (OpenCV/classic definitions)
LAPLACIAN4 = _w([[0, 1, 0], [1, -4, 1], [0, 1, 0]])
LAPLACIAN8 = _w([[1, 1, 1], [1, -8, 1], [1, 1, 1]])

# Unsharp mask: identity*2 - gaussian, as one integer 5x5 kernel with a
# power-of-two scale: 2*256*delta - binomial5x5, /256.
_G5 = np.outer(binomial_1d(5), binomial_1d(5)).astype(np.float32)
UNSHARP5 = (-_G5).copy()
UNSHARP5[2, 2] += 2.0 * 256.0
UNSHARP5_SCALE = 1.0 / 256.0


def box_2d(size: int) -> tuple[np.ndarray, float]:
    k2 = np.ones((size, size), dtype=np.float32)
    return k2, 1.0 / float(size * size)
