"""Global-statistics ops: histogram, equalization, autocontrast, Otsu.

The reference computes no image statistics whatsoever (its three kernels are
all local, kernel.cu:31-94); these ops add the classic histogram toolkit,
designed around the framework's sharded-execution invariant:

every op is decomposed into an *additive* statistic plus a pointwise apply
(see ``GlobalOp`` in ops/spec.py). The statistic is a 256-bin int32
histogram — exact integer counts (f32 would lose exactness past 2^24
pixels; an 8K frame already has 33M), summable across shards with one
``lax.psum``. The LUT derived from it uses only f64-free f32 arithmetic on
exact integers, so sharded and unsharded paths build bit-identical LUTs.

All ops operate on single-channel (grayscale) images, like OpenCV's
``equalizeHist``; run ``grayscale`` first for colour inputs.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from mpi_cuda_imagemanipulation_tpu.ops.spec import F32, U8, GlobalOp

BINS = 256


def histogram_stats(img: jnp.ndarray, valid: jnp.ndarray | None) -> jnp.ndarray:
    """int32[256] pixel-value counts; `valid` (broadcastable to img, 0/1)
    masks rows that are sharding padding, not image content."""
    idx = img.astype(jnp.int32).ravel()
    if valid is None:
        weights = None
    else:
        weights = jnp.broadcast_to(valid.astype(jnp.int32), img.shape).ravel()
    return jnp.bincount(idx, weights=weights, length=BINS).astype(jnp.int32)


def _lut_apply(img: jnp.ndarray, lut_f32: jnp.ndarray) -> jnp.ndarray:
    """Apply an f32[256] LUT holding exact u8 integer values."""
    return jnp.take(lut_f32, img.astype(jnp.int32)).astype(U8)


# --------------------------------------------------------------------------
# Equalize (cv::equalizeHist semantics)
# --------------------------------------------------------------------------


def equalize_apply(img: jnp.ndarray, hist: jnp.ndarray) -> jnp.ndarray:
    """lut[i] = round((cdf(i) - cdf_min) / (N - cdf_min) * 255), where
    cdf_min is the CDF at the lowest occupied bin — OpenCV's equalizeHist
    formula. Constant images (denominator 0) pass through unchanged."""
    cdf = jnp.cumsum(hist)  # int32, exact
    total = cdf[-1]
    # cdf value at the first nonzero bin == min over occupied bins of cdf
    cdf_min = jnp.min(jnp.where(hist > 0, cdf, total))
    denom = (total - cdf_min).astype(F32)
    scaled = (cdf - cdf_min).astype(F32) * (np.float32(255.0) / denom)
    lut = jnp.clip(jnp.rint(scaled), 0.0, 255.0)
    ident = jnp.arange(BINS, dtype=F32)
    lut = jnp.where(denom > 0, lut, ident)
    return _lut_apply(img, lut)


EQUALIZE = GlobalOp(
    "equalize", stats=histogram_stats, apply=equalize_apply
)


# --------------------------------------------------------------------------
# Autocontrast (linear stretch of the occupied range to [0, 255])
# --------------------------------------------------------------------------


def autocontrast_apply(img: jnp.ndarray, hist: jnp.ndarray) -> jnp.ndarray:
    occupied = hist > 0
    bins = jnp.arange(BINS, dtype=jnp.int32)
    lo = jnp.min(jnp.where(occupied, bins, BINS)).astype(F32)
    hi = jnp.max(jnp.where(occupied, bins, -1)).astype(F32)
    span = hi - lo
    ident = jnp.arange(BINS, dtype=F32)
    scaled = (ident - lo) * (np.float32(255.0) / span)
    lut = jnp.clip(jnp.rint(scaled), 0.0, 255.0)
    lut = jnp.where(span > 0, lut, ident)
    return _lut_apply(img, lut)


AUTOCONTRAST = GlobalOp(
    "autocontrast", stats=histogram_stats, apply=autocontrast_apply
)


# --------------------------------------------------------------------------
# Otsu threshold
# --------------------------------------------------------------------------


def otsu_threshold_from_hist(hist: jnp.ndarray) -> jnp.ndarray:
    """Otsu's method: the threshold t maximising between-class variance
    w0(t)·w1(t)·(mu0(t) - mu1(t))^2, pixels <= t in class 0. Class counts
    use exact int32 cumulative sums (total pixels < 2^31); the weighted
    moments would overflow int32 (255 · 33M for an 8K frame) and JAX
    disables x64 by default, so they run in f32 — not bit-exact vs a big
    integer, but *deterministic*: the sharded path psums the integer
    histogram first and then evaluates this same replicated computation, so
    sharded == unsharded exactly."""
    h = hist.astype(jnp.int32)
    bins = jnp.arange(BINS, dtype=jnp.int32)
    w0 = jnp.cumsum(h)  # pixels <= t, exact
    total = w0[-1]
    # per-bin product already overflows int32 (count*bin <= 33M*255), so
    # cast each factor first; f32 cumsum is deterministic (see above)
    s0 = jnp.cumsum(h.astype(F32) * bins.astype(F32))
    stotal = s0[-1]
    w1 = total - w0
    valid = (w0 > 0) & (w1 > 0)
    mu0 = s0 / jnp.maximum(w0, 1).astype(F32)
    mu1 = (stotal - s0) / jnp.maximum(w1, 1).astype(F32)
    d = mu0 - mu1
    between = w0.astype(F32) * w1.astype(F32) * d * d
    between = jnp.where(valid, between, -1.0)
    # jnp.argmax returns the FIRST maximising bin -> deterministic tie-break
    return jnp.argmax(between).astype(jnp.int32)


def otsu_apply(img: jnp.ndarray, hist: jnp.ndarray) -> jnp.ndarray:
    t = otsu_threshold_from_hist(hist)
    return jnp.where(img.astype(jnp.int32) > t, np.uint8(255), np.uint8(0)).astype(U8)


OTSU = GlobalOp("otsu", stats=histogram_stats, apply=otsu_apply)
