"""Concrete op definitions and the name -> op factory registry.

The registry is what the CLI and `Pipeline` parse: an op string is
``name`` or ``name:arg`` (e.g. ``contrast:3.5``, ``emboss:5``, ``gaussian:7``),
and a pipeline string is comma-separated op strings, e.g. the reference
pipeline (kernel.cu:192-195) is ``grayscale,contrast:3.5,emboss:3``.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax.numpy as jnp
import numpy as np

from mpi_cuda_imagemanipulation_tpu.ops import filters, geometry, histogram
from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    F32,
    U8,
    Op,
    PointwiseOp,
    StencilOp,
    pointwise_from_core,
    trunc_clip_f32,
)

# --------------------------------------------------------------------------
# Pointwise op bodies
# --------------------------------------------------------------------------


def grayscale_core(r: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Reference grayscale semantics (kernel.cu:39-42) on f32 channel planes.

    Each weighted term is truncated to u8 *before* summing — the reference's
    quirk, kept as golden per SURVEY.md §2.6. Truncation is jnp.floor in f32
    (terms are non-negative) and the three floored terms sum exactly in f32
    (max 28+150+76 = 254), so this is bit-identical to per-term u8 casts
    while staying in the VPU-native dtype — the same code runs inside Pallas
    kernels. The reference reads BGR (OpenCV) and weights B*0.11 + G*0.59 +
    R*0.3; our I/O layer produces RGB, so the weights are identical per
    colour, just reordered.
    """
    tr = jnp.floor(r * np.float32(0.3))
    tg = jnp.floor(g * np.float32(0.59))
    tb = jnp.floor(b * np.float32(0.11))
    return tr + tg + tb


def grayscale_from_planes(
    r: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray
) -> jnp.ndarray:
    """u8-plane wrapper over grayscale_core."""
    return grayscale_core(r.astype(F32), g.astype(F32), b.astype(F32)).astype(U8)


def grayscale_u8(img: jnp.ndarray) -> jnp.ndarray:
    """Golden grayscale on an (H, W, 3) RGB image; see grayscale_core."""
    return grayscale_from_planes(img[..., 0], img[..., 1], img[..., 2])


def grayscale601_core(r: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """OpenCV-parity Rec.601 grayscale — the *other* reference variant
    (kern.cpp:73 cvtColor COLOR_BGR2GRAY), which rounds instead of
    truncating (SURVEY.md §2.2 notes the two programs disagree).

    Bit-exact to OpenCV's fixed-point formula: (R*4899 + G*9617 + B*1868 +
    8192) >> 14. All intermediates < 2^22, exact in f32; >>14 is an exact
    power-of-two multiply + floor.
    """
    acc = (
        r * np.float32(4899.0)
        + g * np.float32(9617.0)
        + b * np.float32(1868.0)
        + np.float32(8192.0)
    )
    return jnp.floor(acc * np.float32(1.0 / 16384.0))


def grayscale601_u8(img: jnp.ndarray) -> jnp.ndarray:
    return grayscale601_core(
        img[..., 0].astype(F32), img[..., 1].astype(F32), img[..., 2].astype(F32)
    ).astype(U8)


def make_contrast_core(factor: float) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Reference contrast (kernel.cu:49-58): clamp(f*(p-128)+128), truncated.

    All intermediate values are exactly representable in f32 for f = 3.5
    (and any factor with a short binary fraction), so this is bit-exact
    against the C float computation.
    """
    ff = np.float32(factor)

    def contrast(x: jnp.ndarray) -> jnp.ndarray:
        return trunc_clip_f32(ff * (x - np.float32(128.0)) + np.float32(128.0))

    return contrast


def _contrast_rounding_free(factor: float) -> bool:
    """Whether clamp(f*(p-128)+128) incurs zero f32 rounding for every
    p in 0..255 (checked on the host against float64). When it does, the
    computation is immune to fma contraction / reordering and the fast
    in-kernel core is bit-exact on every backend (true for the reference's
    3.5 and 3, and any factor with a short binary fraction). When it does
    not (e.g. 4.3), eager per-op rounding and XLA's fused multiply-add can
    differ in the last ulp, which the trunc quantizer amplifies to a full
    uint8 step — those factors route to a LUT instead."""
    ff = np.float64(np.float32(factor))
    d = np.arange(256, dtype=np.float64) - 128.0
    prod = ff * d
    if not np.array_equal(prod.astype(np.float32).astype(np.float64), prod):
        return False
    s = prod + 128.0
    return bool(np.array_equal(s.astype(np.float32).astype(np.float64), s))


def make_contrast_lut(factor: float) -> np.ndarray:
    """256-entry contrast table reproducing the eager golden computation
    (per-op f32 rounding: mul, add, clip, trunc) on the host — the one
    result every backend then agrees on via a gather.

    Deliberately pure numpy, NOT the jnp core evaluated on arange(256):
    op construction happens at pipeline-parse time, which must never
    dispatch to a device (the default backend can be a wedged remote
    tunnel, utils/platform.py). Agreement with the in-graph core is
    asserted for all 256 inputs by tests/test_golden.py instead."""
    ff = np.float32(factor)
    d = np.arange(256, dtype=np.float32) - np.float32(128.0)
    v = (ff * d).astype(np.float32) + np.float32(128.0)
    return np.floor(np.clip(v.astype(np.float32), 0.0, 255.0)).astype(np.uint8)


def make_brightness_core(delta: float) -> Callable[[jnp.ndarray], jnp.ndarray]:
    d = np.float32(delta)

    def brightness(x: jnp.ndarray) -> jnp.ndarray:
        return trunc_clip_f32(x + d)

    return brightness


def make_brightness_lut(delta: float) -> np.ndarray:
    """Host replay of the brightness core's f32 ops (pure numpy — see
    make_contrast_lut for why no jnp at op-construction time)."""
    v = np.arange(256, dtype=np.float32) + np.float32(delta)
    return np.floor(np.clip(v, 0.0, 255.0)).astype(np.uint8)


def invert_core(x: jnp.ndarray) -> jnp.ndarray:
    return np.float32(255.0) - x


def invert_lut() -> np.ndarray:
    return (255 - np.arange(256)).astype(np.uint8)


def make_threshold_core(t: float) -> Callable[[jnp.ndarray], jnp.ndarray]:
    if not 0 <= t <= 255:
        raise ValueError(f"threshold must be in [0, 255], got {t}")
    tv = np.float32(np.uint8(t))  # match u8 truncation of the threshold arg

    def threshold(x: jnp.ndarray) -> jnp.ndarray:
        return jnp.where(x >= tv, np.float32(255.0), np.float32(0.0))

    return threshold


def make_gamma_lut(g: float) -> np.ndarray:
    """256-entry gamma table computed on the host in float64 — exact and
    backend-independent (f32 pow differs in ulps between CPU libm and the
    TPU VPU, which would break the cross-backend bit-exactness guarantee)."""
    if g <= 0:
        raise ValueError(f"gamma must be > 0, got {g}")
    v = np.arange(256, dtype=np.float64) / 255.0
    return np.rint(255.0 * np.power(v, g)).astype(np.uint8)


def make_lut_op(
    name: str,
    table: np.ndarray,
    in_channels: int = 0,
    out_channels: int = 0,
) -> PointwiseOp:
    """Pointwise op applying a 256-entry u8 lookup table via gather.

    kernel_safe=False: Mosaic has no general gather, so LUT ops run as XLA
    steps between Pallas groups (group_ops splits around them); XLA lowers
    the 256-entry take to a cheap dynamic-slice/select chain.

    Construction is host-pure: the table stays a numpy array until the op
    body runs, so Pipeline.parse never dispatches to a device even for LUT
    ops (advisor round-2 finding: an eager jnp.asarray here initialized the
    default backend at parse time, which can block forever on a wedged
    accelerator tunnel). Under jit the asarray is constant-folded at trace
    time; eager callers were going to dispatch on the very next line anyway.
    """
    table = np.asarray(table, dtype=np.uint8)

    def fn(img: jnp.ndarray) -> jnp.ndarray:
        return jnp.take(jnp.asarray(table), img.astype(jnp.int32))

    return PointwiseOp(name, in_channels, out_channels, fn=fn, kernel_safe=False)


# Standard sepia tone matrix (as used by e.g. Microsoft/ImageMagick docs),
# stored x1000 as integers: integer multiply-accumulate is exact in f32
# (sums < 2**24), so the accumulation is immune to fma contraction and
# reordering across backends; the single 0.001 scale is one exactly-rounded
# op — deterministic everywhere. (Non-integer weights summed in f32 are NOT:
# XLA's fma fusion changed rounding at exactly-.5 boundaries in testing.)
SEPIA_MATRIX_X1000 = np.array(
    [
        [393, 769, 189],
        [349, 686, 168],
        [272, 534, 131],
    ],
    dtype=np.float32,
)


def sepia_planes_core(r: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray):
    from mpi_cuda_imagemanipulation_tpu.ops.spec import rint_clip_f32

    m = SEPIA_MATRIX_X1000
    scale = np.float32(0.001)
    return [
        rint_clip_f32((r * m[i, 0] + g * m[i, 1] + b * m[i, 2]) * scale)
        for i in range(3)
    ]


def sepia_u8(img: jnp.ndarray) -> jnp.ndarray:
    planes = sepia_planes_core(
        img[..., 0].astype(F32), img[..., 1].astype(F32), img[..., 2].astype(F32)
    )
    return jnp.stack([p.astype(U8) for p in planes], axis=-1)


def make_posterize_core(bits: int) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """PIL-parity posterize: keep the top `bits` bits ((x >> s) << s), as an
    exact f32 floor-multiply."""
    if not 1 <= bits <= 8:
        raise ValueError(f"posterize bits must be in [1, 8], got {bits}")
    step = np.float32(float(2 ** (8 - bits)))

    def posterize(x: jnp.ndarray) -> jnp.ndarray:
        return jnp.floor(x / step) * step

    return posterize


def make_solarize_core(t: float) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """PIL-parity solarize: invert every pixel >= threshold."""
    if not 0 <= t <= 255:
        raise ValueError(f"solarize threshold must be in [0, 255], got {t}")
    tv = np.float32(t)

    def solarize(x: jnp.ndarray) -> jnp.ndarray:
        return jnp.where(x >= tv, np.float32(255.0) - x, x)

    return solarize


def gray2rgb_u8(img: jnp.ndarray) -> jnp.ndarray:
    """Channel-replicate, the reference's GRAY2BGR step (kernel.cu:210)."""
    return jnp.broadcast_to(img[..., None], (*img.shape, 3))


def make_contrast(factor: float) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """u8 -> u8 contrast function (see _make_contrast for factor routing)."""
    return _make_contrast(factor).fn


# --------------------------------------------------------------------------
# Stencil op instances
# --------------------------------------------------------------------------


def make_emboss(size: int) -> StencilOp:
    if size not in (3, 5):
        raise ValueError(f"emboss size must be 3 or 5 (kernel.cu:66), got {size}")
    k = filters.EMBOSS3 if size == 3 else filters.EMBOSS5
    return StencilOp(
        name=f"emboss{size}",
        halo=(size - 1) // 2,
        kernels=(k,),
        edge_mode="interior",
        quantize="trunc_clip",
    )


def make_emboss101(size: int) -> StencilOp:
    """The kern.cpp emboss variant (filter2D, kern.cpp:62-75): same filter
    values but edges ARE filtered with OpenCV's default BORDER_REFLECT_101,
    and results round to nearest even (cvRound) — SURVEY.md §2.2."""
    if size not in (3, 5):
        raise ValueError(f"emboss101 size must be 3 or 5, got {size}")
    k = filters.EMBOSS3 if size == 3 else filters.EMBOSS5
    return StencilOp(
        name=f"emboss101_{size}",
        halo=(size - 1) // 2,
        kernels=(k,),
        edge_mode="reflect101",
        quantize="rint_clip",
    )


def make_gaussian(size: int) -> StencilOp:
    if size not in (3, 5, 7):
        raise ValueError(f"gaussian size must be 3, 5 or 7, got {size}")
    k2, scale = filters.gaussian_2d(size)
    return StencilOp(
        name=f"gaussian{size}",
        halo=(size - 1) // 2,
        kernels=(k2,),
        scale=scale,  # power of two — exact
        separable=filters.binomial_1d(size),
        edge_mode="reflect101",
        quantize="rint_clip",
    )


def make_box(size: int) -> StencilOp:
    # even sizes are ill-defined under the symmetric-halo tile machinery
    # (halo = (size-1)//2 under-pads, silently shrinking the golden output
    # and breaking the tiled kernels) — reject like make_morph/make_median.
    # size 1 stays legal: the degenerate halo-0 box is the identity and is
    # used as the halo-0 stencil regression case (tests/test_sharded.py)
    if size < 1 or size % 2 == 0:
        raise ValueError(f"box size must be odd and >= 1, got {size}")
    k2, scale = filters.box_2d(size)
    return StencilOp(
        name=f"box{size}",
        halo=(size - 1) // 2,
        kernels=(k2,),
        scale=scale,
        separable=np.ones((size,), np.float32),
        edge_mode="reflect101",
        quantize="rint_clip",
    )


def make_morph(kind: str, size: int) -> StencilOp:
    """Grayscale morphology: erode (window min) / dilate (window max) over a
    size x size square structuring element. Square min/max is separable, so
    the cost is O(size) shifts per pixel on every backend."""
    if size < 3 or size % 2 == 0:
        raise ValueError(f"{kind} size must be odd and >= 3, got {size}")
    return StencilOp(
        name=f"{kind}{size}",
        halo=(size - 1) // 2,
        kernels=(np.ones((size, size), np.float32),),
        reduce="min" if kind == "erode" else "max",
        edge_mode="edge",  # border-replicate: morphology identity outside
        quantize="rint_clip",  # identity on the integer-valued min/max result
    )


def make_median(size: int) -> StencilOp:
    """Rank filter: 3x3 via Paeth's 19-exchange median-of-9 network, 5x5 via
    a median-pruned Batcher odd-even network (113 min/max exchanges on 25
    wires) — see spec._MEDIAN_NETWORKS. Both are pure elementwise min/max,
    so they lower in Mosaic and are exact on u8-valued f32."""
    if size not in (3, 5):
        raise ValueError(
            f"median supports sizes 3 and 5 (selection networks), got {size}"
        )
    return StencilOp(
        name=f"median{size}",
        halo=(size - 1) // 2,
        kernels=(np.ones((size, size), np.float32),),
        reduce="median",
        edge_mode="reflect101",
        quantize="rint_clip",
    )


SOBEL = StencilOp(
    name="sobel",
    halo=1,
    kernels=(filters.SOBEL_GX, filters.SOBEL_GY),
    combine="magnitude",
    edge_mode="reflect101",
    quantize="rint_clip",
)

PREWITT = StencilOp(
    name="prewitt",
    halo=1,
    kernels=(filters.PREWITT_GX, filters.PREWITT_GY),
    combine="magnitude",
    edge_mode="reflect101",
    quantize="rint_clip",
)

SCHARR = StencilOp(
    name="scharr",
    halo=1,
    kernels=(filters.SCHARR_GX, filters.SCHARR_GY),
    combine="magnitude",
    edge_mode="reflect101",
    quantize="rint_clip",
)

SHARPEN = StencilOp(
    name="sharpen",
    halo=1,
    kernels=(filters.SHARPEN3,),
    edge_mode="reflect101",
    quantize="rint_clip",
)

UNSHARP = StencilOp(
    name="unsharp",
    halo=2,
    kernels=(filters.UNSHARP5,),
    scale=filters.UNSHARP5_SCALE,  # power of two — exact
    edge_mode="reflect101",
    quantize="rint_clip",
)


def make_laplacian(neighbours: int) -> StencilOp:
    if neighbours not in (4, 8):
        raise ValueError(f"laplacian connectivity must be 4 or 8, got {neighbours}")
    k = filters.LAPLACIAN4 if neighbours == 4 else filters.LAPLACIAN8
    return StencilOp(
        name=f"laplacian{neighbours}",
        halo=1,
        kernels=(k,),
        edge_mode="reflect101",
        quantize="rint_clip",  # saturating u8, like filter2D -> CV_8U
    )


def make_filter(arg: str | None) -> StencilOp:
    """Arbitrary odd-square correlation kernel — the framework's counterpart
    to the reference's cv::filter2D with a hand-built Mat (kern.cpp:62-75).

    Spec: ``filter:v1/v2/.../vK*K[:scale]`` with K in {3, 5, 7} inferred
    from the value count; weights ``w[dy, dx]`` row-major. ``/`` separates
    values inside pipeline strings (where ``,`` separates ops); standalone
    specs may use ``,`` too. Integer weights (with any single post-scale)
    keep the framework's cross-backend bit-exactness guarantee; non-integer
    weights are deterministic per backend but may differ in the last ulp
    before quantization.
    """
    if not arg:
        raise ValueError("filter needs filter:v1/v2/...[:scale]")
    parts = arg.split(":")
    sep = "/" if "/" in parts[0] else ","
    vals = [float(v) for v in parts[0].split(sep) if v.strip()]
    size = int(round(len(vals) ** 0.5))
    if size * size != len(vals) or size not in (3, 5, 7):
        raise ValueError(
            f"filter needs 9, 25 or 49 comma-separated values "
            f"(3x3/5x5/7x7 row-major), got {len(vals)}"
        )
    scale = float(parts[1]) if len(parts) > 1 else 1.0
    k = np.asarray(vals, dtype=np.float32).reshape(size, size)
    return StencilOp(
        name=f"filter{size}x{size}",
        halo=(size - 1) // 2,
        kernels=(k,),
        scale=scale,
        edge_mode="reflect101",  # filter2D's default border (kern.cpp:75)
        quantize="rint_clip",
    )

# --------------------------------------------------------------------------
# Registry
# --------------------------------------------------------------------------

_GRAYSCALE = PointwiseOp(
    "grayscale",
    in_channels=3,
    out_channels=1,
    fn=grayscale_u8,
    planes_core=grayscale_core,
)
_GRAYSCALE601 = PointwiseOp(
    "grayscale601",
    in_channels=3,
    out_channels=1,
    fn=grayscale601_u8,
    planes_core=grayscale601_core,
)
_INVERT = pointwise_from_core("invert", 0, 0, invert_core, lut_host=invert_lut)
_GRAY2RGB = PointwiseOp("gray2rgb", in_channels=1, out_channels=3, fn=gray2rgb_u8)
_SEPIA = PointwiseOp(
    "sepia",
    in_channels=3,
    out_channels=3,
    fn=sepia_u8,
    planes_core=sepia_planes_core,
)


def _float_arg(arg: str | None, default: float) -> float:
    return default if arg is None else float(arg)


def _int_arg(arg: str | None, default: int) -> int:
    return default if arg is None else int(arg)


# name -> factory(arg_str_or_None) -> Op
def _make_contrast(f: float) -> PointwiseOp:
    """Reference contrast. Rounding-free factors (3.5, 3, any short binary
    fraction) use the in-kernel f32 core — bit-exact everywhere and fusable
    into Pallas groups; other factors use a host-built LUT so eager, jitted
    XLA (fma contraction) and Pallas execution all agree bit-exactly
    (found by tools/soak.py: contrast:4.3 differed between eager and jit
    by one uint8 step at trunc boundaries)."""
    name = f"contrast{f:g}"
    if _contrast_rounding_free(f):
        # lut_host == the eager golden table (asserted equal to the core
        # on all 256 inputs by tests/test_golden.py) — lets the SWAR
        # backend fuse contrast into stencil streams exactly
        return pointwise_from_core(
            name, 1, 1, make_contrast_core(f),
            lut_host=partial(make_contrast_lut, f),
        )
    return make_lut_op(name, make_contrast_lut(f), in_channels=1, out_channels=1)


REGISTRY: dict[str, Callable[[str | None], Op]] = {
    "grayscale": lambda a: _GRAYSCALE,
    "gray": lambda a: _GRAYSCALE,
    "grayscale601": lambda a: _GRAYSCALE601,
    "gray601": lambda a: _GRAYSCALE601,
    "contrast": lambda a: _make_contrast(_float_arg(a, 3.5)),  # 3.5: kernel.cu:50
    "brightness": lambda a: pointwise_from_core(
        f"brightness{_float_arg(a, 0):g}",
        0,
        0,
        make_brightness_core(_float_arg(a, 0)),
        lut_host=partial(make_brightness_lut, _float_arg(a, 0)),
    ),
    "invert": lambda a: _INVERT,
    "threshold": lambda a: pointwise_from_core(
        f"threshold{_float_arg(a, 128):g}",
        1,
        1,
        make_threshold_core(_float_arg(a, 128)),
    ),
    "gray2rgb": lambda a: _GRAY2RGB,
    "emboss": lambda a: make_emboss(_int_arg(a, 3)),  # smallEmboss=true: kernel.cu:195
    "emboss101": lambda a: make_emboss101(_int_arg(a, 3)),  # kern.cpp variant
    "gaussian": lambda a: make_gaussian(_int_arg(a, 5)),
    "box": lambda a: make_box(_int_arg(a, 3)),
    "sobel": lambda a: SOBEL,
    "prewitt": lambda a: PREWITT,
    "scharr": lambda a: SCHARR,
    "sharpen": lambda a: SHARPEN,
    "unsharp": lambda a: UNSHARP,
    "laplacian": lambda a: make_laplacian(_int_arg(a, 4)),
    "filter": make_filter,
    "gamma": lambda a: make_lut_op(
        f"gamma{_float_arg(a, 1.0):g}", make_gamma_lut(_float_arg(a, 1.0))
    ),
    "sepia": lambda a: _SEPIA,
    "posterize": lambda a: pointwise_from_core(
        f"posterize{_int_arg(a, 4)}", 0, 0, make_posterize_core(_int_arg(a, 4))
    ),
    # bit-depth quantization: keep the top N bits — posterize's core under
    # the name the fusion-planner exemplars use (quantize:6 == posterize:6)
    "quantize": lambda a: pointwise_from_core(
        f"quantize{_int_arg(a, 6)}", 0, 0, make_posterize_core(_int_arg(a, 6))
    ),
    "solarize": lambda a: pointwise_from_core(
        f"solarize{_float_arg(a, 128):g}", 0, 0, make_solarize_core(_float_arg(a, 128))
    ),
    "erode": lambda a: make_morph("erode", _int_arg(a, 3)),
    "dilate": lambda a: make_morph("dilate", _int_arg(a, 3)),
    "median": lambda a: make_median(_int_arg(a, 3)),
    # geometric (ops/geometry.py) — beyond-parity; the reference has none
    "fliph": lambda a: geometry.FLIP_H,
    "mirror": lambda a: geometry.FLIP_H,
    "flipv": lambda a: geometry.FLIP_V,
    "flip": lambda a: geometry.FLIP_V,
    "transpose": lambda a: geometry.TRANSPOSE,
    "rot": lambda a: geometry.make_rot90(_int_arg(a, 90)),
    "rot90": lambda a: geometry.ROT90,
    "rot180": lambda a: geometry.ROT180,
    "rot270": lambda a: geometry.ROT270,
    "crop": lambda a: _parse_crop(a),
    "pad": lambda a: _parse_pad(a),
    "resize": lambda a: _parse_resize(a),
    "scale": lambda a: _parse_scale(a),
    "rotate": lambda a: _parse_rotate(a),
    # global-statistics (ops/histogram.py) — psum-combined histograms
    "equalize": lambda a: histogram.EQUALIZE,
    "autocontrast": lambda a: histogram.AUTOCONTRAST,
    "otsu": lambda a: histogram.OTSU,
}


def _parse_crop(arg: str | None):
    parts = (arg or "").split(":")
    if len(parts) != 4:
        raise ValueError("crop needs crop:y0:x0:height:width")
    y0, x0, h, w = (int(p) for p in parts)
    return geometry.make_crop(y0, x0, h, w)


def _parse_pad(arg: str | None):
    parts = (arg or "").split(":") if arg else []
    if not parts or not parts[0]:
        raise ValueError("pad needs pad:N or pad:N:mode")
    n = int(parts[0])
    mode = parts[1] if len(parts) > 1 else "zero"
    return geometry.make_pad(n, mode)


def _parse_size(size: str) -> tuple[int, int]:
    h, _, w = size.lower().partition("x")
    return int(h), int(w)


def _parse_resize(arg: str | None):
    parts = (arg or "").split(":")
    if not parts or not parts[0]:
        raise ValueError("resize needs resize:HxW or resize:HxW:nearest")
    h, w = _parse_size(parts[0])
    method = parts[1] if len(parts) > 1 else "bilinear"
    return geometry.make_resize(h, w, method)


def _parse_rotate(arg: str | None):
    parts = (arg or "").split(":")
    if not parts or not parts[0]:
        raise ValueError("rotate needs rotate:DEGREES or rotate:DEGREES:nearest")
    angle = float(parts[0])
    method = parts[1] if len(parts) > 1 else "bilinear"
    return geometry.make_rotate(angle, method)


def _parse_scale(arg: str | None):
    parts = (arg or "").split(":")
    if not parts or not parts[0]:
        raise ValueError("scale needs scale:F or scale:F:nearest")
    factor = float(parts[0])
    method = parts[1] if len(parts) > 1 else "bilinear"
    return geometry.make_scale(factor, method)


# --------------------------------------------------------------------------
# Family classification (the fusion planner's dispatch key)
# --------------------------------------------------------------------------

FAMILIES = ("pointwise", "stencil", "geometric", "global-stat")

# registry names whose factories require an argument — the defaults used
# ONLY to materialize a representative instance for the classification
# table (registry_family_table); runtime parsing is unchanged
_FAMILY_PROBE_ARGS = {
    "crop": "0:0:16:16",
    "pad": "2",
    "resize": "32x32",
    "scale": "0.5",
    "rotate": "90",
    "filter": "1/1/1/1/1/1/1/1/1:0.111",
}


def op_family(op: Op) -> str:
    """The op's explicit family: 'pointwise', 'stencil', 'geometric' or
    'global-stat' (the `family` class attribute every op spec declares —
    ops/spec.py). The fusion planner (plan/) and any other
    family-dispatching consumer read THIS, not isinstance checks, so a
    new op kind fails loudly here instead of silently mis-planning."""
    fam = getattr(op, "family", None)
    if fam not in FAMILIES:
        raise TypeError(
            f"op {getattr(op, 'name', op)!r} declares no known family "
            f"(got {fam!r}; known: {FAMILIES}) — set the `family` class "
            "attribute on its spec dataclass (ops/spec.py)"
        )
    return fam


def registry_family_table() -> dict[str, str]:
    """Every registered op name -> family, materialized through each
    factory with its default (or probe) argument. The classification
    completeness test asserts every entry classifies — a registered op
    whose spec class forgot `family` fails there, not in the planner."""
    table: dict[str, str] = {}
    for name, factory in REGISTRY.items():
        table[name] = op_family(factory(_FAMILY_PROBE_ARGS.get(name)))
    return table


def make_op(spec: str) -> Op:
    """Parse ``name`` or ``name:arg`` into an op instance."""
    name, _, arg = spec.strip().partition(":")
    name = name.strip().lower()
    if name not in REGISTRY:
        raise ValueError(f"unknown op {name!r}; known: {sorted(REGISTRY)}")
    return REGISTRY[name](arg.strip() or None if arg else None)


def make_pipeline_ops(spec: str) -> tuple[Op, ...]:
    """Parse a comma-separated pipeline string into op instances, validating
    that channel counts chain (e.g. grayscale — a 3->1 op — cannot follow an
    op that produces 1 channel; stencils accept any channel count and filter
    colour images per channel)."""
    ops = tuple(make_op(s) for s in spec.split(",") if s.strip())
    chan = None  # unknown until first op with a fixed requirement
    for op in ops:
        if op.in_channels and chan and op.in_channels != chan:
            raise ValueError(
                f"op {op.name!r} expects {op.in_channels} channels but the "
                f"previous op produces {chan}"
            )
        if op.out_channels:
            chan = op.out_channels
        elif op.in_channels:
            chan = op.in_channels
    return ops


REFERENCE_PIPELINE_SPEC = "grayscale,contrast:3.5,emboss:3"

# The OTHER reference program (kern.cpp:73-75, the CPU/OpenCV variant):
# Rec.601 rounded grayscale, contrast factor 3 (kern.cpp:74 — integer
# result, so truncating vs rounding quantization cannot differ), and
# filter2D emboss with reflect-101 borders. SURVEY.md §2.2/§2.6.
REFERENCE_CPU_PIPELINE_SPEC = "grayscale601,contrast:3,emboss101:3"
