"""Op specifications — the single source of truth for every image op.

Each op is declared once as a small dataclass whose methods are pure,
jnp-traceable tile functions. Three backends consume the *same* functions:

  1. the golden/XLA full-image path (``op(img)``),
  2. the Pallas tiled kernels (``ops/pallas_kernels.py``), and
  3. the sharded shard_map runner with ppermute halo exchange
     (``parallel/api.py``),

so cross-backend bit-exactness is a structural property, not a coincidence:
all stencil weights are integers (see ``ops/filters.py``), accumulated
exactly in float32, with normalisation by a single multiply.

Numeric semantics are fixed by SURVEY.md §2.6: the reference's ``kernel.cu``
is golden — truncating per-term grayscale (kernel.cu:39-42), contrast 3.5
with clamp (kernel.cu:49-58), interior-only emboss guard (kernel.cu:83) —
with two deliberate, documented fixes:

  * the reference's in-place emboss race (kernel.cu:86-91) is resolved to the
    deterministic double-buffered reading (all neighbour reads see pre-update
    values) — ops here are pure functions, so this holds by construction;
  * the reference guard admits x == W-halo and y == H-halo whose
    neighbourhoods index out of bounds (undefined behaviour in CUDA); we
    shrink the interior to pixels whose full neighbourhood is in bounds.

Grayscale weights are computed in float32 (the TPU-native dtype) rather than
the reference's C double; per-term truncation can therefore differ by at most
1 from the C-double result at exact-integer boundaries (verified against a
float64 emulator in tests/test_golden.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, ClassVar

import jax.numpy as jnp
import numpy as np
from jax import lax

U8 = jnp.uint8
U16 = jnp.uint16
F32 = jnp.float32

# --------------------------------------------------------------------------
# Quantizers: f32 -> u8
# --------------------------------------------------------------------------


def trunc_clip_f32(x: jnp.ndarray) -> jnp.ndarray:
    """C semantics of assigning a clamped float to uchar (kernel.cu:19-24,91):
    clamp to [0, 255] then truncate toward zero — kept in f32 (exact u8
    integer values) so the same code lowers inside Mosaic, where unsigned<->
    float casts don't exist."""
    return jnp.floor(jnp.clip(x, 0.0, 255.0))


def rint_clip_f32(x: jnp.ndarray) -> jnp.ndarray:
    """Round-to-nearest-even then clamp; used by the non-reference filter bank
    (Gaussian/Sobel/box/sharpen) where no C golden semantics exist."""
    return jnp.clip(jnp.rint(x), 0.0, 255.0)


def trunc_clip_u8(x: jnp.ndarray) -> jnp.ndarray:
    return trunc_clip_f32(x).astype(U8)


def rint_clip_u8(x: jnp.ndarray) -> jnp.ndarray:
    return rint_clip_f32(x).astype(U8)


QUANTIZERS_F32: dict[str, Callable[[jnp.ndarray], jnp.ndarray]] = {
    "trunc_clip": trunc_clip_f32,
    "rint_clip": rint_clip_f32,
}

# --------------------------------------------------------------------------
# Core tile machinery (shared verbatim by all backends)
# --------------------------------------------------------------------------


def exact_f32(t: jnp.ndarray) -> jnp.ndarray:
    """Cast integer-valued data to f32 preserving exact values.

    Mosaic has no unsigned<->float casts, so u8 bridges through int32 —
    the single definition of that workaround; every tile function and
    Pallas kernel routes through here. No-op on f32 input (the golden
    path), so behaviour is identical across backends."""
    if t.dtype == F32:
        return t
    return t.astype(jnp.int32).astype(F32)


def corr_valid(xpad: jnp.ndarray, weights: np.ndarray) -> jnp.ndarray:
    """Valid-mode 2-D correlation via unrolled static shifts.

    ``xpad`` is (H + kh - 1, W + kw - 1) float32, or uint8 holding the same
    exact integer values — u8 input is cast to f32 once up front and every
    tap window is sliced from the f32 copy (one convert pass for all taps;
    measured on v5e this beats per-window converts), so the arithmetic is
    identical either way. ``weights`` is a static (kh, kw) array indexed
    ``w[dy, dx]``.
    Returns float32 (H, W). Unrolled shift-multiply-accumulate maps onto the
    TPU VPU (8x128 lanes) and fuses under XLA; the same code runs inside
    Pallas kernels on VMEM tiles. This replaces the CUDA per-thread gather
    loop (kernel.cu:84-90).
    """
    kh, kw = weights.shape
    out_h = xpad.shape[0] - (kh - 1)
    out_w = xpad.shape[1] - (kw - 1)
    # convert the whole tile once, then slice f32: one u8->i32->f32 pass
    # instead of one per nonzero tap (the taps share the same data; on the
    # VPU the per-tap converts dominated the shift cost)
    xf = exact_f32(xpad)
    acc = None
    for dy in range(kh):
        for dx in range(kw):
            w = float(weights[dy, dx])
            if w == 0.0:
                continue
            win = xf[dy : dy + out_h, dx : dx + out_w]
            term = win if w == 1.0 else win * w
            acc = term if acc is None else acc + term
    if acc is None:
        acc = jnp.zeros((out_h, out_w), F32)
    return acc


def separable_valid(xpad: jnp.ndarray, w1d: np.ndarray) -> jnp.ndarray:
    """Valid-mode separable correlation: a (1,k) pass then a (k,1) pass.

    With integer weights both passes accumulate exactly in f32, so the result
    is bit-identical to the full 2-D outer-product correlation while reading
    O(k) instead of O(k^2) terms per pixel.
    """
    row = np.asarray(w1d, dtype=np.float32).reshape(1, -1)
    col = np.asarray(w1d, dtype=np.float32).reshape(-1, 1)
    return corr_valid(corr_valid(xpad, row), col)


def window_reduce_1d(
    xpad: jnp.ndarray, k: int, axis: int, fn: Callable
) -> jnp.ndarray:
    """Valid-mode sliding reduction (min/max) of width k along one axis,
    via k-1 unrolled static shifts — the same VPU-friendly shape as
    corr_valid, so it lowers identically inside Pallas kernels. u8 input is
    cast to f32 once up front and windows are sliced from the f32 copy
    (Mosaic has no u8 min/max; measured on v5e, one whole-tile convert
    beats per-window converts); values are exact integers, so the f32
    reduction is bit-equivalent."""
    out_len = xpad.shape[axis] - (k - 1)
    xf = exact_f32(xpad)  # one convert for all k windows (see corr_valid)
    acc = None
    for d in range(k):
        win = lax.slice_in_dim(xf, d, d + out_len, axis=axis)
        acc = win if acc is None else fn(acc, win)
    return acc


def _sort2(a: jnp.ndarray, b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    return jnp.minimum(a, b), jnp.maximum(a, b)


# Paeth's 19-exchange median-of-9 selection network: after these exchanges
# p[4] holds the median. Pure min/max — elementwise, exact on u8-valued f32,
# and lowers in Mosaic (no sort primitive needed).
_MEDIAN9_EXCHANGES = (
    (1, 2), (4, 5), (7, 8), (0, 1), (3, 4), (6, 7), (1, 2), (4, 5), (7, 8),
    (0, 3), (5, 8), (4, 7), (3, 6), (1, 4), (2, 5), (4, 7), (4, 2), (6, 4),
    (4, 2),
)


def _oddeven_merge_pairs(n: int) -> list[tuple[int, int]]:
    """Batcher odd-even mergesort comparator pairs for arbitrary n (the
    standard iterative clipped construction). Correct by the 0-1 principle;
    additionally verified against numpy sort in tests."""
    pairs: list[tuple[int, int]] = []
    p = 1
    while p < n:
        k = p
        while k >= 1:
            for j in range(k % p, n - k, 2 * k):
                for i in range(0, min(k, n - j - k)):
                    if (i + j) // (p * 2) == (i + j + k) // (p * 2):
                        pairs.append((i + j, i + j + k))
            k //= 2
        p *= 2
    return pairs


def _prune_to_median(pairs: list[tuple[int, int]], n: int) -> tuple:
    """Drop comparators whose outputs never reach the median wire: walking
    the network backwards from wire n//2, a comparator is live iff either of
    its (in-place) output wires is needed downstream. 140 -> 113 comparators
    for n=25."""
    needed = {n // 2}
    kept = []
    for i, j in reversed(pairs):
        if i in needed or j in needed:
            kept.append((i, j))
            needed.add(i)
            needed.add(j)
    return tuple(reversed(kept))


# size -> (exchange network, median wire index). 3x3 keeps Paeth's
# hand-crafted 19-exchange network (pruned Batcher needs 24); 5x5 uses the
# pruned Batcher network (113 min/max exchanges on 25 wires).
_MEDIAN_NETWORKS = {
    3: (_MEDIAN9_EXCHANGES, 4),
    5: (_prune_to_median(_oddeven_merge_pairs(25), 25), 12),
}


def median_valid(xpad: jnp.ndarray, size: int = 3) -> jnp.ndarray:
    """Valid-mode size x size median via a min/max selection network.
    u8 input is cast to f32 once up front, then the size^2 window wires are
    sliced from the f32 copy (see corr_valid). Pure elementwise min/max —
    exact on u8-valued f32 and lowers in Mosaic (no sort primitive
    needed)."""
    exchanges, mid = _MEDIAN_NETWORKS[size]
    out_h = xpad.shape[0] - (size - 1)
    out_w = xpad.shape[1] - (size - 1)
    xf = exact_f32(xpad)  # one convert for all size^2 wires (see corr_valid)
    p = [
        xf[dy : dy + out_h, dx : dx + out_w]
        for dy in range(size)
        for dx in range(size)
    ]
    for i, j in exchanges:
        p[i], p[j] = _sort2(p[i], p[j])
    return p[mid]




_PAD_MODES = {
    "interior": "constant",  # padding value irrelevant — masked by finalize
    "zero": "constant",
    "reflect101": "reflect",  # OpenCV BORDER_REFLECT_101 == numpy 'reflect'
    "edge": "edge",
}


def pad2d(
    xf: jnp.ndarray,
    edge_mode: str,
    top: int,
    bottom: int,
    left: int,
    right: int,
) -> jnp.ndarray:
    """Pad a float32 (H, W) tile on each side per the op's edge mode.

    The sharded runner uses asymmetric pads: sides that received ppermute
    halo rows from a neighbour pad by 0; global-image edges pad per mode.
    """
    if (top, bottom, left, right) == (0, 0, 0, 0):
        return xf
    return jnp.pad(xf, ((top, bottom), (left, right)), mode=_PAD_MODES[edge_mode])


def edge_slices(
    x: jnp.ndarray, k: int, axis: int = 0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(first k, last k) static slices of `x` along `axis`.

    The overlapped-halo runners (parallel/api, parallel/api2d) build every
    boundary strip and prefetch source from these, so the slicing
    convention (and hence the ppermute payload) is defined once."""
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(None, k)
    first = x[tuple(idx)]
    idx[axis] = slice(x.shape[axis] - k, None)
    return first, x[tuple(idx)]


def interior_slice(x: jnp.ndarray, k: int, axis: int = 0) -> jnp.ndarray:
    """`x` with `k` slices shaved off both ends of `axis` — the region a
    halo-`k` stencil can produce from `x` alone, with no ghost data. The
    interior-first overlap path computes exactly this slice while the
    ppermute ghost strips are in flight."""
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(k, x.shape[axis] - k)
    return x[tuple(idx)]


# --------------------------------------------------------------------------
# Op dataclasses
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PointwiseOp:
    """Per-pixel op: no neighbourhood, trivially shardable on any axis.

    `core` is the op's single source of truth: an elementwise f32 -> f32
    function over exact u8 integer values (output also exact integers in
    [0, 255]). The u8 `fn` is derived by casting around `core`; Pallas
    kernels call `core` directly on f32 tiles (no unsigned casts in Mosaic).
    3->1 channel-structure ops set `planes_core` instead (consumed by the
    Pallas planar path); 1->3 replication (gray2rgb) is handled by name.
    """

    # explicit family classification (ops/registry.op_family): the fusion
    # planner and every family-dispatching consumer read this attribute
    # instead of isinstance-sniffing op classes
    family: ClassVar[str] = "pointwise"

    name: str
    in_channels: int  # 3, 1, or 0 (= any)
    out_channels: int  # 3, 1, or 0 (= same as input)
    fn: Callable[[jnp.ndarray], jnp.ndarray]  # u8 -> u8, jnp-traceable
    core: Callable[[jnp.ndarray], jnp.ndarray] | None = None  # f32 -> f32
    # channel-structure ops: (r, g, b) f32 planes -> f32 plane or a
    # list/tuple of planes (3->1 grayscales, 3->3 colour matrices); used by
    # the Pallas planar path (core handles the elementwise case)
    planes_core: Callable | None = None
    # False for ops whose body cannot lower inside a Mosaic kernel (e.g.
    # LUT ops built on gather); they run as XLA steps between Pallas groups
    kernel_safe: bool = True

    # optional host-side (pure numpy, never dispatches to a device) builder
    # of the op's exact 256-entry u8 -> u8 table. An elementwise u8 op IS
    # its LUT, so this is a complete behavioural spec: the SWAR backend
    # fits its in-kernel integer form against it and fuses the op into a
    # stencil stream only when the fit reproduces every entry
    # (ops/swar_kernels._fit_affine_u8). None = not fusable there.
    lut_host: Callable[[], "np.ndarray"] | None = None

    halo: int = 0

    def __call__(self, img: jnp.ndarray) -> jnp.ndarray:
        _check_channels(self.name, self.in_channels, img)
        return self.fn(img)


def pointwise_from_core(
    name: str,
    in_channels: int,
    out_channels: int,
    core: Callable,
    lut_host: Callable | None = None,
) -> PointwiseOp:
    """Build a PointwiseOp whose u8 path is cast -> core -> cast (lossless:
    core maps exact u8 integers to exact u8 integers)."""

    def fn(img: jnp.ndarray) -> jnp.ndarray:
        return core(img.astype(F32)).astype(U8)

    return PointwiseOp(
        name, in_channels, out_channels, fn=fn, core=core, lut_host=lut_host
    )


@dataclasses.dataclass(frozen=True)
class StencilOp:
    """Neighbourhood op over a (H, W) plane or per-channel over (H, W, C).

    kernels  : static correlation weight matrices, ``w[dy, dx]``.
    separable: optional 1-D weight vector for a bit-identical fast path.
    scale    : single post-accumulation multiply (1/norm; power of two for
               Gaussians, so exact).
    combine  : 'single' (one kernel) or 'magnitude' (sqrt(a0^2 + a1^2), for
               Sobel).
    reduce   : 'corr' (weighted-sum correlation, the default), 'min'/'max'
               (morphological erode/dilate over a square window — computed
               separably), or 'median' (3x3/5x5 rank filter via a min/max
               selection network). Non-'corr' modes use kernels[0].shape
               for the window and ignore the weight values.
    edge_mode: 'interior' replicates the reference guard (kernel.cu:83) —
               non-interior pixels pass through the input unchanged; the
               others filter every pixel with the named border extension.
    quantize : 'trunc_clip' (reference C semantics) or 'rint_clip'.
    """

    family: ClassVar[str] = "stencil"  # see PointwiseOp.family

    name: str
    halo: int
    kernels: tuple
    scale: float = 1.0
    separable: np.ndarray | None = None
    combine: str = "single"
    reduce: str = "corr"
    edge_mode: str = "interior"
    quantize: str = "trunc_clip"

    in_channels: int = 0  # any; colour images filter per channel
    out_channels: int = 0  # same as input

    # -- tile functions (used by every backend) --

    def valid(self, xpad: jnp.ndarray) -> jnp.ndarray:
        """float32 (H+2h, W+2h) -> float32 (H, W): correlate + combine + scale."""
        if self.reduce in ("min", "max"):
            fn = jnp.minimum if self.reduce == "min" else jnp.maximum
            kh, kw = self.kernels[0].shape
            # square-window min/max is separable: rows pass then columns pass
            return window_reduce_1d(
                window_reduce_1d(xpad, kw, 1, fn), kh, 0, fn
            )
        if self.reduce == "median":
            return median_valid(xpad, self.kernels[0].shape[0])
        if self.separable is not None:
            accs = [separable_valid(xpad, self.separable)]
        else:
            accs = [corr_valid(xpad, k) for k in self.kernels]
        if self.combine == "single":
            acc = accs[0]
        elif self.combine == "magnitude":
            acc = jnp.sqrt(accs[0] * accs[0] + accs[1] * accs[1])
        else:  # pragma: no cover
            raise ValueError(f"unknown combine {self.combine!r}")
        if self.scale != 1.0:
            acc = acc * np.float32(self.scale)
        return acc

    def finalize_f32(
        self,
        acc: jnp.ndarray,
        orig_f32: jnp.ndarray,
        y0,
        x0,
        global_h: int,
        global_w: int,
    ) -> jnp.ndarray:
        """Quantize (staying in f32 — exact u8 integer values) and, for
        'interior' mode, pass through non-interior pixels.

        (y0, x0) are the tile's global offsets, so the interior mask follows
        *global* image coordinates — this is what removes the reference's
        per-slice seams (SURVEY.md §2.1): a sharded tile in the middle of the
        image is entirely interior.
        """
        q = QUANTIZERS_F32[self.quantize](acc)
        if self.edge_mode != "interior":
            return q
        mask = self.interior_mask(acc.shape, y0, x0, global_h, global_w)
        return jnp.where(mask, q, orig_f32)

    def interior_mask(self, shape, y0, x0, global_h: int, global_w: int):
        """Reference guard (kernel.cu:83): x > o && x <= W-o (likewise y),
        intersected with the in-bounds requirement x <= W-1-o (the
        reference's x == W-o column reads out of bounds — UB we fix).
        Global coordinates, so sharded tiles mask identically to the
        full-image path."""
        h, w = shape
        yy = y0 + lax.broadcasted_iota(jnp.int32, (h, w), 0)
        xx = x0 + lax.broadcasted_iota(jnp.int32, (h, w), 1)
        o = self.halo
        return (
            (xx > o) & (xx <= global_w - 1 - o) & (yy > o) & (yy <= global_h - 1 - o)
        )

    def finalize(
        self,
        acc: jnp.ndarray,
        orig_u8: jnp.ndarray,
        y0,
        x0,
        global_h: int,
        global_w: int,
    ) -> jnp.ndarray:
        return self.finalize_f32(
            acc, orig_u8.astype(F32), y0, x0, global_h, global_w
        ).astype(U8)

    # -- full-image golden path --

    def __call__(self, img: jnp.ndarray) -> jnp.ndarray:
        _check_channels(self.name, self.in_channels, img)
        if img.ndim == 3:  # colour: filter each channel plane independently
            return jnp.stack(
                [self._apply2d(img[..., c]) for c in range(img.shape[2])], axis=-1
            )
        return self._apply2d(img)

    def _apply2d(self, img: jnp.ndarray) -> jnp.ndarray:
        h, w = img.shape
        xpad = pad2d(
            img.astype(F32), self.edge_mode, self.halo, self.halo, self.halo, self.halo
        )
        return self.finalize(self.valid(xpad), img, 0, 0, h, w)


@dataclasses.dataclass(frozen=True)
class GeometricOp:
    """Shape-changing data-movement op (flip / rotate / transpose / crop /
    pad / resize).

    The reference has no geometric ops at all; these extend the framework
    beyond parity. `fn` is the single source of truth for every backend:
    pure gathers + (for resize) a fixed two-tap lerp whose indices and
    weights are precomputed host-side in float64 — so execution is exact
    data movement plus deterministic f32 elementwise math, and the sharded
    path (which runs the *same* `fn` under a sharding constraint, letting
    XLA insert the collectives) is bit-identical to the golden path.

    In the Pallas pipeline these run as their own XLA step between fused
    group kernels (`kernel_safe=False`, like the LUT ops) — data movement
    is XLA's job; Mosaic kernels keep static block shapes.
    """

    family: ClassVar[str] = "geometric"  # see PointwiseOp.family

    name: str
    fn: Callable[[jnp.ndarray], jnp.ndarray]  # u8 -> u8, shape may change
    in_channels: int = 0
    out_channels: int = 0
    halo: int = 0
    kernel_safe: bool = False
    core: Callable | None = None

    def __call__(self, img: jnp.ndarray) -> jnp.ndarray:
        _check_channels(self.name, self.in_channels, img)
        return self.fn(img)


@dataclasses.dataclass(frozen=True)
class GlobalOp:
    """Op whose per-pixel transform depends on a full-image statistic
    (histogram equalization, autocontrast, Otsu threshold).

    Split into two pure pieces so every backend composes them the same way:

      stats(img, valid) -> int32[stat_size]   per-pixel contributions summed
                                              over the image; `valid` masks
                                              rows that are padding (sharded
                                              pad-to-multiple rows must not
                                              pollute the histogram)
      apply(img, stats) -> u8 image           pointwise given the statistic

    The decomposition is chosen to be *additive*: sharded execution computes
    local masked stats and combines them with one `lax.psum` over the mesh
    axis — integer counts, so the combined statistic (and therefore the
    output) is bit-identical to the unsharded path. This is the framework's
    MPI_Allreduce analogue; the reference has no reduction collective at
    all (SURVEY.md §2.3 lists only Bcast/Scatter/Gather/Barrier).
    """

    family: ClassVar[str] = "global-stat"  # see PointwiseOp.family

    name: str
    stats: Callable  # (u8 img, valid mask or None) -> int32 vector
    apply: Callable  # (u8 img, int32 stats) -> u8 img
    in_channels: int = 1
    out_channels: int = 0
    halo: int = 0
    kernel_safe: bool = False
    core: Callable | None = None

    def fn(self, img: jnp.ndarray) -> jnp.ndarray:
        return self.apply(img, self.stats(img, None))

    def __call__(self, img: jnp.ndarray) -> jnp.ndarray:
        _check_channels(self.name, self.in_channels, img)
        return self.fn(img)


Op = PointwiseOp | StencilOp | GeometricOp | GlobalOp


def chain_halo(ops) -> int:
    """Total row context a chain of ops needs on each side of a region to
    reproduce the whole-image result there bit-exactly: the SUM of the
    per-op halos (op k's halo-h output row depends on op k-1's output
    h rows further out, and so on down the chain). This is the seam
    sizing rule the streaming tile engine (stream/tiles.py) and the
    temporally-blocked sharded runners share: one `chain_halo` strip of
    real neighbour rows per seam buys the entire chain, instead of one
    exchange per op."""
    return sum(op.halo for op in ops)


def _check_channels(name: str, want: int, img: jnp.ndarray) -> None:
    got = img.shape[2] if img.ndim == 3 else 1
    if want and got != want:
        raise ValueError(
            f"op {name!r} expects a {want}-channel image, got shape {img.shape}"
        )
