"""Pallas TPU kernels: fused pointwise+stencil pipeline groups, streamed.

This is the framework's answer to kernel.cu's three separate `__global__`
launches (grayscale :31, contrast :49, emboss :64 — each a full HBM
round-trip on its own, kernel.cu:192-195): consecutive pointwise ops fuse
*into* the following stencil's kernel, so one `pallas_call` reads uint8
pixels from HBM once, applies the whole group in VMEM at f32, and writes
uint8 once.

Tiling model (the CUDA dim3-grid analogue, SURVEY.md §2.4): a 1-D grid over
row blocks, executed **sequentially** (TPU grids are sequential per core),
which enables a streaming stencil: each grid step DMAs one (block_h, W)
input block — exactly once, no overlapping halo reads — applies the fused
pointwise chain and the stencil's *row pass*, and stashes the result in a
VMEM scratch carried across steps. The *column pass* for output block j
runs one step later (at grid step j+1), when its bottom halo rows are
available from the freshly loaded block. Total HBM traffic is the
information-theoretic minimum: one u8 read + one u8 write of the image.

Image-edge extension happens *inside* the kernel on the row-pass values
(reflect101 and edge strips built from static single-row/column slices —
Mosaic has no reverse primitive; 'interior' mode needs no real extension
because its mask passes the affected outputs through; true zero-border
stencils are rejected — none exist in the registry), so there is no
XLA-side "prepare" copy of the image either. Separable stencils (Gaussian, box,
erode/dilate) split into true row/column passes — O(k) work per pixel and
a (block_h, W) f32 scratch; non-separable ones (emboss, Sobel, median)
stream raw rows at width W + 2*halo and run their 2-D `valid` as the
column pass. Bit-exactness with the golden path is structural: both call
the same tile functions from ops/spec.py in the same order.

Colour images are decomposed into planar (H, W) channel arrays at the group
boundary — (8,128)-lane-friendly, instead of HWC's 3-wide minor axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
    stage_arm_for,
    stage_valid_mxu,
)
from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    F32,
    U8,
    PointwiseOp,
    StencilOp,
    QUANTIZERS_F32,
    exact_f32,
    window_reduce_1d,
)
from mpi_cuda_imagemanipulation_tpu.utils import calibration
from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

# --------------------------------------------------------------------------
# Pipeline grouping: [pointwise*, stencil?] units, one pallas_call each
# --------------------------------------------------------------------------


def group_ops(ops) -> list[tuple[list[PointwiseOp], StencilOp | None]]:
    groups: list[tuple[list[PointwiseOp], StencilOp | None]] = []
    pointwise: list[PointwiseOp] = []
    for op in ops:
        if isinstance(op, StencilOp):
            groups.append((pointwise, op))
            pointwise = []
        elif not op.kernel_safe:
            # LUT-style ops can't lower in Mosaic: flush the running group
            # and emit the op as its own XLA-side group
            if pointwise:
                groups.append((pointwise, None))
                pointwise = []
            groups.append(([op], None))
        else:
            pointwise.append(op)
    if pointwise:
        groups.append((pointwise, None))
    return groups


def _apply_pointwise_planes(op: PointwiseOp, planes: list) -> list:
    """Apply a pointwise op to the plane-decomposed state (f32 planes holding
    exact u8 integer values — Mosaic has no unsigned<->float casts, so the
    whole kernel body stays in f32)."""
    if op.planes_core is not None:  # channel-structure ops (3->1 or 3->3)
        assert len(planes) == 3, f"{op.name} needs 3 channel planes"
        out = op.planes_core(*planes)
        return list(out) if isinstance(out, (list, tuple)) else [out]
    if op.name == "gray2rgb":
        assert len(planes) == 1
        return [planes[0], planes[0], planes[0]]
    if op.in_channels == 1 and len(planes) != 1:
        raise ValueError(f"op {op.name!r} expects 1 channel, got {len(planes)}")
    if op.core is None:  # pragma: no cover
        raise NotImplementedError(f"op {op.name!r} has no f32 core function")
    # elementwise ops act identically per plane
    return [op.core(p) for p in planes]


def _f32_to_u8(x):
    # the write-side counterpart of spec.exact_f32's u8->f32 bridge
    return x.astype(jnp.int32).astype(U8)


# --------------------------------------------------------------------------
# In-kernel weighted sums and edge columns
#
# Multi-tap passes convert their tile to f32 ONCE up front and slice the
# f32 copy (one u8->i32->f32 pass for all taps) — round-2 A/B on v5e showed
# per-tap converts cost at least as much as the f32 lane shifts they were
# avoiding; do not "optimize" back to per-window casts. Symmetric
# integer kernels regroup into (x_k + x_{K-1-k}) pairs — every intermediate
# is an exact integer below 2^24 in f32, so regrouping is bit-exact.
# Mosaic has no reverse primitive, so reflected strips are built from
# static single-row/column slices (halo <= 3 keeps this trivial).
# --------------------------------------------------------------------------


def _weighted_terms(w: np.ndarray, sl) -> jnp.ndarray:
    """sum_k w[k] * sl(k), pairing mirror taps when the kernel is symmetric
    with integer weights (exact — see module comment)."""
    wi = [float(v) for v in np.asarray(w).reshape(-1)]
    k = len(wi)
    sym = wi == wi[::-1] and all(v == int(v) for v in wi)
    terms = []
    if sym:
        for d in range(k // 2):
            if wi[d] == 0.0:
                continue
            pair = exact_f32(sl(d)) + exact_f32(sl(k - 1 - d))
            terms.append(pair if wi[d] == 1.0 else pair * np.float32(wi[d]))
        if k % 2:
            mid = exact_f32(sl(k // 2))
            if wi[k // 2] != 0.0:
                terms.append(
                    mid if wi[k // 2] == 1.0 else mid * np.float32(wi[k // 2])
                )
    else:
        for d in range(k):
            if wi[d] == 0.0:
                continue
            t = exact_f32(sl(d))
            terms.append(t if wi[d] == 1.0 else t * np.float32(wi[d]))
    if not terms:  # all-zero weights: match corr_valid's zeros result
        probe = exact_f32(sl(0))
        return jnp.zeros(probe.shape, probe.dtype)
    acc = terms[0]
    for t in terms[1:]:
        acc = acc + t
    return acc


def _src_col(c: int, size: int, mode: str | None) -> int | None:
    """Edge-extension source index for a possibly out-of-range coordinate
    (None = zero contribution)."""
    if 0 <= c < size:
        return c
    if mode == "reflect101":
        return -c if c < 0 else 2 * (size - 1) - c
    if mode == "edge":
        return min(max(c, 0), size - 1)
    return None  # interior / zero


def _row_corr(x: jnp.ndarray, w1d: np.ndarray, h: int, mode: str | None):
    """Row pass of a separable correlation over a (rows, W) tile, edge
    columns synthesised per the op's mode. Returns (rows, W) f32.

    The tile is converted to f32 once up front: one u8->i32->f32 pass
    instead of one per tap (measured on v5e, the per-tap converts cost more
    than the f32 lane shifts they were avoiding)."""
    W = x.shape[1]
    x = exact_f32(x)
    wv = np.asarray(w1d, dtype=np.float32).reshape(-1)

    def edge_col(j):
        def sl(k):
            c = _src_col(j + k - h, W, mode)
            if c is None:
                return jnp.zeros((x.shape[0], 1), x.dtype)
            return x[:, c : c + 1]

        return _weighted_terms(wv, sl)

    if W - 2 * h <= 0:  # degenerate narrow tile: every column is an edge
        return jnp.concatenate([edge_col(j) for j in range(W)], axis=1)
    interior = _weighted_terms(
        wv, lambda d: x[:, d : d + W - 2 * h]
    )
    left = [edge_col(j) for j in range(h)]
    right = [edge_col(W - h + j) for j in range(h)]
    return jnp.concatenate(left + [interior] + right, axis=1)


def _row_reduce(x: jnp.ndarray, kw: int, h: int, mode: str | None, fn):
    """Row pass of a sliding min/max. The tile is cast to f32 once (Mosaic
    has no u8 min/max) and windows are sliced from the f32 copy — one
    convert pass for all kw windows; the result holds exact u8 integers."""
    W = x.shape[1]
    x = exact_f32(x)

    def edge_col(j):
        cols = []
        for k in range(kw):
            c = _src_col(j + k - h, W, mode)
            if c is not None:
                cols.append(x[:, c : c + 1])
        acc = cols[0]
        for t in cols[1:]:
            acc = fn(acc, t)
        return acc

    if W - 2 * h <= 0:
        return jnp.concatenate([edge_col(j) for j in range(W)], axis=1)
    interior = window_reduce_1d(x, kw, 1, fn)
    left = [edge_col(j) for j in range(h)]
    right = [edge_col(W - h + j) for j in range(h)]
    return jnp.concatenate(left + [interior] + right, axis=1)


def _row_identity_ext(x: jnp.ndarray, h: int, mode: str | None) -> jnp.ndarray:
    """Width-extend raw rows to W + 2h (non-separable stencils), staying in
    the source dtype."""
    W = x.shape[1]

    def col(c):
        s = _src_col(c, W, mode)
        if s is None:
            return jnp.zeros((x.shape[0], 1), x.dtype)
        return x[:, s : s + 1]

    left = [col(t - h) for t in range(h)]
    right = [col(W + t) for t in range(h)]
    return jnp.concatenate(left + [x] + right, axis=1)


def _top_strip(main: jnp.ndarray, h: int, mode: str | None) -> jnp.ndarray:
    """Rows -h..-1 of the image, synthesised from the first block's rows.
    Strip row p (p = 0..h-1) is image row -(h-p): reflect101 reads row h-p."""
    if mode == "edge":
        return jnp.concatenate([main[:1]] * h, axis=0)
    if mode == "reflect101":
        return jnp.concatenate([main[k : k + 1] for k in range(h, 0, -1)], axis=0)
    return jnp.concatenate([jnp.zeros((1, main.shape[1]), main.dtype)] * h, axis=0)


# --------------------------------------------------------------------------
# Stencil row/column pass split
# --------------------------------------------------------------------------


def _split_passes(op: StencilOp, width: int):
    """Return (row_pass, col_pass, rp_width, rp_needs_f32).

    row_pass maps a raw (rows, W) tile (u8 or post-pointwise f32) to
    (rows, rp_width), including the op's width-edge extension; col_pass maps
    the row-extended (bh+2h, rp_width) stack to the final (bh, W)
    accumulation — combine and scale included, composed in the same exact-
    integer arithmetic as StencilOp.valid, so results are bit-identical.
    rp_needs_f32 says whether the row-pass output carries non-u8 values
    (separable sums); u8-valued passes keep u8 scratch — half the VMEM
    traffic and cheap shifts.
    """
    h = op.halo
    mode = op.edge_mode
    if op.reduce in ("min", "max") and op.edge_mode != "interior":
        # interior mode falls through to the raw-rows branch below: its
        # pass-through needs original pixels, not row-reduced values
        # (advisor round-1 finding; no registry op hits it today)
        fn = jnp.minimum if op.reduce == "min" else jnp.maximum
        kh, kw = op.kernels[0].shape
        return (
            lambda x: _row_reduce(x, kw, h, mode, fn),
            lambda ext: window_reduce_1d(ext, kh, 0, fn),
            width,
            False,
        )
    if op.separable is not None and op.edge_mode != "interior":
        w1d = np.asarray(op.separable, dtype=np.float32).reshape(-1)

        def col_pass(ext):
            acc = _weighted_terms(
                w1d, lambda d: ext[d : d + ext.shape[0] - 2 * h]
            )
            if op.scale != 1.0:
                acc = acc * np.float32(op.scale)
            return acc

        return (lambda x: _row_corr(x, w1d, h, mode), col_pass, width, True)
    # non-separable (or interior-mode, which needs raw rows for the
    # pass-through): stream raw rows at full extended width; op.valid
    # dispatches median (selection network) and interior min/max itself
    return (
        lambda x: _row_identity_ext(x, h, mode),
        op.valid,
        width + 2 * h,
        False,
    )


# --------------------------------------------------------------------------
# The streaming fused group kernel (full-image path)
# --------------------------------------------------------------------------


def _quantize_u8(stencil: StencilOp, acc: jnp.ndarray) -> jnp.ndarray:
    return _f32_to_u8(QUANTIZERS_F32[stencil.quantize](acc))


def _assemble_ext(
    j,
    top,
    main,
    rp,
    beyond,
    beyond_pen,
    *,
    nb: int,
    bh: int,
    h: int,
    a: int,
    nfix: int,
    skip_fixes: bool = False,
):
    """Build the (bh + 2h, rp_w) column-pass input for output block j from
    the streaming carry — the ONE copy of the ragged-last-block math,
    shared by _stream_kernel's two modes: full-image (beyond-image rows
    synthesised from the op's edge extension) and sharded ghost mode
    (beyond-tile rows sourced from the bottom ghost strip; reached via
    parallel/api._apply_group_fused -> run_group).

    `top`/`main`/`rp` are the row-passed carries: block j-1's last h rows
    (already j==0-selected by the caller), block j, and block j+1 (whose
    first h rows are the head). `beyond(t)` returns the 1-row row-passed
    value for tile row local_h + t (t >= 0) as seen at the LAST emit step
    (j == nb-1); `beyond_pen(t)` the same row as seen one step earlier
    (j == nb-2, where the garbage block's row pass lives in `rp`, not
    `main`). Rows a source cannot reach feed only cropped outputs, so
    clamping inside them is safe. With `skip_fixes` (interior mode on the
    full-image path) garbage rows are left in place — the interior mask
    passes exactly those outputs through. `a` is the number of real rows in
    the last block, `nfix` how many garbage rows after them can reach a
    valid output's window.
    """
    if skip_fixes:
        return jnp.concatenate([top, main, rp[:h]], axis=0)
    pieces = [top, main[:a]]
    if nfix:  # garbage rows inside the last block
        fix = jnp.concatenate([beyond(t) for t in range(nfix)], axis=0)
        pieces.append(jnp.where(j == nb - 1, fix, main[a : a + nfix]))
    if a + nfix < bh:
        pieces.append(main[a + nfix :])
    head = rp[:h]
    if a < h and nb >= 2:
        # the penultimate block's head strip crosses into the ragged last
        # block's rows t >= a, whose true values are beyond rows t - a
        pen = jnp.concatenate(
            [rp[t : t + 1] if t < a else beyond_pen(t - a) for t in range(h)],
            axis=0,
        )
        head = jnp.where(j == nb - 2, pen, head)
    # the last block's head rows are tile rows nb*bh + t = beyond (bh-a) + t
    bot_last = jnp.concatenate(
        [beyond(bh - a + t) for t in range(h)], axis=0
    )
    pieces.append(jnp.where(j == nb - 1, bot_last, head))
    return jnp.concatenate(pieces, axis=0)


def _stream_kernel(
    *refs,
    pointwise: list[PointwiseOp],
    stencil: StencilOp,
    n_in: int,
    n_out: int,
    block_h: int,
    nb: int,
    global_h: int,
    global_w: int,
    rp_u8: bool,
    ghosts: bool = False,
    image_h: int | None = None,
    image_w: int | None = None,
):
    """The fused [pointwise*, stencil] streaming kernel.

    Full-image mode (`ghosts=False`): `global_h` is the image height and
    rows beyond it are synthesised from the op's edge extension.
    Sharded ghost mode (`ghosts=True`): the tile is one row-shard of height
    `global_h` (local), refs carry two extra (halo, W) raw pre-pointwise
    ghost strips per input plane plus a leading (1,) SMEM y0 scalar, and
    beyond-tile rows come from the bottom strip; the interior mask then
    follows global coordinates y0 + j*block_h against `image_h`/`image_w`.
    """
    h = stencil.halo
    mode = stencil.edge_mode
    row_pass, col_pass, rp_w, _ = _split_passes(stencil, global_w)
    if ghosts:
        y0_ref = refs[0]
        in_refs = refs[1 : 1 + n_in]
        top_refs = refs[1 + n_in : 1 + 2 * n_in]
        bot_refs = refs[1 + 2 * n_in : 1 + 3 * n_in]
        out_refs = refs[1 + 3 * n_in : 1 + 3 * n_in + n_out]
        scratch = refs[1 + 3 * n_in + n_out :]  # (main, tail, tscr, bscr)/plane
        per_plane = 4
    else:
        in_refs = refs[:n_in]
        out_refs = refs[n_in : n_in + n_out]
        scratch = refs[n_in + n_out :]  # (main, tail) per output plane
        per_plane = 2

    i = pl.program_id(0)
    j = i - 1  # output block index computed this step

    def run_pointwise(rs):
        if pointwise:
            planes = [exact_f32(r[:]) for r in rs]
            for op in pointwise:
                planes = _apply_pointwise_planes(op, planes)
        else:
            planes = [r[:] for r in rs]  # raw u8 — cheap shifts in row_pass
        assert len(planes) == n_out
        return planes

    planes = run_pointwise(in_refs)

    def cast_rp(x):
        if rp_u8 and x.dtype != U8:
            return _f32_to_u8(x)  # exact u8 integers by construction
        return x

    if ghosts:
        # the strips never change across the grid: pointwise + row-pass
        # them once into dedicated scratch at the first emit step
        @pl.when(i == 1)
        def _():
            tops = run_pointwise(top_refs)
            bots = run_pointwise(bot_refs)
            for p_idx in range(n_out):
                scratch[per_plane * p_idx + 2][:] = cast_rp(row_pass(tops[p_idx]))
                scratch[per_plane * p_idx + 3][:] = cast_rp(row_pass(bots[p_idx]))

    # Last-block geometry (static): r1 = in-block row of tile row H-1.
    # Rows past it (in-block and in the bottom strip) hold DMA garbage on
    # the last block; the ones inside reach of a valid output's window —
    # tile rows H..H-1+h — are replaced by the op's edge extension (or, in
    # ghost mode, by real neighbour rows from the bottom strip), as selects
    # on the pieces of the ext concat the kernel builds anyway.
    r1 = (global_h - 1) - (nb - 1) * block_h
    a = min(r1 + 1, block_h)  # real rows in the last block
    nfix = min(h, block_h - a)  # garbage rows to fix inside the block

    for p_idx, x in enumerate(planes):
        main_ref = scratch[per_plane * p_idx]
        tail_ref = scratch[per_plane * p_idx + 1]
        rp = cast_rp(row_pass(x))

        @pl.when(i >= 1)
        def _(rp=rp, main_ref=main_ref, tail_ref=tail_ref, p_idx=p_idx):
            main = main_ref[:]
            if ghosts:
                first_top = scratch[per_plane * p_idx + 2][:]
                bscr = scratch[per_plane * p_idx + 3][:]
            else:
                first_top = _top_strip(main, h, mode)
            top = jnp.where(j == 0, first_top, tail_ref[:])

            if ghosts:

                def beyond(t, bscr=bscr):
                    # tile row H + t is strip row t; rows past the strip
                    # feed only cropped outputs, so the clamp is safe
                    c = min(t, h - 1)
                    return bscr[c : c + 1]

                beyond_pen = beyond
            else:

                def beyond(t):
                    """Row-pass row holding the edge extension of image row
                    H + t, sourced from the last block (`main` at the final
                    emit step) at a static offset; may cross into the halo
                    strip. Unreachable sources are clamped — they feed only
                    outputs past the image bottom (see module comment)."""
                    if mode == "reflect101":
                        gp = 2 * (global_h - 1) - (global_h + t)
                    else:  # edge (zero/interior never fix)
                        gp = global_h - 1
                    p = min(max(gp - (nb - 1) * block_h, -h), block_h - 1)
                    if p >= 0:
                        return main[p : p + 1]
                    return top[h + p : h + p + 1]

                def beyond_pen(t):
                    """Same image row H + t one step earlier (j == nb-2),
                    where the ragged block's row pass lives in `rp` and
                    block nb-2's in `main`. Static reflect source r1-1-t."""
                    p = (r1 - 1 - t) if mode == "reflect101" else r1
                    if p >= 0:
                        return rp[p : p + 1]
                    return main[block_h + p : block_h + p + 1]

            ext = _assemble_ext(
                j, top, main, rp, beyond, beyond_pen,
                nb=nb, bh=block_h, h=h, a=a, nfix=nfix,
                # full-image interior mode: the interior mask passes
                # through exactly the outputs whose windows could touch the
                # garbage rows, so no fixes needed. In ghost mode the
                # beyond-tile rows are real data and must always be fixed.
                skip_fixes=(mode == "interior" and not ghosts),
            )
            q = _quantize_u8(stencil, col_pass(ext))
            if mode == "interior":
                orig = main[:, h : h + global_w] if rp_w != global_w else main
                if orig.dtype != U8:
                    orig = _f32_to_u8(orig)  # exact u8 integers
                if ghosts:
                    base = y0_ref[0] + j * block_h
                    mask = stencil.interior_mask(
                        (block_h, global_w), base, 0, image_h, image_w
                    )
                else:
                    mask = stencil.interior_mask(
                        (block_h, global_w), j * block_h, 0, global_h, global_w
                    )
                q = jnp.where(mask, q, orig)
            out_refs[p_idx][:] = q

        tail_ref[:] = main_ref[block_h - h :]
        main_ref[:] = rp


def _pointwise_kernel(*refs, pointwise, n_in, n_out):
    planes = [exact_f32(r[:]) for r in refs[:n_in]]
    for op in pointwise:
        planes = _apply_pointwise_planes(op, planes)
    assert len(planes) == n_out
    for out_ref, plane in zip(refs[n_in:], planes):
        out_ref[:] = _f32_to_u8(plane)


# --------------------------------------------------------------------------
# Group runner
# --------------------------------------------------------------------------


# Mosaic's default scoped-VMEM limit is 16 MiB; v5e has 128 MiB of VMEM.
# Raising the limit lets wide images keep useful block heights; the block-
# height heuristic then targets a working set below this.
_VMEM_LIMIT = 64 * 1024 * 1024
# older jax names the dataclass TPUCompilerParams
_COMPILER_PARAMS = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)(vmem_limit_bytes=_VMEM_LIMIT)


def _live_f32_temps(stencil: StencilOp | None) -> int:
    """Peak count of concurrently-live f32 block-sized temporaries the
    kernel body creates per output plane.

    Most ops fit the calibrated default of 8 (concat copies, pointwise
    intermediates, accumulators), but wide-fan-in column passes hold more:
    the median selection network keeps every window wire live (size^2), and
    a non-separable correlation's live set scales with its *nonzero* tap
    count (zero-weight taps are skipped; observed on v5e: 25-tap unsharp
    and median:5 crash the Mosaic compile at bh=512, 5-nonzero-tap
    emboss:5 is fine)."""
    if stencil is None:
        return 4
    if stencil.reduce == "median":
        return stencil.kernels[0].shape[0] ** 2 + 4
    if stencil.reduce in ("min", "max"):
        return 8
    if stencil.separable is not None:
        return 8
    taps = sum(int(np.count_nonzero(k)) for k in stencil.kernels)
    return max(8, taps + 4)


def _pick_block_h(
    width: int,
    n_in: int,
    n_out: int,
    halo: int,
    live_f32: int = 8,
    impl: str = "pallas",
    io_scale: float | None = None,
) -> int:
    """Row-block height maximising VMEM use without overflowing it.

    Working-set estimate per row of block height: u8 input blocks (double-
    buffered by the pipeline) + u8 output double-buffer + f32 row-pass
    scratch + `live_f32` live f32 temps per plane while the kernel body
    runs (see _live_f32_temps). Calibrated on v5e: the 8K gaussian5 kernel
    at bh=128 reports ~21 MB scoped use.

    `io_scale` is the measured cost-ledger drift ratio for this stage
    (measured boundary bytes / modelled one-read-one-write bytes,
    obs/cost.attribute_plan). Ratios above 1 mean the executable really
    moves more than the analytical model reserves for, so the working
    set is inflated accordingly — shrink-only, bounded, and the
    analytical estimate stays the answer whenever no measurement exists."""
    budget = 3 * _VMEM_LIMIT // 4
    n_live = max(n_in, n_out)
    # row-pass scratch rows are width + 2*halo wide for non-separable ops;
    # folding the halo into every term over-reserves by a harmless epsilon
    per_row = (width + 2 * halo) * (4 * n_in + 8 * n_out + 4 * live_f32 * n_live)
    if io_scale is not None and io_scale > 1.0:
        # never grow past the model, and never trust a wild measurement
        # with more than the drift-alert band's headroom
        per_row = int(per_row * min(io_scale, 4.0))
    bh = budget // max(per_row, 1)
    bh = int(max(32, min(512, bh)))
    bh = (bh // 32) * 32
    # a measured `autotune` calibration may shrink (never grow) the block:
    # min() keeps the VMEM working-set model authoritative for safety while
    # letting on-device measurement pick the faster height within it
    # (utils/calibration.py; disabled via MCIM_NO_CALIB for A/B tools)
    calibrated = calibration.lookup_block_h(impl=impl, width=width)
    if calibrated is not None:
        bh = max(32, min(bh, (calibrated // 32) * 32))
    return bh


def run_group(
    pointwise: list[PointwiseOp],
    stencil: StencilOp | None,
    planes: list[jnp.ndarray],
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
    ghosts: tuple[list[jnp.ndarray], list[jnp.ndarray]] | None = None,
    y0=None,
    image_h: int | None = None,
    image_w: int | None = None,
) -> list[jnp.ndarray]:
    """Execute one [pointwise*, stencil?] group as a single pallas_call.

    `ghosts=(tops, bots)` switches the stencil kernel to sharded ghost mode
    (see _stream_kernel): raw pre-pointwise (halo, W) strips per input
    plane ride along as VMEM refs, `y0` (traced global row offset) and the
    true `image_h`/`image_w` drive the interior mask. Requires a stencil.
    """
    if (
        stencil is None
        and len(pointwise) == 1
        and not pointwise[0].kernel_safe
    ):
        # LUT-style op: runs as a plain XLA step on the plane-stacked image
        op = pointwise[0]
        state = planes[0] if len(planes) == 1 else jnp.stack(planes, axis=-1)
        out = op(state)  # __call__, so channel validation matches other backends
        if out.ndim == 3:
            return [out[..., c] for c in range(out.shape[2])]
        return [out]
    if stencil is not None and stencil.edge_mode == "zero":
        raise NotImplementedError(
            "zero-mode stencils would need post-pointwise padding in the "
            "Pallas path; none exist in the registry"
        )
    height, width = planes[0].shape
    h = stencil.halo if stencil is not None else 0
    mode = stencil.edge_mode if stencil is not None else None
    if stencil is not None and mode in ("reflect101",) and height <= h:
        raise ValueError(f"image height {height} too small for halo {h}")

    n_in = len(planes)
    n_out = _channels_after(pointwise, n_in)
    bh = block_h or _pick_block_h(width, n_in, n_out, h, _live_f32_temps(stencil))

    if interpret is None:
        interpret = not is_tpu_backend()

    if stencil is None:
        # plain streaming pointwise: one read, one write, ragged last block
        # masked by Pallas
        grid = (-(-height // bh),)
        outs = pl.pallas_call(
            partial(_pointwise_kernel, pointwise=pointwise, n_in=n_in, n_out=n_out),
            grid=grid,
            in_specs=[
                pl.BlockSpec((bh, width), lambda i: (i, 0), memory_space=pltpu.VMEM)
                for _ in range(n_in)
            ],
            out_specs=[
                pl.BlockSpec((bh, width), lambda i: (i, 0), memory_space=pltpu.VMEM)
                for _ in range(n_out)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((height, width), U8) for _ in range(n_out)
            ],
            interpret=interpret,
            compiler_params=_COMPILER_PARAMS,
        )(*planes)
        outs = outs if isinstance(outs, (tuple, list)) else [outs]
        return list(outs)

    if 2 * h > bh:  # streaming needs the halo to fit inside one block
        raise ValueError(f"block_h {bh} too small for halo {h}")

    nb = -(-height // bh)
    _, _, rp_w, rp_needs_f32 = _split_passes(stencil, width)
    # row-pass values that are exact u8 integers keep u8 scratch: half the
    # VMEM traffic and 4x cheaper sublane shifts in the column pass
    rp_u8 = not rp_needs_f32
    rp_dtype = U8 if rp_u8 else F32
    padded_h = nb * bh

    kernel = partial(
        _stream_kernel,
        pointwise=pointwise,
        stencil=stencil,
        n_in=n_in,
        n_out=n_out,
        block_h=bh,
        nb=nb,
        global_h=height,
        global_w=width,
        rp_u8=rp_u8,
        ghosts=ghosts is not None,
        image_h=image_h,
        image_w=image_w,
    )
    per_plane_scratch = 2 if ghosts is None else 4
    scratch_shapes = []
    for _ in range(n_out):
        scratch_shapes.append(pltpu.VMEM((bh, rp_w), rp_dtype))  # main
        scratch_shapes.append(pltpu.VMEM((h, rp_w), rp_dtype))  # tail
        if per_plane_scratch == 4:
            scratch_shapes.append(pltpu.VMEM((h, rp_w), rp_dtype))  # top rp
            scratch_shapes.append(pltpu.VMEM((h, rp_w), rp_dtype))  # bot rp
    in_specs = [
        pl.BlockSpec(
            (bh, width),
            partial(lambda i, n: (jnp.minimum(i, n - 1), 0), n=nb),
            memory_space=pltpu.VMEM,
        )
        for _ in range(n_in)
    ]
    args = list(planes)
    if ghosts is not None:
        tops, bots = ghosts
        strip_spec = pl.BlockSpec(
            (h, width), lambda i: (0, 0), memory_space=pltpu.VMEM
        )
        in_specs = (
            [pl.BlockSpec(memory_space=pltpu.SMEM)]
            + in_specs
            + [strip_spec] * (2 * n_in)
        )
        args = (
            [jnp.asarray(y0, jnp.int32).reshape(1)]
            + args
            + list(tops)
            + list(bots)
        )
    outs = pl.pallas_call(
        kernel,
        grid=(nb + 1,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (bh, width),
                lambda i: (jnp.maximum(i - 1, 0), 0),
                memory_space=pltpu.VMEM,
            )
            for _ in range(n_out)
        ],
        out_shape=[
            jax.ShapeDtypeStruct((padded_h, width), U8) for _ in range(n_out)
        ],
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(*args)
    outs = outs if isinstance(outs, (tuple, list)) else [outs]
    return [o[:height] for o in outs]


def stencil_tile_pallas(
    op: StencilOp,
    ext: jnp.ndarray,
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
) -> jnp.ndarray:
    """Stencil valid+quantize over a pre-extended tile (sharded path).

    `ext` is (local_h + 2*halo, W) uint8 whose ghost rows were already
    materialised by the caller (ppermute halo exchange + global-edge fixup,
    parallel/api.py), so the kernel streams it directly: output block j
    needs ext rows [j*bh, j*bh + bh + 2h), i.e. the previous input block's
    row-pass (VMEM scratch) plus the first 2h rows of the current one. The
    interior mask (if any) is applied by the caller in XLA, since the tile's
    global row offset is a traced value inside shard_map. Returns quantized
    uint8 (local_h, W).
    """
    h = op.halo
    local_h, width = ext.shape[0] - 2 * h, ext.shape[1]
    bh = block_h or _pick_block_h(width, 1, 1, h, _live_f32_temps(op))
    if 2 * h > bh:
        raise ValueError(f"block_h {bh} too small for halo {h}")
    row_pass, col_pass, rp_w, rp_needs_f32 = _split_passes(op, width)
    rp_dtype = F32 if rp_needs_f32 else U8
    nb_out = -(-local_h // bh)
    nb_in = -(-(local_h + 2 * h) // bh)

    def kernel(in_ref, out_ref, main_ref):
        i = pl.program_id(0)
        rp = row_pass(in_ref[:])
        if rp.dtype != rp_dtype:
            rp = _f32_to_u8(rp)  # exact u8 integers by construction

        @pl.when(i >= 1)
        def _():
            ext_rows = jnp.concatenate([main_ref[:], rp[: 2 * h]], axis=0)
            out_ref[:] = _quantize_u8(op, col_pass(ext_rows))

        main_ref[:] = rp

    if interpret is None:
        interpret = not is_tpu_backend()
    out = pl.pallas_call(
        kernel,
        grid=(nb_out + 1,),
        in_specs=[
            pl.BlockSpec(
                (bh, width),
                partial(lambda i, n: (jnp.minimum(i, n - 1), 0), n=nb_in),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (bh, width),
            lambda i: (jnp.maximum(i - 1, 0), 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((nb_out * bh, width), U8),
        scratch_shapes=[pltpu.VMEM((bh, rp_w), rp_dtype)],
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(ext)
    return out[:local_h]


def stencil_tile_pallas_fused(
    op: StencilOp,
    tile: jnp.ndarray,
    top: jnp.ndarray,
    bottom: jnp.ndarray,
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
    y0=0,
    image_h: int | None = None,
    image_w: int | None = None,
) -> jnp.ndarray:
    """Stencil over a sharded tile with its ghost strips as separate refs —
    a single-plane, no-pointwise wrapper over run_group's ghost mode (see
    _stream_kernel). Streams the tile directly instead of a caller-
    materialised halo-extended copy, so sharded HBM traffic matches the
    unsharded streaming kernel: one u8 read + one u8 write of the tile.
    `top`/`bottom` must hold the correct ghost rows (ppermuted neighbour
    rows, with the op's edge extension on global-image edges —
    parallel/api._fix_edge_strips). Caller guarantees: no global pad rows
    inside the tile and local_h > halo. Interior-mode ops additionally
    need the traced global offset `y0` and true image dims for their mask.
    """
    if op.edge_mode == "interior" and (image_h is None or image_w is None):
        raise ValueError("interior-mode fused stencils need image_h/image_w")
    return run_group(
        [],
        op,
        [tile],
        interpret=interpret,
        block_h=block_h,
        ghosts=([top], [bottom]),
        y0=y0,
        image_h=image_h,
        image_w=image_w,
    )[0]


# --------------------------------------------------------------------------
# Fused-stage megakernel (plan=fused-pallas)
#
# One pallas_call per fused plan Stage: the ENTIRE stage — pointwise runs,
# MULTIPLE stencils (temporal blocking), per-op edge extension and finalize
# — executes block-by-block with every intermediate living in VMEM/
# registers. Where `_stream_kernel` above fuses [pointwise*, stencil] (one
# stencil per launch, exactly-once HBM reads via a cross-step scratch
# carry), the megakernel trades a sliver of re-read for generality: each
# grid step reads a HALO-EXTENDED input block — the (block_h, W) main
# block plus two sublane-aligned context strips delivered as separate
# BlockSpec refs over the same array — and computes its output rows
# entirely locally, so chained stencils need no cross-step delay pipeline.
# HBM traffic per stage: one write plus one read times (1 + 2*strip/bh)
# (~5% overlap at the default block heights); intermediates between member
# ops NEVER touch HBM. Pallas's sequential-grid pipelining double-buffers
# the block + strip DMAs under the previous step's compute — the
# "software systolic" stream of PAPERS.md arxiv 1907.06154, per stage.
#
# The in-kernel walk mirrors plan/exec.walk_stage under the MATERIALISED
# convention (context rows present; out-of-image rows rewritten per op
# before that op reads them — the sharded `edge_fix` convention, proven
# bit-exact against the pad2d golden by tests/test_plan.py): each stencil
# rewrites the `halo` out-of-image rows its kept outputs can reach from
# static row slices of the carry (Mosaic has no reverse/pad primitive),
# width-extends per its own mode (`_row_identity_ext`), runs its golden
# `valid` and finalizes at global coordinates. Deeper garbage rows feed
# only outputs that later shrinks/crops discard — the same reachability
# argument `_assemble_ext` documents for the single-stencil kernel.
#
# Two modes, one kernel:
#   * full   — the image itself is the array; rows beyond it synthesised
#              from the op's edge extension at the first/last blocks.
#   * ghost  — the sharded path: the array is a (local_h + 2H, W) tile
#              already extended by the stage's ONE ppermute ghost-strip
#              pair (parallel/api._run_segment_planned), `y0` rides as an
#              SMEM scalar, and edge synthesis fires only on the shards
#              whose tile actually touches a global image edge.
# --------------------------------------------------------------------------


def _stage_strip_h(halo: int) -> int:
    """Context-strip block height: sublane-aligned (multiple of 8) and
    covering 2*halo rows, so ONE bottom strip ref serves both the full
    mode (halo rows) and the ghost mode (2*halo rows)."""
    return max(8, -(-(2 * halo) // 8) * 8)


def _rewrite_rows(cur: jnp.ndarray, pieces: list, lo: int, hi: int, cond):
    """Replace carry rows [lo, hi) with `pieces` (1-row arrays) under the
    scalar condition `cond` — the select-merge all edge fixes share."""
    synth = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces, axis=0)
    mid = jnp.where(cond, synth, cur[lo:hi])
    out = []
    if lo:
        out.append(cur[:lo])
    out.append(mid)
    if hi < cur.shape[0]:
        out.append(cur[hi:])
    return out[0] if len(out) == 1 else jnp.concatenate(out, axis=0)


def _fix_top_edge(cur: jnp.ndarray, op: StencilOp, n_above: int, cond):
    """Synthesise the op's edge extension for the `halo` rows directly
    above global row 0 (carry rows [n_above - k, n_above)), from static
    slices of the carry — reflect101 reads +t, edge reads row 0,
    zero/'interior' write the constant-0 pad2d uses. Deeper out-of-image
    rows feed only outputs the walk's shrinks discard (module comment)."""
    h = op.halo
    k = min(h, n_above)
    if k == 0:
        return cur
    pieces = []
    for t in range(k, 0, -1):  # carry row n_above - t == global row -t
        if op.edge_mode == "reflect101":
            pieces.append(cur[n_above + t : n_above + t + 1])
        elif op.edge_mode == "edge":
            pieces.append(cur[n_above : n_above + 1])
        else:  # zero / interior: constant-0 padding (finalize masks)
            pieces.append(jnp.zeros((1, cur.shape[1]), cur.dtype))
    return _rewrite_rows(cur, pieces, n_above - k, n_above, cond)


def _fix_bottom_edge(cur: jnp.ndarray, op: StencilOp, r_last: int, cond):
    """Synthesise the op's edge extension for the `halo` rows directly
    below the image bottom, whose last real row sits at carry row
    `r_last` (static under `cond`'s block index)."""
    h = op.halo
    k = min(h, cur.shape[0] - 1 - r_last)
    if k <= 0 or r_last < h:  # infeasible reflect gated by the caller
        return cur
    pieces = []
    for t in range(1, k + 1):  # carry row r_last + t == global bottom + t
        if op.edge_mode == "reflect101":
            pieces.append(cur[r_last - t : r_last - t + 1])
        elif op.edge_mode == "edge":
            pieces.append(cur[r_last : r_last + 1])
        else:
            pieces.append(jnp.zeros((1, cur.shape[1]), cur.dtype))
    return _rewrite_rows(cur, pieces, r_last + 1, r_last + 1 + k, cond)


def _stage_kernel(
    *refs,
    stage_ops,
    stage_arms,
    n_in: int,
    n_out: int,
    block_h: int,
    nb: int,
    halo: int,
    height: int,
    width: int,
    ghosts: bool,
    local_h: int | None,
    image_h: int | None,
    image_w: int | None,
):
    """The megakernel body: one halo-extended block through the whole
    stage. `height` is the array height (image height in full mode, the
    extended tile height local_h + 2*halo in ghost mode)."""
    H = halo
    if ghosts:
        y0_ref = refs[0]
        in_refs = refs[1 : 1 + n_in]
        tail_refs = refs[1 + n_in : 1 + 2 * n_in]
        out_refs = refs[1 + 2 * n_in :]
    else:
        in_refs = refs[:n_in]
        top_refs = refs[n_in : 2 * n_in] if H else ()
        tail_refs = refs[2 * n_in : 3 * n_in] if H else ()
        out_refs = refs[(3 * n_in if H else n_in) :]

    i = pl.program_id(0)
    if ghosts:
        # edge synthesis fires only where the tile touches a global edge
        is_top = (i == 0) & (y0_ref[0] == 0)
        is_bot = y0_ref[0] + local_h == image_h
        glob_h, glob_w = image_h, image_w
        # carry row r of block i <-> local row i*block_h - H + r; the
        # last real local row (local_h - 1) in block j's carry:
        r_last_of = lambda j, off: (local_h - 1) - (j * block_h - (H - off))
        y_base = y0_ref[0]
    else:
        is_top = i == 0
        is_bot = True
        glob_h, glob_w = height, width
        r_last_of = lambda j, off: (height - 1) - (j * block_h - (H - off))
        y_base = 0

    # assemble the halo-extended f32 carry: strip tails + main block
    planes = []
    for p_idx in range(n_in):
        main = in_refs[p_idx][:]
        if H == 0:
            planes.append(exact_f32(main))
            continue
        if ghosts:
            ext = jnp.concatenate([main, tail_refs[p_idx][: 2 * H]], axis=0)
        else:
            top = top_refs[p_idx][:]
            ext = jnp.concatenate(
                [top[top.shape[0] - H :], main, tail_refs[p_idx][:H]], axis=0
            )
        planes.append(exact_f32(ext))

    off = 0
    for op, arm in zip(stage_ops, stage_arms):
        if not isinstance(op, StencilOp):
            planes = _apply_pointwise_planes(op, planes)
            continue
        h = op.halo
        rows = planes[0].shape[0]
        n_above = H - off  # carry rows above the first output-reachable row
        new_planes = []
        for p in planes:
            if h:
                if n_above:
                    p = _fix_top_edge(p, op, n_above, is_top)
                # bottom fixes: only the last two blocks' carries can hold
                # rows at/past the image bottom (block_h >= 2*halo)
                for j in (nb - 2, nb - 1):
                    if j < 0:
                        continue
                    r_last = r_last_of(j, off)
                    if 0 <= r_last < rows - 1:
                        p = _fix_bottom_edge(p, op, r_last, (i == j) & is_bot)
            xe = _row_identity_ext(p, h, op.edge_mode)
            if arm == "vpu":
                acc = op.valid(xe)
            else:
                # the per-op MXU arm, resolved host-side by the caller:
                # the same exact integers as op.valid, contracted as
                # dot_generals inside this kernel body (mxu_kernels
                # stage_valid_mxu — bit-exact by the same argument as
                # the whole-op route)
                acc = stage_valid_mxu(op, xe, arm=arm)
            orig = p[h : rows - h] if h else p
            y0 = y_base + i * block_h - n_above + h
            new_planes.append(
                op.finalize_f32(acc, orig, y0, 0, glob_h, glob_w)
            )
        planes = new_planes
        off += h

    assert len(planes) == n_out, (len(planes), n_out)
    for p_idx in range(n_out):
        out_refs[p_idx][:] = _f32_to_u8(planes[p_idx])


def _stage_live_f32(stage_ops) -> int:
    """Peak live block-sized f32 temporaries per plane for the stage walk:
    the widest member op's live set (the walk is sequential, so peaks
    don't stack) plus the carry copies the edge-fix concats hold."""
    live = 8
    for op in stage_ops:
        if isinstance(op, StencilOp):
            live = max(live, _live_f32_temps(op))
    return live + 4


def fused_stage_block_h(
    stage_ops, halo: int, width: int, n_ch: int, block_h: int | None = None,
    io_scale: float | None = None,
) -> int | None:
    """The megakernel's row-block height: the shared VMEM working-set
    model (`_pick_block_h`, impl key 'fused-pallas' for calibration
    overrides, `io_scale` = this stage's measured cost-ledger drift)
    rounded DOWN to the context-strip alignment. None when
    even the minimum block busts the budget — the caller falls back to
    the per-stage XLA walker (plan/pallas_exec counts the rejection)."""
    S = _stage_strip_h(halo)
    if block_h is None:
        block_h = _pick_block_h(
            width, n_ch, n_ch, halo, _stage_live_f32(stage_ops),
            impl="fused-pallas", io_scale=io_scale,
        )
    bh = (block_h // S) * S
    if bh < S or bh < 2 * halo:
        return None
    return bh


def fused_stage_call(
    stage_ops,
    planes: list[jnp.ndarray],
    *,
    halo: int,
    interpret: bool | None = None,
    block_h: int | None = None,
    io_scale: float | None = None,
    ghosts: bool = False,
    y0=None,
    image_h: int | None = None,
    image_w: int | None = None,
    mxu_stage: str | None = None,
) -> list[jnp.ndarray]:
    """Execute one fused plan stage as a single streaming pallas_call.

    Full mode: `planes` are (H, W) image planes; returns output planes.
    Ghost mode: `planes` are (local_h + 2*halo, W) extended tile planes
    (the stage's single ppermute pair already materialised), `y0` is the
    tile's traced global row offset and `image_h`/`image_w` the true
    image dims; returns (local_h, W) planes. Eligibility (edge-synthesis
    feasibility, VMEM budget, kernel-safe members) is the CALLER's
    contract — plan/pallas_exec.stage_pallas_reject gates it.

    `mxu_stage` overrides the MCIM_MXU_STAGE setting for the per-op
    in-stage MXU arm resolution ('on' under plan=fused-pallas-mxu; None
    = env/calibration auto). Arms resolve HERE, host-side, once per
    (re)trace — every consumer (full mode, ghost mode, sharded, serving)
    gets the same per-op-within-stage choice and the same counted
    fallback accounting for free."""
    H = halo
    height, width = planes[0].shape
    stage_arms = tuple(
        stage_arm_for(op, width=image_w or width, setting=mxu_stage)
        for op in stage_ops
    )
    n_in = len(planes)
    n_out = _channels_after(
        [op for op in stage_ops if not isinstance(op, StencilOp)], n_in
    )
    bh = fused_stage_block_h(
        stage_ops, H, width, max(n_in, n_out), block_h, io_scale
    )
    if bh is None:
        raise ValueError(
            f"no feasible megakernel block height for halo {H} at width "
            f"{width} (VMEM budget) — caller must gate on "
            "fused_stage_block_h"
        )
    if interpret is None:
        interpret = not is_tpu_backend()
    S = _stage_strip_h(H)
    r = bh // S
    if ghosts:
        local_h = height - 2 * H
        nb = -(-local_h // bh)
        ns = -(-height // S)
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        in_specs += [
            pl.BlockSpec((bh, width), lambda i: (i, 0), memory_space=pltpu.VMEM)
            for _ in range(n_in)
        ]
        in_specs += [
            pl.BlockSpec(
                (S, width),
                partial(lambda i, n, rr: (jnp.minimum(i * rr + rr, n - 1), 0),
                        n=ns, rr=r),
                memory_space=pltpu.VMEM,
            )
            for _ in range(n_in)
        ]
        args = [jnp.asarray(y0, jnp.int32).reshape(1)] + list(planes) * 2
        out_rows = local_h
    else:
        local_h = None
        nb = -(-height // bh)
        ns = -(-height // S)
        in_specs = [
            pl.BlockSpec(
                (bh, width),
                partial(lambda i, n: (jnp.minimum(i, n - 1), 0), n=nb),
                memory_space=pltpu.VMEM,
            )
            for _ in range(n_in)
        ]
        if H:
            in_specs += [
                pl.BlockSpec(
                    (S, width),
                    partial(lambda i, rr: (jnp.maximum(i * rr - 1, 0), 0),
                            rr=r),
                    memory_space=pltpu.VMEM,
                )
                for _ in range(n_in)
            ]
            in_specs += [
                pl.BlockSpec(
                    (S, width),
                    partial(
                        lambda i, n, rr: (jnp.minimum(i * rr + rr, n - 1), 0),
                        n=ns, rr=r,
                    ),
                    memory_space=pltpu.VMEM,
                )
                for _ in range(n_in)
            ]
            args = list(planes) * 3
        else:
            args = list(planes)
        out_rows = height
    kernel = partial(
        _stage_kernel,
        stage_ops=tuple(stage_ops),
        stage_arms=stage_arms,
        n_in=n_in,
        n_out=n_out,
        block_h=bh,
        nb=nb,
        halo=H,
        height=height,
        width=width,
        ghosts=ghosts,
        local_h=local_h,
        image_h=image_h,
        image_w=image_w,
    )
    outs = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (bh, width), lambda i: (i, 0), memory_space=pltpu.VMEM
            )
            for _ in range(n_out)
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb * bh, width), U8) for _ in range(n_out)
        ],
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(*args)
    outs = outs if isinstance(outs, (tuple, list)) else [outs]
    return [o[:out_rows] for o in outs]


def pipeline_pallas(
    ops,
    img: jnp.ndarray,
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
):
    """Run a full pipeline through fused Pallas group kernels.

    Same uint8 semantics as the golden path (bit-exact — asserted by
    tests/test_pallas.py); images are processed as planar channels.
    (The former `packed=True` wide-word routing was demoted to
    tools/packed_kernels.py after the round-5 on-chip A/B measured it
    4.1x slower than this path — see that module's docstring.)
    """
    if img.ndim == 3:
        planes = [img[..., c] for c in range(img.shape[2])]
    else:
        planes = [img]
    for pointwise, stencil in group_ops(ops):
        planes = run_group(
            pointwise, stencil, planes, interpret=interpret, block_h=block_h
        )
    if len(planes) == 1:
        return planes[0]
    return jnp.stack(planes, axis=-1)


def _channels_after(pointwise: list[PointwiseOp], n_ch: int) -> int:
    for op in pointwise:
        if op.out_channels:
            n_ch = op.out_channels
    return n_ch


def use_pallas_for_stencil(stencil: StencilOp | None, group_in_channels: int) -> bool:
    """Static backend choice, from v5e measurements (BASELINE.md).

    XLA fuses a pointwise chain plus a halo-1 stencil into a single
    HBM pass over the HWC image, which no split or planar re-read beats
    (reference pipeline: 78 GP/s XLA vs 30 GP/s Pallas). Pallas wins once
    the stencil re-reads enough neighbourhood — halo >= 2 (5x5 Gaussian:
    47 GP/s Pallas vs 11 GP/s XLA) — or for a multi-kernel combine
    (Sobel), unless the group drags a 3-channel prologue into planar form.

    `group_in_channels` is the channel count *entering the group*: the
    sharded runner's fused ghost path passes its tile's real channel count
    (parallel.api._run_segment), while its materialised-ext fallback runs
    per plane and passes 1 (_resolve_backend). This single helper is
    shared by pipeline_auto and parallel.api so the auto paths cannot
    drift.
    """
    if stencil is None:
        return False
    if stencil.halo >= 2:
        return True
    return group_in_channels == 1 and len(stencil.kernels) > 1


def prefer_swar() -> bool:
    """Promotion switch for the SWAR quarter-strip backend
    (ops/swar_kernels.py): MCIM_PREFER_SWAR=1 routes eligible stencil
    groups through it on every auto path — CLI default, batch, AND the
    row-sharded runner, where eligible groups take the quarter-strip
    ghost path (parallel/api.py, VERDICT r4 #3). Off by default, and the
    round-5 on-chip capture (BENCH_HISTORY 2026-08-01) measured the
    production SWAR headline at 0.83x the u8 streaming kernel — the
    pre-registered 2-4x prediction did not hold (the element-rate-cap
    premise was itself falsified the same window), so the switch stays
    off; it remains for A/B reproduction. The sharded runner snapshots
    this flag once at build time (sharded_pipeline), so a mid-session env
    change never splits routing across retraces."""
    from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

    return env_registry.get_bool("MCIM_PREFER_SWAR")


def pipeline_auto(
    ops,
    img: jnp.ndarray,
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
):
    """Per-group backend selection: golden/XLA ops where XLA's fusion wins,
    Pallas group kernels where the stencil working set favours them.
    Both branch choices are measured on-chip (use_pallas_for_stencil
    docstring; re-confirmed round 5: 73.3 GP/s XLA vs 33.9 GP/s Pallas on
    the reference pipeline, 44.1 GP/s Pallas vs 11.4 GP/s XLA on the 8K
    gaussian:5). Bit-exact with both pure paths (they are bit-exact with
    each other)."""
    state = img
    swar = prefer_swar()
    for pointwise, stencil in group_ops(ops):
        n_ch = state.shape[2] if state.ndim == 3 else 1
        # MXU banded-matmul routing (round-6 promotion): checked first —
        # it only fires behind a measured per-device-kind calibration win
        # (or the MCIM_PREFER_MXU A/B switch) and never off-TPU, so the
        # default auto behaviour is unchanged (ops/mxu_kernels.py). The
        # pointwise prologue runs on the VPU via its golden fn and fuses
        # into the same XLA launch as the MXU contraction.
        if stencil is not None:
            from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
                mxu_stencil,
                use_mxu_for_stencil,
            )

            mxu_mode_choice = use_mxu_for_stencil(stencil, state.shape[1])
            if mxu_mode_choice is not None:
                for op in pointwise:
                    state = op(state)
                state = mxu_stencil(stencil, state, mode=mxu_mode_choice)
                continue
        # The SWAR promotion switch is checked BEFORE the u8-Pallas gate:
        # use_pallas_for_stencil rejects cheap halo-1 stencils (XLA wins
        # there for u8), but the corr2d SWAR family is mostly halo-1
        # (emboss:3, sharpen, laplacians) and the whole point of the
        # promotion is to route them off the u8 paths — and the sharded
        # auto runner already checks try_swar first (review finding:
        # nesting this under the u8 gate made single- and multi-chip
        # auto routing disagree).
        if swar and state.ndim == 2 and state.dtype == jnp.uint8:
            from mpi_cuda_imagemanipulation_tpu.ops.swar_kernels import (
                _chain_fixes_zero,
                swar_any_eligible,
                swar_fusable,
                swar_stencil,
            )

            if (
                stencil is not None
                and swar_any_eligible(stencil, tuple(state.shape))
                and all(swar_fusable(p) is not None for p in pointwise)
                and (
                    stencil.edge_mode != "zero"
                    or _chain_fixes_zero(pointwise)
                )
            ):
                state = swar_stencil(
                    stencil,
                    state,
                    pre_ops=tuple(pointwise),
                    block_h=block_h,
                    interpret=interpret,
                )
                continue
        if use_pallas_for_stencil(stencil, n_ch):
            planes = (
                [state[..., c] for c in range(state.shape[2])]
                if state.ndim == 3
                else [state]
            )
            planes = run_group(
                pointwise, stencil, planes, interpret=interpret, block_h=block_h
            )
            state = planes[0] if len(planes) == 1 else jnp.stack(planes, -1)
        else:
            for op in pointwise:
                state = op(state)
            if stencil is not None:
                state = stencil(state)
    return state
