"""Pallas TPU kernels: fused pointwise+stencil pipeline groups, 2-D tiled.

This is the framework's answer to kernel.cu's three separate `__global__`
launches (grayscale :31, contrast :49, emboss :64 — each a full HBM
round-trip on its own, kernel.cu:192-195): consecutive pointwise ops fuse
*into* the following stencil's kernel, so one `pallas_call` reads uint8
pixels from HBM once, applies the whole group in VMEM at f32, and writes
uint8 once.

Tiling model (the CUDA dim3-grid analogue, SURVEY.md §2.4): a 1-D grid over
row blocks; each grid step reads three consecutive row blocks (prev/curr/
next) per input plane so the stencil sees `halo` ghost rows without any
dynamic indexing — the overlapping-block pattern. All image-edge extension
(reflect101/edge/zero) is materialised by cheap XLA pads *outside* the
kernel, so the kernel body is pure unrolled shift-multiply-accumulate on the
VPU, bit-identical to the golden path (same tile functions from ops/spec.py,
integer-exact accumulation).

Colour images are decomposed into planar (H, W) channel arrays at the group
boundary — (8,128)-lane-friendly, instead of HWC's 3-wide minor axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    F32,
    U8,
    PointwiseOp,
    StencilOp,
)

# --------------------------------------------------------------------------
# Pipeline grouping: [pointwise*, stencil?] units, one pallas_call each
# --------------------------------------------------------------------------


def group_ops(ops) -> list[tuple[list[PointwiseOp], StencilOp | None]]:
    groups: list[tuple[list[PointwiseOp], StencilOp | None]] = []
    pointwise: list[PointwiseOp] = []
    for op in ops:
        if isinstance(op, StencilOp):
            groups.append((pointwise, op))
            pointwise = []
        elif not op.kernel_safe:
            # LUT-style ops can't lower in Mosaic: flush the running group
            # and emit the op as its own XLA-side group
            if pointwise:
                groups.append((pointwise, None))
                pointwise = []
            groups.append(([op], None))
        else:
            pointwise.append(op)
    if pointwise:
        groups.append((pointwise, None))
    return groups


def _apply_pointwise_planes(op: PointwiseOp, planes: list) -> list:
    """Apply a pointwise op to the plane-decomposed state (f32 planes holding
    exact u8 integer values — Mosaic has no unsigned<->float casts, so the
    whole kernel body stays in f32)."""
    if op.planes_core is not None:  # channel-structure ops (3->1 or 3->3)
        assert len(planes) == 3, f"{op.name} needs 3 channel planes"
        out = op.planes_core(*planes)
        return list(out) if isinstance(out, (list, tuple)) else [out]
    if op.name == "gray2rgb":
        assert len(planes) == 1
        return [planes[0], planes[0], planes[0]]
    if op.in_channels == 1 and len(planes) != 1:
        raise ValueError(f"op {op.name!r} expects 1 channel, got {len(planes)}")
    if op.core is None:  # pragma: no cover
        raise NotImplementedError(f"op {op.name!r} has no f32 core function")
    # elementwise ops act identically per plane
    return [op.core(p) for p in planes]


# --------------------------------------------------------------------------
# Edge extension (XLA-side, outside the kernel)
# --------------------------------------------------------------------------


def _ext_rows(x: jnp.ndarray, h: int, mode: str | None, top: bool) -> jnp.ndarray:
    if mode == "reflect101":
        return x[1 : h + 1][::-1] if top else x[-h - 1 : -1][::-1]
    if mode == "edge":
        return jnp.repeat(x[:1] if top else x[-1:], h, axis=0)
    return jnp.zeros((h, x.shape[1]), x.dtype)  # interior / zero / None


def _ext_cols(x: jnp.ndarray, h: int, mode: str | None, left: bool) -> jnp.ndarray:
    if mode == "reflect101":
        return x[:, 1 : h + 1][:, ::-1] if left else x[:, -h - 1 : -1][:, ::-1]
    if mode == "edge":
        return jnp.repeat(x[:, :1] if left else x[:, -1:], h, axis=1)
    return jnp.zeros((x.shape[0], h), x.dtype)


def _prepare_plane(
    plane: jnp.ndarray, h: int, mode: str | None, block_h: int, padded_h: int
) -> jnp.ndarray:
    """Lay out one channel plane for overlapping-block reads.

    Returns rows = block_h + padded_h + block_h, cols = W + 2h:
      [ zeros(block_h - h) | top edge-ext(h) | image (H) |
        bottom edge-ext(h) | zeros(padded_h - H + block_h - h) ]
    so that array-block k = image rows [(k-1)*block_h, k*block_h) and grid
    step i reading blocks (i, i+1, i+2) sees image rows
    [i*block_h - h, (i+1)*block_h + h) — the halo — with static indexing.
    """
    height = plane.shape[0]
    if h > 0:
        top = _ext_rows(plane, h, mode, top=True)
        bottom = _ext_rows(plane, h, mode, top=False)
        body = [top, plane, bottom]
        left_pad = block_h - h
        bottom_pad = (padded_h - height) + (block_h - h)
    else:
        body = [plane]
        left_pad = block_h
        bottom_pad = (padded_h - height) + block_h
    rows = [jnp.zeros((left_pad, plane.shape[1]), plane.dtype), *body]
    rows.append(jnp.zeros((bottom_pad, plane.shape[1]), plane.dtype))
    out = jnp.concatenate(rows, axis=0)
    if h > 0:
        left = _ext_cols(out, h, mode, left=True)
        right = _ext_cols(out, h, mode, left=False)
        out = jnp.concatenate([left, out, right], axis=1)
    return out


# --------------------------------------------------------------------------
# The fused group kernel
# --------------------------------------------------------------------------


def _group_kernel(
    *refs,
    pointwise: list[PointwiseOp],
    stencil: StencilOp | None,
    n_in: int,
    n_out: int,
    block_h: int,
    halo: int,
    global_h: int,
    global_w: int,
):
    h = halo
    specs_per_plane = 3 if h > 0 else 1
    in_refs = refs[: specs_per_plane * n_in]
    out_refs = refs[specs_per_plane * n_in :]

    def u8_to_f32(x):
        # Mosaic has no unsigned->float cast; bridge through int32.
        return x.astype(jnp.int32).astype(F32)

    def f32_to_u8(x):
        return x.astype(jnp.int32).astype(U8)

    planes = []
    for p in range(n_in):
        if h > 0:
            prev, curr, nxt = in_refs[3 * p : 3 * p + 3]
            ext = jnp.concatenate(
                [u8_to_f32(prev[-h:]), u8_to_f32(curr[:]), u8_to_f32(nxt[:h])],
                axis=0,
            )
        else:
            ext = u8_to_f32(in_refs[p][:])
        planes.append(ext)

    for op in pointwise:
        planes = _apply_pointwise_planes(op, planes)

    if stencil is None:
        assert len(planes) == n_out
        for out_ref, plane in zip(out_refs, planes):
            out_ref[:] = f32_to_u8(plane)
        return

    # stencils filter each plane independently (colour images per channel)
    assert len(planes) == n_out
    y0 = pl.program_id(0) * block_h
    for out_ref, x in zip(out_refs, planes):
        acc = stencil.valid(x)  # (block_h, W)
        orig = x[h : h + block_h, h : h + global_w] if h > 0 else x
        out_ref[:] = f32_to_u8(
            stencil.finalize_f32(acc, orig, y0, 0, global_h, global_w)
        )


# --------------------------------------------------------------------------
# Group runner
# --------------------------------------------------------------------------


def _pick_block_h(width: int, n_in: int, n_out: int, halo: int) -> int:
    """Row-block height maximising VMEM use without overflowing it.

    Working set per row of block height (measured on v5e — bh=64 compiles
    and is fastest for W≈7.7k, bh=128 overflows): u8 input blocks
    (specs_per_plane per plane, double-buffered by the pipeline) plus ~3
    live f32 temps per live plane — colour stencil groups keep all
    max(n_in, n_out) extended channel planes resident at once.
    """
    budget = 10 * 1024 * 1024
    specs_per_plane = 3 if halo > 0 else 1
    n_live = max(n_in, n_out)
    per_row = width * (specs_per_plane * n_in * 2 + 4 * 3 * n_live)
    bh = budget // max(per_row, 1)
    bh = int(max(32, min(512, bh)))
    return (bh // 32) * 32


def run_group(
    pointwise: list[PointwiseOp],
    stencil: StencilOp | None,
    planes: list[jnp.ndarray],
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
) -> list[jnp.ndarray]:
    """Execute one [pointwise*, stencil?] group as a single pallas_call."""
    if (
        stencil is None
        and len(pointwise) == 1
        and not pointwise[0].kernel_safe
    ):
        # LUT-style op: runs as a plain XLA step on the plane-stacked image
        op = pointwise[0]
        state = planes[0] if len(planes) == 1 else jnp.stack(planes, axis=-1)
        out = op(state)  # __call__, so channel validation matches other backends
        if out.ndim == 3:
            return [out[..., c] for c in range(out.shape[2])]
        return [out]
    if stencil is not None and stencil.edge_mode == "zero":
        raise NotImplementedError(
            "zero-mode stencils would need post-pointwise padding in the "
            "Pallas path; none exist in the registry"
        )
    height, width = planes[0].shape
    h = stencil.halo if stencil is not None else 0
    mode = stencil.edge_mode if stencil is not None else None
    if stencil is not None and mode in ("reflect101",) and height <= h:
        raise ValueError(f"image height {height} too small for halo {h}")

    n_in = len(planes)
    n_out = _channels_after(pointwise, n_in)

    bh = block_h or _pick_block_h(width, n_in, n_out, h)
    padded_h = -(-height // bh) * bh
    grid = (padded_h // bh,)

    prepared = [_prepare_plane(p, h, mode, bh, padded_h) for p in planes]
    in_width = width + 2 * h

    # stencil groups read prev/curr/next row blocks of each prepared plane;
    # pointwise-only groups (h == 0) read each block exactly once
    offsets = (0, 1, 2) if h > 0 else (1,)
    in_specs = []
    for _ in range(n_in):
        for off in offsets:
            in_specs.append(
                pl.BlockSpec(
                    (bh, in_width),
                    partial(lambda i, o: (i + o, 0), o=off),
                    memory_space=pltpu.VMEM,
                )
            )
    out_specs = [
        pl.BlockSpec((bh, width), lambda i: (i, 0), memory_space=pltpu.VMEM)
        for _ in range(n_out)
    ]
    out_shapes = [jax.ShapeDtypeStruct((padded_h, width), U8) for _ in range(n_out)]

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    kernel = partial(
        _group_kernel,
        pointwise=pointwise,
        stencil=stencil,
        n_in=n_in,
        n_out=n_out,
        block_h=bh,
        halo=h,
        global_h=height,
        global_w=width,
    )
    # each plane is passed once per spec (prev/curr/next for stencil groups)
    args = [p for p in prepared for _ in range(len(offsets))]
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs if n_out > 1 else out_specs[0],
        out_shape=out_shapes if n_out > 1 else out_shapes[0],
        interpret=interpret,
    )(*args)
    outs = outs if isinstance(outs, (tuple, list)) else [outs]
    return [o[:height] for o in outs]


def stencil_tile_pallas(
    op: StencilOp,
    ext: jnp.ndarray,
    *,
    interpret: bool | None = None,
    block_h: int | None = None,
) -> jnp.ndarray:
    """Stencil valid+quantize over a pre-extended tile (sharded path).

    `ext` is (local_h + 2*halo, W) uint8 whose ghost rows were already
    materialised by the caller (ppermute halo exchange + global-edge fixup,
    parallel/api.py), so the kernel needs no edge logic of its own; the
    interior mask (if any) is applied by the caller in XLA, since the tile's
    global row offset is a traced value inside shard_map. Returns quantized
    uint8 (local_h, W).
    """
    h = op.halo
    local_h, width = ext.shape[0] - 2 * h, ext.shape[1]
    bh = block_h or _pick_block_h(width, 1, 1, h)
    padded_h = -(-local_h // bh) * bh

    # width extension per op mode (the W axis is never sharded)
    if h > 0:
        left = _ext_cols(ext, h, op.edge_mode, left=True)
        right = _ext_cols(ext, h, op.edge_mode, left=False)
        ext = jnp.concatenate([left, ext, right], axis=1)
    # row layout for overlapping prev/curr/next blocks (top halo already
    # present in ext, so the leading zero filler is block_h - h rows)
    filler_top = jnp.zeros((bh - h, ext.shape[1]), ext.dtype)
    filler_bottom = jnp.zeros(
        ((padded_h - local_h) + (bh - h), ext.shape[1]), ext.dtype
    )
    prepared = jnp.concatenate([filler_top, ext, filler_bottom], axis=0)

    def kernel(prev, curr, nxt, out_ref):
        x = jnp.concatenate(
            [
                prev[-h:].astype(jnp.int32).astype(F32),
                curr[:].astype(jnp.int32).astype(F32),
                nxt[:h].astype(jnp.int32).astype(F32),
            ],
            axis=0,
        )
        from mpi_cuda_imagemanipulation_tpu.ops.spec import QUANTIZERS_F32

        q = QUANTIZERS_F32[op.quantize](op.valid(x))
        out_ref[:] = q.astype(jnp.int32).astype(U8)

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    in_specs = [
        pl.BlockSpec(
            (bh, ext.shape[1]),
            partial(lambda i, o: (i + o, 0), o=off),
            memory_space=pltpu.VMEM,
        )
        for off in (0, 1, 2)
    ]
    out = pl.pallas_call(
        kernel,
        grid=(padded_h // bh,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (bh, width), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((padded_h, width), U8),
        interpret=interpret,
    )(prepared, prepared, prepared)
    return out[:local_h]


def pipeline_pallas(ops, img: jnp.ndarray, *, interpret: bool | None = None):
    """Run a full pipeline through fused Pallas group kernels.

    Same uint8 semantics as the golden path (bit-exact — asserted by
    tests/test_pallas.py); images are processed as planar channels.
    """
    if img.ndim == 3:
        planes = [img[..., c] for c in range(img.shape[2])]
    else:
        planes = [img]
    for pointwise, stencil in group_ops(ops):
        planes = run_group(pointwise, stencil, planes, interpret=interpret)
    if len(planes) == 1:
        return planes[0]
    return jnp.stack(planes, axis=-1)


def _channels_after(pointwise: list[PointwiseOp], n_ch: int) -> int:
    for op in pointwise:
        if op.out_channels:
            n_ch = op.out_channels
    return n_ch


def use_pallas_for_stencil(stencil: StencilOp | None, group_in_channels: int) -> bool:
    """Static backend choice, from v5e measurements (BASELINE.md).

    XLA fuses a pointwise chain plus a halo-1 stencil into a single
    HBM pass over the HWC image, which no split or planar re-read beats
    (reference pipeline: 78 GP/s XLA vs 30 GP/s Pallas). Pallas wins once
    the stencil re-reads enough neighbourhood — halo >= 2 (5x5 Gaussian:
    47 GP/s Pallas vs 11 GP/s XLA) — or for a multi-kernel combine
    (Sobel), unless the group drags a 3-channel prologue into planar form.

    `group_in_channels` is the channel count *entering the group* (the
    sharded runner has no fused prologue, so it passes 1). This single
    helper is shared by pipeline_auto and parallel.api so the two auto
    paths cannot drift.
    """
    if stencil is None:
        return False
    if stencil.halo >= 2:
        return True
    return group_in_channels == 1 and len(stencil.kernels) > 1


def pipeline_auto(ops, img: jnp.ndarray, *, interpret: bool | None = None):
    """Per-group backend selection: golden/XLA ops where XLA's fusion wins,
    Pallas group kernels where the stencil working set favours them.
    Bit-exact with both pure paths (they are bit-exact with each other)."""
    state = img
    for pointwise, stencil in group_ops(ops):
        n_ch = state.shape[2] if state.ndim == 3 else 1
        if use_pallas_for_stencil(stencil, n_ch):
            planes = (
                [state[..., c] for c in range(state.shape[2])]
                if state.ndim == 3
                else [state]
            )
            planes = run_group(pointwise, stencil, planes, interpret=interpret)
            state = planes[0] if len(planes) == 1 else jnp.stack(planes, -1)
        else:
            for op in pointwise:
                state = op(state)
            if stencil is not None:
                state = stencil(state)
    return state
