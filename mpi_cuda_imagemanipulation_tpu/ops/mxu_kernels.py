"""Production MXU banded-matmul stencil backend (``impl='mxu'``).

Promotion of the tools/mxu_proto.py design into the framework (round 6).
Why this exists (the round-5 roofline result, BASELINE.md): the u8 copy
probe measured 552-658 GB/s, falsifying the element-rate-ceiling theory —
the headline 5x5 Gaussian (45.4k MP/s/chip) is VPU-COMPUTE-bound at ~11%
of the HBM roofline with the MXU (~197 TFLOP/s bf16 on v5e) idle. This
backend reformulates the correlation-class stencils as blocked banded
matmuls so the taps contract on the MXU instead of the VPU, mirroring the
systolic/tensor-core retargeting literature (PAPERS.md: "A Versatile
Software Systolic Execution Model for GPU Memory-Bound Kernels",
"SparStencil").

Formulation (separable row pass; the column pass is the mirror):

    out[h, B*j + n] = sum_k in_pad[h, B*j + n + k] * t[k],   k in [0, 2h]

With block width B=128, gather In_ext[j] = in_pad[:, B*j : B*j + B + 2h]
(static slices) and build the banded tap matrix C[i, n] = t[i - n] on the
valid band (shape (B + 2h, B)); then out_block_j = In_ext[j] @ C — an
einsum with M=H, K=B+2h, N=B=128: real MXU shapes. FLOPs are
(B+2h)/(2h+1) ~ 26x the arithmetic minimum for a 5-tap kernel, but the
MXU has ~430x the VPU's sustained MAC rate.

Exactness (the non-negotiable — every backend must be bit-exact against
the golden ops/spec.py path):

  * u8 pixel values (<= 255) and small integer taps are exactly
    representable in bf16 (8-bit significand: all integers <= 256, and any
    integer whose odd part is < 256 — checked per kernel at eligibility
    time via an ml_dtypes round-trip).
  * jnp.einsum with preferred_element_type=f32 accumulates exactly: every
    partial product and every partial sum is an integer bounded by
    255 * sum|w| < 2^24, so f32 addition is exact regardless of order.
  * The SEPARABLE column pass consumes the row-pass sums (<= 255*S, up to
    14 bits — NOT bf16-exact beyond 256), so it runs as the proven 64a+b
    split: tmp = 64*a + b with a = floor(tmp/64) and b = tmp - 64a; for
    tap sum S <= 64 both halves are <= 255 (bf16-exact) and
    colsum(tmp) = 64*colsum(a) + colsum(b) — integer-exact by linearity.
    (An f32-einsum column variant is kept for the A/B lane: exact
    directly, lower MXU rate.)
  * NON-SEPARABLE integer kernels (emboss/emboss101, sharpen, laplacians,
    unsharp, custom integer `filter`) contract in ONE einsum: kh
    row-shifted views of the width-blocked tile joint-contract over
    (row offset, band position) against C2[dy, i, n] = w[dy, i - n].
    Inputs are raw u8 values (bf16-exact), so no split is needed.
  * combine='magnitude' (sobel/prewitt/scharr) and any post `scale`
    REPLAY the golden float ops on the exact integer accumulations
    (jnp.sqrt(a0*a0 + a1*a1), acc * np.float32(scale)) — identical inputs
    + identical op sequence = identical f32 results, the same argument
    the SWAR wide mode rests on (ops/swar_kernels.py).
  * Quantization and interior-guard masking reuse StencilOp.finalize on
    the exact accumulations, so the final u8 is golden by construction.

The ``hybrid`` sub-mode splits the work across units inside ONE fused XLA
launch: the cheap u8 row pass runs on the VPU (the golden corr_valid's
exact shift-multiply-accumulate — O(k) adds over integers) and only the
column pass contracts on the MXU (halving the banded FLOPs); pointwise
prefixes always run on the VPU and fuse into the same program under jit.
Both modes are bit-exact; the mxu_ab bench lane measures vpu vs mxu vs
hybrid per silicon window.

Eligibility (``mxu_eligible``): ``reduce='corr'`` StencilOps whose
kernels are bf16-exact integers with 255 * sum|w| < 2^24, combine
'single' or 'magnitude', any edge mode / quantizer (the backend operates
on the caller's pre-extended tile and replays the golden finalize). The
separable banded path additionally needs non-negative integer taps with
sum S <= 64 (the 64a+b bound — all registry separables qualify);
separable ops outside that bound fall to the one-einsum 2-D path. Rank /
morphology ops (median, erode, dilate) have no linear identity and fall
back per op to the VPU paths — ``impl='mxu'`` is always-correct, the
same contract as ``impl='swar'``.

``backend='auto'`` routes a stencil group here only when (a) the op
family is eligible, (b) the live backend is a real TPU (platforms
without an MXU always take the VPU/XLA paths, bit-exactly), and (c) the
calibration store records a measured per-device-kind win for the family
(``mcim-tpu autotune --dimension backend``; utils/calibration.py) — or
the MCIM_PREFER_MXU=1 A/B switch is set (TPU-only, like
MCIM_PREFER_SWAR).

**In-stage contraction (``stage_valid_mxu``, round 8).** The whole-op
route above and the fused-pallas megakernel (ops/pallas_kernels.py)
were mutually exclusive: an MXU-eligible stencil inside a fused stage
ran on the VPU inside the ``pallas_call``. ``stage_valid_mxu`` is the
same banded contraction emitted INSIDE the stage kernel body — a 2-D
``lax.dot_general`` per 128-wide block, kh row-shifted views stacked on
the contracting axis so one dot covers the whole (row offset, band
position) reduction. The carry planes between in-stage ops are exact
u8-integer-valued f32 (every pointwise core maps exact integers to
exact integers and each stencil re-quantizes), so the whole-op
exactness argument transfers verbatim. Backend choice becomes
per-op-WITHIN-stage (``stage_arm_for``): 'vpu' (the golden walk),
'mxu' (bf16 operands, f32 accumulation) or 'mxu-int8' (operands shifted
by -128 into int8, int32 accumulation, the +128*sum(w) correction
re-added in f32 — exact because every intermediate is an integer below
2^24; ``mxu_int8_ok`` proves the |w| <= 127 operand bound). Arms key
the calibration store's ``stage_arm`` table; every MXU-capable op that
lands on the VPU inside a fused stage is counted under a closed reason
vocabulary (``count_stage_fallback`` ->
mcim_plan_mxu_in_stage_fallback_total) instead of dropping the signal.

**Morphology widening (SparStencil retargeting, round 8).** erode /
dilate (``reduce`` 'min'/'max' over a square all-ones structuring
element) gain a whole-op MXU identity via threshold decomposition:
y = sum_t [window_reduce(x) > t] for t in 0..254, and [max > t] ==
[windowsum([x > t]) >= 1], [min > t] == [windowsum([x > t]) == K^2] —
the rank reduce becomes counted ones-windowsums, i.e. banded matmuls
with all-ones taps (the structured-sparsity max-plus retargeting of
arxiv 2506.22969 made exact by counting). Indicator planes for m
thresholds pack base M = K^2 + 1 into one f32 plane (digits never
carry: a window holds at most K^2 ones), m chosen so the packed
windowsum M^m - 1 < 2^24 keeps every f32 intermediate exact; digits
extract in int32. Packed values exceed 256, so BOTH banded passes stay
f32 (never bf16). ~ceil(255/m) rounds make this an honest
calibration-gated candidate (it will lose at small K on most chips) —
but forced ``impl='mxu'`` now covers the family bit-exactly instead of
falling back, and the eligibility gate finally matches the paper's
coverage claim.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    F32,
    Op,
    StencilOp,
    corr_valid,
    exact_f32,
    pad2d,
)
from mpi_cuda_imagemanipulation_tpu.utils import calibration
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

B = 128  # one MXU / lane tile: the banded-matmul block width
_SPLIT = 64.0  # the 64a+b column-split radix (both halves <= 255: bf16-exact)
_F32_EXACT = 1 << 24  # integers below this are exact in f32

MXU_MODES = ("banded", "hybrid")
MXU_COL_VARIANTS = ("bf16split", "f32")


def mxu_mode() -> str:
    """Execution mode: 'banded' (both separable passes on the MXU) or
    'hybrid' (row pass on the VPU, column pass on the MXU) — env
    MCIM_MXU_MODE, default banded."""
    m = env_registry.get("MCIM_MXU_MODE") or "banded"
    if m not in MXU_MODES:
        raise ValueError(f"MCIM_MXU_MODE={m!r}; known: {MXU_MODES}")
    return m


def mxu_col_variant() -> str:
    """Column-pass arithmetic: 'bf16split' (the proven 64a+b split — the
    production default) or 'f32' (direct f32 einsum, kept for the A/B
    lane) — env MCIM_MXU_COL."""
    v = env_registry.get("MCIM_MXU_COL") or "bf16split"
    if v not in MXU_COL_VARIANTS:
        raise ValueError(f"MCIM_MXU_COL={v!r}; known: {MXU_COL_VARIANTS}")
    return v


def prefer_mxu() -> bool:
    """A/B promotion switch (mirrors prefer_swar): MCIM_PREFER_MXU=1
    routes eligible stencil groups through the MXU path on every auto
    path without a calibration entry. Honored only on real TPU backends —
    auto must never route to the MXU on platforms that lack one."""
    return env_registry.get_bool("MCIM_PREFER_MXU")


# --------------------------------------------------------------------------
# Eligibility
# --------------------------------------------------------------------------


def _bf16_exact(a: np.ndarray) -> bool:
    """Whether every value round-trips bf16 exactly (host-pure)."""
    try:
        import ml_dtypes

        af = np.asarray(a, np.float64)
        return bool(np.array_equal(af.astype(ml_dtypes.bfloat16).astype(np.float64), af))
    except ImportError:  # pragma: no cover - ml_dtypes ships with jax
        # integers are bf16-exact iff their odd part fits 8 significand bits
        af = np.abs(np.asarray(a, np.int64)).reshape(-1)
        for v in af:
            v = int(v)
            while v and v % 2 == 0:
                v //= 2
            if v >= 256:
                return False
        return True


def _int_kernels_ok(op: StencilOp) -> bool:
    for k in op.kernels:
        ka = np.asarray(k, np.float64)
        if not np.array_equal(ka, np.round(ka)):
            return False
        if not _bf16_exact(ka):
            return False
        if 255.0 * float(np.abs(ka).sum()) >= _F32_EXACT:
            return False
    return True


def _sep_taps(op: StencilOp) -> tuple[float, ...] | None:
    """The op's separable taps when the 64a+b banded path applies: integer,
    non-negative, bf16-exact, length 2*halo + 1, sum S in [1, 64] (so the
    split halves a = floor(s/64) <= 255*S/64 <= 255 stay bf16-exact).
    Every registry separable (binomial Gaussians, odd boxes) qualifies;
    anything else falls to the one-einsum 2-D path."""
    t = op.separable
    if t is None:
        return None
    ta = np.asarray(t, np.float64).reshape(-1)
    if not np.array_equal(ta, np.round(ta)) or np.any(ta < 0):
        return None
    if len(ta) - 1 != 2 * op.halo:
        return None
    s = float(ta.sum())
    if s < 1 or s > _SPLIT:
        return None
    if not _bf16_exact(ta):
        return None
    return tuple(float(v) for v in ta)


def _morph_ok(op: StencilOp) -> bool:
    """Whether the threshold-decomposition morphology identity (module
    docstring) applies: min/max reduce over a square all-ones structuring
    element — exactly what make_morph builds."""
    if op.reduce not in ("min", "max"):
        return False
    if op.combine != "single":
        return False
    if 2 * op.halo >= B:
        return False
    k = 2 * op.halo + 1
    return all(
        tuple(kk.shape) == (k, k) and np.array_equal(np.asarray(kk), np.ones((k, k)))
        for kk in op.kernels
    )


def mxu_eligible(op: Op) -> bool:
    """True iff `op` has a proven MXU banded-matmul identity (module
    docstring). This is the registry/spec-level gate every router
    (pipeline_mxu, auto, sharded, serving) consults — `auto` can never
    select the MXU for an op family outside it."""
    if not isinstance(op, StencilOp):
        return False
    if op.reduce in ("min", "max"):
        # erode/dilate via threshold decomposition (round 8); median has
        # no linear identity and stays VPU-only
        return _morph_ok(op)
    if op.reduce != "corr":
        return False
    if op.combine not in ("single", "magnitude"):
        return False
    if 2 * op.halo >= B:
        return False
    # the band geometry assumes square (2h+1)-kernels — true for every
    # registry op; reject anything else instead of miscomputing
    k = 2 * op.halo + 1
    if any(tuple(kk.shape) != (k, k) for kk in op.kernels):
        return False
    return _int_kernels_ok(op)


def mxu_family(op: Op) -> str | None:
    """Calibration key for the op's MXU formulation class: 'sepK' (banded
    separable, K taps), 'gradKxK' (magnitude combine), 'corrKxK' (one-shot
    2-D einsum), 'morphKxK' (threshold-decomposition erode/dilate). None
    for ineligible ops."""
    if not mxu_eligible(op):
        return None
    k = int(op.kernels[0].shape[0])
    if op.reduce in ("min", "max"):
        return f"morph{k}x{k}"
    if op.combine == "magnitude":
        return f"grad{k}x{k}"
    if _sep_taps(op) is not None:
        return f"sep{k}"
    return f"corr{k}x{k}"


def mxu_int8_ok(op: Op) -> bool:
    """Whether the int8-accumulation in-stage variant is PROVEN exact for
    `op`: MXU-eligible corr reduce with every kernel weight an integer in
    [-127, 127] (the int8 operand bound; symmetric so the banded matrix
    negates safely). The accumulator bound is already implied by
    eligibility — ``_int_kernels_ok`` requires 255 * sum|w| < 2^24, so
    the shifted contraction sum(w * (x - 128)), its +128*sum(w)
    correction, and their f32 recombination are all exact integers below
    2^24 (module docstring). Ops outside the operand bound downgrade to
    the f32-accumulation 'mxu' arm, never to wrong pixels."""
    if not isinstance(op, StencilOp) or op.reduce != "corr":
        return False
    if not mxu_eligible(op):
        return False
    for k in op.kernels:
        if float(np.abs(np.asarray(k, np.float64)).max()) > 127:
            return False
    return True


# --------------------------------------------------------------------------
# Banded tap matrices (host-built, cached per weights)
# --------------------------------------------------------------------------

_band_cache: dict = {}


def _band_np(taps: tuple, h: int) -> np.ndarray:
    """(B + 2h, B) banded matrix with C[n + i, n] = taps[i]."""
    key = ("1d", taps, h)
    got = _band_cache.get(key)
    if got is None:
        C = np.zeros((B + 2 * h, B), np.float32)
        for n in range(B):
            for i, t in enumerate(taps):
                C[n + i, n] = t
        got = _band_cache[key] = C
    return got


def _band2_np(w2d: np.ndarray, h: int) -> np.ndarray:
    """(kh, B + 2h, B) per-row-offset banded matrices for the one-einsum
    2-D path: C2[d, n + i, n] = w2d[d, i]."""
    wa = np.asarray(w2d, np.float32)
    key = ("2d", wa.tobytes(), wa.shape, h)
    got = _band_cache.get(key)
    if got is None:
        kh, kw = wa.shape
        C2 = np.zeros((kh, B + 2 * h, B), np.float32)
        for d in range(kh):
            for n in range(B):
                for i in range(kw):
                    C2[d, n + i, n] = wa[d, i]
        got = _band_cache[key] = C2
    return got


def _band_blocks(xp: jnp.ndarray, axis: int, h: int) -> jnp.ndarray:
    """Static sliding blocks of width B + 2h along `axis` with stride B,
    stacked on a new leading axis; `xp` must carry the 2h halo at both
    ends of `axis` and a block-multiple core."""
    n = (xp.shape[axis] - 2 * h) // B
    slices = []
    for j in range(n):
        idx = [slice(None)] * xp.ndim
        idx[axis] = slice(j * B, j * B + B + 2 * h)
        slices.append(xp[tuple(idx)])
    return jnp.stack(slices, axis=0)


# --------------------------------------------------------------------------
# Exact banded passes
# --------------------------------------------------------------------------


def _row_pass_banded(rows: jnp.ndarray, taps: tuple, h: int) -> jnp.ndarray:
    """(R, Wc + 2h) exact u8-integer f32 -> (R, Wc) f32 row sums (Wc a
    block multiple). bf16 inputs are exact (values <= 255); the f32
    accumulation is exact (integer partial sums < 2^24)."""
    C = jnp.asarray(_band_np(taps, h), jnp.bfloat16)
    ext = _band_blocks(rows.astype(jnp.bfloat16), 1, h)  # (nb, R, B+2h)
    out = jnp.einsum("jrk,kn->rjn", ext, C, preferred_element_type=F32)
    return out.reshape(out.shape[0], -1)


def _col_pass_banded(
    tmp: jnp.ndarray, taps: tuple, h: int, variant: str
) -> jnp.ndarray:
    """(Rc + 2h, W) f32 exact-integer row sums -> (Rc, W) column sums
    (Rc a block multiple). 'bf16split': tmp = 64a + b, both halves
    bf16-exact, recombined in f32 — integer-exact by linearity. 'f32':
    direct f32 einsum (exact; lower MXU rate, kept for the A/B lane)."""
    if variant == "f32":
        C = jnp.asarray(_band_np(taps, h), F32)
        ext = _band_blocks(tmp, 0, h)  # (nb, B+2h, W)
        out = jnp.einsum("jkw,km->jmw", ext, C, preferred_element_type=F32)
        return out.reshape(-1, out.shape[-1])
    C = jnp.asarray(_band_np(taps, h), jnp.bfloat16)
    a = jnp.floor(tmp * np.float32(1.0 / _SPLIT))
    b = tmp - a * np.float32(_SPLIT)
    ea = _band_blocks(a.astype(jnp.bfloat16), 0, h)
    eb = _band_blocks(b.astype(jnp.bfloat16), 0, h)
    oa = jnp.einsum("jkw,km->jmw", ea, C, preferred_element_type=F32)
    ob = jnp.einsum("jkw,km->jmw", eb, C, preferred_element_type=F32)
    out = oa * np.float32(_SPLIT) + ob
    return out.reshape(-1, out.shape[-1])


def _sep_valid_mxu(
    xpad: jnp.ndarray, taps: tuple, h: int, *, mode: str, col_variant: str
) -> jnp.ndarray:
    """Separable valid-mode correlation via banded matmuls — bit-identical
    to spec.separable_valid (both compute the same exact integers)."""
    hh = xpad.shape[0] - 2 * h
    ww = xpad.shape[1] - 2 * h
    xf = exact_f32(xpad)
    if mode == "hybrid":
        # row pass on the VPU: the golden exact integer row correlation;
        # output width is already ww, so no width block-padding at all
        tmp = corr_valid(xf, np.asarray(taps, np.float32).reshape(1, -1))
    else:
        wpad = (-ww) % B
        core = xf if wpad == 0 else jnp.pad(xf, ((0, 0), (0, wpad)))
        tmp = _row_pass_banded(core, taps, h)  # (hh + 2h, ww + wpad)
    hpad = (-hh) % B
    if hpad:
        tmp = jnp.pad(tmp, ((0, hpad), (0, 0)))
    out = _col_pass_banded(tmp, taps, h, col_variant)
    return out[:hh, :ww]


def _corr2d_valid_mxu(xpad: jnp.ndarray, w2d: np.ndarray, h: int) -> jnp.ndarray:
    """Valid 2-D integer correlation as ONE banded einsum: kh row-shifted
    views of the width-blocked tile joint-contract over (row offset,
    band position). Raw u8 values are bf16-exact, so no split is needed;
    the f32 accumulation of integer products is exact (module docstring)."""
    kh, kw = w2d.shape
    hh = xpad.shape[0] - (kh - 1)
    ww = xpad.shape[1] - (kw - 1)
    xf = exact_f32(xpad)
    wpad = (-ww) % B
    if wpad:
        xf = jnp.pad(xf, ((0, 0), (0, wpad)))
    xb = xf.astype(jnp.bfloat16)
    views = jnp.stack([xb[d : d + hh] for d in range(kh)], axis=0)
    ext = _band_blocks(views, 2, h)  # (nb, kh, hh, B + 2h)
    C2 = jnp.asarray(_band2_np(w2d, h), jnp.bfloat16)
    out = jnp.einsum("jdhk,dkn->hjn", ext, C2, preferred_element_type=F32)
    return out.reshape(hh, -1)[:, :ww]


def _morph_digits(M: int) -> int:
    """Digits per packed plane: the largest m with M^m - 1 < 2^24, so the
    packed ones-windowsum (whose base-M digits are window counts <= K^2 =
    M - 1, hence never carry) stays an exact f32 integer."""
    m = 1
    while M ** (m + 1) - 1 < _F32_EXACT:
        m += 1
    return m


def _ones_windowsum_f32(xp: jnp.ndarray, K: int, h: int) -> jnp.ndarray:
    """(R + 2h, C + 2h) exact-integer f32 plane -> (R, C) K x K window
    sums via two all-ones banded f32 einsums. Packed digit planes exceed
    256, so the bf16 row pass is NOT exact here — both passes stay f32
    (every partial sum is an integer bounded by the packed windowsum
    bound M^m - 1 < 2^24, so f32 accumulation is exact)."""
    hh = xp.shape[0] - 2 * h
    ww = xp.shape[1] - 2 * h
    taps = (1.0,) * K
    wpad = (-ww) % B
    core = xp if wpad == 0 else jnp.pad(xp, ((0, 0), (0, wpad)))
    C = jnp.asarray(_band_np(taps, h), F32)
    ext = _band_blocks(core, 1, h)  # (nb, R + 2h, B + 2h)
    tmp = jnp.einsum("jrk,kn->rjn", ext, C, preferred_element_type=F32)
    tmp = tmp.reshape(tmp.shape[0], -1)
    hpad = (-hh) % B
    if hpad:
        tmp = jnp.pad(tmp, ((0, hpad), (0, 0)))
    out = _col_pass_banded(tmp, taps, h, "f32")
    return out[:hh, :ww]


def _morph_valid_mxu(op: StencilOp, xpad: jnp.ndarray) -> jnp.ndarray:
    """Valid-mode erode/dilate via threshold decomposition on the MXU
    (module docstring): for each threshold t, the 0/1 indicator [x > t]
    windowsums on the matrix unit; dilate counts windows with >= 1 hit,
    erode counts all-K^2 windows; the rank result is the count over
    t = 0..254. m indicator planes pack base M = K^2 + 1 per round (a
    window holds at most K^2 ones, so digits never carry) and extract in
    int32 — every f32 intermediate is an exact integer < 2^24."""
    K = 2 * op.halo + 1
    h = op.halo
    hh = xpad.shape[0] - 2 * h
    ww = xpad.shape[1] - 2 * h
    xf = exact_f32(xpad)
    M = K * K + 1
    m = _morph_digits(M)
    full = K * K
    acc = jnp.zeros((hh, ww), F32)
    for t0 in range(0, 255, m):
        ts = range(t0, min(t0 + m, 255))
        packed = jnp.zeros_like(xf)
        for i, t in enumerate(ts):
            bit = (xf > np.float32(t)).astype(F32)
            packed = packed + bit * np.float32(M**i)
        si = _ones_windowsum_f32(packed, K, h).astype(jnp.int32)
        for i, _t in enumerate(ts):
            d = (si // (M**i)) % M
            hit = (d >= 1) if op.reduce == "max" else (d == full)
            acc = acc + hit.astype(F32)
    return acc


def mxu_valid(
    op: StencilOp,
    xpad: jnp.ndarray,
    *,
    mode: str | None = None,
    col_variant: str | None = None,
) -> jnp.ndarray:
    """Drop-in for StencilOp.valid on an eligible op: float32
    (H + 2h, W + 2h) -> float32 (H, W) accumulation, bit-identical to the
    golden path (exact integer sums + replayed combine/scale). This is
    the single primitive every MXU route shares — the full-image
    pipeline, the sharded materialised-ext path, and the serving
    bucket-padded executor all call it on their own pre-extended tiles,
    so the edge-extension machinery is never duplicated."""
    if not mxu_eligible(op):
        raise ValueError(f"op {op.name!r} has no MXU formulation")
    if op.reduce in ("min", "max"):
        # morphology: threshold decomposition (no combine/scale replay —
        # make_morph builds single-combine, scale-1 ops by construction)
        return _morph_valid_mxu(op, xpad)
    mode = mode or mxu_mode()
    col_variant = col_variant or mxu_col_variant()
    h = op.halo
    taps = _sep_taps(op)
    if taps is not None and op.combine == "single":
        accs = [
            _sep_valid_mxu(xpad, taps, h, mode=mode, col_variant=col_variant)
        ]
    else:
        accs = [
            _corr2d_valid_mxu(xpad, np.asarray(k, np.float32), h)
            for k in op.kernels
        ]
    if op.combine == "single":
        acc = accs[0]
    elif op.combine == "magnitude":
        # replay the golden combine on the exact integer accumulations
        acc = jnp.sqrt(accs[0] * accs[0] + accs[1] * accs[1])
    else:  # pragma: no cover - mxu_eligible rejects other combines
        raise ValueError(f"unknown combine {op.combine!r}")
    if op.scale != 1.0:
        acc = acc * np.float32(op.scale)
    return acc


# --------------------------------------------------------------------------
# Op / pipeline entry points
# --------------------------------------------------------------------------


def mxu_stencil(
    op: StencilOp,
    img: jnp.ndarray,
    *,
    mode: str | None = None,
    col_variant: str | None = None,
) -> jnp.ndarray:
    """One eligible stencil over a u8 image (per channel plane), bit-exact
    against ``op(img)``: golden pad2d edge extension, banded-matmul
    accumulation, golden finalize (quantize + interior mask)."""

    def plane(x: jnp.ndarray) -> jnp.ndarray:
        hh, ww = x.shape
        h = op.halo
        xpad = pad2d(exact_f32(x), op.edge_mode, h, h, h, h)
        acc = mxu_valid(op, xpad, mode=mode, col_variant=col_variant)
        return op.finalize(acc, x, 0, 0, hh, ww)

    if img.ndim == 3:
        return jnp.stack(
            [plane(img[..., c]) for c in range(img.shape[2])], axis=-1
        )
    return plane(img)


def pipeline_mxu(
    ops,
    img: jnp.ndarray,
    *,
    mode: str | None = None,
    interpret: bool | None = None,
    block_h: int | None = None,
):
    """Run a full pipeline with eligible stencils on the MXU banded path
    and everything else on its golden op (per-op fallback — always
    correct, the same contract as pipeline_swar). The whole chain is one
    XLA program under jit, so pointwise prefixes run on the VPU and fuse
    into the same launch as the MXU contraction — the hybrid
    pointwise/stencil split happens by construction.

    `interpret`/`block_h` are accepted for backend-API parity and
    ignored: the MXU path is pure XLA (no Pallas kernel to interpret, no
    row-block knob)."""
    del interpret, block_h
    mode = mode or mxu_mode()
    state = img
    for op in ops:
        if isinstance(op, StencilOp) and mxu_eligible(op):
            state = mxu_stencil(op, state, mode=mode)
        else:
            state = op(state)
    return state


# --------------------------------------------------------------------------
# Auto routing
# --------------------------------------------------------------------------


def use_mxu_for_stencil(op: Op, width: int | None = None) -> str | None:
    """Auto-routing decision for one stencil group: the MXU mode to run
    ('banded'/'hybrid') or None to stay on the VPU/XLA paths.

    Routes only when ALL of: the op family has a proven identity
    (mxu_eligible), the live backend is a real TPU (no-MXU platforms
    always fall through, bit-exactly), and either MCIM_PREFER_MXU=1 (the
    A/B switch) or the calibration store records a measured win for
    (op family, device kind, width window) — `mcim-tpu autotune
    --dimension backend`. Shared by pipeline_auto, the sharded runner and
    the serving executor so the auto paths cannot drift."""
    if not isinstance(op, StencilOp) or not mxu_eligible(op):
        return None
    if not is_tpu_backend():
        return None
    if prefer_mxu():
        return mxu_mode()
    choice = calibration.lookup_backend_choice(mxu_family(op), width=width)
    if choice == "mxu":
        return "banded"
    if choice == "hybrid":
        return "hybrid"
    return None


# --------------------------------------------------------------------------
# In-stage contraction (inside the fused-pallas megakernel)
# --------------------------------------------------------------------------

STAGE_ARMS = ("vpu", "mxu", "mxu-int8")
MXU_STAGE_SETTINGS = ("auto", "off", "on", "f32", "int8")

# Closed vocabulary for the silent-ineligibility counter: why an op with
# an MXU identity (mxu_family is not None) landed on the VPU inside a
# fused-pallas stage. Advances once per stage (re)trace, like
# mcim_plan_pallas_stages_total — a steady-state serving process shows
# the arms its executables were BUILT with.
#
#   off            MCIM_MXU_STAGE=off — the operator disabled the arm
#   family         the identity is whole-op only (morphology: threshold
#                  decomposition needs its own pass structure, which the
#                  in-stage valid-mode contraction point cannot host)
#   not-tpu        auto setting off-TPU — interpret-mode dots win nothing
#   no-calibration auto setting with no measured stage_arm record for
#                  (family, device kind, width window)
STAGE_FALLBACK_REASONS = ("off", "family", "not-tpu", "no-calibration")


def count_stage_fallback(counter, reason: str) -> None:
    """The single choke point for mxu-in-stage fallback accounting
    (mirrors graph/systolic.count_fallback): every VPU landing of an
    MXU-capable op inside a fused stage passes through here, so the
    reason vocabulary above is enforced at runtime and the analysis
    suite can statically prove no call site invents reasons
    (analysis/rules_obs.py obs-mxu-stage-fallback-*)."""
    if reason not in STAGE_FALLBACK_REASONS:
        raise ValueError(
            f"unknown mxu-in-stage fallback reason {reason!r}; "
            f"known: {STAGE_FALLBACK_REASONS}"
        )
    counter.inc(reason=reason)


def mxu_stage_setting() -> str:
    """The MCIM_MXU_STAGE knob: 'auto' (default — a real MXU plus a
    measured stage_arm calibration win), 'off', 'on' (force the MXU arm
    on every eligible op, int8 where proven — works off-TPU too, the
    interpret-mode test/bench switch), 'f32' (force the plain bf16/f32
    arm, never int8 — the A/B control), 'int8' (force int8 where proven,
    f32 otherwise)."""
    v = env_registry.get("MCIM_MXU_STAGE") or "auto"
    if v not in MXU_STAGE_SETTINGS:
        raise ValueError(
            f"MCIM_MXU_STAGE={v!r}; known: {MXU_STAGE_SETTINGS}"
        )
    return v


def _stage_metrics():
    # plan.metrics imports nothing from ops/, but keep the edge lazy so
    # the ops layer stays importable without the plan package
    from mpi_cuda_imagemanipulation_tpu.plan.metrics import plan_metrics

    return plan_metrics


def stage_arm_for(
    op: Op, width: int | None = None, setting: str | None = None
) -> str:
    """The in-stage execution arm for one op inside a fused-pallas stage:
    'vpu', 'mxu' or 'mxu-int8' (STAGE_ARMS). Resolved HOST-SIDE at stage
    build/trace time — the kernel body branches statically, so the
    lowered Mosaic program contains either the dot contraction or the
    shift-multiply walk, never both.

    `setting` overrides MCIM_MXU_STAGE (the plan mode 'fused-pallas-mxu'
    forces 'on'). Every op with an MXU identity that lands on 'vpu' is
    counted through count_stage_fallback; ops with no identity at all
    (pointwise, median, float kernels) are not a lost signal and stay
    uncounted. A calibrated 'vpu' record is a measured decision, also
    uncounted. int8 is auto-selected only where mxu_int8_ok PROVES the
    operand bound; otherwise the choice downgrades to 'mxu'."""
    if not isinstance(op, StencilOp):
        return "vpu"
    fam = mxu_family(op)
    if fam is None:
        return "vpu"
    setting = setting or mxu_stage_setting()
    metrics = _stage_metrics()
    if setting == "off":
        count_stage_fallback(metrics.mxu_stage_fallbacks, "off")
        return "vpu"
    if op.reduce != "corr":
        # whole-op identity only (morphology) — the in-stage valid-mode
        # contraction point cannot host the threshold-decomposition pass
        count_stage_fallback(metrics.mxu_stage_fallbacks, "family")
        return "vpu"
    if setting in ("on", "int8"):
        arm = "mxu-int8" if mxu_int8_ok(op) else "mxu"
    elif setting == "f32":
        arm = "mxu"
    else:  # auto
        if not is_tpu_backend():
            count_stage_fallback(metrics.mxu_stage_fallbacks, "not-tpu")
            return "vpu"
        choice = calibration.lookup_stage_arm(fam, width=width)
        if choice is None:
            count_stage_fallback(
                metrics.mxu_stage_fallbacks, "no-calibration"
            )
            return "vpu"
        if choice == "vpu":  # a measured VPU win — chosen, not fallen back
            return "vpu"
        arm = choice if choice != "mxu-int8" or mxu_int8_ok(op) else "mxu"
    metrics.mxu_stage_ops.inc(arm=arm)
    return arm


def _stage_blocked(xe: jnp.ndarray, h: int) -> tuple[jnp.ndarray, int, int]:
    """Zero-pad the width-extended carry (rows, W + 2h) to a whole number
    of B-blocks plus halo; returns (padded, W, out_rows). Pad columns
    only reach output columns >= W (sliced away): output column j reads
    input columns j..j+2h <= W - 1 + 2h, all real."""
    rows, we = xe.shape
    W = we - 2 * h
    nbw = -(-W // B)
    need = nbw * B + 2 * h
    if need > we:
        xe = jnp.concatenate(
            [xe, jnp.zeros((rows, need - we), xe.dtype)], axis=1
        )
    return xe, W, rows - 2 * h


def _band2_traced(w2d: np.ndarray, h: int, dtype) -> jnp.ndarray:
    """Traced ``(kh * (B + 2h), B)`` stacked banded matrices — the
    reshaped `_band2_np` layout, but built INSIDE the traced kernel from
    scalar weights and iota masks: a pallas kernel body may not close
    over materialised array constants, so the band matrix is
    reconstructed from scalars at trace time (Mosaic constant-folds the
    masks). Weights stay exactly representable in `dtype` — bf16 holds
    the eligibility-gated integer taps exactly, int8 holds |w| <= 127."""
    wa = np.asarray(w2d, np.float32)
    kh, kw = wa.shape
    r = lax.broadcasted_iota(jnp.int32, (B + 2 * h, B), 0)
    c = lax.broadcasted_iota(jnp.int32, (B + 2 * h, B), 1)
    slabs = []
    for d in range(kh):
        slab = jnp.zeros((B + 2 * h, B), F32)
        for i in range(kw):
            slab = jnp.where(r == c + i, np.float32(wa[d, i]), slab)
        slabs.append(slab)
    out = slabs[0] if kh == 1 else jnp.concatenate(slabs, axis=0)
    return out.astype(dtype)


def _stage_corr2d(xe: jnp.ndarray, w2d: np.ndarray, h: int) -> jnp.ndarray:
    """In-kernel valid 2-D correlation: (rows, W + 2h) exact u8-integer
    f32 carry -> (rows - 2h, W) f32 accumulation, as ONE
    ``lax.dot_general`` per 128-wide block — kh row-shifted views
    concatenate on the contracting axis against the stacked banded
    matrices, so K = kh * (B + 2h) and N = B = 128: real MXU shapes
    inside the Mosaic kernel. bf16 operands are exact (u8 values and
    eligibility-gated integer taps), f32 accumulation of integer partial
    sums bounded by 255 * sum|w| < 2^24 is exact — bit-identical to the
    golden op.valid on the same carry."""
    kh, _kw = w2d.shape
    xe, W, out_rows = _stage_blocked(xe, h)
    xb = xe.astype(jnp.bfloat16)
    C = _band2_traced(w2d, h, jnp.bfloat16)
    nbw = (xe.shape[1] - 2 * h) // B
    cols = []
    for n in range(nbw):
        blk = xb[:, n * B : n * B + B + 2 * h]
        a = jnp.concatenate(
            [blk[d : d + out_rows] for d in range(kh)], axis=1
        )
        cols.append(
            lax.dot_general(
                a, C, (((1,), (0,)), ((), ())), preferred_element_type=F32
            )
        )
    out = cols[0] if nbw == 1 else jnp.concatenate(cols, axis=1)
    return out[:, :W]


def _stage_corr2d_int8(
    xe: jnp.ndarray, w2d: np.ndarray, h: int
) -> jnp.ndarray:
    """The int8-accumulation variant: operands shift by -128 into
    [-128, 127] (exact int8), taps are eligibility-proven integers in
    [-127, 127], the dot accumulates in int32 (|sum| <= 128 * sum|w| <
    2^23 — no overflow), and the constant +128 * sum(w) correction
    re-adds in f32: sum(w * (x - 128)) + 128 * sum(w) = sum(w * x), every
    term an exact integer below 2^24, so the f32 result is bit-identical
    to the f32 arm (mxu_int8_ok is the proof obligation)."""
    kh, _kw = w2d.shape
    xe, W, out_rows = _stage_blocked(xe, h)
    xs = (xe - np.float32(128.0)).astype(jnp.int32).astype(jnp.int8)
    C = _band2_traced(w2d, h, jnp.int8)
    corr = np.float32(128.0 * float(np.asarray(w2d, np.float64).sum()))
    nbw = (xe.shape[1] - 2 * h) // B
    cols = []
    for n in range(nbw):
        blk = xs[:, n * B : n * B + B + 2 * h]
        a = jnp.concatenate(
            [blk[d : d + out_rows] for d in range(kh)], axis=1
        )
        s = lax.dot_general(
            a, C, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        cols.append(s.astype(F32) + corr)
    out = cols[0] if nbw == 1 else jnp.concatenate(cols, axis=1)
    return out[:, :W]


def stage_valid_mxu(
    op: StencilOp, xe: jnp.ndarray, *, arm: str
) -> jnp.ndarray:
    """Drop-in for ``op.valid`` at the megakernel's per-op contraction
    point (ops/pallas_kernels._stage_kernel): the width-extended carry
    (rows, W + 2h) -> (rows - 2h, W) accumulation on the chosen MXU arm.
    Separable ops contract their 2-D outer-product kernel — the one-shot
    form computes the same exact integers as the two-pass walk, so it is
    uniformly bit-exact; magnitude combine and post-scale replay the
    golden float ops on the exact accumulations (whole-op mxu_valid's
    argument)."""
    if arm not in ("mxu", "mxu-int8"):
        raise ValueError(f"not an MXU stage arm: {arm!r}")
    h = op.halo
    fn = _stage_corr2d_int8 if arm == "mxu-int8" else _stage_corr2d
    accs = [fn(xe, np.asarray(k, np.float32), h) for k in op.kernels]
    if op.combine == "single":
        acc = accs[0]
    elif op.combine == "magnitude":
        acc = jnp.sqrt(accs[0] * accs[0] + accs[1] * accs[1])
    else:  # pragma: no cover - mxu_eligible rejects other combines
        raise ValueError(f"unknown combine {op.combine!r}")
    if op.scale != 1.0:
        acc = acc * np.float32(op.scale)
    return acc
