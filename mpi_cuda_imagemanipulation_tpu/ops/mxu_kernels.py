"""Production MXU banded-matmul stencil backend (``impl='mxu'``).

Promotion of the tools/mxu_proto.py design into the framework (round 6).
Why this exists (the round-5 roofline result, BASELINE.md): the u8 copy
probe measured 552-658 GB/s, falsifying the element-rate-ceiling theory —
the headline 5x5 Gaussian (45.4k MP/s/chip) is VPU-COMPUTE-bound at ~11%
of the HBM roofline with the MXU (~197 TFLOP/s bf16 on v5e) idle. This
backend reformulates the correlation-class stencils as blocked banded
matmuls so the taps contract on the MXU instead of the VPU, mirroring the
systolic/tensor-core retargeting literature (PAPERS.md: "A Versatile
Software Systolic Execution Model for GPU Memory-Bound Kernels",
"SparStencil").

Formulation (separable row pass; the column pass is the mirror):

    out[h, B*j + n] = sum_k in_pad[h, B*j + n + k] * t[k],   k in [0, 2h]

With block width B=128, gather In_ext[j] = in_pad[:, B*j : B*j + B + 2h]
(static slices) and build the banded tap matrix C[i, n] = t[i - n] on the
valid band (shape (B + 2h, B)); then out_block_j = In_ext[j] @ C — an
einsum with M=H, K=B+2h, N=B=128: real MXU shapes. FLOPs are
(B+2h)/(2h+1) ~ 26x the arithmetic minimum for a 5-tap kernel, but the
MXU has ~430x the VPU's sustained MAC rate.

Exactness (the non-negotiable — every backend must be bit-exact against
the golden ops/spec.py path):

  * u8 pixel values (<= 255) and small integer taps are exactly
    representable in bf16 (8-bit significand: all integers <= 256, and any
    integer whose odd part is < 256 — checked per kernel at eligibility
    time via an ml_dtypes round-trip).
  * jnp.einsum with preferred_element_type=f32 accumulates exactly: every
    partial product and every partial sum is an integer bounded by
    255 * sum|w| < 2^24, so f32 addition is exact regardless of order.
  * The SEPARABLE column pass consumes the row-pass sums (<= 255*S, up to
    14 bits — NOT bf16-exact beyond 256), so it runs as the proven 64a+b
    split: tmp = 64*a + b with a = floor(tmp/64) and b = tmp - 64a; for
    tap sum S <= 64 both halves are <= 255 (bf16-exact) and
    colsum(tmp) = 64*colsum(a) + colsum(b) — integer-exact by linearity.
    (An f32-einsum column variant is kept for the A/B lane: exact
    directly, lower MXU rate.)
  * NON-SEPARABLE integer kernels (emboss/emboss101, sharpen, laplacians,
    unsharp, custom integer `filter`) contract in ONE einsum: kh
    row-shifted views of the width-blocked tile joint-contract over
    (row offset, band position) against C2[dy, i, n] = w[dy, i - n].
    Inputs are raw u8 values (bf16-exact), so no split is needed.
  * combine='magnitude' (sobel/prewitt/scharr) and any post `scale`
    REPLAY the golden float ops on the exact integer accumulations
    (jnp.sqrt(a0*a0 + a1*a1), acc * np.float32(scale)) — identical inputs
    + identical op sequence = identical f32 results, the same argument
    the SWAR wide mode rests on (ops/swar_kernels.py).
  * Quantization and interior-guard masking reuse StencilOp.finalize on
    the exact accumulations, so the final u8 is golden by construction.

The ``hybrid`` sub-mode splits the work across units inside ONE fused XLA
launch: the cheap u8 row pass runs on the VPU (the golden corr_valid's
exact shift-multiply-accumulate — O(k) adds over integers) and only the
column pass contracts on the MXU (halving the banded FLOPs); pointwise
prefixes always run on the VPU and fuse into the same program under jit.
Both modes are bit-exact; the mxu_ab bench lane measures vpu vs mxu vs
hybrid per silicon window.

Eligibility (``mxu_eligible``): ``reduce='corr'`` StencilOps whose
kernels are bf16-exact integers with 255 * sum|w| < 2^24, combine
'single' or 'magnitude', any edge mode / quantizer (the backend operates
on the caller's pre-extended tile and replays the golden finalize). The
separable banded path additionally needs non-negative integer taps with
sum S <= 64 (the 64a+b bound — all registry separables qualify);
separable ops outside that bound fall to the one-einsum 2-D path. Rank /
morphology ops (median, erode, dilate) have no linear identity and fall
back per op to the VPU paths — ``impl='mxu'`` is always-correct, the
same contract as ``impl='swar'``.

``backend='auto'`` routes a stencil group here only when (a) the op
family is eligible, (b) the live backend is a real TPU (platforms
without an MXU always take the VPU/XLA paths, bit-exactly), and (c) the
calibration store records a measured per-device-kind win for the family
(``mcim-tpu autotune --dimension backend``; utils/calibration.py) — or
the MCIM_PREFER_MXU=1 A/B switch is set (TPU-only, like
MCIM_PREFER_SWAR).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    F32,
    Op,
    StencilOp,
    corr_valid,
    exact_f32,
    pad2d,
)
from mpi_cuda_imagemanipulation_tpu.utils import calibration
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend

B = 128  # one MXU / lane tile: the banded-matmul block width
_SPLIT = 64.0  # the 64a+b column-split radix (both halves <= 255: bf16-exact)
_F32_EXACT = 1 << 24  # integers below this are exact in f32

MXU_MODES = ("banded", "hybrid")
MXU_COL_VARIANTS = ("bf16split", "f32")


def mxu_mode() -> str:
    """Execution mode: 'banded' (both separable passes on the MXU) or
    'hybrid' (row pass on the VPU, column pass on the MXU) — env
    MCIM_MXU_MODE, default banded."""
    m = env_registry.get("MCIM_MXU_MODE") or "banded"
    if m not in MXU_MODES:
        raise ValueError(f"MCIM_MXU_MODE={m!r}; known: {MXU_MODES}")
    return m


def mxu_col_variant() -> str:
    """Column-pass arithmetic: 'bf16split' (the proven 64a+b split — the
    production default) or 'f32' (direct f32 einsum, kept for the A/B
    lane) — env MCIM_MXU_COL."""
    v = env_registry.get("MCIM_MXU_COL") or "bf16split"
    if v not in MXU_COL_VARIANTS:
        raise ValueError(f"MCIM_MXU_COL={v!r}; known: {MXU_COL_VARIANTS}")
    return v


def prefer_mxu() -> bool:
    """A/B promotion switch (mirrors prefer_swar): MCIM_PREFER_MXU=1
    routes eligible stencil groups through the MXU path on every auto
    path without a calibration entry. Honored only on real TPU backends —
    auto must never route to the MXU on platforms that lack one."""
    return env_registry.get_bool("MCIM_PREFER_MXU")


# --------------------------------------------------------------------------
# Eligibility
# --------------------------------------------------------------------------


def _bf16_exact(a: np.ndarray) -> bool:
    """Whether every value round-trips bf16 exactly (host-pure)."""
    try:
        import ml_dtypes

        af = np.asarray(a, np.float64)
        return bool(np.array_equal(af.astype(ml_dtypes.bfloat16).astype(np.float64), af))
    except ImportError:  # pragma: no cover - ml_dtypes ships with jax
        # integers are bf16-exact iff their odd part fits 8 significand bits
        af = np.abs(np.asarray(a, np.int64)).reshape(-1)
        for v in af:
            v = int(v)
            while v and v % 2 == 0:
                v //= 2
            if v >= 256:
                return False
        return True


def _int_kernels_ok(op: StencilOp) -> bool:
    for k in op.kernels:
        ka = np.asarray(k, np.float64)
        if not np.array_equal(ka, np.round(ka)):
            return False
        if not _bf16_exact(ka):
            return False
        if 255.0 * float(np.abs(ka).sum()) >= _F32_EXACT:
            return False
    return True


def _sep_taps(op: StencilOp) -> tuple[float, ...] | None:
    """The op's separable taps when the 64a+b banded path applies: integer,
    non-negative, bf16-exact, length 2*halo + 1, sum S in [1, 64] (so the
    split halves a = floor(s/64) <= 255*S/64 <= 255 stay bf16-exact).
    Every registry separable (binomial Gaussians, odd boxes) qualifies;
    anything else falls to the one-einsum 2-D path."""
    t = op.separable
    if t is None:
        return None
    ta = np.asarray(t, np.float64).reshape(-1)
    if not np.array_equal(ta, np.round(ta)) or np.any(ta < 0):
        return None
    if len(ta) - 1 != 2 * op.halo:
        return None
    s = float(ta.sum())
    if s < 1 or s > _SPLIT:
        return None
    if not _bf16_exact(ta):
        return None
    return tuple(float(v) for v in ta)


def mxu_eligible(op: Op) -> bool:
    """True iff `op` has a proven MXU banded-matmul identity (module
    docstring). This is the registry/spec-level gate every router
    (pipeline_mxu, auto, sharded, serving) consults — `auto` can never
    select the MXU for an op family outside it."""
    if not isinstance(op, StencilOp):
        return False
    if op.reduce != "corr":
        return False
    if op.combine not in ("single", "magnitude"):
        return False
    if 2 * op.halo >= B:
        return False
    # the band geometry assumes square (2h+1)-kernels — true for every
    # registry op; reject anything else instead of miscomputing
    k = 2 * op.halo + 1
    if any(tuple(kk.shape) != (k, k) for kk in op.kernels):
        return False
    return _int_kernels_ok(op)


def mxu_family(op: Op) -> str | None:
    """Calibration key for the op's MXU formulation class: 'sepK' (banded
    separable, K taps), 'gradKxK' (magnitude combine), 'corrKxK' (one-shot
    2-D einsum). None for ineligible ops."""
    if not mxu_eligible(op):
        return None
    k = int(op.kernels[0].shape[0])
    if op.combine == "magnitude":
        return f"grad{k}x{k}"
    if _sep_taps(op) is not None:
        return f"sep{k}"
    return f"corr{k}x{k}"


# --------------------------------------------------------------------------
# Banded tap matrices (host-built, cached per weights)
# --------------------------------------------------------------------------

_band_cache: dict = {}


def _band_np(taps: tuple, h: int) -> np.ndarray:
    """(B + 2h, B) banded matrix with C[n + i, n] = taps[i]."""
    key = ("1d", taps, h)
    got = _band_cache.get(key)
    if got is None:
        C = np.zeros((B + 2 * h, B), np.float32)
        for n in range(B):
            for i, t in enumerate(taps):
                C[n + i, n] = t
        got = _band_cache[key] = C
    return got


def _band2_np(w2d: np.ndarray, h: int) -> np.ndarray:
    """(kh, B + 2h, B) per-row-offset banded matrices for the one-einsum
    2-D path: C2[d, n + i, n] = w2d[d, i]."""
    wa = np.asarray(w2d, np.float32)
    key = ("2d", wa.tobytes(), wa.shape, h)
    got = _band_cache.get(key)
    if got is None:
        kh, kw = wa.shape
        C2 = np.zeros((kh, B + 2 * h, B), np.float32)
        for d in range(kh):
            for n in range(B):
                for i in range(kw):
                    C2[d, n + i, n] = wa[d, i]
        got = _band_cache[key] = C2
    return got


def _band_blocks(xp: jnp.ndarray, axis: int, h: int) -> jnp.ndarray:
    """Static sliding blocks of width B + 2h along `axis` with stride B,
    stacked on a new leading axis; `xp` must carry the 2h halo at both
    ends of `axis` and a block-multiple core."""
    n = (xp.shape[axis] - 2 * h) // B
    slices = []
    for j in range(n):
        idx = [slice(None)] * xp.ndim
        idx[axis] = slice(j * B, j * B + B + 2 * h)
        slices.append(xp[tuple(idx)])
    return jnp.stack(slices, axis=0)


# --------------------------------------------------------------------------
# Exact banded passes
# --------------------------------------------------------------------------


def _row_pass_banded(rows: jnp.ndarray, taps: tuple, h: int) -> jnp.ndarray:
    """(R, Wc + 2h) exact u8-integer f32 -> (R, Wc) f32 row sums (Wc a
    block multiple). bf16 inputs are exact (values <= 255); the f32
    accumulation is exact (integer partial sums < 2^24)."""
    C = jnp.asarray(_band_np(taps, h), jnp.bfloat16)
    ext = _band_blocks(rows.astype(jnp.bfloat16), 1, h)  # (nb, R, B+2h)
    out = jnp.einsum("jrk,kn->rjn", ext, C, preferred_element_type=F32)
    return out.reshape(out.shape[0], -1)


def _col_pass_banded(
    tmp: jnp.ndarray, taps: tuple, h: int, variant: str
) -> jnp.ndarray:
    """(Rc + 2h, W) f32 exact-integer row sums -> (Rc, W) column sums
    (Rc a block multiple). 'bf16split': tmp = 64a + b, both halves
    bf16-exact, recombined in f32 — integer-exact by linearity. 'f32':
    direct f32 einsum (exact; lower MXU rate, kept for the A/B lane)."""
    if variant == "f32":
        C = jnp.asarray(_band_np(taps, h), F32)
        ext = _band_blocks(tmp, 0, h)  # (nb, B+2h, W)
        out = jnp.einsum("jkw,km->jmw", ext, C, preferred_element_type=F32)
        return out.reshape(-1, out.shape[-1])
    C = jnp.asarray(_band_np(taps, h), jnp.bfloat16)
    a = jnp.floor(tmp * np.float32(1.0 / _SPLIT))
    b = tmp - a * np.float32(_SPLIT)
    ea = _band_blocks(a.astype(jnp.bfloat16), 0, h)
    eb = _band_blocks(b.astype(jnp.bfloat16), 0, h)
    oa = jnp.einsum("jkw,km->jmw", ea, C, preferred_element_type=F32)
    ob = jnp.einsum("jkw,km->jmw", eb, C, preferred_element_type=F32)
    out = oa * np.float32(_SPLIT) + ob
    return out.reshape(-1, out.shape[-1])


def _sep_valid_mxu(
    xpad: jnp.ndarray, taps: tuple, h: int, *, mode: str, col_variant: str
) -> jnp.ndarray:
    """Separable valid-mode correlation via banded matmuls — bit-identical
    to spec.separable_valid (both compute the same exact integers)."""
    hh = xpad.shape[0] - 2 * h
    ww = xpad.shape[1] - 2 * h
    xf = exact_f32(xpad)
    if mode == "hybrid":
        # row pass on the VPU: the golden exact integer row correlation;
        # output width is already ww, so no width block-padding at all
        tmp = corr_valid(xf, np.asarray(taps, np.float32).reshape(1, -1))
    else:
        wpad = (-ww) % B
        core = xf if wpad == 0 else jnp.pad(xf, ((0, 0), (0, wpad)))
        tmp = _row_pass_banded(core, taps, h)  # (hh + 2h, ww + wpad)
    hpad = (-hh) % B
    if hpad:
        tmp = jnp.pad(tmp, ((0, hpad), (0, 0)))
    out = _col_pass_banded(tmp, taps, h, col_variant)
    return out[:hh, :ww]


def _corr2d_valid_mxu(xpad: jnp.ndarray, w2d: np.ndarray, h: int) -> jnp.ndarray:
    """Valid 2-D integer correlation as ONE banded einsum: kh row-shifted
    views of the width-blocked tile joint-contract over (row offset,
    band position). Raw u8 values are bf16-exact, so no split is needed;
    the f32 accumulation of integer products is exact (module docstring)."""
    kh, kw = w2d.shape
    hh = xpad.shape[0] - (kh - 1)
    ww = xpad.shape[1] - (kw - 1)
    xf = exact_f32(xpad)
    wpad = (-ww) % B
    if wpad:
        xf = jnp.pad(xf, ((0, 0), (0, wpad)))
    xb = xf.astype(jnp.bfloat16)
    views = jnp.stack([xb[d : d + hh] for d in range(kh)], axis=0)
    ext = _band_blocks(views, 2, h)  # (nb, kh, hh, B + 2h)
    C2 = jnp.asarray(_band2_np(w2d, h), jnp.bfloat16)
    out = jnp.einsum("jdhk,dkn->hjn", ext, C2, preferred_element_type=F32)
    return out.reshape(hh, -1)[:, :ww]


def mxu_valid(
    op: StencilOp,
    xpad: jnp.ndarray,
    *,
    mode: str | None = None,
    col_variant: str | None = None,
) -> jnp.ndarray:
    """Drop-in for StencilOp.valid on an eligible op: float32
    (H + 2h, W + 2h) -> float32 (H, W) accumulation, bit-identical to the
    golden path (exact integer sums + replayed combine/scale). This is
    the single primitive every MXU route shares — the full-image
    pipeline, the sharded materialised-ext path, and the serving
    bucket-padded executor all call it on their own pre-extended tiles,
    so the edge-extension machinery is never duplicated."""
    if not mxu_eligible(op):
        raise ValueError(f"op {op.name!r} has no MXU formulation")
    mode = mode or mxu_mode()
    col_variant = col_variant or mxu_col_variant()
    h = op.halo
    taps = _sep_taps(op)
    if taps is not None and op.combine == "single":
        accs = [
            _sep_valid_mxu(xpad, taps, h, mode=mode, col_variant=col_variant)
        ]
    else:
        accs = [
            _corr2d_valid_mxu(xpad, np.asarray(k, np.float32), h)
            for k in op.kernels
        ]
    if op.combine == "single":
        acc = accs[0]
    elif op.combine == "magnitude":
        # replay the golden combine on the exact integer accumulations
        acc = jnp.sqrt(accs[0] * accs[0] + accs[1] * accs[1])
    else:  # pragma: no cover - mxu_eligible rejects other combines
        raise ValueError(f"unknown combine {op.combine!r}")
    if op.scale != 1.0:
        acc = acc * np.float32(op.scale)
    return acc


# --------------------------------------------------------------------------
# Op / pipeline entry points
# --------------------------------------------------------------------------


def mxu_stencil(
    op: StencilOp,
    img: jnp.ndarray,
    *,
    mode: str | None = None,
    col_variant: str | None = None,
) -> jnp.ndarray:
    """One eligible stencil over a u8 image (per channel plane), bit-exact
    against ``op(img)``: golden pad2d edge extension, banded-matmul
    accumulation, golden finalize (quantize + interior mask)."""

    def plane(x: jnp.ndarray) -> jnp.ndarray:
        hh, ww = x.shape
        h = op.halo
        xpad = pad2d(exact_f32(x), op.edge_mode, h, h, h, h)
        acc = mxu_valid(op, xpad, mode=mode, col_variant=col_variant)
        return op.finalize(acc, x, 0, 0, hh, ww)

    if img.ndim == 3:
        return jnp.stack(
            [plane(img[..., c]) for c in range(img.shape[2])], axis=-1
        )
    return plane(img)


def pipeline_mxu(
    ops,
    img: jnp.ndarray,
    *,
    mode: str | None = None,
    interpret: bool | None = None,
    block_h: int | None = None,
):
    """Run a full pipeline with eligible stencils on the MXU banded path
    and everything else on its golden op (per-op fallback — always
    correct, the same contract as pipeline_swar). The whole chain is one
    XLA program under jit, so pointwise prefixes run on the VPU and fuse
    into the same launch as the MXU contraction — the hybrid
    pointwise/stencil split happens by construction.

    `interpret`/`block_h` are accepted for backend-API parity and
    ignored: the MXU path is pure XLA (no Pallas kernel to interpret, no
    row-block knob)."""
    del interpret, block_h
    mode = mode or mxu_mode()
    state = img
    for op in ops:
        if isinstance(op, StencilOp) and mxu_eligible(op):
            state = mxu_stencil(op, state, mode=mode)
        else:
            state = op(state)
    return state


# --------------------------------------------------------------------------
# Auto routing
# --------------------------------------------------------------------------


def use_mxu_for_stencil(op: Op, width: int | None = None) -> str | None:
    """Auto-routing decision for one stencil group: the MXU mode to run
    ('banded'/'hybrid') or None to stay on the VPU/XLA paths.

    Routes only when ALL of: the op family has a proven identity
    (mxu_eligible), the live backend is a real TPU (no-MXU platforms
    always fall through, bit-exactly), and either MCIM_PREFER_MXU=1 (the
    A/B switch) or the calibration store records a measured win for
    (op family, device kind, width window) — `mcim-tpu autotune
    --dimension backend`. Shared by pipeline_auto, the sharded runner and
    the serving executor so the auto paths cannot drift."""
    if not isinstance(op, StencilOp) or not mxu_eligible(op):
        return None
    if not is_tpu_backend():
        return None
    if prefer_mxu():
        return mxu_mode()
    choice = calibration.lookup_backend_choice(mxu_family(op), width=width)
    if choice == "mxu":
        return "banded"
    if choice == "hybrid":
        return "hybrid"
    return None
