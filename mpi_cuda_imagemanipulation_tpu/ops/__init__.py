"""Ops: golden uint8-exact semantics + filter bank + registry.

The golden semantics (SURVEY.md §2.6) follow the reference's kernel.cu with
its races/UB fixed; see `ops.spec` for the exact rules and provenance.
"""

from mpi_cuda_imagemanipulation_tpu.ops import filters
from mpi_cuda_imagemanipulation_tpu.ops.registry import (
    REFERENCE_PIPELINE_SPEC,
    REGISTRY,
    grayscale_u8,
    make_contrast,
    make_emboss,
    make_gaussian,
    make_op,
    make_pipeline_ops,
)
from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    Op,
    PointwiseOp,
    StencilOp,
    corr_valid,
    pad2d,
    rint_clip_u8,
    separable_valid,
    trunc_clip_u8,
)

__all__ = [
    "filters",
    "REFERENCE_PIPELINE_SPEC",
    "REGISTRY",
    "grayscale_u8",
    "make_contrast",
    "make_emboss",
    "make_gaussian",
    "make_op",
    "make_pipeline_ops",
    "Op",
    "PointwiseOp",
    "StencilOp",
    "corr_valid",
    "pad2d",
    "rint_clip_u8",
    "separable_valid",
    "trunc_clip_u8",
]
