"""Multi-host mesh lane — one LARGE request spans hosts; small requests
ride data-parallel replicas.

The replica tier scales throughput: N processes, each one device (or one
slice), each serving bucketed small images. What it cannot do is serve an
image bigger than one replica's largest bucket. This lane is the other
axis of the paper's MPI story: the row-scatter across ranks
(kern.cpp:55), but as a `jax.distributed`-initialized `Mesh` whose
devices may live on MANY hosts — the same `pipe.sharded` program the
single-host sharded path compiles (pad-to-multiple + crop, ghost-row
ppermutes, bit-exact vs the golden path) just runs with DCN-backed ICI
collectives once `jax.distributed.initialize` has stitched the processes
together (parallel/mesh.distributed_init, driven by
JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).

On TPU pods that is real multi-host execution. On CPU — CI and tests —
the same program runs against fake host devices
(`XLA_FLAGS=--xla_force_host_platform_device_count=N`, which
tests/conftest.py already arms): structurally the identical mesh +
ppermute program, minus the wire. `simulated_hosts_xla_flags` builds that
env for spawned processes.

Dispatches jit-cache per (shape, channels): the lane exists for RARE
oversize requests, so a trace per novel shape is the right trade — the
bucket grid's zero-trace contract stays a replica property.
"""

from __future__ import annotations

import threading

import numpy as np

from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
from mpi_cuda_imagemanipulation_tpu.parallel.mesh import (
    distributed_init,
    make_mesh,
)


def simulated_hosts_xla_flags(n_devices: int, existing: str = "") -> str:
    """XLA_FLAGS value giving a CPU process `n_devices` fake host devices
    (the tests' stand-in for a multi-host slice). Appends to `existing`,
    replacing any previous force-host-device-count flag."""
    kept = [
        f
        for f in existing.split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    kept.append(f"--xla_force_host_platform_device_count={n_devices}")
    return " ".join(kept)


class MeshLane:
    """The router's oversize-request executor: `pipe.sharded` over an
    `n_shards`-device (possibly multi-host) row mesh."""

    def __init__(
        self,
        ops: str,
        n_shards: int,
        *,
        halo_mode: str = "serial",
        backend: str = "xla",
    ):
        # multi-host first: initialize() must run before any backend
        # query; a single-process run no-ops here (parallel/mesh.py)
        distributed_init()
        self.pipe = Pipeline.parse(ops)
        self.n_shards = n_shards
        self.mesh = make_mesh(n_shards)
        self._fn = self.pipe.sharded(
            self.mesh, backend=backend, halo_mode=halo_mode
        )
        self._lock = threading.Lock()
        self._dispatches = 0
        self._shapes: set[tuple] = set()

    def process(self, img: np.ndarray) -> np.ndarray:
        """Run one image through the sharded pipeline; bit-exact vs the
        golden path by the sharded runner's contract (pad-to-multiple +
        crop, parallel/api.py)."""
        import jax

        out = np.asarray(jax.block_until_ready(self._fn(img)))
        with self._lock:
            self._dispatches += 1
            self._shapes.add(img.shape)
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "shards": self.n_shards,
                "ops": self.pipe.name,
                "dispatches": self._dispatches,
                "shapes_seen": len(self._shapes),
            }
