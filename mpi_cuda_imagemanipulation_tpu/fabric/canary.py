"""Canary rollback gate — a config flip earns the pod, it is not handed it.

A "config flip" (plan mode, backend choice, calibration update) used to
deploy to 100% of traffic the moment the replicas restarted with it. The
gate inverts that: the flip goes to ONE canary replica first, the router
steers a small deterministic slice of front-door traffic (~5%,
`MCIM_FABRIC_CANARY_FRAC`) at it, and every outcome lands in one of two
lanes — canary or stable. Two checks guard the flip:

  * **burn-rate comparison** — the canary lane's bad-outcome rate must
    stay under `MCIM_FABRIC_CANARY_BURN_RATIO` x the stable lanes' rate
    over the gate window (and under the absolute
    `MCIM_FABRIC_CANARY_BAD_FRAC` floor for the quiet-pod case where
    stable has no errors to compare against). This is the same
    error-budget arithmetic the SLO engine runs, scoped to the flip.
  * **bit-exactness spot checks** — every k-th canary-routed request is
    SHADOWED: the router forwards a duplicate to a stable replica,
    compares response digests, and answers the client from STABLE (a
    shadowed request can never be hurt by the canary). One digest
    mismatch is a breach on its own — a flip that changes pixels is
    wrong regardless of its error rate (the serving contract is
    bit-exact across plan/backend flips).

Breach -> the gate flips to `rolled_back`, the router dumps the
`canary_rollback` flight-recorder artifact with the lane counts, and the
`on_rollback` callback (the Fabric) respawns the canary replica with the
stable config. The gate is pure decision logic over injected outcomes —
no sockets, no clocks it does not receive — so the hysteresis and breach
arithmetic are unit-testable; the router owns the routing side.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

ENV_FRAC = "MCIM_FABRIC_CANARY_FRAC"
ENV_MIN_REQUESTS = "MCIM_FABRIC_CANARY_MIN_REQUESTS"
ENV_SHADOW_EVERY = "MCIM_FABRIC_CANARY_SHADOW_EVERY"
ENV_BAD_FRAC = "MCIM_FABRIC_CANARY_BAD_FRAC"
ENV_BURN_RATIO = "MCIM_FABRIC_CANARY_BURN_RATIO"
ENV_PROMOTE_REQUESTS = "MCIM_FABRIC_CANARY_PROMOTE_REQUESTS"

# gate lifecycle: idle -> canary -> (rolled_back | promoted) -> idle
IDLE = "idle"
CANARY = "canary"
ROLLED_BACK = "rolled_back"
PROMOTED = "promoted"


@dataclasses.dataclass(frozen=True)
class CanaryConfig:
    frac: float | None = None  # None: MCIM_FABRIC_CANARY_FRAC
    min_requests: int | None = None
    shadow_every: int | None = None
    bad_frac: float | None = None
    burn_ratio: float | None = None
    promote_requests: int | None = None

    def resolved(self) -> "CanaryConfig":
        def _f(v, name):
            return float(env_registry.get(name)) if v is None else float(v)

        def _i(v, name):
            return int(env_registry.get(name)) if v is None else int(v)

        return CanaryConfig(
            frac=_f(self.frac, ENV_FRAC),
            min_requests=_i(self.min_requests, ENV_MIN_REQUESTS),
            shadow_every=_i(self.shadow_every, ENV_SHADOW_EVERY),
            bad_frac=_f(self.bad_frac, ENV_BAD_FRAC),
            burn_ratio=_f(self.burn_ratio, ENV_BURN_RATIO),
            promote_requests=_i(self.promote_requests, ENV_PROMOTE_REQUESTS),
        )


class CanaryGate:
    """One flip's lifecycle + the rollback decision. Thread-safe: the
    router records outcomes from handler threads; `start`/`finish` come
    from the control plane."""

    def __init__(self, config: CanaryConfig | None = None, *,
                 clock=time.monotonic):
        self.config = (config or CanaryConfig()).resolved()
        self._clock = clock
        self._lock = threading.Lock()
        self.state = IDLE
        self.replica_id: str | None = None
        self.flip: dict = {}
        self.started_at: float | None = None
        self.decided_at: float | None = None
        self.reason: str | None = None
        # lane counts for THIS flip (reset per start)
        self.canary_ok = 0
        self.canary_bad = 0
        self.stable_ok = 0
        self.stable_bad = 0
        self.shadow_match = 0
        self.shadow_mismatch = 0
        self._route_counter = 0
        self._shadow_counter = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self, replica_id: str, flip: dict) -> None:
        with self._lock:
            if self.state == CANARY:
                raise RuntimeError(
                    f"canary already in flight on {self.replica_id!r}"
                )
            self.state = CANARY
            self.replica_id = replica_id
            self.flip = dict(flip)
            self.started_at = self._clock()
            self.decided_at = None
            self.reason = None
            self.canary_ok = self.canary_bad = 0
            self.stable_ok = self.stable_bad = 0
            self.shadow_match = self.shadow_mismatch = 0
            self._route_counter = 0
            self._shadow_counter = 0

    def abort(self, reason: str = "aborted") -> None:
        with self._lock:
            if self.state == CANARY:
                self._decide(ROLLED_BACK, reason)

    def reset(self) -> None:
        """Back to idle after the rollback/promotion has been ACTED on
        (the Fabric respawned the replica); the decided stats survive in
        `last` until the next start."""
        with self._lock:
            if self.state != CANARY:
                self.state = IDLE

    # -- routing decisions (router hot path) ---------------------------------

    def take_canary(self) -> bool:
        """Deterministic traffic slice: every round(1/frac)-th front-door
        request routes to the canary (counter-based, so the slice holds
        under any request rate and is reproducible in tests)."""
        with self._lock:
            if self.state != CANARY:
                return False
            period = max(1, round(1.0 / max(self.config.frac, 1e-6)))
            self._route_counter += 1
            return self._route_counter % period == 0

    def take_shadow(self) -> bool:
        """Among canary-routed requests, every k-th also shadows to
        stable for the digest spot check."""
        with self._lock:
            if self.state != CANARY:
                return False
            self._shadow_counter += 1
            return self._shadow_counter % max(1, self.config.shadow_every) == 0

    # -- outcome recording + the gate ----------------------------------------

    def record(self, lane: str, ok: bool) -> str:
        """Fold one request outcome in; returns the (possibly new) gate
        state so the router can act on a breach in the same call."""
        with self._lock:
            if self.state != CANARY:
                return self.state
            if lane == "canary":
                if ok:
                    self.canary_ok += 1
                else:
                    self.canary_bad += 1
            else:
                if ok:
                    self.stable_ok += 1
                else:
                    self.stable_bad += 1
            self._evaluate()
            return self.state

    def record_shadow(self, match: bool) -> str:
        with self._lock:
            if self.state != CANARY:
                return self.state
            if match:
                self.shadow_match += 1
            else:
                self.shadow_mismatch += 1
            self._evaluate()
            return self.state

    def _evaluate(self) -> None:
        """The rollback gate (lock held). A digest mismatch breaches
        immediately; rate breaches wait for min_requests canary outcomes
        so one unlucky request cannot roll a healthy flip back."""
        cfg = self.config
        if self.shadow_mismatch > 0:
            self._decide(ROLLED_BACK, "shadow digest mismatch")
            return
        n_canary = self.canary_ok + self.canary_bad
        if n_canary < cfg.min_requests:
            return
        canary_rate = self.canary_bad / n_canary
        n_stable = self.stable_ok + self.stable_bad
        stable_rate = (self.stable_bad / n_stable) if n_stable else 0.0
        if canary_rate > cfg.bad_frac and (
            n_stable == 0 or canary_rate > cfg.burn_ratio * stable_rate
        ):
            self._decide(
                ROLLED_BACK,
                f"canary bad rate {canary_rate:.3f} vs stable "
                f"{stable_rate:.3f} (ratio limit {cfg.burn_ratio:g}, "
                f"abs limit {cfg.bad_frac:g})",
            )
            return
        if n_canary >= cfg.promote_requests:
            self._decide(PROMOTED, "no breach over the promote window")

    def _decide(self, state: str, reason: str) -> None:
        self.state = state
        self.reason = reason
        self.decided_at = self._clock()

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "replica": self.replica_id,
                "flip": dict(self.flip),
                "frac": self.config.frac,
                "reason": self.reason,
                "canary": {"ok": self.canary_ok, "bad": self.canary_bad},
                "stable": {"ok": self.stable_ok, "bad": self.stable_bad},
                "shadow": {
                    "match": self.shadow_match,
                    "mismatch": self.shadow_mismatch,
                },
                "age_s": (
                    None
                    if self.started_at is None
                    else self._clock() - self.started_at
                ),
            }
