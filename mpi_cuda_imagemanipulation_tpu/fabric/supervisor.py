"""Replica supervision — spawn, monitor, restart-with-backoff — and the
`Fabric` facade that runs router + supervised replicas as one unit.

The supervisor is deliberately dumb: it owns PROCESS lifecycle only.
Liveness, routing and breakers are the router's job (heartbeats), so the
supervisor never talks to replicas beyond signals — the same separation
that lets a real deployment swap this module for systemd/k8s while the
router stays unchanged.

Restart policy: a replica that exits (crash, OOM kill, the churn test's
SIGKILL) is respawned after an exponential backoff (base * 2^attempt,
capped), and the attempt counter resets once an incarnation survives
`stable_s` — so a crash loop backs off instead of spinning, while a
one-off kill rejoins after one base delay. Each restart increments
`mcim_fabric_replica_restarts_total{replica=...}` on the shared fabric
registry.

PREEMPTION is not a crash: a replica that exits `PREEMPT_EXIT_CODE`
(fabric/control.py) drained gracefully after an eviction notice — it is
replaced IMMEDIATELY, with no backoff and no attempt-counter increment
(backing off on the platform's scheduling decision would compound the
capacity loss), and counted separately in
`mcim_fabric_replica_preemptions_total`. The replica already wrote the
`preempt` post-mortem dump; the supervisor only logs.

The membership is DYNAMIC for the autoscaler (fabric/autoscaler.py):
`add()` grows the set, `remove()` SIGTERMs a (drained) replica and
forgets it — the monitor will not resurrect a removed replica — and
`respawn()` is the canary deploy path: replace one replica's process
with a (possibly different) spec, gracefully.

`Fabric` is the assembly the CLI (`serve --replicas N` / `fabric`) and
the tests use:

    with Fabric(FabricConfig(replicas=3, ...)).start() as fab:
        ... fab.url ...            # the front door
        fab.kill_replica("r1")     # churn: SIGKILL; supervisor restarts it
    # replicas SIGTERMed (graceful drain), router closed, on every path
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

from mpi_cuda_imagemanipulation_tpu.fabric.control import PREEMPT_EXIT_CODE
from mpi_cuda_imagemanipulation_tpu.fabric.router import (
    Router,
    RouterConfig,
)
from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry
from mpi_cuda_imagemanipulation_tpu.serve import bucketing
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@dataclasses.dataclass
class ReplicaSpec:
    """How to (re)spawn one replica: its stable id, argv and env extras."""

    replica_id: str
    argv: list[str]
    extra_env: dict[str, str] = dataclasses.field(default_factory=dict)


class _Managed:
    """Supervisor-internal per-replica state (monitor thread only)."""

    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        self.proc: subprocess.Popen | None = None
        self.spawned_at = 0.0
        self.attempts = 0  # consecutive restarts without a stable run
        self.restart_due: float | None = None
        self.removed = False  # hands-off flag: remove()/respawn() owns it


class Supervisor:
    def __init__(
        self,
        specs: list[ReplicaSpec],
        *,
        registry: Registry | None = None,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 10.0,
        stable_s: float = 5.0,
        clock=time.monotonic,
        death_info=None,
    ):
        self.specs = list(specs)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.stable_s = stable_s
        self._clock = clock
        # death_info(replica_id) -> dict: extra context for the
        # replica_death flight-recorder dump (Fabric passes the router's
        # last heartbeat view, so the dump names the dead replica's warm
        # buckets even though its own ring died with it)
        self._death_info = death_info
        self._managed = {s.replica_id: _Managed(s) for s in specs}
        self._lock = threading.Lock()  # guards _managed.proc handles
        self._running = False
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._log = get_logger()
        reg = registry or Registry()
        self._m_restarts = reg.counter(
            "mcim_fabric_replica_restarts_total",
            "Replica processes respawned by the supervisor, per replica.",
            labels=("replica",),
        )
        self._m_preemptions = reg.counter(
            "mcim_fabric_replica_preemptions_total",
            "Graceful preemption exits replaced WITHOUT backoff, per "
            "replica.",
            labels=("replica",),
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Supervisor":
        self._running = True
        for m in self._managed.values():
            self._spawn(m)
        self._thread = threading.Thread(
            target=self._monitor, name="mcim-fabric-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def _spawn(self, m: _Managed) -> None:
        env = dict(os.environ)
        # the worker must import THIS checkout even without an installed
        # package (tests); prepending is harmless when one is installed
        env["PYTHONPATH"] = _REPO_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        env.update(m.spec.extra_env)
        m.proc = subprocess.Popen(m.spec.argv, env=env)
        m.spawned_at = self._clock()
        m.restart_due = None
        self._log.info(
            "spawned replica %s (pid %d)", m.spec.replica_id, m.proc.pid
        )

    def _monitor(self) -> None:
        while self._running:
            now = self._clock()
            with self._lock:
                managed = list(self._managed.values())
            for m in managed:
                proc = m.proc
                if proc is None or m.removed:
                    continue
                if proc.poll() is None:
                    # alive; a long stable run forgives past crashes
                    if m.attempts and now - m.spawned_at >= self.stable_s:
                        m.attempts = 0
                    continue
                if not self._running:
                    break
                if proc.returncode == PREEMPT_EXIT_CODE:
                    # preemption: the replica drained and dumped its own
                    # post-mortem; replace NOW — backoff is for crash
                    # loops, not for the platform evicting a slice
                    self._m_preemptions.inc(replica=m.spec.replica_id)
                    self._m_restarts.inc(replica=m.spec.replica_id)
                    self._log.warning(
                        "replica %s preempted (rc %d); immediate "
                        "replacement, no backoff",
                        m.spec.replica_id, proc.returncode,
                    )
                    self._spawn(m)
                    continue
                if m.restart_due is None:
                    if now - m.spawned_at >= self.stable_s:
                        m.attempts = 0
                    delay = min(
                        self.backoff_base_s * (2**m.attempts),
                        self.backoff_max_s,
                    )
                    m.restart_due = now + delay
                    self._log.warning(
                        "replica %s exited (rc %s); restart in %.2fs "
                        "(attempt %d)",
                        m.spec.replica_id, proc.returncode, delay,
                        m.attempts + 1,
                    )
                    self._dump_death(m.spec.replica_id, proc)
                elif now >= m.restart_due:
                    m.attempts += 1
                    self._m_restarts.inc(replica=m.spec.replica_id)
                    self._spawn(m)
            self._wake.wait(0.05)

    def _dump_death(self, replica_id: str, proc) -> None:
        """A replica died while the pod was supposed to be up: write the
        replica_death flight-recorder post-mortem. The SUPERVISOR process
        ring (shared with the router in a `Fabric`) holds the dead
        replica's last heartbeats — `death_info` lifts its warm buckets
        and state into the dump header. Never raises (runs on the
        monitor thread)."""
        from mpi_cuda_imagemanipulation_tpu.obs import recorder

        extra = {"replica": replica_id, "returncode": proc.returncode}
        if self._death_info is not None:
            try:
                extra.update(self._death_info(replica_id) or {})
            except Exception:  # a racing table read must not kill monitor
                pass
        path = recorder.dump("replica_death", extra=extra)
        if path:
            self._log.warning(
                "replica %s death post-mortem -> %s", replica_id, path
            )

    def stop(self, *, drain: bool = True, deadline_s: float = 30.0) -> None:
        """SIGTERM every replica (graceful drain in the worker), wait out
        the deadline, SIGKILL stragglers. Idempotent."""
        self._running = False
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        procs = [
            m.proc for m in self._managed.values() if m.proc is not None
        ]
        sig = signal.SIGTERM if drain else signal.SIGKILL
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass
        deadline = self._clock() + deadline_s
        for p in procs:
            left = max(0.1, deadline - self._clock())
            try:
                p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                self._log.warning(
                    "replica pid %d ignored the drain deadline; killing",
                    p.pid,
                )
                p.kill()
                p.wait(timeout=10.0)

    # -- dynamic membership (autoscaler + canary) --------------------------

    def add(self, spec: ReplicaSpec) -> None:
        """Grow the set by one replica (autoscaler scale-up). The new
        process registers itself with the router by heartbeat like any
        other."""
        with self._lock:
            if spec.replica_id in self._managed:
                raise ValueError(
                    f"replica {spec.replica_id!r} is already managed"
                )
            m = self._managed[spec.replica_id] = _Managed(spec)
        self._spawn(m)

    def remove(self, replica_id: str, *, deadline_s: float = 30.0) -> None:
        """Shrink the set: SIGTERM (the replica drains what it still
        holds) and FORGET — the monitor will not resurrect it. The
        autoscaler only calls this after the router-side drain emptied
        the replica's queue (drain-before-kill)."""
        with self._lock:
            m = self._managed.get(replica_id)
            if m is None:
                return
            m.removed = True
            del self._managed[replica_id]
        proc = m.proc
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.send_signal(signal.SIGTERM)
        except OSError:
            return
        try:
            proc.wait(timeout=deadline_s)
        except subprocess.TimeoutExpired:
            self._log.warning(
                "removed replica %s ignored the drain deadline; killing",
                replica_id,
            )
            proc.kill()
            proc.wait(timeout=10.0)
        self._log.info(
            "replica %s removed (rc %s)", replica_id, proc.returncode
        )

    def respawn(
        self,
        replica_id: str,
        *,
        spec: ReplicaSpec | None = None,
        deadline_s: float = 30.0,
    ) -> None:
        """Replace one replica's PROCESS, gracefully, optionally with a
        new spec — the canary deploy/revert path (a config flip is a
        respawn with different argv/env, nothing more)."""
        with self._lock:
            m = self._managed[replica_id]
            m.removed = True  # monitor hands off while we swap
            proc = m.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
                proc.wait(timeout=deadline_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
            except OSError:
                pass
        with self._lock:
            if spec is not None:
                m.spec = spec
            m.attempts = 0
            m.removed = False
        self._m_restarts.inc(replica=replica_id)
        self._spawn(m)

    def spec_of(self, replica_id: str) -> ReplicaSpec:
        with self._lock:
            return self._managed[replica_id].spec

    def replica_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._managed)

    # -- churn / introspection --------------------------------------------

    def kill(self, replica_id: str) -> int:
        """SIGKILL one replica (no drain, no warning — the churn test's
        simulated hard failure). The monitor restarts it with backoff.
        Returns the killed pid."""
        with self._lock:
            m = self._managed[replica_id]
            proc = m.proc
        assert proc is not None, f"{replica_id} was never spawned"
        proc.kill()
        proc.wait(timeout=10.0)
        return proc.pid

    def pids(self) -> dict[str, int | None]:
        with self._lock:
            return {
                rid: (m.proc.pid if m.proc is not None else None)
                for rid, m in self._managed.items()
            }

    def restarts(self, replica_id: str) -> int:
        return int(self._m_restarts.value(replica=replica_id))

    def preemptions(self, replica_id: str) -> int:
        return int(self._m_preemptions.value(replica=replica_id))


@dataclasses.dataclass(frozen=True)
class FabricConfig:
    """The whole pod in one value: replica count + the serve knobs each
    replica runs with + router policy overrides."""

    replicas: int = 3
    ops: str = "grayscale,contrast:3.5,emboss:3"
    buckets: str = "512,1024,2048,4096"  # CLI spec; parsed for the router
    channels: str = "1,3"
    max_batch: int = 8
    max_delay_ms: float = 5.0
    queue_depth: int = 64
    impl: str = "xla"
    heartbeat_s: float | None = None  # None: MCIM_FABRIC_HEARTBEAT_S
    router: RouterConfig | None = None  # None: RouterConfig(buckets=...)
    mesh_shards: int = 0  # >0: arm the oversize mesh lane in the router
    mesh_halo_mode: str = "serial"
    # fusion-plan mode every replica serves with (the canary deploy path
    # flips it per replica via `--plan` in the flip argv)
    plan: str = "auto"
    # continuous autotuning (tune/): tune=True arms MCIM_TUNE=1 on every
    # replica (observations persist to the shared calibration store) and
    # starts a TuneController on the router that proposes config flips
    # from those observations and promotes/rolls them back through the
    # canary gate with no human in the loop
    tune: bool = False
    tune_arms: str | None = None  # comma list; None: MCIM_TUNE_ARMS/default
    tune_config: object | None = None  # tune.controller.TuneConfig; None: env
    # pod-level systolic execution: arm the router's stage-sharding lane
    # AND start every replica with --systolic so heartbeats advertise
    # stage ownership (graph/systolic.py)
    systolic: bool = False
    # per-replica env overrides (failpoint injection on one worker, trace
    # export paths, ...) and extra replica argv (e.g. --trace-out)
    replica_env: dict[str, dict[str, str]] = dataclasses.field(
        default_factory=dict
    )
    replica_argv_extra: dict[str, list[str]] = dataclasses.field(
        default_factory=dict
    )
    # env applied to EVERY replica, including ones the autoscaler adds
    # later (per-replica replica_env wins on clashes)
    all_replica_env: dict[str, str] = dataclasses.field(
        default_factory=dict
    )
    supervisor_backoff_s: float = 0.5
    supervisor_stable_s: float = 5.0
    # -- elastic control loop (fabric/autoscaler.py) ------------------------
    # autoscale=True arms the loop; `replicas` is the STARTING count and
    # the loop then steers within [min_replicas, max_replicas] (None
    # fields fall back to MCIM_FABRIC_MIN/MAX_REPLICAS / SCALE_* env)
    autoscale: bool = False
    min_replicas: int | None = None
    max_replicas: int | None = None
    scale_up_frac: float | None = None
    scale_down_frac: float | None = None
    scale_sustain_s: float | None = None
    scale_cooldown_s: float | None = None
    scale_tick_s: float | None = None
    scale_drain_deadline_s: float | None = None
    # -- multi-pod federation (federation/) ---------------------------------
    # federate=<front-door URL> arms the router's pod-level uplink: this
    # pod pushes aggregate heartbeats there and applies quota leases
    # from the acks; pod_id is the pod's stable identity across restarts
    federate: str | None = None
    pod_id: str | None = None
    fed_heartbeat_s: float | None = None  # None: MCIM_FED_HEARTBEAT_S


class Fabric:
    """Router + supervised replicas, one lifecycle."""

    def __init__(self, config: FabricConfig):
        self.config = config
        self.registry = Registry()
        mesh_lane = None
        if config.mesh_shards > 0:
            from mpi_cuda_imagemanipulation_tpu.fabric.mesh import MeshLane

            mesh_lane = MeshLane(
                config.ops,
                config.mesh_shards,
                halo_mode=config.mesh_halo_mode,
            )
        router_cfg = config.router or RouterConfig(
            buckets=bucketing.parse_buckets(config.buckets)
        )
        if config.systolic:
            router_cfg = dataclasses.replace(router_cfg, systolic=True)
        self.router = Router(
            router_cfg,
            registry=self.registry,
            mesh_lane=mesh_lane,
        )
        # canary control plane: the router gates + decides, the Fabric
        # owns the process swaps (deploy = respawn with the flip config,
        # rollback = respawn with the stable one)
        self.router.on_canary_deploy = self._canary_deploy
        self.router.on_canary_rollback = self._canary_rollback
        self._canary_stable_spec: ReplicaSpec | None = None
        # tune controller state: a promoted flip's argv/env delta joins
        # every FUTURE replica spec too (autoscaler scale-ups, supervisor
        # restarts), so the fleet stays converged across churn
        self.tuner = None
        self._tune_argv: list[str] = []
        self._tune_env: dict[str, str] = {}
        self.supervisor: Supervisor | None = None
        self.autoscaler = None
        # injectable like the Supervisor's (line ~245): the _wait_*
        # helpers poll through these, so fake-clock tests can exercise
        # their timeout paths without real 180s waits
        self._clock = time.monotonic
        self._sleep = time.sleep
        self._log = get_logger()

    def replica_ids(self) -> list[str]:
        return [f"r{i}" for i in range(self.config.replicas)]

    def _death_info(self, replica_id: str) -> dict:
        """Context for the replica_death post-mortem dump: the dead
        replica's last heartbeat as the router saw it — state, queue
        fill and (the churn question) which buckets it was serving warm."""
        view = self.router.table.get(replica_id)
        if view is None:
            return {}
        return {
            "last_state": view.hb.state,
            "last_queued": view.hb.queued,
            "warm_buckets": list(view.hb.warm_buckets),
            "breaker_open": list(view.hb.breaker_open),
            "incarnation": view.hb.incarnation,
        }

    def _replica_argv(self, rid: str) -> list[str]:
        c = self.config
        argv = [
            sys.executable, "-m",
            "mpi_cuda_imagemanipulation_tpu.fabric.replica",
            "--replica-id", rid,
            "--router", self.router.url,
            "--ops", c.ops,
            "--buckets", c.buckets,
            "--channels", c.channels,
            "--max-batch", str(c.max_batch),
            "--max-delay-ms", str(c.max_delay_ms),
            "--queue-depth", str(c.queue_depth),
            "--impl", c.impl,
            "--plan", c.plan,
        ]
        if c.systolic:
            argv += ["--systolic"]
        if c.heartbeat_s is not None:
            argv += ["--heartbeat-s", str(c.heartbeat_s)]
        argv += c.replica_argv_extra.get(rid, [])
        # a tuner-promoted flip outranks the pinned config (argparse
        # last-wins — the same mechanism as the canary flip argv)
        argv += self._tune_argv
        return argv

    def _replica_spec(self, rid: str) -> ReplicaSpec:
        tune_env = {}
        if self.config.tune:
            # every replica ingests + persists online observations; the
            # configured env (user/all_replica_env) still wins on clash
            tune_env["MCIM_TUNE"] = "1"
        return ReplicaSpec(
            replica_id=rid,
            argv=self._replica_argv(rid),
            extra_env={
                **tune_env,
                **self._tune_env,
                **self.config.all_replica_env,
                **self.config.replica_env.get(rid, {}),
            },
        )

    def start(
        self,
        host: str = "",
        port: int = 0,
        *,
        ready_timeout_s: float = 180.0,
    ) -> "Fabric":
        try:
            self.router.start(host, port)
            if self.config.federate:
                # pod-level uplink AFTER the listener is bound (the pod
                # heartbeat advertises the router's real address) and
                # BEFORE the replicas: the front door learns of this
                # pod within one beat of it being reachable
                self.router.federate(
                    self.config.federate,
                    self.config.pod_id or f"pod-{os.getpid()}",
                    interval_s=self.config.fed_heartbeat_s,
                )
            specs = [
                self._replica_spec(rid) for rid in self.replica_ids()
            ]
            self.supervisor = Supervisor(
                specs,
                registry=self.registry,
                backoff_base_s=self.config.supervisor_backoff_s,
                stable_s=self.config.supervisor_stable_s,
                death_info=self._death_info,
            ).start()
            if self.config.autoscale:
                from mpi_cuda_imagemanipulation_tpu.fabric.autoscaler import (
                    Autoscaler,
                    AutoscalerConfig,
                )

                c = self.config
                self.autoscaler = Autoscaler(
                    self.router,
                    scale_up=self._scale_up_replica,
                    scale_down=self._scale_down_replica,
                    live_count=lambda: len(self.supervisor.replica_ids()),
                    config=AutoscalerConfig(
                        min_replicas=c.min_replicas,
                        max_replicas=c.max_replicas,
                        up_frac=c.scale_up_frac,
                        down_frac=c.scale_down_frac,
                        sustain_s=c.scale_sustain_s,
                        cooldown_s=c.scale_cooldown_s,
                        tick_s=c.scale_tick_s,
                        drain_deadline_s=c.scale_drain_deadline_s,
                    ),
                    registry=self.registry,
                )
                self.router.autoscaler = self.autoscaler
            self.wait_ready(
                self.config.replicas, timeout_s=ready_timeout_s
            )
            if self.autoscaler is not None:
                # only after the seed set is serving: the loop must not
                # misread warmup as an outage and over-spawn
                self.autoscaler.start()
            if self.config.tune:
                # after the seed set is serving, like the autoscaler:
                # the first tick must see a routable pod, not warmup
                self._start_tuner()
        except BaseException:
            self.close(drain=False)
            raise
        return self

    def _start_tuner(self) -> None:
        from mpi_cuda_imagemanipulation_tpu.ops.registry import (
            make_pipeline_ops,
        )
        from mpi_cuda_imagemanipulation_tpu.plan.ir import (
            pipeline_fingerprint,
        )
        from mpi_cuda_imagemanipulation_tpu.plan.planner import (
            resolve_plan_mode,
        )
        from mpi_cuda_imagemanipulation_tpu.tune.controller import (
            TuneController,
        )
        from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

        c = self.config
        ops = make_pipeline_ops(c.ops)
        width = max(w for (_h, w) in bucketing.parse_buckets(c.buckets))
        # the arm the fleet is serving RIGHT NOW: the same resolution the
        # replicas ran (env/calibration-aware), so the controller's
        # incumbent matches reality even under plan='auto'
        mode = resolve_plan_mode(ops, c.plan, backend=c.impl, width=width)
        current_arm = f"plan:{mode}"
        raw = c.tune_arms or env_registry.get("MCIM_TUNE_ARMS")
        if raw:
            arms = tuple(a.strip() for a in raw.split(",") if a.strip())
        else:
            arms = ("plan:off", "plan:fused")
            try:
                from mpi_cuda_imagemanipulation_tpu.utils.platform import (
                    is_tpu_backend,
                )

                if is_tpu_backend():
                    # the megakernel is a candidate only where it is real
                    # (interpret mode would "win" nothing off-TPU); same
                    # for its forced-MXU-arms variant (round 8)
                    arms += ("plan:fused-pallas", "plan:fused-pallas-mxu")
            except Exception:
                pass
        if current_arm not in arms:
            arms = (current_arm,) + arms
        self.tuner = TuneController(
            gate=self.router.canary,
            deploy=self.router.canary_deploy,
            pipe_fp=pipeline_fingerprint(ops),
            current_arm=current_arm,
            arms=arms,
            registry=self.registry,
            on_promote=self._tune_promote,
            on_revert=self._canary_rollback,
            config=c.tune_config,
        )
        self.router.tuner = self.tuner
        self.tuner.start()

    def _tune_promote(self, flip: dict) -> None:
        """Tuner promote hook: the canary replica already runs the flip
        and proved it — roll the REST of the fleet onto it, one replica
        at a time so the pod keeps serving throughout, and fold the
        delta into the base spec so scale-ups and restarts inherit it."""
        assert self.supervisor is not None
        argv_extra = [str(a) for a in flip.get("argv", [])]
        env_extra = {
            str(k): str(v) for k, v in flip.get("env", {}).items()
        }
        canary_rid = self.router.canary.replica_id
        self._tune_argv = self._tune_argv + argv_extra
        self._tune_env = {**self._tune_env, **env_extra}
        for rid in sorted(self.supervisor.replica_ids()):
            if rid == canary_rid:
                continue
            view = self.router.table.get(rid)
            old_inc = view.hb.incarnation if view is not None else None
            self._log.info(
                "tune promote: respawning %s with argv+=%s", rid, argv_extra
            )
            self.supervisor.respawn(rid, spec=self._replica_spec(rid))
            self._wait_incarnation_change(rid, old_inc)
        # the canary's one-off spec is now the fleet's config; its next
        # respawn (supervisor restart, scale churn) rebuilds from the
        # updated base, so the stale stable snapshot must not revive
        self._canary_stable_spec = None

    # -- elastic membership (autoscaler callbacks) -------------------------

    def _next_replica_id(self) -> str:
        """Lowest free index, so drained ids are REUSED: metric label
        sets and rendezvous layouts stay bounded over any number of
        scale cycles."""
        assert self.supervisor is not None
        taken = set(self.supervisor.replica_ids())
        i = 0
        while f"r{i}" in taken:
            i += 1
        return f"r{i}"

    def _scale_up_replica(self) -> str:
        assert self.supervisor is not None
        rid = self._next_replica_id()
        self.supervisor.add(self._replica_spec(rid))
        return rid

    def _scale_down_replica(self, rid: str) -> None:
        assert self.supervisor is not None
        self.supervisor.remove(
            rid,
            deadline_s=self.config.scale_drain_deadline_s or 30.0,
        )

    # -- canary control plane (router callbacks) ---------------------------

    def _wait_incarnation_change(
        self, rid: str, old_incarnation: str | None, timeout_s: float = 180.0
    ) -> None:
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            view = self.router.table.get(rid)
            if (
                view is not None
                and view.hb.incarnation != old_incarnation
                and view.hb.state == "serving"
            ):
                return
            self._sleep(0.1)
        raise TimeoutError(
            f"replica {rid} did not re-register serving within "
            f"{timeout_s:.0f}s"
        )

    def _canary_pick(self) -> str:
        """The flip's guinea pig: the highest-index routable replica —
        deterministic, and r0 (the rendezvous-heaviest seed) keeps
        serving stable traffic."""
        live = sorted(v.replica_id for v in self.router._routable())
        if not live:
            raise RuntimeError("no routable replica to canary")
        return live[-1]

    def _canary_deploy(self, flip: dict) -> str:
        """Router deploy hook: respawn one replica with the flip's
        argv/env delta, block until its new incarnation is serving, and
        hand the id back for the gate to open the traffic slice."""
        assert self.supervisor is not None
        rid = flip.get("replica") or self._canary_pick()
        stable = self.supervisor.spec_of(rid)
        self._canary_stable_spec = stable
        view = self.router.table.get(rid)
        old_inc = view.hb.incarnation if view is not None else None
        canary_spec = ReplicaSpec(
            replica_id=rid,
            argv=list(stable.argv) + [str(a) for a in flip.get("argv", [])],
            extra_env={
                **stable.extra_env,
                **{str(k): str(v) for k, v in flip.get("env", {}).items()},
            },
        )
        self._log.info(
            "canary deploy on %s: argv+=%s env+=%s",
            rid, flip.get("argv", []), sorted(flip.get("env", {})),
        )
        self.supervisor.respawn(rid, spec=canary_spec)
        self._wait_incarnation_change(rid, old_inc)
        return rid

    def _canary_rollback(self, status: dict) -> None:
        """Router rollback hook (off the request thread): put the stable
        spec back, wait for it to serve, then return the gate to idle."""
        assert self.supervisor is not None
        rid = status.get("replica")
        stable = self._canary_stable_spec
        if rid is None or stable is None:
            return
        view = self.router.table.get(rid)
        old_inc = view.hb.incarnation if view is not None else None
        self._log.warning(
            "canary rollback on %s: reverting to the stable spec", rid
        )
        try:
            self.supervisor.respawn(rid, spec=stable)
            self._wait_incarnation_change(rid, old_inc)
        finally:
            self.router.canary.reset()

    def wait_ready(self, n: int, *, timeout_s: float = 180.0) -> None:
        """Block until `n` replicas are fresh + routable (each has warmed
        its compile cache and heartbeated `serving`)."""
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            if len(self.router._routable()) >= n:
                return
            self._sleep(0.1)
        pids = self.supervisor.pids() if self.supervisor else {}
        raise TimeoutError(
            f"{n} replicas not serving within {timeout_s:.0f}s "
            f"(routable: {sorted(v.replica_id for v in self.router._routable())}, "
            f"pids: {pids})"
        )

    @property
    def url(self) -> str:
        return self.router.url

    def kill_replica(self, replica_id: str) -> int:
        assert self.supervisor is not None
        return self.supervisor.kill(replica_id)

    def stats(self) -> dict:
        return {
            "router": self.router.stats(),
            "pids": self.supervisor.pids() if self.supervisor else {},
        }

    def scrape(self) -> str:
        """The router's /metrics body over HTTP (what a Prometheus scrape
        sees — exercised, not simulated)."""
        with urllib.request.urlopen(
            self.url + "/metrics", timeout=10.0
        ) as resp:
            return resp.read().decode()

    def http_stats(self) -> dict:
        with urllib.request.urlopen(
            self.url + "/stats", timeout=10.0
        ) as resp:
            return json.loads(resp.read())

    def close(self, *, drain: bool = True, deadline_s: float = 30.0) -> None:
        if self.tuner is not None:
            # before the supervisor: a mid-close promote must not respawn
            # replicas the supervisor is tearing down
            self.tuner.stop()
            self.tuner = None
        if self.autoscaler is not None:
            self.autoscaler.stop()
            self.autoscaler = None
        if self.supervisor is not None:
            self.supervisor.stop(drain=drain, deadline_s=deadline_s)
            self.supervisor = None
        self.router.close()

    def __enter__(self) -> "Fabric":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
