"""Live video sessions — sticky affinity + journal-tail failover state.

A video session is an ordered frame stream whose temporal ops make each
output depend on the last `window` INPUT frames: the serving replica
holds that history in per-session frame rings (stream/video.py
`VideoSessionHost`), which makes a replica death mid-stream a stateful
loss — unless someone can rebuild the rings. The router can, because it
is the only hop every frame already crosses:

  * **sticky affinity** — a session binds to the rendezvous-hash winner
    of (session id, replica id) over the routable set at FIRST frame,
    and stays bound while that replica serves (scale-up must never
    migrate a live ring just because the hash winner changed; only
    death/drain unbinds).
  * **journal tail** — the router retains the last K frame bodies per
    session (K = sum of the pipeline's temporal windows, the exact
    history the rings need — `MCIM_FABRIC_SESSION_TAIL` overrides). The
    tail is the session's journal: bounded, newest-suffix, enough to
    reconstruct every ring bit-exactly.
  * **failover replay** — when the bound replica dies (forward failure
    or no longer routable), the router rebinds to the current rendezvous
    winner among survivors and REPLAYS the tail with the replay flag
    set: the replica decodes and pushes rings but skips compute+encode
    (204), then the live frame processes normally — bit-exact with the
    uninterrupted stream, which the churn test asserts pixel for pixel.

This module is the pure state side (table, binding, tail arithmetic);
fabric/router.py owns the HTTP forwarding around it.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

ENV_SESSION_TAIL = "MCIM_FABRIC_SESSION_TAIL"

SESSION_PATH_PREFIX = "/v1/session/"

# request headers the session hop rides on
HDR_SEQ = "X-Session-Seq"
HDR_OPS = "X-Video-Ops"
HDR_REPLAY = "X-Session-Replay"
HDR_RESET = "X-Session-Reset"


def tail_capacity(ops_spec: str) -> int:
    """Frames of history that reconstruct every temporal ring exactly:
    ring k's oldest retained output needs full upstream history, which a
    replay of sum(window_i) frames always provides (>= the tight
    sum(window_i - 1) + 1 bound). Env override wins when larger."""
    from mpi_cuda_imagemanipulation_tpu.ops.temporal import split_temporal

    temporal, _rest = split_temporal(ops_spec)
    need = max(1, sum(op.window for op in temporal))
    override = int(env_registry.get(ENV_SESSION_TAIL) or 0)
    return max(need, override)


class Session:
    """One live stream as the router sees it: the binding plus the
    replayable frame tail. Guarded by its own lock — frames of ONE
    session serialize (ordered stream), different sessions don't."""

    def __init__(self, sid: str, ops: str):
        self.sid = sid
        self.ops = ops
        self.lock = threading.Lock()
        self.replica_id: str | None = None
        self.next_seq = 0
        self.tail: deque[tuple[int, bytes]] = deque(
            maxlen=tail_capacity(ops)
        )
        self.frames = 0
        self.failovers = 0
        self.last_active = time.monotonic()

    def remember(self, seq: int, body: bytes) -> None:
        self.tail.append((seq, body))
        self.next_seq = seq + 1
        self.frames += 1
        self.last_active = time.monotonic()

    def replay_frames(self, before_seq: int) -> list[tuple[int, bytes]]:
        """The journal tail strictly before `before_seq`, oldest first —
        what a fresh replica must ingest before the live frame."""
        return [(s, b) for s, b in self.tail if s < before_seq]

    def to_dict(self) -> dict:
        return {
            "ops": self.ops,
            "replica": self.replica_id,
            "next_seq": self.next_seq,
            "frames": self.frames,
            "failovers": self.failovers,
            "tail": len(self.tail),
            "tail_cap": self.tail.maxlen,
        }


class SessionTable:
    """sid -> Session, bounded. The cap is a safety valve against id
    churn (every sid mints a tail buffer); eviction is oldest-idle
    first, never the youngest — a live stream cannot be evicted by
    garbage sids."""

    def __init__(self, cap: int = 512):
        self.cap = cap
        self._lock = threading.Lock()
        self._sessions: dict[str, Session] = {}
        self.evicted = 0

    def get_or_create(self, sid: str, ops: str) -> Session:
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is not None:
                return sess
            if len(self._sessions) >= self.cap:
                victim = min(
                    self._sessions.values(), key=lambda s: s.last_active
                )
                del self._sessions[victim.sid]
                self.evicted += 1
            sess = self._sessions[sid] = Session(sid, ops)
            return sess

    def get(self, sid: str) -> Session | None:
        with self._lock:
            return self._sessions.get(sid)

    def sessions(self) -> list[Session]:
        with self._lock:
            return list(self._sessions.values())

    def bound_to(self, replica_id: str) -> list[Session]:
        with self._lock:
            return [
                s
                for s in self._sessions.values()
                if s.replica_id == replica_id
            ]

    def stats(self) -> dict:
        with self._lock:
            return {
                "sessions": len(self._sessions),
                "evicted": self.evicted,
                "by_id": {
                    sid: s.to_dict() for sid, s in self._sessions.items()
                },
            }


def parse_session_path(path: str) -> tuple[str, str] | None:
    """`/v1/session/<sid>/frame` -> (sid, verb); None when the path is
    not a session route."""
    if not path.startswith(SESSION_PATH_PREFIX):
        return None
    rest = path[len(SESSION_PATH_PREFIX):]
    sid, sep, verb = rest.partition("/")
    if not sid or not sep or verb != "frame":
        return None
    return sid, verb
