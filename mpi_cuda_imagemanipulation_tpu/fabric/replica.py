"""One replica worker — the existing serve stack as a supervised process.

`python -m mpi_cuda_imagemanipulation_tpu.fabric.replica --replica-id r0
--router http://host:port ...` stands up exactly the PR 2-6 serving stack
(ServeApp: scheduler + async engine + pre-warmed shape-bucket compile
cache + HTTP Server) on `--port 0` (kernel-assigned, race-free) and
pushes heartbeats to the router, which learns the bound port from the
first beat — the supervisor never has to guess ports.

The heartbeat payload is assembled here from the stack's own state:
health machine state, admission-queue fill, "HxW" buckets whose dispatch
breaker is open (BreakerBoard.open_keys), and the warm-affinity signal
(the compile cache's warmed bucket set, serve/cache.warm_buckets).

SIGTERM drains gracefully: admission stops, queued + in-flight work
flushes under `--drain-deadline-s`, the trace buffer exports (so a
drained replica's spans still join the router's on trace id), then exit
0. A SIGKILL (the churn test / a real OOM) skips all of that — which is
precisely what the router's staleness window, per-replica breaker and
rerouting retries exist to absorb.

Two more ways out, both graceful:

  * **drain ack** — the router's heartbeat ack carries `drain: true`
    when the autoscaler marked this replica for scale-down: the health
    machine flips to `draining` (admission refused, /v1/process answers
    503 + Retry-After), in-flight work flushes, and the beats keep
    flowing so the autoscaler can watch the queue empty before SIGTERM.
  * **preemption notice** — SIGUSR1 (the spot/maintenance eviction
    stand-in) or a `replica.preempt` failpoint hit: drain as above, dump
    the `preempt` flight-recorder artifact (the ring still holds the
    serving-time facts the post-mortem needs), exit `PREEMPT_EXIT_CODE`
    so the supervisor replaces immediately instead of backing off.

This module is also importable: `ReplicaRuntime` runs the same wiring
in-process for tests that don't need process isolation.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time

from mpi_cuda_imagemanipulation_tpu.fabric.control import (
    PREEMPT_EXIT_CODE,
    Heartbeat,
    HeartbeatSender,
)
from mpi_cuda_imagemanipulation_tpu.graph.systolic import ENV_SYSTOLIC
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger


class ReplicaRuntime:
    """Server + HeartbeatSender for one replica id, embeddable in-process
    (tests) or driven by main() as a worker process."""

    def __init__(
        self,
        replica_id: str,
        router_url: str,
        serve_config,
        *,
        host: str = "",
        port: int = 0,
        heartbeat_s: float | None = None,
    ):
        from mpi_cuda_imagemanipulation_tpu.obs.fleet import DeltaSource
        from mpi_cuda_imagemanipulation_tpu.serve.server import Server

        self.replica_id = replica_id
        self.router_url = router_url
        # incarnation: unique per construction, so the router can tell a
        # restart from a continuation and reset the replica's breaker
        self.incarnation = f"{os.getpid():x}-{time.time_ns():x}"
        # set by a preemption notice (SIGUSR1 / replica.preempt
        # failpoint); main() watches it next to the SIGTERM event
        self.preempted = threading.Event()
        self.server = Server(serve_config, host, port)
        # metrics federation (obs/fleet.py): every heartbeat carries the
        # compact delta of this replica's registries; the router's ack
        # advances the baseline (or asks for a full resync)
        self.delta_source = DeltaSource(self.server.app.fleet_registries())
        self.sender = HeartbeatSender(
            router_url,
            self._collect,
            interval_s=heartbeat_s,
            on_ack=self._on_heartbeat_ack,
        )

    def _collect(self, seq: int) -> Heartbeat:
        app = self.server.app
        try:
            # a hit is a PREEMPTION NOTICE, not a dropped beat: the beat
            # still goes out (the router should see the drain coming)
            failpoints.maybe_fail(
                "replica.preempt", replica=self.replica_id, seq=seq
            )
        except failpoints.FailpointError:
            self.preempted.set()
        return Heartbeat(
            replica_id=self.replica_id,
            addr="127.0.0.1",
            port=self.server.address[1] if self.server.httpd else 0,
            pid=os.getpid(),
            incarnation=self.incarnation,
            state=app.health.state,
            queued=app.metrics.queued,
            queue_depth=app.config.queue_depth,
            breaker_open=[
                f"{k[0]}x{k[1]}" for k in app.breakers.open_keys()
            ],
            warm_buckets=app.cache.warm_buckets(),
            seq=seq,
            sent_unix_s=time.time(),
            metrics=self.delta_source.delta(),
            pipelines=app.graph_pipeline_ids(),
            systolic=app.config.systolic,
        )

    def _on_heartbeat_ack(self, hb: Heartbeat, ack: dict) -> None:
        if ack.get("drain"):
            # the autoscaler marked us for scale-down: stop admitting,
            # keep serving what's queued, keep beating so the router can
            # watch the queue empty before the SIGTERM arrives
            self.begin_drain()
        if ack.get("resync"):
            # router baseline mismatch (restart / missed epoch): next
            # beat carries a full snapshot
            self.delta_source.force_full()
        elif hb.metrics is not None:
            self.delta_source.ack(hb.metrics["seq"])

    def begin_drain(self) -> None:
        """Drain-before-kill step on the replica: health -> draining
        (admission refused by the HTTP front end), dispatch keeps
        running so in-flight + queued work flushes. Idempotent — every
        subsequent ack carries the flag again."""
        from mpi_cuda_imagemanipulation_tpu.resilience.health import (
            DEGRADED,
            DRAINING,
            SERVING,
        )

        health = self.server.app.health
        if health.state in (SERVING, DEGRADED):
            health.to(DRAINING)
            get_logger().info(
                "replica %s: drain requested by router; admission stopped",
                self.replica_id,
            )

    def start(self) -> "ReplicaRuntime":
        # warmup + socket first: the first heartbeat must carry the real
        # port and a state the router can act on
        self.server.start()
        self.sender.start()
        return self

    def close(self, *, drain: bool = True, deadline_s: float = 30.0) -> None:
        self.sender.stop()
        self.server.close(drain=drain, deadline_s=deadline_s)

    def __enter__(self) -> "ReplicaRuntime":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mcim-fabric-replica",
        description="one fabric replica worker (spawned by the supervisor)",
    )
    p.add_argument("--replica-id", required=True)
    p.add_argument("--router", required=True, help="router base URL")
    p.add_argument("--ops", default="grayscale,contrast:3.5,emboss:3")
    p.add_argument("--buckets", default="512,1024,2048,4096")
    p.add_argument("--channels", default="1,3")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-delay-ms", type=float, default=5.0)
    p.add_argument("--queue-depth", type=int, default=64)
    p.add_argument("--impl", default="xla", choices=("auto", "xla", "mxu"))
    # the canary deploy path flips this per replica (plan-mode config
    # flips are the gate's canonical workload)
    p.add_argument("--plan", default="auto")
    # pod-level systolic execution (graph/systolic.py): accept placed
    # stage ranges + /v1/systolic hops; advertised in every heartbeat
    p.add_argument(
        "--systolic",
        action="store_true",
        default=env_registry.get_bool(ENV_SYSTOLIC),
    )
    p.add_argument("--host", default="")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--heartbeat-s", type=float, default=None)
    p.add_argument("--drain-deadline-s", type=float, default=30.0)
    p.add_argument("--trace-out", default=None)
    p.add_argument("--trace-sample", type=float, default=None)
    return p


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    # the worker inherits JAX_PLATFORMS / MCIM_FAILPOINTS / MCIM_TRACE_*
    # from the supervisor's env (per-replica overrides ride extra_env)
    from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
    from mpi_cuda_imagemanipulation_tpu.serve.bucketing import parse_buckets
    from mpi_cuda_imagemanipulation_tpu.serve.server import ServeConfig

    log = get_logger()
    if args.trace_out or args.trace_sample is not None:
        obs_trace.configure(
            sample=1.0 if args.trace_sample is None else args.trace_sample
        )
    else:
        obs_trace.configure_from_env()
    channels = tuple(
        sorted({int(c) for c in args.channels.split(",") if c.strip()})
    )
    cfg = ServeConfig(
        ops=args.ops,
        buckets=parse_buckets(args.buckets),
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        queue_depth=args.queue_depth,
        channels=channels,
        backend="xla" if args.impl == "auto" else args.impl,
        plan=args.plan,
        systolic=args.systolic,
    )
    rt = ReplicaRuntime(
        args.replica_id,
        args.router,
        cfg,
        host=args.host,
        port=args.port,
        heartbeat_s=args.heartbeat_s,
    )
    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        log.info(
            "replica %s: signal %s, draining (deadline %.0fs)",
            args.replica_id, signal.Signals(signum).name,
            args.drain_deadline_s,
        )
        stop_evt.set()

    def _on_preempt(signum, frame):
        log.warning(
            "replica %s: SIGUSR1 preemption notice — draining for "
            "replacement", args.replica_id,
        )
        rt.preempted.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    # the spot/maintenance eviction stand-in: a real deployment's
    # preemption watcher delivers exactly this kind of early notice
    signal.signal(signal.SIGUSR1, _on_preempt)
    rt.start()
    log.info(
        "replica %s serving on port %d (router %s, heartbeat %.2fs)",
        args.replica_id, rt.server.address[1], args.router,
        rt.sender.interval_s,
    )
    while not stop_evt.wait(0.1):
        if rt.preempted.is_set():
            break
    preempted = rt.preempted.is_set() and not stop_evt.is_set()
    rt.close(drain=True, deadline_s=args.drain_deadline_s)
    # flight recorder (obs/recorder.py): both exits are dump triggers —
    # the ring still holds the serving-time facts (hot buckets, breaker
    # transitions, failpoint hits) plus the drain itself. A preemption
    # writes its OWN trigger so the post-mortem names the eviction.
    from mpi_cuda_imagemanipulation_tpu.obs import recorder

    if preempted:
        dump_path = recorder.dump(
            "preempt", extra={"replica_id": args.replica_id}
        )
    else:
        dump_path = recorder.dump(
            "sigterm_drain", extra={"replica_id": args.replica_id}
        )
    if dump_path:
        log.info("replica %s recorder dump -> %s", args.replica_id, dump_path)
    if args.trace_out:
        n = obs_trace.export(args.trace_out)
        log.info(
            "replica %s trace: %d events -> %s",
            args.replica_id, n, args.trace_out,
        )
    return PREEMPT_EXIT_CODE if preempted else 0


if __name__ == "__main__":
    sys.exit(main())
