"""Elastic control loop — replica count follows load, scale-down drains.

The pod was static: `--replicas N` at launch was N forever, however the
offered load moved. This loop closes the control circuit that PR 11's
signal plane opened: every tick it reads the signals the router already
holds — per-replica queue-fill fraction (heartbeats), the idle-replica
fraction (queued == 0, the device-idle proxy a heartbeat can carry), and
the federated e2e p99 (obs/fleet.py) — and moves the replica set between
`MCIM_FABRIC_MIN_REPLICAS` and `MCIM_FABRIC_MAX_REPLICAS`.

Hysteresis, not reflexes: a signal must persist for
`MCIM_FABRIC_SCALE_SUSTAIN_S` before the loop acts, every action starts
a `MCIM_FABRIC_SCALE_COOLDOWN_S` quiet period, and scale-up and
scale-down thresholds are separated (`SCALE_UP_FRAC` vs
`SCALE_DOWN_FRAC`) so the loop cannot oscillate on the boundary.

Scale-up is cheap: spawn one replica (the supervisor owns the process;
warmup + the first heartbeat make it routable). Scale-down is the part
that must not drop work — **drain-before-kill**:

    1. pick the victim (fewest warm buckets, then least queued — the
       cheapest affinity loss) and mark it draining ON THE ROUTER: new
       traffic stops immediately, and the next heartbeat ack tells the
       replica, which flips its health machine to `draining` (admission
       refused end to end).
    2. wait for the victim's heartbeat to report `draining` with an
       EMPTY queue — in-flight work finishes on the replica that
       admitted it; nothing is rerouted mid-request.
    3. only then SIGTERM (`scale_down` callback -> supervisor.remove);
       a victim that never empties is SIGTERMed at
       `MCIM_FABRIC_SCALE_DRAIN_DEADLINE_S` — the replica's own drain
       deadline still flushes what it holds.

The victim's warm buckets remap by the existing rendezvous hash the
moment it stops being routable; live video sessions bound to it replay
their journal tails to the new winner (fabric/session.py). Every action
increments `mcim_fabric_scale_events_total{direction}` and writes an
`autoscale` flight-recorder dump carrying the signals that drove it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from mpi_cuda_imagemanipulation_tpu.fabric import canary as fabric_canary
from mpi_cuda_imagemanipulation_tpu.obs import recorder as flight_recorder
from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

ENV_MIN_REPLICAS = "MCIM_FABRIC_MIN_REPLICAS"
ENV_MAX_REPLICAS = "MCIM_FABRIC_MAX_REPLICAS"
ENV_UP_FRAC = "MCIM_FABRIC_SCALE_UP_FRAC"
ENV_DOWN_FRAC = "MCIM_FABRIC_SCALE_DOWN_FRAC"
ENV_SUSTAIN_S = "MCIM_FABRIC_SCALE_SUSTAIN_S"
ENV_COOLDOWN_S = "MCIM_FABRIC_SCALE_COOLDOWN_S"
ENV_TICK_S = "MCIM_FABRIC_SCALE_TICK_S"
ENV_P99_TARGET_S = "MCIM_FABRIC_SCALE_P99_TARGET_S"
ENV_DRAIN_DEADLINE_S = "MCIM_FABRIC_SCALE_DRAIN_DEADLINE_S"


class AutoscalerConfig:
    """Resolved knobs (None falls back to the MCIM_FABRIC_* env)."""

    def __init__(
        self,
        *,
        min_replicas: int | None = None,
        max_replicas: int | None = None,
        up_frac: float | None = None,
        down_frac: float | None = None,
        sustain_s: float | None = None,
        cooldown_s: float | None = None,
        tick_s: float | None = None,
        p99_target_s: float | None = None,
        drain_deadline_s: float | None = None,
    ):
        def _f(v, name):
            return float(env_registry.get(name)) if v is None else float(v)

        self.min_replicas = (
            int(env_registry.get(ENV_MIN_REPLICAS))
            if min_replicas is None
            else int(min_replicas)
        )
        self.max_replicas = (
            int(env_registry.get(ENV_MAX_REPLICAS))
            if max_replicas is None
            else int(max_replicas)
        )
        self.up_frac = _f(up_frac, ENV_UP_FRAC)
        self.down_frac = _f(down_frac, ENV_DOWN_FRAC)
        self.sustain_s = _f(sustain_s, ENV_SUSTAIN_S)
        self.cooldown_s = _f(cooldown_s, ENV_COOLDOWN_S)
        self.tick_s = _f(tick_s, ENV_TICK_S)
        self.p99_target_s = (
            env_registry.get_float(ENV_P99_TARGET_S)
            if p99_target_s is None
            else float(p99_target_s)
        )
        self.drain_deadline_s = _f(drain_deadline_s, ENV_DRAIN_DEADLINE_S)
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"bad replica bounds [{self.min_replicas}, "
                f"{self.max_replicas}]"
            )


class Autoscaler:
    """The loop. `scale_up()` must spawn one replica and return its id;
    `scale_down(rid)` must SIGTERM + forget a (drained) replica. Both
    are the Fabric's; the loop itself only reads router state and holds
    the drain state machine. `tick(now)` is callable directly with a
    fake clock — the thread is just tick-on-a-timer."""

    def __init__(
        self,
        router,
        *,
        scale_up: Callable[[], str],
        scale_down: Callable[[str], None],
        live_count: Callable[[], int] | None = None,
        config: AutoscalerConfig | None = None,
        registry: Registry | None = None,
        clock=time.monotonic,
    ):
        self.router = router
        self.config = config or AutoscalerConfig()
        self._scale_up = scale_up
        self._scale_down = scale_down
        # how many replicas EXIST (supervisor view) — routable undercounts
        # during warmup, and a loop that counts only routable replicas
        # would over-spawn while the first ones are still compiling
        self._live_count = live_count
        self._clock = clock
        self._lock = threading.Lock()
        self._up_since: float | None = None
        self._down_since: float | None = None
        self._last_action: float = -1e18
        self.target = self.config.min_replicas
        # drain in flight: (rid, marked_at) — one at a time, on purpose:
        # parallel drains under a falling load could empty the pod
        self.draining: tuple[str, float] | None = None
        self.events: list[dict] = []  # bounded action history (/stats)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._log = get_logger()
        r = registry or Registry()
        self._m_events = r.counter(
            "mcim_fabric_scale_events_total",
            "Autoscaler actions by direction (up/down).",
            labels=("direction",),
        )
        r.gauge(
            "mcim_fabric_scale_target_replicas",
            "Replica count the autoscaler is currently steering toward.",
            fn=lambda: float(self.target),
        )
        r.gauge(
            "mcim_fabric_scale_draining",
            "1 while a scale-down drain is in flight.",
            fn=lambda: 1.0 if self.draining is not None else 0.0,
        )

    # -- signals -------------------------------------------------------------

    def signals(self) -> dict:
        """The tick's inputs, from state the router already holds: mean
        queue-fill and idle fraction over fresh routable replicas, the
        federated p99, and the current live count (routable + the one
        mid-drain, which still owns in-flight work)."""
        views = self.router._routable()
        if self._live_count is not None:
            n_live = self._live_count()
        else:
            n_live = len(views) + (1 if self.draining is not None else 0)
        fills = [v.load_frac() for v in views]
        idle = sum(1 for v in views if v.hb.queued == 0)
        p99 = None
        if self.config.p99_target_s is not None:
            try:
                p99 = self.router.fleet_p99().get("p99_s")
            except Exception:  # federation gap: queue fill still steers
                p99 = None
        return {
            "replicas": n_live,
            "routable": len(views),
            "queue_fill": sum(fills) / len(fills) if fills else 0.0,
            "idle_frac": idle / len(views) if views else 0.0,
            "p99_s": p99,
        }

    # -- the loop ------------------------------------------------------------

    def tick(self, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            if self.draining is not None:
                self._check_drain(now)
                return
            sig = self.signals()
            n = sig["replicas"]
            cfg = self.config
            # bounds enforcement needs no hysteresis: below the floor is
            # an outage-shaped state, not a pressure signal
            if n < cfg.min_replicas:
                self._act_up(now, sig, reason="below min_replicas")
                return
            up = sig["routable"] > 0 and (
                sig["queue_fill"] >= cfg.up_frac
                or (
                    cfg.p99_target_s is not None
                    and sig["p99_s"] is not None
                    and sig["p99_s"] >= cfg.p99_target_s
                )
            )
            gate = getattr(self.router, "canary", None)
            down = (
                sig["routable"] > 0
                # only shrink on a COMPLETE picture: a replica that is
                # warming up or heartbeat-gapped makes the routable set
                # unrepresentative, and "the replicas I can see are
                # idle" is not "the pod is idle"
                and sig["routable"] >= sig["replicas"]
                and sig["queue_fill"] <= cfg.down_frac
                and sig["idle_frac"] >= 0.5
                # no membership churn under an active flip: draining a
                # replica mid-canary would skew the lane comparison (and
                # could drain the canary itself)
                and (gate is None or gate.state != fabric_canary.CANARY)
            )
            self._up_since = (
                (self._up_since or now) if up else None
            )
            self._down_since = (
                (self._down_since or now) if down else None
            )
            if now - self._last_action < cfg.cooldown_s:
                return
            if (
                up
                and n < cfg.max_replicas
                and now - self._up_since >= cfg.sustain_s
            ):
                self._act_up(now, sig, reason="sustained pressure")
            elif (
                down
                and n > cfg.min_replicas
                and now - self._down_since >= cfg.sustain_s
            ):
                self._act_down(now, sig)

    def _act_up(self, now: float, sig: dict, *, reason: str) -> None:
        rid = self._scale_up()
        self.target = sig["replicas"] + 1
        self._last_action = now
        self._up_since = self._down_since = None
        self._record("up", rid, now, sig, reason)

    def _act_down(self, now: float, sig: dict) -> None:
        victim = self._pick_victim()
        if victim is None:
            return
        self.router.mark_draining(victim)
        self.draining = (victim, now)
        self.target = sig["replicas"] - 1
        self._last_action = now
        self._up_since = self._down_since = None
        self._log.info(
            "autoscale: draining %s (queue_fill %.2f, idle %.2f)",
            victim, sig["queue_fill"], sig["idle_frac"],
        )

    def _pick_victim(self) -> str | None:
        """The cheapest replica to lose: fewest warm buckets (smallest
        affinity remap), then least queued, then highest id (so r0, the
        seed replica, goes last — deterministic for tests)."""
        views = self.router._routable()
        if not views:
            return None
        return min(
            views,
            key=lambda v: (
                len(v.hb.warm_buckets),
                v.hb.queued,
                # highest id first among ties
                tuple(-ord(c) for c in v.replica_id),
            ),
        ).replica_id

    def _check_drain(self, now: float) -> None:
        """Step 2/3 of drain-before-kill (lock held): SIGTERM only once
        the victim's heartbeat shows an empty queue in the draining
        state, or the drain deadline passes."""
        rid, since = self.draining
        view = self.router.table.get(rid)
        drained = (
            view is not None
            and view.hb.state == "draining"
            and view.hb.queued == 0
        )
        gone = view is None  # died mid-drain: nothing left to kill nicely
        expired = now - since >= self.config.drain_deadline_s
        if not (drained or gone or expired):
            return
        self.draining = None
        self._last_action = now
        try:
            self._scale_down(rid)
        finally:
            self.router.unmark_draining(rid)
        self._record(
            "down", rid, now, self.signals(),
            "drained" if drained else ("gone" if gone else "drain deadline"),
        )

    def _record(
        self, direction: str, rid: str, now: float, sig: dict, reason: str
    ) -> None:
        self._m_events.inc(direction=direction)
        event = {
            "direction": direction,
            "replica": rid,
            "reason": reason,
            "signals": sig,
            "t": now,
        }
        self.events.append(event)
        del self.events[:-50]
        self._log.info(
            "autoscale %s: %s (%s; queue_fill %.2f, idle %.2f, p99 %s)",
            direction, rid, reason, sig["queue_fill"], sig["idle_frac"],
            f"{sig['p99_s'] * 1e3:.1f}ms" if sig.get("p99_s") else "n/a",
        )
        # post-mortem-grade record: the router/supervisor ring holds the
        # heartbeats that produced these signals — freeze them with the
        # decision (rate-limited like every trigger)
        flight_recorder.dump("autoscale", extra=event)

    # -- lifecycle + introspection -------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="mcim-fabric-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                self._log.exception("autoscaler tick failed")
            self._stop.wait(self.config.tick_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def status(self) -> dict:
        with self._lock:
            return {
                "target": self.target,
                "bounds": [
                    self.config.min_replicas, self.config.max_replicas
                ],
                "draining": self.draining[0] if self.draining else None,
                "signals": self.signals(),
                "events": list(self.events[-10:]),
            }
