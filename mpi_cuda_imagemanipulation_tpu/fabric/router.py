"""Front-door router — health- and affinity-aware load balancing over
replica workers.

One `POST /v1/process` arrives; the router sniffs the image's shape
bucket from the PNG header (no full decode on the proxy path), orders the
live replicas, and proxies the body to the first that takes it:

  1. **sticky bucket affinity** — among fresh serving replicas, prefer
     those whose heartbeat lists the bucket as WARM in their compile
     cache; the rendezvous hash of (bucket, replica_id) picks the sticky
     target inside that pool (and is the consistent-hash fallback when
     nothing reports warm): every router instance picks the same target
     without coordination, one replica's death only remaps ITS buckets,
     and a RESTARTED replica reclaims them as soon as warmup re-reports
     the grid.
  2. **shed when the sticky target is unhealthy** — degraded state, a
     breaker open for this very bucket, or queue fill past
     MCIM_FABRIC_SHED_FRAC demotes the sticky pick behind the
     least-loaded healthy replica (draining/stale replicas are excluded
     outright).
  3. **reroute on failure** — a connection error, timeout, or 5xx/429
     moves to the next candidate (up to MCIM_FABRIC_FORWARD_ATTEMPTS
     distinct replicas); connection-class failures feed that replica's
     circuit breaker so a dead worker is routed around for the breaker
     window instead of eating a timeout per request. A replica restart
     (new heartbeat incarnation) resets its breaker.
  4. **503 + Retry-After only when NO replica is serving** — the fabric's
     equivalent of the scheduler's explicit shed: callers get a clear
     signal, never a hang.

Requests too large for every replica bucket take the optional MESH lane
(fabric/mesh.py): one jax.distributed row-sharded dispatch spanning hosts,
in the router process — big requests span the pod, small requests ride
data-parallel replicas.

Observability: every quantity is an `mcim_fabric_*` family on the
router's registry (`GET /metrics`), the router's root span propagates its
trace id to the replica via X-Trace-Id (the replica ADOPTS it — one trace
covers the full hop), and `router.forward` is a failpoint so rerouting is
testable without killing anything.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import http.client
import io as _io
import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from mpi_cuda_imagemanipulation_tpu.fabric import canary as fabric_canary
from mpi_cuda_imagemanipulation_tpu.fabric import session as fabric_session
from mpi_cuda_imagemanipulation_tpu.fabric.control import (
    HEARTBEAT_PATH,
    Heartbeat,
)
from mpi_cuda_imagemanipulation_tpu.federation import control as fed_control
from mpi_cuda_imagemanipulation_tpu.graph import systolic as graph_systolic
from mpi_cuda_imagemanipulation_tpu.obs import fleet as obs_fleet
from mpi_cuda_imagemanipulation_tpu.obs import metrics as obs_metrics
from mpi_cuda_imagemanipulation_tpu.obs import recorder as flight_recorder
from mpi_cuda_imagemanipulation_tpu.obs import slo as obs_slo
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry
from mpi_cuda_imagemanipulation_tpu.resilience import deadline as deadline_mod
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.resilience.breaker import BreakerBoard
from mpi_cuda_imagemanipulation_tpu.serve import bucketing
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

ENV_STALE_S = "MCIM_FABRIC_STALE_S"
ENV_FORWARD_TIMEOUT_S = "MCIM_FABRIC_FORWARD_TIMEOUT_S"
ENV_FORWARD_ATTEMPTS = "MCIM_FABRIC_FORWARD_ATTEMPTS"
ENV_SHED_FRAC = "MCIM_FABRIC_SHED_FRAC"

# replica states that may receive proxied traffic at all; "serving" alone
# qualifies for the sticky fast path (degraded = shed to least-loaded)
_ROUTABLE = ("serving", "degraded")

# HTTP status -> the bounded label set of mcim_fabric_requests_total
_STATUS_LABEL = {
    200: "ok", 400: "rejected", 422: "quarantined", 429: "overloaded",
    503: "unavailable", 504: "deadline_expired",
}

_PNG_MAGIC = b"\x89PNG\r\n\x1a\n"


class _ConnPool:
    """Keep-alive connection reuse per (addr, port): the proxy hot path
    must not pay a TCP handshake per forward. Connections come back to
    the pool only after a CLEAN full response; any error path closes and
    discards, so a half-read socket can never serve the next request."""

    def __init__(self, timeout_s: float, cap_per_target: int = 32):
        self.timeout_s = timeout_s
        self.cap = cap_per_target
        self._lock = threading.Lock()
        self._pools: dict[tuple[str, int], list] = {}

    def take(self, addr: str, port: int) -> http.client.HTTPConnection:
        with self._lock:
            pool = self._pools.get((addr, port))
            if pool:
                return pool.pop()
        return http.client.HTTPConnection(
            addr, port, timeout=self.timeout_s
        )

    def give(self, addr: str, port: int, conn) -> None:
        with self._lock:
            pool = self._pools.setdefault((addr, port), [])
            if len(pool) < self.cap:
                pool.append(conn)
                return
        conn.close()

    def close_all(self) -> None:
        with self._lock:
            conns = [c for pool in self._pools.values() for c in pool]
            self._pools.clear()
        for c in conns:
            c.close()


def _rendezvous_score(bucket: str, replica_id: str) -> int:
    """Deterministic cross-process score for consistent hashing (never
    builtins.hash — PYTHONHASHSEED would shuffle routing per process)."""
    import hashlib

    h = hashlib.blake2b(
        f"{bucket}|{replica_id}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big")


@dataclasses.dataclass
class ReplicaView:
    """The router's picture of one replica: the last heartbeat plus the
    router-side receive clock (freshness uses OUR clock — the wire
    timestamp would import cross-process clock skew)."""

    hb: Heartbeat
    last_seen: float  # router monotonic
    beats: int = 0

    @property
    def replica_id(self) -> str:
        return self.hb.replica_id

    def fresh(self, now: float, stale_s: float) -> bool:
        return now - self.last_seen <= stale_s

    def load_frac(self) -> float:
        depth = max(1, self.hb.queue_depth)
        return self.hb.queued / depth


class ReplicaTable:
    """Heartbeat-built replica registry. The lock guards only dict
    mutation; routing works on snapshot copies."""

    def __init__(self):
        self._lock = threading.Lock()
        self._replicas: dict[str, ReplicaView] = {}

    def observe(self, hb: Heartbeat, now: float) -> bool:
        """Fold one heartbeat in; returns True when this is a NEW
        incarnation of the replica id (first sight or restart)."""
        with self._lock:
            prev = self._replicas.get(hb.replica_id)
            new_inc = prev is None or prev.hb.incarnation != hb.incarnation
            beats = 1 if prev is None else prev.beats + 1
            self._replicas[hb.replica_id] = ReplicaView(
                hb=hb, last_seen=now, beats=beats
            )
            return new_inc

    def views(self) -> list[ReplicaView]:
        with self._lock:
            return list(self._replicas.values())

    def get(self, replica_id: str) -> ReplicaView | None:
        with self._lock:
            return self._replicas.get(replica_id)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    buckets: tuple[tuple[int, int], ...] = bucketing.DEFAULT_BUCKETS
    stale_s: float | None = None  # None: MCIM_FABRIC_STALE_S
    forward_timeout_s: float | None = None
    forward_attempts: int | None = None
    shed_frac: float | None = None
    # router-side per-replica breaker: trips fast (a dead replica costs a
    # connect timeout per probe) and resets fast (restarts should rejoin
    # within a breaker window, not a serving outage)
    breaker_threshold: int = 2
    breaker_reset_s: float = 3.0
    # SLO burn-rate engine (obs/slo.py) over the federated registries;
    # None fields fall back to their MCIM_SLO_* env defaults
    slo_specs: str | None = None
    slo_fast_s: float | None = None
    slo_slow_s: float | None = None
    slo_tick_s: float | None = None
    slo_burn_threshold: float | None = None
    # canary rollback gate knobs (fabric/canary.py); None fields fall
    # back to their MCIM_FABRIC_CANARY_* env defaults
    canary: fabric_canary.CanaryConfig | None = None
    # pod-level systolic execution (graph/systolic.py): stage-shard
    # eligible graph programs across systolic-advertising replicas
    systolic: bool = False
    # -- request lifecycle (resilience/deadline.py) ------------------------
    # retry-budget token bucket: deposit `frac` per accepted request,
    # withdraw 1 per retry/hedge; `reserve` covers cold-start failover.
    # None fields fall back to MCIM_RETRY_BUDGET_FRAC / _RESERVE
    retry_budget_frac: float | None = None
    retry_budget_reserve: float | None = None
    # hedged requests on the idempotent chain lane: a first attempt
    # still pending past hedge_delay_frac x (federated e2e p99) gets ONE
    # secondary forward to a different replica, first response wins;
    # hedges withdraw from the retry budget and are capped at
    # hedge_max_frac of accepted requests. delay frac 0 disables. None
    # fields fall back to MCIM_HEDGE_DELAY_FRAC / MCIM_HEDGE_MAX_FRAC
    hedge_delay_frac: float | None = None
    hedge_max_frac: float | None = None


class Router:
    """The front door. `start()` binds the HTTP listener; replicas
    register themselves by heartbeating `POST /control/heartbeat`.

        POST /v1/process        proxied to a replica (see module doc).
                                With X-MCIM-Pipeline/?pipeline=: the
                                graph lane — sticky on (tenant,
                                pipeline, bucket), headers forwarded,
                                stored specs re-pushed to replicas
                                whose heartbeat lacks the id
        POST /v1/pipelines      validate + store + broadcast a pipeline
                                spec to every routable replica (graph/)
        POST /v1/tenants        tenant QoS/quota config, same broadcast
        POST /v1/session/<sid>/frame
                                live video frame: sticky session routing
                                with journal-tail failover replay
                                (fabric/session.py)
        POST /control/heartbeat replica state push (fabric/control.py);
                                the ack carries drain/resync flags
        GET|POST /control/canary
                                canary gate status / deploy / abort
                                (fabric/canary.py)
        GET  /control/tune      tune controller status: current arm,
                                in-flight proposal, recent decisions
                                (tune/controller.py; Fabric tune=True)
        POST /control/profile   on-demand fleet profiling: relay a
                                rate-limited jax.profiler capture to one
                                replica under live traffic; the merged
                                host+device artifact path rides back
                                (obs/profile.capture_live)
        GET  /healthz           200 while >=1 routable fresh replica
        GET  /stats             replica table + routing counters (JSON)
        GET  /metrics           Prometheus exposition (mcim_fabric_*)
        GET  /slo               SLO burn-rate engine status (obs/slo.py)
    """

    def __init__(
        self,
        config: RouterConfig,
        *,
        registry: Registry | None = None,
        mesh_lane=None,
        clock=time.monotonic,
    ):
        self.config = config
        self.buckets = tuple(config.buckets)
        self.stale_s = (
            float(env_registry.get(ENV_STALE_S))
            if config.stale_s is None
            else config.stale_s
        )
        self.forward_timeout_s = (
            float(env_registry.get(ENV_FORWARD_TIMEOUT_S))
            if config.forward_timeout_s is None
            else config.forward_timeout_s
        )
        self.forward_attempts = (
            int(env_registry.get(ENV_FORWARD_ATTEMPTS))
            if config.forward_attempts is None
            else config.forward_attempts
        )
        self.shed_frac = (
            float(env_registry.get(ENV_SHED_FRAC))
            if config.shed_frac is None
            else config.shed_frac
        )
        self.table = ReplicaTable()
        self.breakers = BreakerBoard(
            failure_threshold=config.breaker_threshold,
            reset_timeout_s=config.breaker_reset_s,
        )
        # replicas the control plane is DRAINING (autoscaler scale-down):
        # routing stops here immediately, and the next heartbeat ack
        # carries drain=true so the replica stops admitting end to end
        self._draining: set[str] = set()
        self._draining_lock = threading.Lock()
        # canary rollback gate (fabric/canary.py); the Fabric wires the
        # deploy/rollback callbacks (it owns the replica processes)
        self.canary = fabric_canary.CanaryGate(config.canary, clock=clock)
        self.on_canary_deploy = None  # callable(flip: dict) -> replica_id
        self.on_canary_rollback = None  # callable(status: dict) -> None
        self._canary_rollback_handled = False
        # continuous autotuning (tune/controller.py); the Fabric wires a
        # TuneController here when started with tune=True — the router
        # only exposes its status (the controller drives canary_deploy
        # through the same hooks as an operator flip)
        self.tuner = None
        # live video sessions (fabric/session.py): sticky affinity +
        # journal-tail failover
        self.sessions = fabric_session.SessionTable()
        # pipeline-service state (graph/): specs registered THROUGH this
        # front door, keyed (tenant, pipeline id), plus tenant configs.
        # The router re-pushes a stored spec to any replica whose
        # heartbeat lacks the id before forwarding to it — so replica
        # restarts and late joiners reconverge without client retries.
        self._graph_lock = threading.Lock()
        self.graph_specs: dict[tuple[str, str], dict] = {}
        self.graph_tenants: dict[str, dict] = {}
        # (replica id, incarnation) -> tenants whose config this exact
        # process has received: tenant configs have no heartbeat echo
        # (unlike pipelines), so the re-push bookkeeping lives here — a
        # restart changes the incarnation and naturally re-pushes
        self._tenant_pushed: dict[tuple[str, str], set[str]] = {}
        # systolic lane state: compiled-program cache (compile_graph is
        # pure Python — cheap, but not per-request cheap) + the last
        # placement per pipeline for /stats
        self.systolic = config.systolic
        self.systolic_min_steps = int(
            env_registry.get(graph_systolic.ENV_MIN_STEPS)
        )
        self._systolic_programs: dict[tuple[str, str], object] = {}
        self._systolic_last: dict[str, dict] = {}
        # set by the Fabric when the elastic loop is armed (status only)
        self.autoscaler = None
        self.mesh_lane = mesh_lane
        # federation uplink (federation/): armed by federate() — this
        # router then represents its whole pod to a front door, pushing
        # pod-aggregate heartbeats and applying quota leases from acks
        self._fed_sender = None
        self._fed_pod_id: str | None = None
        self._fed_incarnation: str | None = None
        self._fed_source = None
        self._pool = _ConnPool(self.forward_timeout_s)
        self._clock = clock
        # request lifecycle (resilience/deadline.py): this tier's retry
        # budget + hedging knobs. The hedge worker pool is lazy — only
        # a router that actually hedges pays the threads.
        self.retry_budget = deadline_mod.RetryBudget(
            frac=(
                float(env_registry.get(deadline_mod.ENV_BUDGET_FRAC))
                if config.retry_budget_frac is None
                else config.retry_budget_frac
            ),
            reserve=(
                float(env_registry.get(deadline_mod.ENV_BUDGET_RESERVE))
                if config.retry_budget_reserve is None
                else config.retry_budget_reserve
            ),
        )
        self.hedge_delay_frac = (
            float(env_registry.get(deadline_mod.ENV_HEDGE_DELAY_FRAC))
            if config.hedge_delay_frac is None
            else config.hedge_delay_frac
        )
        self.hedge_max_frac = (
            float(env_registry.get(deadline_mod.ENV_HEDGE_MAX_FRAC))
            if config.hedge_max_frac is None
            else config.hedge_max_frac
        )
        self._hedge_lock = threading.Lock()
        self._hedge_pool = None
        self._hedges_fired = 0
        self._hedge_delay_cache: tuple[float, float | None] = (-1e18, None)
        self.registry = registry or Registry()
        # metrics federation (obs/fleet.py): per-replica registries fold
        # into this view via heartbeat deltas; staleness shares the
        # routing liveness window so "routable" and "counted" agree
        self.fleet = obs_fleet.FleetAggregator(
            stale_s=self.stale_s, clock=clock
        )
        self._fleet_scraped_at: dict[str, float] = {}
        # SLO burn-rate engine over the fleet view (obs/slo.py); the
        # ticker thread starts with the router
        self.slo = obs_slo.SLOEngine(
            obs_slo.parse_slo_specs(
                config.slo_specs
                if config.slo_specs is not None
                else env_registry.get(obs_slo.ENV_SPECS)
            ),
            obs_slo.fleet_slo_source(self.fleet.merged),
            fast_s=config.slo_fast_s,
            slow_s=config.slo_slow_s,
            tick_s=config.slo_tick_s,
            burn_threshold=config.slo_burn_threshold,
            registry=self.registry,
            clock=clock,
        )
        self._register_metrics()
        self.httpd: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._closed = False
        self._log = get_logger()

    # -- metrics -----------------------------------------------------------

    def _register_metrics(self) -> None:
        r = self.registry
        self._m_requests = r.counter(
            "mcim_fabric_requests_total",
            "Front-door requests by terminal status.",
            labels=("status",),
        )
        self._m_forwards = r.counter(
            "mcim_fabric_forwards_total",
            "Proxy attempts per replica, by outcome (ok/http_error/"
            "net_error).",
            labels=("replica", "outcome"),
        )
        self._m_retries = r.counter(
            "mcim_fabric_forward_retries_total",
            "Requests re-forwarded to another replica after a failed "
            "attempt (attempt 2+ each counts once).",
        )
        self._m_route = r.counter(
            "mcim_fabric_route_total",
            "Routing decisions by policy (sticky/least_loaded/mesh).",
            labels=("policy",),
        )
        self._m_heartbeats = r.counter(
            "mcim_fabric_heartbeats_total",
            "Heartbeats accepted per replica.",
            labels=("replica",),
        )
        self._m_forward_s = r.histogram(
            "mcim_fabric_forward_seconds",
            "Router->replica proxy time per successful attempt.",
        )
        # request-lifecycle accounting (resilience/deadline.py)
        self._m_deadline = deadline_mod.expired_counter(r)
        self._m_budget_denied = deadline_mod.budget_denied_counter(r)
        self._m_hedges = deadline_mod.hedge_counter(r)
        # -- pipeline service (graph/) --------------------------------------
        self._m_graph_pushes = r.counter(
            "mcim_fabric_graph_pushes_total",
            "Pipeline specs re-pushed to a replica whose heartbeat "
            "lacked the id (restart/late-join reconvergence).",
        )
        r.gauge(
            "mcim_fabric_graph_specs",
            "(tenant, pipeline) specs registered through this router.",
            fn=lambda: float(len(self.graph_specs)),
        )
        # -- pod-level systolic execution (graph/systolic.py) ---------------
        self._m_sys_requests = r.counter(
            "mcim_systolic_requests_total",
            "Graph requests dispatched on the stage-sharded lane, by "
            "terminal outcome (ok = final owner's response relayed; "
            "refused = the entry owner's own 4xx/shed relayed verbatim).",
            labels=("status",),
        )
        self._m_sys_placed = r.counter(
            "mcim_systolic_stages_placed_total",
            "Step ranges placed onto stage owners (one per owner per "
            "placed request).",
        )
        self._m_sys_fallbacks = r.counter(
            "mcim_systolic_fallbacks_total",
            "Graph requests answered on the pinned-replica lane "
            "instead, by reason (graph/systolic.FALLBACK_REASONS — a "
            "closed vocabulary enforced at the count_fallback choke "
            "point).",
            labels=("reason",),
        )
        # -- on-demand fleet profiling (obs/profile.capture_live) -----------
        self._m_profile = r.counter(
            "mcim_fabric_profile_captures_total",
            "On-demand replica profile captures relayed through the "
            "front door, by outcome (ok/rate_limited/error).",
            labels=("outcome",),
        )
        # -- canary rollback gate (fabric/canary.py) ------------------------
        self._m_canary = r.counter(
            "mcim_fabric_canary_requests_total",
            "Canary-gate outcomes by lane (canary/stable) and result "
            "(ok/bad).",
            labels=("lane", "result"),
        )
        self._m_canary_shadow = r.counter(
            "mcim_fabric_canary_shadow_total",
            "Shadow digest spot checks by result (match/mismatch).",
            labels=("result",),
        )
        self._m_canary_rollbacks = r.counter(
            "mcim_fabric_canary_rollbacks_total",
            "Config flips auto-reverted by the rollback gate.",
        )
        r.gauge(
            "mcim_fabric_canary_active",
            "1 while a canary flip is under evaluation.",
            fn=lambda: (
                1.0 if self.canary.state == fabric_canary.CANARY else 0.0
            ),
        )
        # -- live video sessions (fabric/session.py) ------------------------
        self._m_session_frames = r.counter(
            "mcim_fabric_session_frames_total",
            "Session frames through the front door by outcome "
            "(ok/unavailable/error).",
            labels=("outcome",),
        )
        self._m_session_failovers = r.counter(
            "mcim_fabric_session_failovers_total",
            "Live sessions rebound to a new replica with journal-tail "
            "replay after their replica died or drained.",
        )
        self._m_session_replayed = r.counter(
            "mcim_fabric_session_replayed_frames_total",
            "Journal-tail frames replayed to rebuild temporal rings on "
            "a replacement replica.",
        )
        r.gauge(
            "mcim_fabric_sessions_live",
            "Video sessions the router currently tracks.",
            fn=lambda: float(len(self.sessions.sessions())),
        )
        r.gauge(
            "mcim_fabric_replicas_draining",
            "Replicas the control plane is draining (routing stopped, "
            "SIGTERM pending on empty queue).",
            fn=lambda: float(len(self.draining_ids())),
        )
        r.gauge(
            "mcim_fabric_replica_serving",
            "1 when the replica is fresh and routable (serving/degraded), "
            "0 otherwise.",
            labels=("replica",),
            fn=self._serving_gauge,
        )
        r.gauge(
            "mcim_fabric_replica_queue_depth",
            "Last-heartbeat admission-queue fill per replica.",
            labels=("replica",),
            fn=lambda: {
                (v.replica_id,): float(v.hb.queued)
                for v in self.table.views()
            },
        )
        r.gauge(
            "mcim_fabric_replicas_routable",
            "Count of fresh serving/degraded replicas.",
            fn=lambda: float(len(self._routable())),
        )
        r.gauge(
            "mcim_fabric_breaker_open_events",
            "Cumulative router-side replica-breaker trips.",
            fn=lambda: float(self.breakers.snapshot()["open_events"]),
        )
        # -- fleet federation health (obs/fleet.py) -------------------------
        r.gauge(
            "mcim_fleet_replicas",
            "Replicas currently contributing to the federated view.",
            fn=lambda: float(len(self.fleet.fresh_ids())),
        )
        r.gauge(
            "mcim_fleet_snapshot_age_seconds",
            "Seconds since each replica's metrics snapshot last advanced.",
            labels=("replica",),
            fn=lambda: {
                (rid,): age for rid, age in self.fleet.ages().items()
            },
        )
        r.gauge(
            "mcim_fleet_applied_deltas",
            "Heartbeat metrics deltas folded into the fleet view.",
            fn=lambda: float(self.fleet.applied_deltas),
        )
        r.gauge(
            "mcim_fleet_full_syncs",
            "Full snapshots applied (first beats, resyncs, scrapes).",
            fn=lambda: float(self.fleet.full_syncs),
        )
        r.gauge(
            "mcim_fleet_resyncs",
            "Heartbeat deltas refused for a stale baseline (the ack asked "
            "the replica to resend full).",
            fn=lambda: float(self.fleet.resyncs),
        )

    def _serving_gauge(self) -> dict:
        now = self._clock()
        return {
            (v.replica_id,): (
                1.0
                if v.fresh(now, self.stale_s) and v.hb.state in _ROUTABLE
                else 0.0
            )
            for v in self.table.views()
        }

    # -- drain control (autoscaler scale-down) -----------------------------

    def mark_draining(self, replica_id: str) -> None:
        """Stop routing to this replica NOW; its next heartbeat ack
        carries drain=true so the replica flips its health machine to
        draining (admission refused end to end). Its live sessions
        rebind with tail replay on their next frame."""
        with self._draining_lock:
            self._draining.add(replica_id)
        self._log.info("draining %s: routing stopped", replica_id)

    def unmark_draining(self, replica_id: str) -> None:
        with self._draining_lock:
            self._draining.discard(replica_id)

    def draining_ids(self) -> list[str]:
        with self._draining_lock:
            return sorted(self._draining)

    def _is_draining(self, replica_id: str) -> bool:
        with self._draining_lock:
            return replica_id in self._draining

    # -- routing policy ----------------------------------------------------

    def _routable(self) -> list[ReplicaView]:
        now = self._clock()
        with self._draining_lock:
            draining = set(self._draining)
        return [
            v
            for v in self.table.views()
            if v.fresh(now, self.stale_s)
            and v.hb.state in _ROUTABLE
            and v.replica_id not in draining
        ]

    def route(
        self, bucket: str, *, affinity_key: str | None = None,
        prefer_warm: bool = True,
    ) -> tuple[list[ReplicaView], str]:
        """Ordered forward candidates for a "HxW" bucket + the policy
        label. Pure over the current table snapshot (unit-testable).

        `affinity_key` overrides the rendezvous-hash key: graph requests
        sticky on (tenant, pipeline id, bucket) so one tenant-pipeline's
        jitted executables concentrate on one replica per bucket
        (`prefer_warm=False` there — chain-cache warmth says nothing
        about graph executables)."""
        live = self._routable()
        if not live:
            return [], "none"
        warm = (
            [v for v in live if bucket in v.hb.warm_buckets]
            if prefer_warm
            else []
        )
        pool = warm or live
        sticky = max(
            pool,
            key=lambda v: _rendezvous_score(
                affinity_key or bucket, v.replica_id
            ),
        )
        sticky_ok = (
            sticky.hb.state == "serving"
            and bucket not in sticky.hb.breaker_open
            and sticky.load_frac() < self.shed_frac
        )
        rest = sorted(
            (v for v in live if v.replica_id != sticky.replica_id),
            key=lambda v: (
                # a replica with THIS bucket's breaker open or in degraded
                # state is a last resort, then least-loaded first
                bucket in v.hb.breaker_open,
                v.hb.state != "serving",
                v.load_frac(),
            ),
        )
        if sticky_ok:
            return [sticky] + rest, "sticky"
        return rest + [sticky], "least_loaded"

    # -- request path ------------------------------------------------------

    @staticmethod
    def _sniff_dims(data: bytes) -> tuple[int, int]:
        """(h, w) from the image header only — the proxy path must not pay
        a full decode (or even a PIL import) for routing. PNG is the wire
        format, so its fixed-offset IHDR is read directly; anything else
        falls back to PIL's lazy header parse."""
        if data[:8] == _PNG_MAGIC and data[12:16] == b"IHDR":
            w = int.from_bytes(data[16:20], "big")
            h = int.from_bytes(data[20:24], "big")
            if h > 0 and w > 0:
                return h, w
        from PIL import Image

        with Image.open(_io.BytesIO(data)) as im:
            w, h = im.size
        return h, w

    def handle_process(
        self, body: bytes, headers, query: dict | None = None
    ) -> tuple[int, str, bytes, list[tuple[str, str]]]:
        """One front-door request -> (status, content_type, body, extra
        headers). Runs on the HTTP handler thread. A request carrying a
        pipeline id (X-MCIM-Pipeline header or ?pipeline=) takes the
        graph lane: sticky affinity on (tenant, pipeline, bucket), the
        tenant + pipeline headers forwarded verbatim, and a stored-spec
        re-push to any replica whose heartbeat lacks the id."""
        from mpi_cuda_imagemanipulation_tpu.graph.service import (
            HDR_PIPELINE,
            HDR_TENANT,
        )

        q = query or {}

        def _pick(hname: str, qname: str) -> str:
            v = headers.get(hname)
            if v:
                return v
            vals = q.get(qname)
            return vals[0] if vals else ""

        tenant = _pick(HDR_TENANT, "tenant") or "default"
        pipeline = _pick(HDR_PIPELINE, "pipeline")
        # the federation identity thread: a front door stamps X-Fed-Pod
        # on its forward; the pod router relays it replica-deep so the
        # serving process can echo which pod carried the request
        fed_pod = headers.get(fed_control.HDR_FED_POD) or ""
        # the deadline chain (resilience/deadline.py): re-anchor the
        # remaining budget from the wire on this process's clock; a
        # request already dead answers 504 before any replica burns on it
        dl = deadline_mod.from_headers(headers, clock=self._clock)
        if dl is not None and dl.expired():
            deadline_mod.count_expired(self._m_deadline, "router")
            self._m_requests.inc(status="deadline_expired")
            return _json_response(
                504, deadline_mod.expired_response_body()
            )
        try:
            h, w = self._sniff_dims(body)
        except Exception as e:
            self._m_requests.inc(status="rejected")
            return _json_response(400, {"error": f"undecodable image: {e}"})
        if pipeline:
            return self._handle_graph_process(
                body, tenant, pipeline, h, w, fed_pod=fed_pod, deadline=dl
            )
        picked = bucketing.pick_bucket(h, w, self.buckets)
        if picked is None:
            if self.mesh_lane is not None:
                return self._dispatch_mesh(body, h, w)
            self._m_requests.inc(status="rejected")
            big = self.buckets[-1]
            return _json_response(
                400,
                {
                    "error": (
                        f"image {h}x{w} exceeds the largest bucket "
                        f"{big[0]}x{big[1]} and no mesh lane is configured"
                    )
                },
            )
        bucket = f"{picked[0]}x{picked[1]}"
        candidates, policy = self.route(bucket)
        if not candidates:
            self._m_requests.inc(status="unavailable")
            return _json_response(
                503,
                {"error": "no replica is serving", "status": "unavailable"},
                extra=[("Retry-After", "1")],
            )
        mode, canary_view, candidates = self._apply_canary(candidates)
        if not candidates and mode != "shadow":
            # the canary slice never strands a request: with no stable
            # replica left the canary itself is the only door
            candidates = [canary_view] if canary_view is not None else []
        self._m_route.inc(policy=policy)
        root = obs_trace.start_trace(
            "fabric.request", h=h, w=w, bucket=bucket, policy=policy
        )
        self.retry_budget.deposit()
        if mode == "shadow":
            code, ctype, out, extra = self._shadow_forward(
                root, bucket, body, canary_view, candidates
            )
        else:
            code, ctype, out, extra = self._forward_with_retries(
                root, bucket, body, candidates,
                extra_headers=(
                    ((fed_control.HDR_FED_POD, fed_pod),) if fed_pod else ()
                ),
                deadline=dl,
                # the chain lane is idempotent by construction (pure
                # image in -> image out), so it may hedge the tail
                hedge=True,
            )
        self._m_requests.inc(
            status=_STATUS_LABEL.get(code, "error" if code >= 500 else "ok")
        )
        root.set(status=code)
        root.end()
        if root.trace_id:
            extra = extra + [("X-Trace-Id", root.trace_id)]
        return code, ctype, out, extra

    def _forward_with_retries(
        self,
        root,
        bucket: str,
        body: bytes,
        candidates: list[ReplicaView],
        *,
        extra_headers: tuple[tuple[str, str], ...] = (),
        before_forward=None,
        admission_shed_is_final: bool = False,
        deadline: deadline_mod.Deadline | None = None,
        hedge: bool = False,
    ) -> tuple[int, str, bytes, list[tuple[str, str]]]:
        """Walk the replica candidates until one answers. Deadline-honest
        and retry-bounded (resilience/deadline.py): the remaining budget
        is re-checked before every attempt (an expired request answers
        504 HERE, never burns a replica), each forward carries the
        remainder on the wire, attempt 2+ must withdraw from the retry
        budget (a refused withdrawal gives up with the best answer so
        far), and — on the idempotent chain lane (`hedge=True`) — a
        first attempt still pending past the p99-based hedge delay gets
        one secondary forward to the next candidate, first response
        wins."""
        attempts = 0
        last: tuple[int, str, bytes, list] | None = None
        hedge_delay = self._hedge_delay_s() if hedge else None
        for ci, view in enumerate(candidates):
            if attempts >= self.forward_attempts:
                break
            if deadline is not None and deadline.expired():
                deadline_mod.count_expired(self._m_deadline, "router")
                self._m_requests.inc(status="deadline_expired")
                return _json_response(
                    504, deadline_mod.expired_response_body()
                )
            rid = view.replica_id
            breaker = self.breakers.get(rid)
            if not breaker.allow():
                continue  # routed around for the breaker window
            attempts += 1
            if attempts > 1:
                if not self.retry_budget.try_withdraw():
                    deadline_mod.count_budget_denied(
                        self._m_budget_denied, "router"
                    )
                    break  # give up with the best answer so far
                self._m_retries.inc()
                obs_trace.event(
                    "fabric.retry", parent=root.context(),
                    attempt=attempts, replica=rid,
                )
            fwd_extra = extra_headers
            if deadline is not None:
                # remaining-budget form, recomputed PER ATTEMPT so the
                # wire always carries what is actually left
                fwd_extra = tuple(fwd_extra) + (
                    (deadline_mod.HEADER, deadline.header_value()),
                )
            t0 = self._clock()
            try:
                with obs_trace.span(
                    "fabric.forward", parent=root.context(), replica=rid
                ):
                    failpoints.maybe_fail(
                        "router.forward", replica=rid, attempt=attempts
                    )
                    if before_forward is not None:
                        # graph lane: converge the replica's pipeline
                        # registry first (spec re-push); a push failure
                        # is a net-error-class miss — next candidate
                        before_forward(view)
                    if hedge_delay is not None and attempts == 1:
                        (
                            code, ctype, out, fwd_hdrs, rid, extra_fwds,
                        ) = self._forward_maybe_hedged(
                            view, candidates[ci + 1:], body,
                            root.trace_id, fwd_extra, hedge_delay,
                        )
                        attempts += extra_fwds
                        breaker = self.breakers.get(rid)
                    else:
                        code, ctype, out, fwd_hdrs = self._forward_once(
                            view, body, root.trace_id,
                            extra_headers=fwd_extra,
                        )
            except Exception as e:
                # connection-class failure: the replica is gone or wedged —
                # feed its breaker and move on to the next candidate
                breaker.on_failure()
                self._maybe_breaker_dump(rid, breaker)
                self._m_forwards.inc(replica=rid, outcome="net_error")
                self._canary_record(rid, False)
                self._log.warning(
                    "forward to %s failed (%s: %s)",
                    rid, type(e).__name__, str(e)[:120],
                )
                continue
            # a 422 from the CANARY replica is a flip signal, not a
            # poison-request verdict: the flip itself may be what breaks
            # the request, so the gate counts it bad and the client gets
            # the stable answer instead (stable 422s stay final — the
            # quarantine contract is per-request there)
            canary_quarantine = (
                code == 422
                and self.canary.state == fabric_canary.CANARY
                and rid == self.canary.replica_id
            )
            if (
                admission_shed_is_final
                and code == 503
                and _is_admission_shed(out)
            ):
                # a tenant-level admission verdict (quota window / QoS
                # ladder — the graph lane's {"status": "shed"} body):
                # rerouting it to a sibling would multiply the tenant's
                # budget by the replica count, so it relays as FINAL.
                # Drain/stopped 503s keep rerouting — those are about
                # the replica, not the tenant.
                self._m_forwards.inc(replica=rid, outcome="ok")
                return (
                    code, ctype, out,
                    [("X-Fabric-Replica", rid)] + fwd_hdrs,
                )
            if code == 504:
                # a downstream deadline_expired verdict is FINAL: the
                # request's budget is gone everywhere, so rerouting it
                # would burn another replica on work the caller already
                # abandoned. Not a replica-health signal either — the
                # deadline died, not the server.
                breaker.on_success()
                self._m_forwards.inc(replica=rid, outcome="http_error")
                return (
                    code, ctype, out,
                    [
                        ("X-Fabric-Replica", rid),
                        ("X-Fabric-Attempts", str(attempts)),
                    ]
                    + fwd_hdrs,
                )
            if code in (429, 503) or code >= 500 or canary_quarantine:
                # the replica answered but couldn't take it: 429 means
                # alive-but-full and 503 not-admitting (a draining
                # scale-down victim in its last heartbeat window — no
                # breaker signal, no canary signal, load shedding is not
                # a config defect; the next candidate may well take it),
                # 5xx feeds both
                if code >= 500:
                    breaker.on_failure()
                    self._maybe_breaker_dump(rid, breaker)
                if code >= 500 or canary_quarantine:
                    self._canary_record(rid, False)
                self._m_forwards.inc(replica=rid, outcome="http_error")
                # a relayed shed keeps its retry-later semantics: the
                # replica's 429/503 carried Retry-After (passed through
                # with its REAL value — a quota window's remainder, not
                # a router guess), and stripping it would turn an
                # explicit shed into apparent downtime in every
                # client's accounting
                shed_hdr = (
                    [("Retry-After", "1")]
                    if code in (429, 503)
                    and not any(k == "Retry-After" for k, _ in fwd_hdrs)
                    else []
                )
                last = (
                    code, ctype, out,
                    [("X-Fabric-Replica", rid)] + fwd_hdrs + shed_hdr,
                )
                continue
            breaker.on_success()
            self._m_forwards.inc(replica=rid, outcome="ok")
            self._canary_record(rid, True)
            # exemplar: the proxy-time histogram keeps this request's
            # trace id per bucket, so a forward-latency spike in the
            # exposition pulls up the exact router->replica trace
            self._m_forward_s.observe(
                self._clock() - t0, exemplar=root.trace_id or None
            )
            return (
                code, ctype, out,
                [
                    ("X-Fabric-Replica", rid),
                    ("X-Fabric-Attempts", str(attempts)),
                ]
                + fwd_hdrs,
            )
        if last is not None:
            # every candidate was tried; surface the most recent replica
            # answer (e.g. pod-wide 429) rather than masking it as 503
            return last
        return _json_response(
            503,
            {"error": "no replica accepted the request",
             "status": "unavailable"},
            extra=[("Retry-After", "1")],
        )

    def _maybe_breaker_dump(self, rid: str, breaker) -> None:
        """A router-side replica breaker that is (now) open is a
        post-mortem moment: dump the flight recorder (rate-limited per
        trigger, so a dead replica's retry storm writes one artifact)."""
        if breaker.state == "open":
            flight_recorder.dump(
                "breaker_open", extra={"scope": "router", "replica": rid}
            )

    # -- hedged forwards (resilience/deadline.py) --------------------------

    def _ensure_hedge_pool(self):
        with self._hedge_lock:
            if self._hedge_pool is None:
                self._hedge_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix="mcim-hedge"
                )
            return self._hedge_pool

    def _hedge_delay_s(self) -> float | None:
        """The current hedge trigger delay: MCIM_HEDGE_DELAY_FRAC of the
        federated p99, cached for 1s (fleet_p99 merges every replica's
        histogram — too heavy per request). None = don't hedge (disabled
        or the fleet has no latency data yet)."""
        if self.hedge_delay_frac <= 0.0:
            return None
        now = self._clock()
        cached_at, cached = self._hedge_delay_cache
        if now - cached_at < 1.0:
            return cached
        try:
            p99 = self.fleet_p99().get("p99_s")
        except Exception:
            p99 = None
        delay = deadline_mod.hedge_delay_s(p99, self.hedge_delay_frac)
        self._hedge_delay_cache = (now, delay)
        return delay

    def _book_hedge_loser(self, view: ReplicaView):
        """Done-callback for the hedge leg that lost: its answer still
        feeds the breaker and forward accounting — a hedge must never
        make a replica's failures invisible."""

        def _cb(fut) -> None:
            rid = view.replica_id
            breaker = self.breakers.get(rid)
            try:
                code = fut.result()[0]
            except Exception:
                breaker.on_failure()
                self._maybe_breaker_dump(rid, breaker)
                self._m_forwards.inc(replica=rid, outcome="net_error")
                return
            if code >= 500 and code != 504:
                breaker.on_failure()
                self._maybe_breaker_dump(rid, breaker)
            else:
                breaker.on_success()
            self._m_forwards.inc(
                replica=rid,
                outcome="ok" if code < 400 else "http_error",
            )

        return _cb

    def _forward_maybe_hedged(
        self,
        view: ReplicaView,
        rest: list[ReplicaView],
        body: bytes,
        trace_id: str,
        extra_headers: tuple[tuple[str, str], ...],
        delay_s: float,
    ) -> tuple[int, str, bytes, list, str, int]:
        """First forward attempt with a tail hedge: if the primary is
        still pending after `delay_s` (a fraction of the federated p99),
        fire ONE secondary to the next routable candidate; the first
        usable response wins. Hedges withdraw from the retry budget and
        are capped at MCIM_HEDGE_MAX_FRAC of accepted requests, so the
        tail-chasing extra load is bounded like every other retry.

        Returns (code, ctype, out, fwd_hdrs, winner_replica_id,
        extra_forwards); raises the primary's exception if no leg
        produced a response. The caller books the winner's breaker /
        forward metrics as usual; the losing leg books itself via a done
        callback."""
        pool = self._ensure_hedge_pool()
        primary = pool.submit(
            self._forward_once, view, body, trace_id,
            extra_headers=extra_headers,
        )
        try:
            code, ctype, out, fwd_hdrs = primary.result(timeout=delay_s)
            return code, ctype, out, fwd_hdrs, view.replica_id, 0
        except concurrent.futures.TimeoutError:
            pass
        # the primary is past the hedge delay — find a different
        # routable replica to race it against
        second = next(
            (
                v for v in rest
                if v.replica_id != view.replica_id
                and self.breakers.get(v.replica_id).allow()
            ),
            None,
        )
        fire = second is not None
        if fire:
            with self._hedge_lock:
                cap = self.hedge_max_frac * max(
                    1.0, float(self.retry_budget.deposits)
                )
                if self._hedges_fired + 1 > cap:
                    fire = False
                else:
                    self._hedges_fired += 1
            if not fire:
                deadline_mod.count_hedge(self._m_hedges, "suppressed_cap")
            elif not self.retry_budget.try_withdraw():
                with self._hedge_lock:
                    self._hedges_fired -= 1
                deadline_mod.count_hedge(
                    self._m_hedges, "suppressed_budget"
                )
                fire = False
        if not fire:
            # no sibling / cap / budget: just wait out the primary
            code, ctype, out, fwd_hdrs = primary.result()
            return code, ctype, out, fwd_hdrs, view.replica_id, 0
        obs_trace.event(
            "fabric.hedge", primary=view.replica_id,
            secondary=second.replica_id, delay_s=round(delay_s, 4),
        )
        secondary = pool.submit(
            self._forward_once, second, body, trace_id,
            extra_headers=extra_headers,
        )
        legs = {primary: view, secondary: second}
        results: dict = {}
        pending = set(legs)
        winner = None
        while pending and winner is None:
            done, pending = concurrent.futures.wait(
                pending,
                return_when=concurrent.futures.FIRST_COMPLETED,
            )
            for fut in done:
                try:
                    results[fut] = ("ok", fut.result())
                except Exception as e:
                    results[fut] = ("err", e)
            for fut in (primary, secondary):  # primary-first: stable
                got = results.get(fut)
                if got is None or got[0] != "ok":
                    continue
                code = got[1][0]
                # usable = final for the request: not a shed/retryable
                # error (those fall back to the outer reroute loop),
                # where 504 counts as final (deadline verdicts relay)
                if code not in (429, 503) and (code < 500 or code == 504):
                    winner = fut
                    break
        if winner is not None:
            loser = secondary if winner is primary else primary
            loserv = legs[loser]
            loser.add_done_callback(self._book_hedge_loser(loserv))
            if winner is primary:
                deadline_mod.count_hedge(self._m_hedges, "lost")
            else:
                deadline_mod.count_hedge(self._m_hedges, "won")
            code, ctype, out, fwd_hdrs = results[winner][1]
            return (
                code, ctype, out, fwd_hdrs,
                legs[winner].replica_id, 1,
            )
        # both legs finished, neither final: book the secondary here and
        # surface the primary's outcome to the outer loop (which owns
        # the primary's breaker / reroute bookkeeping)
        deadline_mod.count_hedge(self._m_hedges, "lost")
        secondary.add_done_callback(self._book_hedge_loser(second))
        kind, payload = results[primary]
        if kind == "err":
            raise payload
        code, ctype, out, fwd_hdrs = payload
        return code, ctype, out, fwd_hdrs, view.replica_id, 1

    def _forward_once(
        self,
        view: ReplicaView,
        body: bytes,
        trace_id: str,
        *,
        extra_headers: tuple[tuple[str, str], ...] = (),
    ) -> tuple[int, str, bytes]:
        """One proxy attempt: POST the body to the replica, read fully.
        Connections are pooled (HTTP/1.1 keep-alive); an error closes the
        socket instead of returning it. `extra_headers` rides the graph
        lane's tenant + pipeline identity to the replica verbatim.
        Returns (status, content type, body, pass-through headers) — the
        replica's Retry-After (the REAL quota-window remainder, not a
        router guess) and the graph side-output headers survive the hop."""
        addr = view.hb.addr or "127.0.0.1"
        port = view.hb.port
        conn = self._pool.take(addr, port)
        try:
            hdrs = {"Content-Type": "application/octet-stream"}
            for k, v in extra_headers:
                hdrs[k] = v
            if trace_id:
                # the distributed-trace hop: the replica adopts this id as
                # its serve.request root, so both processes' exports join
                hdrs["X-Trace-Id"] = trace_id
            conn.request("POST", "/v1/process", body=body, headers=hdrs)
            resp = conn.getresponse()
            out = resp.read()
            ctype = resp.getheader("Content-Type", "application/json")
            passthrough = [
                (name, val)
                for name in (
                    "Retry-After", "X-MCIM-Histogram", "X-MCIM-Stats",
                )
                if (val := resp.getheader(name))
            ]
        except BaseException:
            conn.close()
            raise
        self._pool.give(addr, port, conn)
        return resp.status, ctype, out, passthrough

    def _dispatch_mesh(
        self, body: bytes, h: int, w: int
    ) -> tuple[int, str, bytes, list[tuple[str, str]]]:
        """The oversize lane: ONE request row-sharded over the multi-host
        mesh in the router process (fabric/mesh.py)."""
        from mpi_cuda_imagemanipulation_tpu.io.image import (
            decode_image_bytes,
            encode_image_bytes,
        )

        self._m_route.inc(policy="mesh")
        root = obs_trace.start_trace(
            "fabric.request", h=h, w=w, bucket="mesh", policy="mesh"
        )
        try:
            with obs_trace.span("fabric.mesh", parent=root.context()):
                img = decode_image_bytes(body)
                out = self.mesh_lane.process(img)
            png = encode_image_bytes(out)
        except Exception as e:
            self._m_requests.inc(status="error")
            root.set(status=500)
            root.end()
            return _json_response(
                500, {"error": f"mesh dispatch failed: {e}"}
            )
        self._m_requests.inc(status="ok")
        root.set(status=200)
        root.end()
        extra = [("X-Fabric-Replica", "mesh")]
        if root.trace_id:
            extra.append(("X-Trace-Id", root.trace_id))
        return 200, "image/png", png, extra

    # -- pipeline service lane (graph/) ------------------------------------

    def _systolic_program(self, tenant: str, pipeline: str):
        """The compiled GraphProgram for a stored spec (placement needs
        its step structure + balancer weights), cached per (tenant,
        pipeline) — compile_graph is pure Python, but not per-request
        cheap. None when the spec never registered through this router."""
        from mpi_cuda_imagemanipulation_tpu.graph.compile import (
            compile_graph,
            split_for_placement,
        )
        from mpi_cuda_imagemanipulation_tpu.graph.spec import parse_spec

        with self._graph_lock:
            prog = self._systolic_programs.get((tenant, pipeline))
            reg = self.graph_specs.get((tenant, pipeline))
        if prog is not None:
            return prog
        if reg is None:
            return None
        try:
            # the canonical systolic step form — plan='off' + stage
            # splitting, matching graph/service._sub_fn exactly so the
            # placement's step indices mean the same thing on the owners
            prog = split_for_placement(
                compile_graph(parse_spec(reg["spec"]), plan="off")
            )
        except Exception:
            return None
        with self._graph_lock:
            self._systolic_programs[(tenant, pipeline)] = prog
        return prog

    def _systolic_owners(self, tenant: str, pipeline: str):
        """Routable stage-owner candidates, rendezvous-ordered per
        pipeline so repeated requests land on the same owners (warm
        subrange executables), in a stable stage order."""
        views = [v for v in self._routable() if v.hb.systolic]
        views.sort(
            key=lambda v: _rendezvous_score(
                f"systolic|{tenant}|{pipeline}", v.replica_id
            ),
            reverse=True,
        )
        return views

    def _try_systolic(
        self, body: bytes, tenant: str, pipeline: str, h: int, w: int,
        deadline: deadline_mod.Deadline | None = None,
    ):
        """Attempt the stage-sharded lane for one graph request. Returns
        a complete HTTP response tuple, or None to fall back to the
        pinned-replica lane — every None counts exactly one closed-
        vocabulary fallback reason, and the failure-shaped reasons
        (owner_down / forward_failed) file a flight-recorder dump. A
        fallback re-dispatches the SAME body pinned, so a broken chain
        can slow an answer but never wrong it."""
        from mpi_cuda_imagemanipulation_tpu.graph.compile import place_steps

        fall = self._m_sys_fallbacks
        program = self._systolic_program(tenant, pipeline)
        if program is None or len(program.steps) < self.systolic_min_steps:
            graph_systolic.count_fallback(fall, "ineligible")
            return None
        owners = self._systolic_owners(tenant, pipeline)
        if len(owners) < 2:
            graph_systolic.count_fallback(fall, "replicas")
            return None
        placement = place_steps(program, len(owners))
        if placement is None:
            graph_systolic.count_fallback(fall, "ineligible")
            return None
        owners = owners[: placement.n_ranges]
        try:
            for v in owners:
                self._ensure_graph_state(v, tenant, pipeline)
        except Exception as e:
            graph_systolic.count_fallback(fall, "owner_down")
            flight_recorder.dump(
                "systolic_fallback",
                extra={
                    "reason": "owner_down",
                    "tenant": tenant,
                    "pipeline": pipeline,
                    "error": f"{type(e).__name__}: {e}",
                },
            )
            return None
        root = obs_trace.start_trace(
            "fabric.systolic", tenant=tenant, pipeline=pipeline,
            h=h, w=w, owners=len(owners),
        )
        header = graph_systolic.encode_placement(
            tenant=tenant,
            pipeline=pipeline,
            ranges=placement.ranges,
            addrs=[
                f"{v.hb.addr or '127.0.0.1'}:{v.hb.port}" for v in owners
            ],
            trace_id=root.trace_id,
        )
        from mpi_cuda_imagemanipulation_tpu.graph.service import (
            HDR_PIPELINE,
            HDR_TENANT,
        )

        sys_extra = (
            (HDR_TENANT, tenant),
            (HDR_PIPELINE, pipeline),
            (graph_systolic.HDR_PLAN, header),
        )
        if deadline is not None:
            # the stage chain inherits the remaining budget: the entry
            # owner's scheduler (and each stage handoff behind it) must
            # expire this request like any other
            sys_extra += ((deadline_mod.HEADER, deadline.header_value()),)
        try:
            code, ctype, out, passthrough = self._forward_once(
                owners[0], body, root.trace_id,
                extra_headers=sys_extra,
            )
        except Exception as e:
            root.set(status="owner_down")
            root.end()
            graph_systolic.count_fallback(fall, "owner_down")
            flight_recorder.dump(
                "systolic_fallback",
                extra={
                    "reason": "owner_down",
                    "tenant": tenant,
                    "pipeline": pipeline,
                    "owner": owners[0].replica_id,
                    "error": f"{type(e).__name__}: {e}",
                },
            )
            return None
        if code == 424 or (code >= 500 and code != 504):
            # a broken stage chain (entry answered systolic-broken, or
            # an owner died into a 5xx): rerun pinned — idempotent
            # compute, so the client still gets the bit-exact answer.
            # 504 stays FINAL: the deadline died, not the chain, and a
            # pinned rerun would burn replicas on abandoned work
            root.set(status="forward_failed", code=code)
            root.end()
            graph_systolic.count_fallback(fall, "forward_failed")
            flight_recorder.dump(
                "systolic_fallback",
                extra={
                    "reason": "forward_failed",
                    "tenant": tenant,
                    "pipeline": pipeline,
                    "owner": owners[0].replica_id,
                    "code": code,
                },
            )
            return None
        # 200 (relayed final response) or the entry owner's own
        # refusal/shed — either way the systolic lane answered
        self._m_sys_placed.inc(placement.n_ranges)
        self._m_sys_requests.inc(
            status="ok" if code == 200 else "refused"
        )
        self._m_requests.inc(
            status=_STATUS_LABEL.get(code, "error" if code >= 500 else "ok")
        )
        with self._graph_lock:
            self._systolic_last[pipeline] = {
                "tenant": tenant,
                "ranges": [list(r) for r in placement.ranges],
                "owners": [v.replica_id for v in owners],
                "weights": [
                    round(placement.range_weight(k), 3)
                    for k in range(placement.n_ranges)
                ],
                "source": placement.source,
            }
        root.set(status=code)
        root.end()
        extra = list(passthrough)
        if root.trace_id:
            extra.append(("X-Trace-Id", root.trace_id))
        return code, ctype, out, extra

    def _handle_graph_process(
        self, body: bytes, tenant: str, pipeline: str, h: int, w: int,
        fed_pod: str = "",
        deadline: deadline_mod.Deadline | None = None,
    ) -> tuple[int, str, bytes, list[tuple[str, str]]]:
        """The graph lane: sticky affinity keyed on (tenant, pipeline,
        bucket), tenant + pipeline headers forwarded verbatim, stored
        specs re-pushed to replicas whose heartbeat lacks the id. The
        canary gate does not slice this lane — a pipeline flip is its
        own deploy unit (the spec re-registers), not a replica config."""
        from mpi_cuda_imagemanipulation_tpu.graph.service import (
            HDR_PIPELINE,
            HDR_TENANT,
        )

        picked = bucketing.pick_bucket(h, w, self.buckets)
        if picked is None:
            self._m_requests.inc(status="rejected")
            big = self.buckets[-1]
            return _json_response(
                400,
                {
                    "code": "bad-image",
                    "error": (
                        f"image {h}x{w} exceeds the largest bucket "
                        f"{big[0]}x{big[1]} (the mesh lane serves chains "
                        "only)"
                    ),
                },
            )
        bucket = f"{picked[0]}x{picked[1]}"
        if self.systolic:
            resp = self._try_systolic(
                body, tenant, pipeline, h, w, deadline=deadline
            )
            if resp is not None:
                return resp
        else:
            # knob accounting: every graph request lands in exactly one
            # lane, so fallbacks_total partitions the traffic even when
            # the mode is off
            graph_systolic.count_fallback(self._m_sys_fallbacks, "off")
        candidates, policy = self.route(
            bucket,
            affinity_key=f"{tenant}|{pipeline}|{bucket}",
            prefer_warm=False,
        )
        if not candidates:
            self._m_requests.inc(status="unavailable")
            return _json_response(
                503,
                {"error": "no replica is serving", "status": "unavailable"},
                extra=[("Retry-After", "1")],
            )
        self._m_route.inc(policy=policy)
        root = obs_trace.start_trace(
            "fabric.request", h=h, w=w, bucket=bucket, policy=policy,
            tenant=tenant, pipeline=pipeline,
        )
        # both lanes fund the SAME router budget: graph traffic earns
        # the retry headroom its own reroutes spend
        self.retry_budget.deposit()
        code, ctype, out, extra = self._forward_with_retries(
            root, bucket, body, candidates,
            extra_headers=(
                (HDR_TENANT, tenant), (HDR_PIPELINE, pipeline),
            )
            + (((fed_control.HDR_FED_POD, fed_pod),) if fed_pod else ()),
            before_forward=lambda v: self._ensure_graph_state(
                v, tenant, pipeline
            ),
            admission_shed_is_final=True,
            # the graph lane propagates the deadline but does NOT hedge:
            # DAG dispatch may carry side outputs / tenant accounting a
            # duplicate dispatch would double-bill
            deadline=deadline,
        )
        self._m_requests.inc(
            status=_STATUS_LABEL.get(code, "error" if code >= 500 else "ok")
        )
        root.set(status=code)
        root.end()
        if root.trace_id:
            extra = extra + [("X-Trace-Id", root.trace_id)]
        return code, ctype, out, extra

    def _push_json(self, view: ReplicaView, path: str, payload: dict):
        """POST one JSON control payload to a replica over the pooled
        proxy connection; (status, body) back, errors propagate."""
        addr = view.hb.addr or "127.0.0.1"
        port = view.hb.port
        conn = self._pool.take(addr, port)
        try:
            conn.request(
                "POST", path, body=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            out = resp.read()
        except BaseException:
            conn.close()
            raise
        self._pool.give(addr, port, conn)
        return resp.status, out

    def _ensure_graph_state(
        self, view: ReplicaView, tenant: str, pipeline: str
    ) -> None:
        """Converge one replica's graph state before a forward: push the
        stored spec when its heartbeat lacks the pipeline id, and push
        the stored tenant config when THIS incarnation has never
        received it (tenant configs have no heartbeat echo, so the
        bookkeeping is per (replica, incarnation) — a restart re-pushes
        both). Restart/late-join recovery, the graph analogue of warmup
        re-reporting the chain buckets."""
        from mpi_cuda_imagemanipulation_tpu.graph.service import (
            PIPELINES_PATH,
            TENANTS_PATH,
        )

        inc_key = (view.replica_id, view.hb.incarnation)
        with self._graph_lock:
            reg = self.graph_specs.get((tenant, pipeline))
            tcfg = self.graph_tenants.get(tenant)
            need_tenant = (
                tcfg is not None
                and tenant not in self._tenant_pushed.get(inc_key, ())
            )
        need_spec = (
            reg is not None and pipeline not in (view.hb.pipelines or ())
        )
        # a pipeline never registered through this front door forwards
        # as-is: the replica may know it (direct registration), and its
        # structured unknown-pipeline refusal beats a router guess
        if not need_tenant and not need_spec:
            return
        if need_tenant:
            code, out = self._push_json(view, TENANTS_PATH, tcfg)
            if code != 200:
                raise RuntimeError(
                    f"tenant push to {view.replica_id} answered {code}: "
                    f"{out[:120]!r}"
                )
            self._note_tenant_pushed(view, tenant)
        if need_spec:
            code, out = self._push_json(view, PIPELINES_PATH, reg)
            if code != 200:
                raise RuntimeError(
                    f"spec push to {view.replica_id} answered {code}: "
                    f"{out[:120]!r}"
                )
        self._m_graph_pushes.inc()
        self._log.info(
            "graph: re-pushed %s/%s to %s (tenant=%s spec=%s)",
            tenant, pipeline, view.replica_id, need_tenant, need_spec,
        )

    def _note_tenant_pushed(self, view: ReplicaView, tenant: str) -> None:
        with self._graph_lock:
            self._tenant_pushed.setdefault(
                (view.replica_id, view.hb.incarnation), set()
            ).add(tenant)

    def handle_graph_register(self, body: bytes) -> tuple[int, dict]:
        """`POST /v1/pipelines` at the front door: validate HERE (the
        closed taxonomy — a malformed spec never costs a replica
        round-trip), store for re-push, broadcast to every routable
        replica, answer with the per-replica outcome."""
        from mpi_cuda_imagemanipulation_tpu.graph.ir import dag_fingerprint
        from mpi_cuda_imagemanipulation_tpu.graph.spec import (
            SpecError,
            parse_spec,
        )

        try:
            try:
                payload = json.loads(body or b"null")
            except ValueError as e:
                raise SpecError(
                    "bad-json", f"body is not JSON: {e}"
                ) from None
            if not isinstance(payload, dict):
                raise SpecError(
                    "bad-root", "registration body must be an object"
                )
            spec = payload.get("spec", payload)
            tenant = payload.get("tenant") or "default"
            graph = parse_spec(spec)
        except SpecError as e:
            return (
                400 if e.code == "bad-json" else 422,
                {"status": "rejected", "code": e.code, "error": str(e)},
            )
        pid = dag_fingerprint(graph)
        reg = {"tenant": tenant, "spec": spec}
        with self._graph_lock:
            self.graph_specs[(tenant, pid)] = reg
        pushed: dict[str, object] = {}
        for v in self._routable():
            try:
                code, _out = self._push_json(v, "/v1/pipelines", reg)
                pushed[v.replica_id] = code
            except Exception as e:
                pushed[v.replica_id] = f"error: {type(e).__name__}"
        return 200, {
            "pipeline": pid,
            "tenant": tenant,
            "name": graph.name,
            "nodes": len(graph.nodes),
            "outputs": sorted(graph.outputs),
            "replicas": pushed,
        }

    def handle_graph_tenant(self, body: bytes) -> tuple[int, dict]:
        """`POST /v1/tenants` at the front door: validate, store for
        re-push, broadcast (same shape as spec registration)."""
        from mpi_cuda_imagemanipulation_tpu.graph.spec import SpecError
        from mpi_cuda_imagemanipulation_tpu.graph.tenancy import (
            TenantConfig,
        )

        try:
            try:
                payload = json.loads(body or b"null")
            except ValueError as e:
                raise SpecError(
                    "bad-json", f"body is not JSON: {e}"
                ) from None
            if not isinstance(payload, dict):
                raise SpecError(
                    "bad-root", "tenant config must be an object"
                )
            TenantConfig(  # validation only; replicas hold the state
                tenant_id=payload.get("tenant", ""),
                qos=payload.get("qos", "standard"),
                quota_requests=payload.get("quota_requests"),
                quota_bytes=payload.get("quota_bytes"),
                window_s=payload.get("window_s"),
            )
        except SpecError as e:
            return (
                400 if e.code == "bad-json" else 422,
                {"status": "rejected", "code": e.code, "error": str(e)},
            )
        tenant = payload["tenant"]
        with self._graph_lock:
            self.graph_tenants[tenant] = payload
        pushed: dict[str, object] = {}
        for v in self._routable():
            try:
                code, _out = self._push_json(v, "/v1/tenants", payload)
                pushed[v.replica_id] = code
                if code == 200:
                    self._note_tenant_pushed(v, tenant)
            except Exception as e:
                pushed[v.replica_id] = f"error: {type(e).__name__}"
        return 200, {"tenant": tenant, "replicas": pushed}

    # -- canary / shadow routing (fabric/canary.py) ------------------------

    def _apply_canary(
        self, candidates: list[ReplicaView]
    ) -> tuple[str, ReplicaView | None, list[ReplicaView]]:
        """Split routing for an in-flight flip: stable traffic never
        touches the canary replica; the deterministic ~frac slice routes
        canary-first (stable candidates stay as fallback, so a broken
        canary costs the client a retry, not an error); every k-th
        canary request shadows instead. Returns (mode, canary view,
        forward candidates)."""
        gate = self.canary
        if gate.state != fabric_canary.CANARY:
            return "off", None, candidates
        crid = gate.replica_id
        canary_view = next(
            (v for v in candidates if v.replica_id == crid), None
        )
        stable = [v for v in candidates if v.replica_id != crid]
        if canary_view is None:
            return "off", None, stable or candidates
        if not gate.take_canary():
            return "stable", canary_view, stable
        if gate.take_shadow():
            return "shadow", canary_view, stable
        return "canary", canary_view, [canary_view] + stable

    def _canary_record(self, rid: str, ok: bool) -> None:
        gate = self.canary
        if gate.state != fabric_canary.CANARY:
            return
        lane = "canary" if rid == gate.replica_id else "stable"
        self._m_canary.inc(lane=lane, result="ok" if ok else "bad")
        if gate.record(lane, ok) == fabric_canary.ROLLED_BACK:
            self._handle_canary_rollback()

    def _shadow_forward(
        self,
        root,
        bucket: str,
        body: bytes,
        canary_view: ReplicaView,
        stable_candidates: list[ReplicaView],
    ) -> tuple[int, str, bytes, list[tuple[str, str]]]:
        """The bit-exactness spot check: duplicate one sampled request to
        canary AND stable, compare response digests, answer the client
        from STABLE — the canary cannot hurt a shadowed request no
        matter how broken the flip is."""
        import hashlib

        c_code = None
        c_digest = None
        try:
            with obs_trace.span(
                "fabric.shadow", parent=root.context(),
                replica=canary_view.replica_id,
            ):
                c_code, _ct, c_out, _ph = self._forward_once(
                    canary_view, body, root.trace_id
                )
            if c_code == 200:
                c_digest = hashlib.sha256(c_out).hexdigest()
        except Exception as e:
            self._log.warning(
                "shadow forward to canary %s failed (%s)",
                canary_view.replica_id, type(e).__name__,
            )
        self._canary_record(
            canary_view.replica_id,
            c_code is not None and c_code < 500 and c_code != 422,
        )
        code, ctype, out, extra = self._forward_with_retries(
            root, bucket, body, stable_candidates or [canary_view]
        )
        if c_code == 200 and code == 200:
            match = hashlib.sha256(out).hexdigest() == c_digest
            self._m_canary_shadow.inc(
                result="match" if match else "mismatch"
            )
            if (
                self.canary.record_shadow(match)
                == fabric_canary.ROLLED_BACK
            ):
                self._handle_canary_rollback()
        return code, ctype, out, extra + [
            ("X-Fabric-Shadow", canary_view.replica_id)
        ]

    def _handle_canary_rollback(self) -> None:
        """Breach -> exactly one rollback: dump the post-mortem, count
        it, and hand the revert to the Fabric OFF the request thread
        (the respawn takes seconds; the breaching request must not)."""
        with self._draining_lock:
            if self._canary_rollback_handled:
                return
            self._canary_rollback_handled = True
        status = self.canary.status()
        self._m_canary_rollbacks.inc()
        flight_recorder.dump("canary_rollback", extra=status)
        self._log.warning(
            "canary rollback on %s: %s", status["replica"], status["reason"]
        )
        cb = self.on_canary_rollback
        if cb is not None:
            threading.Thread(
                target=cb, args=(status,),
                name="mcim-canary-rollback", daemon=True,
            ).start()

    def canary_deploy(self, flip: dict) -> dict:
        """Start a flip: the Fabric's deploy hook respawns one replica
        with the flip config and blocks until it is serving again; only
        then does the gate open the traffic slice."""
        if self.on_canary_deploy is None:
            raise RuntimeError(
                "no canary deploy hook (router running without a Fabric)"
            )
        rid = self.on_canary_deploy(flip)
        with self._draining_lock:
            self._canary_rollback_handled = False
        self.canary.start(rid, flip)
        return self.canary.status()

    # -- live video sessions (fabric/session.py) ---------------------------

    def handle_session_frame(
        self, sid: str, body: bytes, headers
    ) -> tuple[int, str, bytes, list[tuple[str, str]]]:
        """One session frame through the front door. Frames of one
        session serialize on its lock (an ordered stream has no
        concurrency to exploit); the sticky binding, tail bookkeeping
        and failover replay all happen under it."""
        ops = headers.get(fabric_session.HDR_OPS) or ""
        if not ops:
            self._m_session_frames.inc(outcome="error")
            return _json_response(
                400, {"error": f"missing {fabric_session.HDR_OPS} header"}
            )
        sess = self.sessions.get_or_create(sid, ops)
        with sess.lock:
            raw_seq = headers.get(fabric_session.HDR_SEQ)
            try:
                seq = sess.next_seq if raw_seq is None else int(raw_seq)
            except ValueError:
                self._m_session_frames.inc(outcome="error")
                return _json_response(
                    400, {"error": f"bad {fabric_session.HDR_SEQ} {raw_seq!r}"}
                )
            with obs_trace.start_trace(
                "fabric.session", sid=sid, seq=seq
            ) as root:
                # each accepted frame banks retry-budget tokens, same as
                # a chain request — failover retries withdraw from it
                self.retry_budget.deposit()
                code, ctype, out, extra = self._forward_session(
                    root, sess, seq, body
                )
                root.set(status=code)
            if root.trace_id:
                extra = extra + [("X-Trace-Id", root.trace_id)]
            return code, ctype, out, extra

    def _forward_session(
        self, root, sess, seq: int, body: bytes
    ) -> tuple[int, str, bytes, list[tuple[str, str]]]:
        prev_rid = sess.replica_id if sess.frames > 0 else None
        tried: set[str] = set()
        last: tuple[int, str, bytes, list] | None = None
        for _attempt in range(self.forward_attempts):
            if _attempt > 0 and not self.retry_budget.try_withdraw():
                # session failover retries draw from the same bucket as
                # chain reroutes: a brownout must not amplify through
                # the stateful lane either
                deadline_mod.count_budget_denied(
                    self._m_budget_denied, "router"
                )
                break
            live = [
                v for v in self._routable() if v.replica_id not in tried
            ]
            if not live:
                break
            bound = next(
                (v for v in live if v.replica_id == sess.replica_id), None
            )
            if bound is None:
                # rebind: rendezvous winner among survivors — the same
                # hash discipline as bucket affinity, keyed by session
                view = max(
                    live,
                    key=lambda v: _rendezvous_score(
                        "sess|" + sess.sid, v.replica_id
                    ),
                )
                rebind = True
            else:
                view, rebind = bound, False
            rid = view.replica_id
            breaker = self.breakers.get(rid)
            if not breaker.allow():
                tried.add(rid)
                continue
            try:
                with obs_trace.span(
                    "fabric.session_forward", parent=root.context(),
                    replica=rid, rebind=rebind,
                ):
                    if rebind:
                        self._replay_tail(view, sess, seq, root.trace_id)
                    code, ctype, out = self._forward_session_once(
                        view, sess, seq, body, root.trace_id,
                        replay=False, reset=False,
                    )
            except Exception as e:
                breaker.on_failure()
                self._maybe_breaker_dump(rid, breaker)
                tried.add(rid)
                sess.replica_id = None  # force a clean replay elsewhere
                self._log.warning(
                    "session %s frame %d to %s failed (%s: %s)",
                    sess.sid, seq, rid, type(e).__name__, str(e)[:120],
                )
                continue
            if code in (429, 503) or code >= 500:
                if code >= 500:
                    breaker.on_failure()
                    self._maybe_breaker_dump(rid, breaker)
                tried.add(rid)
                sess.replica_id = None
                last = (code, ctype, out, [("X-Fabric-Replica", rid)])
                continue
            breaker.on_success()
            if rebind and prev_rid is not None and rid != prev_rid:
                sess.failovers += 1
                self._m_session_failovers.inc()
                self._log.info(
                    "session %s failed over %s -> %s at frame %d "
                    "(%d tail frames replayed)",
                    sess.sid, prev_rid, rid, seq, len(sess.tail),
                )
            sess.replica_id = rid
            if code == 200:
                sess.remember(seq, bytes(body))
                self._m_session_frames.inc(outcome="ok")
            else:
                self._m_session_frames.inc(outcome="error")
            return (
                code, ctype, out,
                [
                    ("X-Fabric-Replica", rid),
                    (fabric_session.HDR_SEQ, str(seq)),
                ],
            )
        if last is not None:
            self._m_session_frames.inc(outcome="error")
            return last
        self._m_session_frames.inc(outcome="unavailable")
        return _json_response(
            503,
            {"error": "no replica can take the session frame",
             "status": "unavailable"},
            extra=[("Retry-After", "1")],
        )

    def _replay_tail(self, view, sess, before_seq: int, trace_id) -> int:
        """Rebuild the temporal rings on a replacement replica: push the
        journal tail (oldest first, reset on the first frame so stale
        state from an earlier binding can never contaminate the rings);
        replayed frames decode + push but skip compute/encode (204)."""
        frames = sess.replay_frames(before_seq)
        n = 0
        for i, (s, b) in enumerate(frames):
            code, _ct, _out = self._forward_session_once(
                view, sess, s, b, trace_id, replay=True, reset=(i == 0)
            )
            if code not in (200, 204):
                raise RuntimeError(
                    f"session {sess.sid}: replay of frame {s} to "
                    f"{view.replica_id} answered {code}"
                )
            n += 1
        if n:
            self._m_session_replayed.inc(n)
        return n

    def _forward_session_once(
        self, view, sess, seq: int, body: bytes, trace_id,
        *, replay: bool, reset: bool,
    ) -> tuple[int, str, bytes]:
        addr = view.hb.addr or "127.0.0.1"
        port = view.hb.port
        conn = self._pool.take(addr, port)
        try:
            hdrs = {
                "Content-Type": "application/octet-stream",
                fabric_session.HDR_OPS: sess.ops,
                fabric_session.HDR_SEQ: str(seq),
            }
            if replay:
                hdrs[fabric_session.HDR_REPLAY] = "1"
            if reset:
                hdrs[fabric_session.HDR_RESET] = "1"
            if trace_id:
                hdrs["X-Trace-Id"] = trace_id
            conn.request(
                "POST",
                f"{fabric_session.SESSION_PATH_PREFIX}{sess.sid}/frame",
                body=body,
                headers=hdrs,
            )
            resp = conn.getresponse()
            out = resp.read()
            ctype = resp.getheader("Content-Type", "application/json")
        except BaseException:
            conn.close()
            raise
        self._pool.give(addr, port, conn)
        return resp.status, ctype, out

    # -- control + introspection ------------------------------------------

    def handle_profile(self, body: bytes) -> tuple[int, dict]:
        """`POST /control/profile`: target ONE replica with an on-demand
        `jax.profiler` capture under live traffic (body: {"replica":
        optional id, "seconds": optional float}). The replica runs the
        rate-limited capture (obs/profile.capture_live), merges its obs
        host spans onto the device timeline, files the artifact + a
        `profile_capture` recorder dump, and the whole result relays
        back through the front door — so a fleet operator profiles a
        serving pod with one HTTP call and zero SSH."""
        try:
            payload = json.loads(body or b"{}")
        except ValueError as e:
            return 400, {"error": f"body is not JSON: {e}"}
        if not isinstance(payload, dict):
            return 400, {"error": "profile request must be an object"}
        want = payload.get("replica") or ""
        live = self._routable()
        if not live:
            self._m_profile.inc(outcome="error")
            return 503, {"error": "no replica is serving"}
        if want:
            view = next(
                (v for v in live if v.replica_id == want), None
            )
            if view is None:
                self._m_profile.inc(outcome="error")
                return 404, {
                    "error": f"replica {want!r} is not routable",
                    "routable": sorted(v.replica_id for v in live),
                }
        else:
            # default target: the least-loaded serving replica — the
            # capture steals cycles, so don't aim it at the hottest one
            # unless the operator names it
            view = min(live, key=lambda v: v.load_frac())
        try:
            code, out = self._push_json(
                view, "/control/profile",
                {"seconds": payload.get("seconds")},
            )
        except Exception as e:
            self._m_profile.inc(outcome="error")
            return 502, {
                "error": (
                    f"profile relay to {view.replica_id} failed "
                    f"({type(e).__name__}: {str(e)[:120]})"
                ),
                "replica": view.replica_id,
            }
        try:
            resp = json.loads(out)
        except ValueError:
            resp = {"raw": out[:200].decode(errors="replace")}
        self._m_profile.inc(
            outcome="ok" if code == 200
            else "rate_limited" if code == 429 else "error"
        )
        return code, {"replica": view.replica_id, **resp}

    def handle_heartbeat(self, body: bytes) -> tuple[int, dict]:
        try:
            hb = Heartbeat.from_json(body)
        except (ValueError, TypeError) as e:
            return 400, {"error": f"bad heartbeat: {e}"}
        now = self._clock()
        prev = self.table.get(hb.replica_id)
        new_inc = self.table.observe(hb, now)
        if new_inc:
            # fresh process behind the same id: it must not inherit its
            # predecessor's open breaker (the restart IS the recovery)
            self.breakers.reset(hb.replica_id)
            self._log.info(
                "replica %s registered (incarnation %s, %s:%d, state %s)",
                hb.replica_id, hb.incarnation, hb.addr or "127.0.0.1",
                hb.port, hb.state,
            )
        if (
            new_inc
            or prev is None
            or prev.hb.state != hb.state
            or prev.hb.breaker_open != hb.breaker_open
            or set(prev.hb.warm_buckets) != set(hb.warm_buckets)
        ):
            # flight recorder (obs/recorder.py): the router's ring keeps
            # each replica's last meaningful heartbeat, so a post-mortem
            # dump after a SIGKILL still names the dead replica's warm
            # buckets (the supervisor's replica_death dump reads this)
            flight_recorder.note(
                "heartbeat",
                replica=hb.replica_id,
                state=hb.state,
                queued=hb.queued,
                warm_buckets=list(hb.warm_buckets),
                breaker_open=list(hb.breaker_open),
                incarnation=hb.incarnation,
            )
        self._m_heartbeats.inc(replica=hb.replica_id)
        # metrics federation: fold the beat's delta in; a refused
        # baseline rides back on the ack as resync=true and the replica
        # pushes a full snapshot next beat
        ok = self.fleet.apply(
            hb.replica_id, hb.incarnation, hb.metrics, now
        )
        # drain=true tells a scale-down victim to stop admitting: the
        # router already stopped routing to it (mark_draining); the ack
        # closes the loop on the replica side within one heartbeat
        return 200, {
            "ok": True,
            "resync": not ok,
            "drain": self._is_draining(hb.replica_id),
        }

    def _fleet_refresh(self) -> None:
        """Full-scrape fallback: a replica the table knows about whose
        fleet snapshot is stale (heartbeats lost or deltas refused) gets
        one `GET /fleet/snapshot` pull per staleness window — the
        federation survives heartbeat gaps as long as the replica's HTTP
        port answers. Runs on the /metrics//slo scrape path, bounded by
        a short timeout per replica."""
        now = self._clock()
        ages = self.fleet.ages(now)
        for v in self.table.views():
            rid = v.replica_id
            age = ages.get(rid)
            if age is not None and age <= self.stale_s:
                continue
            if now - self._fleet_scraped_at.get(rid, -1e18) < self.stale_s:
                continue
            self._fleet_scraped_at[rid] = now
            url = (
                f"http://{v.hb.addr or '127.0.0.1'}:{v.hb.port}"
                f"{obs_fleet.SNAPSHOT_PATH}"
            )
            try:
                with urllib.request.urlopen(url, timeout=2.0) as resp:
                    snap = json.loads(resp.read())
                self.fleet.full_sync(rid, v.hb.incarnation, snap, now)
                self._log.info(
                    "fleet: full-scraped %s (snapshot age was %s)",
                    rid, "inf" if age is None else f"{age:.1f}s",
                )
            except Exception as e:
                self._log.debug(
                    "fleet: full scrape of %s failed (%s)", rid,
                    type(e).__name__,
                )

    # -- federation uplink (federation/) -----------------------------------

    def federate(
        self, frontdoor_url: str, pod_id: str, *,
        interval_s: float | None = None,
    ):
        """Arm this router's pod-level uplink to a federation front
        door: a PodHeartbeatSender pushing pod aggregates (the same
        push protocol the replicas speak to THIS router, one tier up),
        with the ack applying quota leases and metrics-resync. The pod
        incarnation is minted per call, so a pod restart is visible to
        the front door the way a replica restart is visible here."""
        if self._fed_sender is not None:
            return self._fed_sender
        self._fed_pod_id = pod_id
        self._fed_incarnation = f"{os.getpid():x}-{time.time_ns():x}"
        # second federation hop: the delta rides the pod heartbeat and
        # the front door's FleetAggregator folds it in keyed by pod id
        self._fed_source = obs_fleet.DeltaSource([self.registry])
        self._fed_sender = fed_control.PodHeartbeatSender(
            frontdoor_url,
            self._collect_pod_heartbeat,
            interval_s=interval_s,
            on_ack=self._on_fed_ack,
        ).start()
        self._log.info(
            "federation: pod %s heartbeating to %s", pod_id, frontdoor_url
        )
        return self._fed_sender

    def _collect_pod_heartbeat(self, seq: int) -> fed_control.PodHeartbeat:
        live = self._routable()
        with self._graph_lock:
            pipelines = {p for (_t, p) in self.graph_specs}
        for v in live:
            pipelines.update(v.hb.pipelines or ())
        addr, port = self.address
        return fed_control.PodHeartbeat(
            pod_id=self._fed_pod_id or "",
            addr="" if addr in ("", "0.0.0.0") else addr,
            port=port,
            pid=os.getpid(),
            incarnation=self._fed_incarnation or "",
            routable=len(live),
            queued=sum(v.hb.queued for v in live),
            queue_depth=max(1, sum(v.hb.queue_depth for v in live)),
            warm_buckets=sorted(
                {b for v in live for b in v.hb.warm_buckets}
            ),
            pipelines=sorted(pipelines),
            seq=seq,
            sent_unix_s=time.time(),
            metrics=self._fed_source.delta(),
        )

    def _on_fed_ack(self, hb, ack: dict) -> None:
        if ack.get("resync"):
            self._fed_source.force_full()
        elif hb.metrics is not None:
            self._fed_source.ack(hb.metrics["seq"])
        leases = ack.get("leases")
        if leases:
            self._apply_leases(leases)

    def _apply_leases(self, leases: dict) -> None:
        """Overwrite stored tenant quotas with the front door's leased
        shares and force a re-push to the replicas (their TenantRegistry
        keeps spent window counters across a configure(), so a
        mid-window lease update never refunds spent tokens). A tenant
        the front door leases but this pod never saw is adopted — the
        lease payload IS a valid tenant config."""
        changed: list[str] = []
        with self._graph_lock:
            for tenant, lease in leases.items():
                if not isinstance(lease, dict):
                    continue
                cfg = self.graph_tenants.get(tenant)
                if cfg is None:
                    cfg = {"tenant": tenant}
                new = {
                    **cfg,
                    "quota_requests": lease.get("quota_requests"),
                    "quota_bytes": lease.get("quota_bytes"),
                }
                if new == cfg and tenant in self.graph_tenants:
                    continue
                self.graph_tenants[tenant] = new
                changed.append(tenant)
            if changed:
                # replica re-push happens lazily on the next forward
                # (_ensure_graph_state), exactly like a fresh config
                for pushed in self._tenant_pushed.values():
                    pushed.difference_update(changed)
        for tenant in changed:
            self._log.info(
                "federation: lease applied for tenant %s "
                "(quota_requests=%s quota_bytes=%s)",
                tenant,
                self.graph_tenants[tenant].get("quota_requests"),
                self.graph_tenants[tenant].get("quota_bytes"),
            )

    def render_metrics(self) -> str:
        """The router `GET /metrics` body: the router's own families plus
        the FEDERATED replica families (counters summed, histograms
        bucket-merged, gauges labeled {replica=...})."""
        self._fleet_refresh()
        return self.registry.render() + self.fleet.render()

    def fleet_p99(self) -> dict:
        """The federated e2e p99 with its exemplar trace id — the number
        the pod's operators actually ask for, joined to the trace that
        shows where the time went."""
        merged = self.fleet.merged()
        entry = merged.get("mcim_serve_e2e_latency_seconds")
        if not entry:
            return {"p99_s": None, "exemplar_trace_id": None}
        data = entry["series"].get(())
        if not data:
            return {"p99_s": None, "exemplar_trace_id": None}
        p99 = obs_fleet.quantile_from_buckets(
            entry["bounds"], data["buckets"], data["count"], 99
        )
        ex = obs_fleet.merged_exemplar_for_quantile(entry, 99)
        return {
            "p99_s": p99,
            "exemplar_trace_id": ex[0] if ex else None,
            "exemplar_value_s": ex[1] if ex else None,
        }

    def slo_status(self) -> dict:
        """The `GET /slo` body: engine status + the federated p99 and
        fleet freshness, one JSON for dashboards and the acceptance
        tests."""
        self._fleet_refresh()
        return {
            **self.slo.status(),
            "fleet": self.fleet.stats(),
            "p99": self.fleet_p99(),
        }

    def healthz(self) -> tuple[int, dict]:
        routable = self._routable()
        code = 200 if routable else 503
        return code, {
            "state": "serving" if routable else "unavailable",
            "routable": sorted(v.replica_id for v in routable),
            "known": len(self.table.views()),
        }

    def stats(self) -> dict:
        now = self._clock()
        return {
            "buckets": [f"{h}x{w}" for h, w in self.buckets],
            "stale_s": self.stale_s,
            "forward_attempts": self.forward_attempts,
            "shed_frac": self.shed_frac,
            "retry_budget": self.retry_budget.stats(),
            "hedge": {
                "delay_frac": self.hedge_delay_frac,
                "max_frac": self.hedge_max_frac,
                "fired": self._hedges_fired,
                "delay_s": self._hedge_delay_cache[1],
            },
            "draining": self.draining_ids(),
            "graph": {
                "specs": sorted(
                    f"{t}/{p}" for (t, p) in self.graph_specs
                ),
                "tenants": sorted(self.graph_tenants),
            },
            "systolic": {
                "enabled": self.systolic,
                "min_steps": self.systolic_min_steps,
                "placements": dict(self._systolic_last),
            },
            "canary": self.canary.status(),
            "tune": self.tuner.status() if self.tuner is not None else None,
            "sessions": self.sessions.stats(),
            "autoscaler": (
                self.autoscaler.status()
                if self.autoscaler is not None
                else None
            ),
            "mesh_lane": (
                self.mesh_lane.stats() if self.mesh_lane is not None else None
            ),
            "federation": (
                {
                    "pod_id": self._fed_pod_id,
                    "incarnation": self._fed_incarnation,
                    "sent": self._fed_sender.sent,
                    "dropped": self._fed_sender.dropped,
                    "failed": self._fed_sender.failed,
                }
                if self._fed_sender is not None
                else None
            ),
            "fleet": self.fleet.stats(now),
            "slo": self.slo.status(),
            "replicas": {
                v.replica_id: {
                    "addr": v.hb.addr or "127.0.0.1",
                    "port": v.hb.port,
                    "pid": v.hb.pid,
                    "incarnation": v.hb.incarnation,
                    "state": v.hb.state,
                    "fresh": v.fresh(now, self.stale_s),
                    "age_s": now - v.last_seen,
                    "queued": v.hb.queued,
                    "queue_depth": v.hb.queue_depth,
                    "breaker_open": v.hb.breaker_open,
                    "warm_buckets": v.hb.warm_buckets,
                    "systolic": v.hb.systolic,
                    "beats": v.beats,
                }
                for v in self.table.views()
            },
            "breakers": self.breakers.snapshot(),
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self, host: str = "", port: int = 0) -> "Router":
        try:
            self.httpd = _RouterHTTPServer(
                (host, port), _make_handler(self)
            )
            self._http_thread = threading.Thread(
                target=self.httpd.serve_forever,
                name="mcim-fabric-router",
                daemon=True,
            )
            self._http_thread.start()
            self.slo.start()
        except BaseException:
            self.close()
            raise
        return self

    @property
    def address(self) -> tuple[str, int]:
        assert self.httpd is not None, "Router not started"
        host, port = self.httpd.server_address[:2]
        return (host, port)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.address[1]}"

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._fed_sender is not None:
            self._fed_sender.stop()
        self.slo.stop()
        if self.httpd is not None:
            try:
                self.httpd.shutdown()
            except Exception:
                pass
            self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=10.0)
        with self._hedge_lock:
            pool, self._hedge_pool = self._hedge_pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        self._pool.close_all()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class _RouterHTTPServer(ThreadingHTTPServer):
    # the front door takes every client's connection burst: the stock
    # backlog of 5 turns load spikes into refused connections
    request_queue_size = 128


def _is_admission_shed(body: bytes) -> bool:
    """Whether a replica's 503 body is the graph lane's tenant-level
    admission shed ({"status": "shed", ...}) as opposed to a
    replica-level drain/stopped refusal."""
    try:
        return json.loads(body).get("status") == "shed"
    except Exception:
        return False


def _json_response(
    code: int, payload: dict, extra: list[tuple[str, str]] | None = None
) -> tuple[int, str, bytes, list[tuple[str, str]]]:
    return (
        code,
        "application/json",
        json.dumps(payload).encode(),
        list(extra or ()),
    )


def _make_handler(router: Router):
    log = get_logger()

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 keep-alive toward clients too (Content-Length is
        # always set, so persistent connections are safe)
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log.debug("fabric-http: " + fmt, *args)

        def _reply(self, code, ctype, body, extra=()):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in extra:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, code, payload, extra=()):
            c, t, b, e = _json_response(code, payload, list(extra))
            self._reply(c, t, b, e)

        def do_GET(self):  # noqa: N802 (stdlib casing)
            if self.path == "/healthz":
                code, payload = router.healthz()
                self._reply_json(code, payload)
            elif self.path == "/stats":
                self._reply_json(200, router.stats())
            elif self.path == "/metrics":
                # router families + the federated per-replica families
                body = router.render_metrics().encode()
                self._reply(200, obs_metrics.CONTENT_TYPE, body)
            elif self.path == "/slo":
                self._reply_json(200, router.slo_status())
            elif self.path == obs_fleet.SNAPSHOT_PATH:
                # the federation front door's full-scrape fallback: the
                # pod router's own registry (the same payload the pod
                # heartbeat's delta narrows), one tier above the
                # replica's /fleet/snapshot
                self._reply_json(
                    200, obs_fleet.snapshot_registries([router.registry])
                )
            elif self.path == "/control/canary":
                self._reply_json(200, router.canary.status())
            elif self.path == "/control/tune":
                self._reply_json(
                    200,
                    router.tuner.status()
                    if router.tuner is not None
                    else {"enabled": False},
                )
            else:
                self._reply_json(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802
            from urllib.parse import parse_qs, urlsplit

            n = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(n)
            split = urlsplit(self.path)
            path = split.path
            if self.path == HEARTBEAT_PATH:
                code, payload = router.handle_heartbeat(body)
                self._reply_json(code, payload)
            elif path == "/v1/process":
                code, ctype, out, extra = router.handle_process(
                    body, self.headers, query=parse_qs(split.query)
                )
                self._reply(code, ctype, out, extra)
            elif path == "/v1/pipelines":
                code, payload = router.handle_graph_register(body)
                self._reply_json(code, payload)
            elif path == "/v1/tenants":
                code, payload = router.handle_graph_tenant(body)
                self._reply_json(code, payload)
            elif (route := fabric_session.parse_session_path(self.path)):
                code, ctype, out, extra = router.handle_session_frame(
                    route[0], body, self.headers
                )
                self._reply(code, ctype, out, extra)
            elif self.path == "/control/profile":
                code, payload = router.handle_profile(body)
                extra = (
                    # keep the replica's real rate-limit remainder on the
                    # relayed shed, like every other Retry-After pass-through
                    [("Retry-After",
                      str(max(1, int(payload.get("retry_after_s", 1)))))]
                    if code == 429
                    else []
                )
                self._reply_json(code, payload, extra)
            elif self.path == "/control/canary":
                # operator/bench control plane: start a flip ({"env":
                # {...}, "argv": [...]}) or abort the one in flight
                try:
                    req = json.loads(body or b"{}")
                    if req.get("action") == "abort":
                        router.canary.abort("operator abort")
                        router._handle_canary_rollback()
                        self._reply_json(200, router.canary.status())
                    else:
                        self._reply_json(200, router.canary_deploy(req))
                except Exception as e:
                    self._reply_json(400, {"error": str(e)})
            else:
                self._reply_json(404, {"error": f"no route {self.path}"})

    return Handler
