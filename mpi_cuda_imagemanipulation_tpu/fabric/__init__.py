"""Pod-scale serving fabric — front-door router over N replica workers.

The source paper's whole distribution story is MPI rank coordination:
scatter rows to N workers, compute, gather (kern.cpp:55-83). The serving
tier's analogue of "N workers" is N *replica processes*, each the full
serve stack (scheduler + async engine + shape-bucket compile cache), with
a front-door HTTP router load-balancing `POST /v1/process` across them —
and, unlike MPI_COMM_WORLD, surviving a worker dying mid-collective.

    fabric/control.py     replica -> router heartbeat protocol (health
                          state, queue depth, open breakers, hot buckets)
    fabric/router.py      the front door: sticky shape-bucket affinity
                          with consistent-hash fallback, health-/load-
                          aware shedding, per-replica circuit breakers,
                          rerouting retries, 503 + Retry-After only when
                          NO replica is serving
    fabric/replica.py     one replica worker process (python -m ...fabric
                          .replica): Server + HeartbeatSender + SIGTERM
                          drain
    fabric/supervisor.py  spawn + monitor + restart-with-backoff, and the
                          `Fabric` facade (router + supervised replicas
                          as one context manager)
    fabric/mesh.py        the multi-host lane: jax.distributed-
                          initialized mesh so ONE oversize request spans
                          hosts while small requests ride data-parallel
                          replicas (CPU-simulated in tests via
                          XLA_FLAGS=--xla_force_host_platform_device_count)

The guiding principle is the software-systolic one (PAPERS.md, arxiv
1907.06154): keep every replica's scheduler fed from the request stream
even while sibling replicas churn.
"""

from mpi_cuda_imagemanipulation_tpu.fabric.control import (  # noqa: F401
    Heartbeat,
    HeartbeatSender,
)
from mpi_cuda_imagemanipulation_tpu.fabric.router import (  # noqa: F401
    Router,
    RouterConfig,
)
from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import (  # noqa: F401
    Fabric,
    FabricConfig,
    Supervisor,
)
