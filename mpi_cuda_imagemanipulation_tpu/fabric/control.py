"""Fabric control plane — the replica -> router heartbeat protocol.

Replicas PUSH state; the router never polls. Every `MCIM_FABRIC_HEARTBEAT_S`
seconds each replica POSTs one JSON `Heartbeat` to the router's
`/control/heartbeat` endpoint:

    replica_id    stable identity (the supervisor reuses it across restarts,
                  so routing affinity and metrics labels stay bounded)
    incarnation   unique per process start — the router detects a restart
                  by the change and resets that replica's breaker (a new
                  process must not inherit its predecessor's open circuit)
    addr/port     where /v1/process actually listens (replicas bind port 0
                  and report the real port here, so there is no port-
                  assignment race between supervisor and worker)
    pid           the worker's OS pid — surfaced in the router's /stats so
                  an external churn driver (the fabric_loadgen bench lane)
                  can SIGKILL a specific replica without asking the
                  supervisor
    state         the health state machine (resilience/health.py): only
                  serving/degraded replicas receive traffic
    queued/queue_depth   current admission-queue fill — the router's
                  least-loaded shedding signal
    breaker_open  "HxW" buckets whose dispatch breaker is not closed on
                  this replica — the router routes exactly those buckets
                  around it while the rest of its traffic flows normally
    warm_buckets  "HxW" buckets with a compiled executable in this
                  replica's cache — the warm-affinity signal. Warmup
                  rebuilds it on restart, so a respawned replica reclaims
                  its consistent-hash buckets (a serving-history signal
                  would starve it forever)
    metrics       compact metrics-federation delta (obs/fleet.py
                  DeltaSource payload: only the series that changed since
                  the last router-ACKED snapshot, absolute values) — the
                  router folds these into its fleet view so federation
                  costs no extra scrape round-trip. The router's ack body
                  carries `resync: true` when its baseline is stale
                  (router restart, missed epoch); the sender then resets
                  its DeltaSource and the next beat pushes a FULL
                  snapshot. May be None (metrics-less heartbeat).

The router's ACK body closes two control loops without a second channel:
`resync: true` asks for a full metrics snapshot next beat (obs/fleet.py),
and `drain: true` tells a scale-down victim to stop admitting — the
router already stopped routing to it (`mark_draining`), so within one
heartbeat period the drain is honored end to end and the replica's
subsequent beats report `state: draining` with a falling queue, which is
exactly the signal the autoscaler waits on before SIGTERM
(drain-before-kill, fabric/autoscaler.py).

A replica that exits with `PREEMPT_EXIT_CODE` was PREEMPTED (spot/
maintenance eviction, or the `replica.preempt` failpoint): it drained
gracefully and dumped the `preempt` flight-recorder artifact on its way
out. The supervisor replaces it immediately — no crash-loop backoff,
because a preemption is the platform's doing, not the replica's.

Liveness is the ABSENCE of heartbeats: the router marks a replica stale
after `MCIM_FABRIC_STALE_S` without a beat and routes around it. The
`replica.heartbeat` failpoint drops beats (the loss is injected on the
sender, so the replica keeps serving — exactly the partition the router
must tolerate; the fleet view falls back to a full scrape of the
replica's `GET /fleet/snapshot`), and a router outage only costs the
replica a log line.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import urllib.request
from typing import Callable

from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

ENV_HEARTBEAT_S = "MCIM_FABRIC_HEARTBEAT_S"

HEARTBEAT_PATH = "/control/heartbeat"

# exit status of a replica that drained after a preemption notice — the
# supervisor reads it to skip crash-loop backoff (immediate replacement)
PREEMPT_EXIT_CODE = 43


@dataclasses.dataclass
class Heartbeat:
    """One replica's pushed state — the wire format is its JSON dict."""

    replica_id: str
    addr: str
    port: int
    pid: int
    incarnation: str
    state: str
    queued: int
    queue_depth: int
    breaker_open: list[str]
    warm_buckets: list[str]
    seq: int
    sent_unix_s: float
    # metrics-federation delta (obs/fleet.py DeltaSource payload), or
    # None for a metrics-less beat
    metrics: dict | None = None
    # pipeline-service state (graph/service.py): the pipeline ids this
    # replica has registered. The router re-pushes a stored spec before
    # forwarding a graph request to a replica whose beat lacks its id —
    # so a RESTARTED replica (empty registry, same warm discipline as
    # the compile cache) reconverges within one forward, not never.
    pipelines: list[str] | None = None
    # stage-ownership advert (graph/systolic.py): True when this replica
    # accepts /v1/systolic hops, so the router only places program
    # stages on replicas that will run them. None (the wire default) is
    # "not advertised" — old beats parse, and the router treats both
    # None and False as ineligible.
    systolic: bool | None = None

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Heartbeat":
        raw = json.loads(data)
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - fields
        if unknown:
            # tolerate FUTURE extra fields? No: the fabric ships router and
            # replica from one tree, so an unknown field is a version skew
            # bug worth failing loudly on, not silently dropping
            raise ValueError(f"heartbeat has unknown fields {sorted(unknown)}")
        required = {
            f.name
            for f in dataclasses.fields(cls)
            if f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        }
        missing = required - set(raw)
        if missing:
            raise ValueError(f"heartbeat missing fields {sorted(missing)}")
        return cls(**raw)


def default_heartbeat_s() -> float:
    return float(env_registry.get(ENV_HEARTBEAT_S))


class HeartbeatSender:
    """The replica-side push loop: one daemon thread POSTing `collect()`'s
    Heartbeat to the router until `stop()`.

    Failure posture: a dropped beat (armed `replica.heartbeat` failpoint)
    or an unreachable router NEVER raises out of the loop — the replica's
    job is serving, and the router's staleness window is the protocol's
    loss handling. Send timeouts are bounded by the interval so a wedged
    router can't back beats up behind a stuck socket."""

    def __init__(
        self,
        control_url: str,
        collect: Callable[[int], Heartbeat],
        *,
        interval_s: float | None = None,
        on_ack: Callable[[Heartbeat, dict], None] | None = None,
    ):
        # control_url is the router base (http://host:port); beats go to
        # its /control/heartbeat route
        self.url = control_url.rstrip("/") + HEARTBEAT_PATH
        self._collect = collect
        # on_ack(hb, ack_body): the router acknowledged this beat — the
        # metrics DeltaSource advances its baseline here (and resets it
        # when the ack carries resync=true)
        self._on_ack = on_ack
        self.interval_s = (
            default_heartbeat_s() if interval_s is None else interval_s
        )
        self.sent = 0
        self.dropped = 0  # failpoint-dropped beats
        self.failed = 0  # router unreachable / send error
        self._seq = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._log = get_logger()

    def start(self) -> "HeartbeatSender":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="mcim-fabric-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def _loop(self) -> None:
        # first beat immediately: the router learns the replica's bound
        # port from it, so registration latency is one send, not one period
        while not self._stop.is_set():
            self.beat()
            self._stop.wait(self.interval_s)

    def beat(self) -> bool:
        """One send attempt; returns True when the router acknowledged."""
        self._seq += 1
        hb = self._collect(self._seq)
        try:
            # an armed replica.heartbeat failpoint models HEARTBEAT LOSS:
            # the beat is dropped before the socket, the replica serves on
            failpoints.maybe_fail(
                "replica.heartbeat", replica=hb.replica_id, seq=hb.seq
            )
        except failpoints.FailpointError:
            self.dropped += 1
            return False
        req = urllib.request.Request(
            self.url,
            data=hb.to_json(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=max(self.interval_s, 0.2)
            ) as resp:
                body = resp.read()
            self.sent += 1
            if self._on_ack is not None:
                try:
                    ack = json.loads(body) if body else {}
                except ValueError:
                    ack = {}
                self._on_ack(hb, ack)
            return True
        except Exception as e:  # router down/restarting: serve on, log once
            self.failed += 1
            if self.failed in (1, 10, 100):
                self._log.warning(
                    "heartbeat %s -> %s failed (%s; %d so far)",
                    hb.replica_id, self.url, type(e).__name__, self.failed,
                )
            return False
