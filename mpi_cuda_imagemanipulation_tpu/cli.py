"""CLI driver — the framework's L5 entry point.

Replaces the reference's hardcoded main() (kern.cpp:17, kernel.cu:96 — fixed
Windows input path kernel.cu:110, fixed output name :236, hardwired contrast
3.5 :50 and filter choice :195) with real flags, per SURVEY.md §5's config
audit. Every hardcoded knob in the reference is a flag here.

Usage:
  python -m mpi_cuda_imagemanipulation_tpu run --input in.png --output out.png
      [--ops grayscale,contrast:3.5,emboss:3] [--impl xla|pallas]
      [--shards N] [--device cpu|tpu] [--show-timing] [--json-metrics PATH|-]
      [--profile-dir DIR] [--trace-out T.json] [--trace-sample F]
  python -m mpi_cuda_imagemanipulation_tpu serve [--ops ...] [--buckets ...]
      [--max-batch N] [--max-delay-ms MS] [--queue-depth N] [--port P]
      [--trace-out T.json] [--trace-sample F]   # GET /metrics is built in
  python -m mpi_cuda_imagemanipulation_tpu bench [--configs ...]
  python -m mpi_cuda_imagemanipulation_tpu info [--device cpu|tpu]

`--device cpu` (or JAX_PLATFORMS=cpu in the env) stays pure-host even when a
boot hook has force-registered an accelerator plugin whose first backend
init could block on a wedged tunnel.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _add_failpoint_flags(sp: argparse.ArgumentParser) -> None:
    sp.add_argument(
        "--failpoints",
        default=None,
        metavar="SPEC",
        help="arm deterministic fault injection, e.g. "
        "'serve.dispatch=0.1,io.decode=first:2' (sites/modes: "
        "resilience/failpoints.py; env MCIM_FAILPOINTS works too). For "
        "testing the recovery paths — never set in production",
    )
    sp.add_argument(
        "--failpoint-seed",
        type=int,
        default=0,
        help="seed for probabilistic failpoint modes (deterministic "
        "fail/pass sequence per site)",
    )


def _arm_failpoints(args: argparse.Namespace) -> None:
    if getattr(args, "failpoints", None):
        from mpi_cuda_imagemanipulation_tpu.resilience import failpoints

        failpoints.configure(args.failpoints, seed=args.failpoint_seed)


def _add_trace_flags(sp: argparse.ArgumentParser) -> None:
    sp.add_argument(
        "--trace-out",
        default=None,
        metavar="PATH",
        help="write request-scoped trace spans as Chrome/Perfetto trace-"
        "event JSON to this path at exit (obs/trace.py; load in "
        "ui.perfetto.dev, or merge with a jax.profiler device trace "
        "via tools/profile_capture.py --merge-host-trace)",
    )
    sp.add_argument(
        "--trace-sample",
        type=float,
        default=None,
        metavar="FRAC",
        help="trace this fraction of requests/dispatches (deterministic "
        "every-k-th sampling; default 1.0 with --trace-out). Sampled-out "
        "work pays one flag check — cheap enough to leave on in "
        "production (env MCIM_TRACE_SAMPLE arms tracing too)",
    )


def _add_plan_flag(sp: argparse.ArgumentParser) -> None:
    sp.add_argument(
        "--plan",
        choices=("auto", "off", "pointwise", "fused", "fused-pallas",
                 "fused-pallas-mxu"),
        default="auto",
        help="fusion-planner execution structure (plan/): 'off' runs "
        "op-by-op (the golden reference — one HBM pass and, sharded, one "
        "ghost exchange per op); 'pointwise' absorbs pointwise runs into "
        "their neighbouring stencil's pass; 'fused' additionally "
        "temporally blocks consecutive stencils behind ONE grown-halo "
        "exchange per stage; 'fused-pallas' lowers each eligible fused "
        "stage into ONE VMEM-resident Pallas megakernel (one HBM read + "
        "one write per stage; per-op fallback otherwise); "
        "'fused-pallas-mxu' additionally forces eligible stencils inside "
        "each megakernel onto MXU dot contractions (per-op-within-stage "
        "arms; ops/mxu_kernels); 'auto' "
        "consults the calibration store (`autotune --dimension plan`), "
        "then the backend default. Bit-identical output in every mode",
    )


def _configure_tracing(args: argparse.Namespace) -> bool:
    """Arm the obs tracer from --trace-out/--trace-sample (or the
    MCIM_TRACE_SAMPLE env). Returns True when armed."""
    from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace

    sample = getattr(args, "trace_sample", None)
    if getattr(args, "trace_out", None) or sample is not None:
        obs_trace.configure(sample=1.0 if sample is None else sample)
        return True
    return obs_trace.configure_from_env() is not None


def _export_trace(args: argparse.Namespace, log) -> None:
    if getattr(args, "trace_out", None):
        from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace

        n = obs_trace.export(args.trace_out)
        log.info("trace: %d events -> %s", n, args.trace_out)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mcim-tpu",
        description="TPU-native image-manipulation pipeline",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="run a pipeline on one image")
    run.add_argument("--input", required=True, help="input image path")
    run.add_argument("--output", required=True, help="output image path")
    run.add_argument(
        "--ops",
        default="grayscale,contrast:3.5,emboss:3",
        help="comma-separated pipeline (default: the reference pipeline, "
        "kernel.cu:192-195)",
    )
    run.add_argument(
        "--impl",
        choices=("auto", "xla", "pallas", "swar", "mxu"),
        default="auto",
        help="compute backend for the op kernels (auto: measured per-group "
        "choice between XLA fusion, Pallas kernels, and — behind a "
        "calibration win — the MXU banded-matmul path; mxu: force the "
        "banded-matmul stencil contraction, golden fallback per op)",
    )
    run.add_argument(
        "--shards",
        default="1",
        help="shard the image over devices: N row-shards (mpirun -np "
        "analogue), RxC tile-shards over a 2-D rows x cols mesh with "
        "corner-carrying halo exchange (e.g. 2x4); 1 = single device",
    )
    run.add_argument(
        "--device",
        default=None,
        help="JAX platform to use (e.g. tpu, cpu); default: JAX's default",
    )
    run.add_argument(
        "--halo-mode",
        choices=("serial", "overlap"),
        default="serial",
        help="sharded halo execution: 'serial' gates every stencil group "
        "on its ghost-strip ppermutes; 'overlap' computes interior rows "
        "while the ICI transfers are in flight and prefetches the next "
        "group's exchange (bit-identical output; no-op without --shards)",
    )
    run.add_argument(
        "--gray-output",
        action="store_true",
        help="write single-channel output instead of replicating gray to RGB "
        "(the reference replicates: kernel.cu:210)",
    )
    run.add_argument("--show-timing", action="store_true", help="print timing")
    run.add_argument(
        "--json-metrics",
        default=None,
        help="write a JSON metrics line to this path ('-' = stdout)",
    )
    run.add_argument(
        "--profile-dir",
        default=None,
        help="capture a jax.profiler trace (TensorBoard/Perfetto) to this dir",
    )
    run.add_argument(
        "--block",
        type=int,
        default=None,
        help="Pallas row-block height override (the reference's BLOCK_SIZE "
        "knob, kernel.cu:13; default: auto-tuned to VMEM)",
    )
    run.add_argument(
        "--show",
        action="store_true",
        help="open the result in the system image viewer (the reference's "
        "imshow/waitKey, kernel.cu:233-235; no-op on headless hosts)",
    )
    def _positive_float(v: str) -> float:
        f = float(v)
        if f <= 0:
            raise argparse.ArgumentTypeError(
                f"--device-timeout must be positive, got {v}"
            )
        return f

    run.add_argument(
        "--device-timeout",
        type=_positive_float,
        default=None,
        metavar="SECS",
        help="run the device computation in a watchdog subprocess with this "
        "wall-clock budget; a wedged accelerator backend then fails fast "
        "with a clean error instead of hanging the process (failure-"
        "detection posture, SURVEY.md §5 — the reference deadlocks its "
        "peers on mid-collective failure, kernel.cu:150)",
    )
    _add_plan_flag(run)
    _add_failpoint_flags(run)
    _add_trace_flags(run)

    batch = sub.add_parser(
        "batch", help="run a pipeline over every image in a directory"
    )
    batch.add_argument("--input-dir", required=True)
    batch.add_argument("--output-dir", required=True)
    batch.add_argument("--glob", default="*", help="input filename pattern")
    batch.add_argument("--ops", default="grayscale,contrast:3.5,emboss:3")
    batch.add_argument(
        "--impl",
        choices=("auto", "xla", "pallas", "swar", "mxu"),
        default="auto",
    )
    batch.add_argument(
        "--shards",
        default="1",
        help="N row-shards per image, or RxC 2-D tile-shards (run --help); "
        "with --stack the flat device count hosts the data-parallel stack",
    )
    batch.add_argument("--device", default=None)
    batch.add_argument(
        "--halo-mode",
        choices=("serial", "overlap"),
        default="serial",
        help="sharded halo execution (see `run --help`)",
    )
    batch.add_argument(
        "--threads", type=int, default=4, help="decode prefetch threads"
    )
    batch.add_argument(
        "--inflight",
        type=int,
        default=None,
        help="device dispatches kept outstanding through the async engine "
        "(engine/core.py): >= 2 double-buffers, so the device computes "
        "batch N while the host decodes N+1 and encodes N-1 (the "
        "reference instead round-trips per stage); default 2",
    )
    batch.add_argument(
        "--window",
        type=int,
        default=None,
        help=argparse.SUPPRESS,  # deprecated alias for --inflight
    )
    batch.add_argument(
        "--io-threads",
        type=int,
        default=4,
        help="encode/write worker threads draining completed dispatches "
        "(the engine's output pool; decode prefetch is --threads)",
    )
    batch.add_argument(
        "--stream-rows",
        type=int,
        default=0,
        metavar="N",
        help="N > 0 routes every input through the streaming tile engine "
        "(stream/) in N-row bands with the output encoded incrementally "
        "— device rows hand to the encoder single-copy, full frames "
        "never buffer host-side (gigapixel inputs in a batch dir); "
        "incompatible with --stack/--shards",
    )
    batch.add_argument(
        "--stack",
        type=int,
        default=1,
        help="vmap-stack up to N same-shape images into one device "
        "dispatch (amortises per-call overhead); combined with --shards M "
        "the stack is data-parallel over an M-device mesh — each device "
        "runs the pipeline on its slice of the images",
    )
    batch.add_argument("--gray-output", action="store_true")
    batch.add_argument("--show-timing", action="store_true")
    batch.add_argument(
        "--json-metrics",
        default=None,
        help="write a JSON metrics line (incl. the skipped-file list) to "
        "this path ('-' = stdout)",
    )
    batch.add_argument(
        "--resume",
        action="store_true",
        help="skip inputs already journaled ok (content-hash-verified) from "
        "a previous run over this output dir — a batch killed mid-way "
        "finishes by re-running only failures and never-reached inputs",
    )
    batch.add_argument(
        "--journal",
        default=None,
        metavar="PATH",
        help="batch journal path (append-only JSONL of per-input outcomes; "
        "default: <output-dir>/.mcim_batch_journal.jsonl)",
    )
    batch.add_argument(
        "--no-journal",
        action="store_true",
        help="disable the journal (no crash-resume for this run)",
    )
    batch.add_argument(
        "--metrics-out",
        default=None,
        metavar="PATH",
        help="write a Prometheus text-exposition snapshot of the batch "
        "registry (engine stages, inflight, per-outcome input counts) "
        "at exit — the offline counterpart of the serving GET /metrics "
        "(obs/metrics.py)",
    )
    _add_plan_flag(batch)
    _add_failpoint_flags(batch)
    _add_trace_flags(batch)

    srv = sub.add_parser(
        "serve",
        help="online micro-batching server: POST /v1/process (image bytes "
        "in, PNG out), GET /healthz, GET /stats — bounded queue, shape-"
        "bucketed pre-warmed compile cache, bit-identical to per-request "
        "`run` output (serve/)",
    )
    srv.add_argument("--ops", default="grayscale,contrast:3.5,emboss:3")
    srv.add_argument(
        "--impl",
        choices=("auto", "xla", "mxu"),
        default="xla",
        help="serving computes with XLA fusion (the bucket-padded executor "
        "rebuilds each op's border at the dynamic true shape, which the "
        "Pallas streaming kernels' static in-kernel edge extension cannot "
        "do); 'mxu' contracts eligible stencil families on the matrix "
        "unit inside the same padded executor (bit-identical; "
        "ops/mxu_kernels.py); 'auto' is an accepted alias for xla",
    )
    srv.add_argument(
        "--shards",
        type=int,
        default=1,
        help="data-parallel serving over N devices: each dispatch's stack "
        "shards over the mesh batch axis (batch sizes are rounded to "
        "mesh multiples); 1 = single device",
    )
    srv.add_argument(
        "--buckets",
        default="512,1024,2048,4096",
        help="comma-separated shape buckets, N (square) or RxC; requests "
        "pad up to the smallest fitting bucket so every executable is "
        "compiled at startup — larger images are rejected, never traced",
    )
    srv.add_argument(
        "--max-batch",
        type=int,
        default=8,
        help="requests coalesced per dispatch (must be a multiple of "
        "--shards)",
    )
    srv.add_argument(
        "--max-delay-ms",
        type=float,
        default=5.0,
        help="longest a request waits for batch-mates before a partial "
        "dispatch ships (the latency cost ceiling of coalescing)",
    )
    srv.add_argument(
        "--queue-depth",
        type=int,
        default=64,
        help="admission bound: submissions beyond this many queued "
        "requests are shed with the 'overloaded' status (HTTP 429) "
        "instead of buffering without bound",
    )
    srv.add_argument(
        "--deadline-ms",
        type=float,
        default=None,
        help="per-request deadline; requests that expire while queued are "
        "answered 'deadline_expired' (HTTP 504) and never take a device "
        "slot",
    )
    srv.add_argument(
        "--channels",
        default="1,3",
        help="channel counts to pre-compile (and admit), comma-separated",
    )
    srv.add_argument("--host", default="", help="bind address")
    srv.add_argument("--port", type=int, default=8000)
    srv.add_argument("--device", default=None)
    srv.add_argument(
        "--json-metrics",
        default=None,
        help="write the shutdown stats record to this path ('-' = stdout)",
    )
    srv.add_argument(
        "--retry-attempts",
        type=int,
        default=3,
        help="dispatch attempts per micro-batch (1 = no retry); transient "
        "device/compile failures back off exponentially with jitter",
    )
    srv.add_argument(
        "--breaker-threshold",
        type=int,
        default=5,
        help="consecutive dispatch failures that trip a bucket's circuit "
        "breaker open (its traffic then degrades to the golden "
        "per-request path until a half-open probe succeeds)",
    )
    srv.add_argument(
        "--breaker-reset-s",
        type=float,
        default=30.0,
        help="quiet seconds an open breaker waits before admitting a "
        "half-open probe dispatch",
    )
    srv.add_argument(
        "--inflight",
        type=int,
        default=2,
        help="micro-batch dispatches kept outstanding through the async "
        "engine (engine/core.py): >= 2 keeps the device busy while "
        "results transfer back and responses encode; 1 = serial "
        "dispatch-then-drain",
    )
    srv.add_argument(
        "--io-threads",
        type=int,
        default=4,
        help="completion worker threads cropping results and resolving "
        "responses (the engine's output pool)",
    )
    srv.add_argument(
        "--drain-deadline-s",
        type=float,
        default=30.0,
        help="SIGTERM graceful-drain budget: admission stops immediately, "
        "queued + in-flight work gets this long to flush before the "
        "scheduler is stopped",
    )
    srv.add_argument(
        "--replicas",
        type=int,
        default=1,
        help="N > 1 runs the pod-scale fabric instead of a single "
        "process: a front-door router on --port load-balancing over N "
        "supervised replica worker processes (each this serve stack), "
        "with sticky shape-bucket affinity, health-aware shedding and "
        "restart-with-backoff (fabric/; the `fabric` subcommand exposes "
        "the router knobs)",
    )
    _add_plan_flag(srv)
    _add_failpoint_flags(srv)
    _add_trace_flags(srv)

    fab = sub.add_parser(
        "fabric",
        help="pod-scale serving fabric: front-door router + N supervised "
        "replica workers (each the full serve stack), heartbeat-driven "
        "health/affinity routing, rerouting retries, restart-with-"
        "backoff; optional jax.distributed mesh lane for requests too "
        "large for any replica bucket (fabric/)",
    )
    fab.add_argument("--replicas", type=int, default=3)
    fab.add_argument("--ops", default="grayscale,contrast:3.5,emboss:3")
    fab.add_argument("--buckets", default="512,1024,2048,4096")
    fab.add_argument("--channels", default="1,3")
    fab.add_argument("--max-batch", type=int, default=8)
    fab.add_argument("--max-delay-ms", type=float, default=5.0)
    fab.add_argument("--queue-depth", type=int, default=64)
    fab.add_argument(
        "--impl", choices=("auto", "xla", "mxu"), default="xla"
    )
    fab.add_argument("--host", default="", help="router bind address")
    fab.add_argument("--port", type=int, default=8000)
    fab.add_argument("--device", default=None)
    fab.add_argument(
        "--heartbeat-s",
        type=float,
        default=None,
        help="replica heartbeat period (default: MCIM_FABRIC_HEARTBEAT_S); "
        "the router marks a replica stale after --stale-s without one",
    )
    fab.add_argument(
        "--stale-s",
        type=float,
        default=None,
        help="router freshness window: replicas silent this long are "
        "routed around (default: MCIM_FABRIC_STALE_S)",
    )
    fab.add_argument(
        "--forward-attempts",
        type=int,
        default=None,
        help="distinct replicas tried per request before 503 (default: "
        "MCIM_FABRIC_FORWARD_ATTEMPTS); attempt 2+ counts as retried",
    )
    fab.add_argument(
        "--mesh-shards",
        type=int,
        default=0,
        help="N > 0 arms the oversize mesh lane: requests exceeding every "
        "replica bucket run ONE row-sharded dispatch over an N-device "
        "jax.distributed mesh (spanning hosts on a pod; CPU-simulated "
        "via forced host device count in tests) instead of being "
        "rejected",
    )
    fab.add_argument(
        "--autoscale",
        action="store_true",
        help="arm the elastic control loop (fabric/autoscaler.py): "
        "replica count follows queue-fill/p99 pressure between "
        "--min-replicas and --max-replicas with hysteresis; scale-down "
        "is drain-before-kill (routing stops, the queue empties, THEN "
        "SIGTERM). --replicas is the starting count",
    )
    fab.add_argument(
        "--min-replicas",
        type=int,
        default=None,
        help="autoscaler floor (default MCIM_FABRIC_MIN_REPLICAS)",
    )
    fab.add_argument(
        "--systolic",
        action="store_true",
        default=None,
        help="pod-level systolic execution (graph/systolic.py): the "
        "router stage-shards registered DAG pipelines across replicas "
        "and the live env streams replica-to-replica at each stage "
        "boundary; replicas advertise stage ownership in heartbeats and "
        "any fallback is the pinned single-replica path — never a wrong "
        "answer (default MCIM_SYSTOLIC)",
    )
    fab.add_argument(
        "--tune",
        action="store_true",
        help="arm the continuous autotuning loop (tune/): replicas "
        "persist serve-path observations to the calibration store and "
        "the router's tune controller proposes config flips from them, "
        "deploying each through the canary gate (shadow-digest "
        "bit-exactness, burn limits) and promoting fleet-wide or "
        "rolling back with no human in the loop — MCIM_TUNE_* env "
        "tunes the cadence/thresholds",
    )
    fab.add_argument(
        "--tune-arms",
        default=None,
        help="comma-separated candidate arms the controller may propose "
        "(e.g. plan:off,plan:fused; default MCIM_TUNE_ARMS or every "
        "plan mode real on this backend)",
    )
    fab.add_argument(
        "--max-replicas",
        type=int,
        default=None,
        help="autoscaler ceiling (default MCIM_FABRIC_MAX_REPLICAS)",
    )
    _add_plan_flag(fab)
    fab.add_argument(
        "--slo",
        default=None,
        metavar="SPECS",
        help="SLO specs the router's burn-rate engine evaluates over the "
        "federated fleet metrics: comma-separated avail:<pct> and "
        "latency:<le_seconds>:<pct> entries (default MCIM_SLO_SPECS; "
        "served at GET /slo and as mcim_slo_* gauges)",
    )
    fab.add_argument(
        "--json-metrics",
        default=None,
        help="write the shutdown fabric stats record to this path "
        "('-' = stdout)",
    )
    fab.add_argument(
        "--federate",
        default=None,
        metavar="URL",
        help="federation front-door URL (federation/): this pod's router "
        "pushes pod-aggregate heartbeats there, receives tenant "
        "quota-share leases on the acks, and serves forwarded /v1/* "
        "traffic as one pod among many",
    )
    fab.add_argument(
        "--pod-id",
        default=None,
        help="stable pod identity at the federation tier (affinity "
        "routing and mcim_fed_* labels key on it; default pod-<pid>)",
    )
    _add_failpoint_flags(fab)
    _add_trace_flags(fab)

    fed = sub.add_parser(
        "federation",
        help="multi-pod federation front door (federation/): routes "
        "/v1/* across registered pods (rendezvous affinity, per-pod "
        "breakers, whole-pod failover), persists tenant configs + "
        "pipeline specs in an fsync'd registry that survives restarts, "
        "and leases per-pod shares of each tenant's global fixed-window "
        "quota; pods join with `fabric --federate URL --pod-id NAME`",
    )
    fed.add_argument("--host", default="", help="front-door bind address")
    fed.add_argument("--port", type=int, default=8100)
    fed.add_argument(
        "--registry",
        default=None,
        help="durable tenant/spec/session registry path (default: "
        "MCIM_FED_REGISTRY)",
    )
    fed.add_argument(
        "--stale-s",
        type=float,
        default=None,
        help="pod freshness window: pods silent this long are routed "
        "around (default: MCIM_FED_STALE_S)",
    )
    fed.add_argument(
        "--shed-frac",
        type=float,
        default=0.9,
        help="pod queue-fill fraction past which a pod loses sticky "
        "preference (counted reroute reason 'overloaded')",
    )
    _add_failpoint_flags(fed)
    _add_trace_flags(fed)

    stm = sub.add_parser(
        "stream",
        help="constant-memory streaming tile engine: run a pipeline over "
        "an arbitrarily large image (or a video frame sequence) as "
        "fixed-height row bands with seam-stitched halos — bit-exact "
        "against the whole-image path, peak resident bytes set by "
        "--tile-rows/--inflight, never by image size (stream/)",
    )
    stm.add_argument(
        "--input",
        default=None,
        help="input image path (ppm/pgm stream via seek, png via the "
        "scanline decoder; other formats fall back to whole-image "
        "decode with a warning)",
    )
    stm.add_argument(
        "--synthetic",
        default=None,
        metavar="HxW[xC]",
        help="process a deterministic synthetic image of this shape "
        "instead of --input (windowed generation — a 100000x4096 scan "
        "never materialises host-side; the gigapixel demo/bench source)",
    )
    stm.add_argument(
        "--output",
        default=None,
        help="output path, encoded incrementally (png: streamed IDAT "
        "bands; ppm/pgm: appended raw rows — the resumable container)",
    )
    stm.add_argument(
        "--video-frames",
        default=None,
        metavar="GLOB",
        help="video mode: process this ordered frame glob instead of one "
        "image; temporal ops (framediff, tdenoise:K) may lead --ops and "
        "read a bounded frame-history ring (ops/temporal.py)",
    )
    stm.add_argument(
        "--output-dir",
        default=None,
        help="video mode: directory for per-frame outputs (basename "
        "preserved, extension from --out-ext)",
    )
    stm.add_argument(
        "--out-ext",
        default=".png",
        help="video mode: output frame container extension",
    )
    stm.add_argument("--ops", default="grayscale,contrast:3.5,emboss:3")
    stm.add_argument(
        "--impl",
        choices=("auto", "xla", "mxu"),
        default="xla",
        help="tile compute backend: xla (golden), mxu (banded-matmul "
        "contraction for eligible stencil families, bit-identical), "
        "auto (calibration-gated MXU routing — never off-TPU)",
    )
    stm.add_argument(
        "--tile-rows",
        type=int,
        default=None,
        help="row-band height — the memory budget knob (default "
        "MCIM_STREAM_TILE_ROWS=512); must be at least the chain halo",
    )
    stm.add_argument(
        "--inflight",
        type=int,
        default=None,
        help="tile dispatches kept outstanding (default "
        "MCIM_STREAM_INFLIGHT=2): >= 2 stages tile k+1's H2D while "
        "tile k computes and k-1 encodes",
    )
    stm.add_argument(
        "--io-threads",
        type=int,
        default=2,
        help="engine completion workers (writes are delivered in tile "
        "order regardless)",
    )
    stm.add_argument("--device", default=None)
    stm.add_argument(
        "--resume",
        action="store_true",
        help="skip tiles (or video frames) journaled ok by a previous "
        "killed run — image-mode resume needs a ppm/pgm output (a PNG "
        "compressor's state does not survive a kill)",
    )
    stm.add_argument(
        "--journal",
        default=None,
        metavar="PATH",
        help="stream journal path (default: <output>.journal.jsonl, or "
        "<output-dir>/.mcim_stream_journal.jsonl for video)",
    )
    stm.add_argument(
        "--no-journal",
        action="store_true",
        help="disable the journal (no kill-mid-stream resume)",
    )
    stm.add_argument("--show-timing", action="store_true")
    stm.add_argument(
        "--json-metrics",
        default=None,
        help="write the stream summary record to this path ('-' = stdout)",
    )
    stm.add_argument(
        "--metrics-out",
        default=None,
        metavar="PATH",
        help="write a Prometheus snapshot of the stream registry "
        "(mcim_stream_* incl. the peak-resident-bytes gauge, plus the "
        "engine families) at exit",
    )
    _add_plan_flag(stm)
    _add_failpoint_flags(stm)
    _add_trace_flags(stm)

    gph = sub.add_parser(
        "graph",
        help="validate/run a pipeline-spec DAG (graph/): branch taps, "
        "merge combinators, side outputs — the file form of what "
        "POST /v1/pipelines registers",
    )
    gph.add_argument(
        "--spec",
        required=True,
        metavar="PATH",
        help="pipeline spec JSON (graph/spec.py schema; refusals print "
        "their closed-taxonomy code and exit 2)",
    )
    gph.add_argument(
        "--input", default=None, help="image to run the graph on"
    )
    gph.add_argument(
        "--synthetic",
        default=None,
        metavar="HxW[xC]",
        help="run on a deterministic synthetic image of this shape "
        "instead of --input",
    )
    gph.add_argument(
        "--output", default=None, help="write the image output here"
    )
    gph.add_argument(
        "--histogram-out",
        default=None,
        metavar="PATH",
        help="write the histogram side output (JSON int[256]); needs a "
        "spec with outputs.histogram",
    )
    gph.add_argument(
        "--stats-out",
        default=None,
        metavar="PATH",
        help="write the stats side output (JSON count/min/max/mean); "
        "needs a spec with outputs.stats",
    )
    gph.add_argument(
        "--impl",
        choices=("xla", "mxu", "auto"),
        default="xla",
        help="stencil accumulation backend for the graph's fused "
        "segments (the plan-executor impls)",
    )
    gph.add_argument(
        "--validate-only",
        action="store_true",
        help="parse + compile-plan the spec and print its structure "
        "without running anything (no device touch)",
    )
    gph.add_argument("--device", default=None)
    gph.add_argument(
        "--json-metrics", default=None, help="write the run record "
        "('-' = stdout)"
    )
    _add_plan_flag(gph)

    bench = sub.add_parser("bench", help="run the benchmark suite")
    bench.add_argument("--configs", default=None, help="subset, comma-separated")
    bench.add_argument("--device", default=None)
    bench.add_argument(
        "--impl",
        choices=("xla", "pallas", "swar", "mxu", "auto", "both"),
        default="both",
    )
    bench.add_argument(
        "--halo-mode",
        choices=("serial", "overlap"),
        default=None,
        help="override the sharded configs' halo execution mode "
        "(default: each config's own setting)",
    )
    bench.add_argument("--json-metrics", default=None)

    diff = sub.add_parser(
        "diff",
        help="compare two images: max abs diff, differing pixels, PSNR "
        "(the BASELINE.json parity metric); exit 0 iff bit-identical",
    )
    diff.add_argument("a", help="first image path")
    diff.add_argument("b", help="second image path")
    diff.add_argument(
        "--json-metrics", default=None, help="write the record ('-' = stdout)"
    )

    tune = sub.add_parser(
        "autotune",
        help="measure the fastest Pallas block height on the live backend "
        "and record it in the calibration store (the measured replacement "
        "for the reference's hand-tuned compile-time BLOCK_SIZE, "
        "kernel.cu:13; see utils/calibration.py)",
    )
    tune.add_argument(
        "action",
        nargs="?",
        choices=("run", "info"),
        default="run",
        help="'run' (default) sweeps and records; 'info' prints the "
        "store's records for --ops — with --online, both the offline "
        "sweep records AND the online observations/promotions the "
        "continuous tuner accumulated (tune/store), plus which side the "
        "newest-wins precedence rule would pick",
    )
    tune.add_argument(
        "--online",
        action="store_true",
        help="with 'info': include online observations, promotions, "
        "quarantines and the audit-trail tail next to the offline records",
    )
    tune.add_argument(
        "--ops",
        default="gaussian:5",
        help="pipeline to tune against (default: the headline 5x5 Gaussian)",
    )
    tune.add_argument(
        "--impl", choices=("pallas", "swar"), default="pallas"
    )
    tune.add_argument(
        "--dimension",
        choices=("block", "backend", "plan"),
        default="block",
        help="what to calibrate: 'block' sweeps Pallas row-block heights "
        "(--impl/--blocks apply); 'backend' measures VPU (pallas) vs MXU "
        "banded vs hybrid per eligible stencil family in --ops and "
        "records the winner per device kind — `--impl auto` then routes "
        "a family to the MXU only behind such a recorded win "
        "(ops/mxu_kernels.py, utils/calibration.py); 'plan' measures the "
        "per-op / pointwise-absorption / fully-fused execution plans of "
        "--ops (all bit-identical, gated before timing) and records the "
        "fastest per (device kind, pipeline fingerprint) — `--plan auto` "
        "entry points then route through the recorded structure "
        "(plan/planner.py)",
    )
    tune.add_argument("--height", type=int, default=4320)
    tune.add_argument("--width", type=int, default=7680)
    tune.add_argument(
        "--blocks",
        default="64,128,192,256,384,512",
        help="comma-separated candidate block heights; candidates above "
        "the VMEM-safe heuristic are skipped",
    )
    tune.add_argument("--device", default=None)
    tune.add_argument(
        "--calib-file",
        default=None,
        help="calibration store path (default: $MCIM_CALIB_FILE or "
        "./.mcim_calibration.json)",
    )
    tune.add_argument(
        "--dry-run",
        action="store_true",
        help="measure and print, but do not write the calibration store",
    )
    tune.add_argument(
        "--allow-interpret",
        action="store_true",
        help="permit the sweep on a non-TPU backend, where Pallas runs in "
        "INTERPRET mode — the recorded height is meaningless for real "
        "hardware (CPU tests/dev only; refused otherwise)",
    )
    tune.add_argument("--json-metrics", default=None)

    info = sub.add_parser("info", help="print device/mesh/version info")
    info.add_argument(
        "--device",
        default=None,
        help="backend to report on (cpu|tpu); cpu never touches the TPU "
        "plugin, so it works even when the chip/tunnel is wedged",
    )
    return p


def _configure_platform(device: str | None) -> None:
    # Honor JAX_PLATFORMS from the environment when no --device was given
    # (comma lists pass through verbatim): a user asking for cpu must never
    # block on a wedged accelerator plugin.
    if device is None:
        device = os.environ.get("JAX_PLATFORMS") or None
    if device:
        from mpi_cuda_imagemanipulation_tpu.utils.platform import claim_platform

        claim_platform(device)


def cmd_run(args: argparse.Namespace) -> int:
    _configure_platform(args.device)
    _arm_failpoints(args)
    _configure_tracing(args)
    import jax
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.io.image import load_image, save_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
    from mpi_cuda_imagemanipulation_tpu.parallel.mesh import (
        distributed_init,
        mesh_from_shards,
    )
    from mpi_cuda_imagemanipulation_tpu.utils.log import (
        emit_json_metrics,
        get_logger,
    )

    log = get_logger()
    distributed_init()
    pipe = Pipeline.parse(args.ops)
    needs_rgb_output = not args.gray_output

    # one trace for the whole run: load → compute (compile + steady) →
    # save each get a span, so --trace-out answers "where did the
    # invocation's wall time go" on one timeline
    root = obs_trace.start_trace(
        "run", ops=pipe.name, impl=args.impl, shards=str(args.shards)
    )
    with obs_trace.span("run.load", parent=root.context(), path=args.input):
        img = load_image(args.input)
    log.info("loaded %s: %s", args.input, img.shape)

    guarded = args.device_timeout is not None
    if guarded:
        from mpi_cuda_imagemanipulation_tpu.utils.guard import (
            DeviceTimeoutError,
            run_guarded,
        )
        from mpi_cuda_imagemanipulation_tpu.parallel.mesh import parse_shards

        # validate the shards/backend combo BEFORE spawning the watchdog
        # child: the 2-D runner computes tiles with XLA only, and surfacing
        # that from the child would be an opaque RuntimeError traceback
        # instead of main()'s clean one-line error (review finding)
        _n_r, _n_c = parse_shards(args.shards)
        if _n_c is not None and args.impl not in ("xla", "auto"):
            raise ValueError(
                "2-D sharding (--shards RxC) computes tiles with XLA; use "
                f"--impl xla or auto (got {args.impl!r})"
            )
        if args.profile_dir:
            log.warning(
                "--profile-dir is not supported in guarded mode "
                "(--device-timeout); ignored"
            )
        t0 = time.perf_counter()
        timings: dict = {}
        try:
            out = run_guarded(
                args.ops,
                np.asarray(img),
                args.device_timeout,
                impl=args.impl,
                block_h=args.block,
                shards=args.shards,
                halo_mode=args.halo_mode,
                timings=timings,
            )
        except DeviceTimeoutError as e:
            log.error("%s", e)
            root.set(error="DeviceTimeoutError")
            root.end()
            _export_trace(args, log)
            return 4
        # the child reports device-synced windows; fall back to the outer
        # wall (incl. process spawn) only if the sidecar went missing
        compile_and_run_s = timings.get(
            "compile_and_run_s", time.perf_counter() - t0
        )
        steady_s = timings.get("steady_s")
    else:
        mesh = mesh_from_shards(args.shards)
        if mesh is not None:
            if args.block:
                log.warning(
                    "--block applies to single-device Pallas runs; ignored"
                )
            fn = pipe.sharded(
                mesh, backend=args.impl, halo_mode=args.halo_mode,
                plan=args.plan,
            )
        else:
            if args.block and args.impl == "xla":
                log.warning(
                    "--block only affects Pallas kernels; ignored for xla"
                )
            fn = pipe.jit(
                backend=args.impl, block_h=args.block, plan=args.plan
            )

        if args.profile_dir:
            jax.profiler.start_trace(args.profile_dir)

        t0 = time.perf_counter()
        with obs_trace.span("run.compile_and_run", parent=root.context()):
            out = jax.block_until_ready(fn(img))
        compile_and_run_s = time.perf_counter() - t0
        steady_s = None
        if args.show_timing or args.json_metrics:
            # second run isolates steady-state latency from compile time
            t0 = time.perf_counter()
            with obs_trace.span("run.steady", parent=root.context()):
                out = jax.block_until_ready(fn(img))
            steady_s = time.perf_counter() - t0

        if args.profile_dir:
            jax.profiler.stop_trace()
            log.info("profile written to %s", args.profile_dir)

    out = np.asarray(out)
    if needs_rgb_output and out.ndim == 2:
        from mpi_cuda_imagemanipulation_tpu.io.image import gray_to_rgb

        out = gray_to_rgb(out)
    with obs_trace.span("run.save", parent=root.context(), path=args.output):
        save_image(args.output, out)
    log.info("wrote %s: %s", args.output, out.shape)
    if args.show:
        try:
            from PIL import Image

            Image.fromarray(out).show(title=args.output)
        except Exception as e:  # headless host — keep the batch exit clean
            log.warning("--show failed (headless?): %s", e)

    mp = img.shape[0] * img.shape[1] / 1e6
    if args.show_timing:
        if steady_s is not None:
            print(
                f"pipeline [{pipe.name}] impl={args.impl} shards={args.shards}"
                f"{' (guarded)' if guarded else ''}: "
                f"first call (incl. compile) {compile_and_run_s * 1e3:.2f} ms, "
                f"steady-state {steady_s * 1e3:.2f} ms "
                f"({mp / steady_s:.1f} MP/s)"
            )
        else:
            print(
                f"pipeline [{pipe.name}] impl={args.impl} shards={args.shards} "
                f"(guarded subprocess): {compile_and_run_s * 1e3:.2f} ms incl. "
                f"compile + process spawn; steady-state timing unavailable"
            )
    if args.json_metrics:
        emit_json_metrics(
            {
                "event": "run",
                "ops": pipe.name,
                "impl": args.impl,
                "shards": args.shards,
                "halo_mode": args.halo_mode,
                "guarded": guarded,
                "height": img.shape[0],
                "width": img.shape[1],
                "compile_and_run_s": compile_and_run_s,
                "steady_s": steady_s,
                "mp_per_s": mp / steady_s if steady_s else None,
            },
            None if args.json_metrics == "-" else args.json_metrics,
        )
    root.end()
    _export_trace(args, log)
    return 0


def cmd_batch(args: argparse.Namespace) -> int:
    _configure_platform(args.device)
    _arm_failpoints(args)
    _configure_tracing(args)
    import glob as globmod

    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.io.image import (
        batch_load,
        gray_to_rgb,
        save_image,
    )
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.parallel.mesh import (
        distributed_init,
        make_mesh,
        make_mesh_2d,
        parse_shards,
    )
    from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
    from mpi_cuda_imagemanipulation_tpu.resilience.journal import (
        DEFAULT_NAME as JOURNAL_DEFAULT_NAME,
        BatchJournal,
        content_digest,
    )
    from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

    log = get_logger()
    distributed_init()
    paths = sorted(
        p
        for p in globmod.glob(
            os.path.join(globmod.escape(args.input_dir), args.glob)
        )
        if os.path.isfile(p)
    )
    if not paths:
        # distinct exit code: an empty glob is a different scripting error
        # than inputs that failed to decode (advisor/VERDICT r2 weak #5)
        log.error("no inputs match %s/%s", args.input_dir, args.glob)
        return 3
    os.makedirs(args.output_dir, exist_ok=True)
    # mirror the input's path relative to input-dir, so glob patterns
    # spanning subdirectories can't collide on basenames
    rels = [os.path.relpath(p, args.input_dir) for p in paths]

    # -- journal / resume (resilience/journal.py) --------------------------
    journal = None
    if not args.no_journal:
        journal = BatchJournal(
            args.journal
            or os.path.join(args.output_dir, JOURNAL_DEFAULT_NAME)
        )
    _digests: dict[int, str | None] = {}

    def _digest(i: int) -> str | None:
        if i not in _digests:
            try:
                _digests[i] = content_digest(paths[i])
            except OSError:
                _digests[i] = None
        return _digests[i]

    resumed: set[int] = set()
    if args.resume:
        if journal is None:
            raise ValueError("--resume needs the journal (drop --no-journal)")
        prior = journal.load()
        for i, rel in enumerate(rels):
            rec = prior.get(rel)
            # trust only ok records whose digest still matches the input's
            # current bytes — an edited input is reprocessed, never stale
            if (
                rec
                and rec.get("status") == "ok"
                and rec.get("digest")
                and rec.get("digest") == _digest(i)
            ):
                resumed.add(i)
        log.info(
            "resume: %d/%d inputs already journaled ok, %d to (re)run",
            len(resumed), len(paths), len(paths) - len(resumed),
        )
    failed: dict[int, str] = {}  # index -> error (decode or compute)
    pipe = Pipeline.parse(args.ops)
    if args.stream_rows:
        _n_r, _n_c = parse_shards(args.shards)
        if max(1, args.stack) > 1 or _n_r * (_n_c or 1) > 1:
            raise ValueError(
                "--stream-rows streams each input through the tile "
                "engine and is incompatible with --stack/--shards"
            )
        return _batch_stream(args, paths, rels, resumed, journal, _digest, pipe, log)
    stack = max(1, args.stack)
    n_r, n_c = parse_shards(args.shards)
    n_flat = n_r * (n_c or 1)
    stage = None  # H2D pre-staging hook; only for single-device dispatches
    if stack > 1 and n_flat > 1:
        # data parallelism: the stack is sharded over the device mesh, each
        # device running the full pipeline on its slice of the images
        # (Pipeline.data_parallel — throughput counterpart of the
        # row-sharded latency path); a 2-D spec contributes its flat count
        if stack % n_flat:
            log.warning(
                "--stack %d is not a multiple of %d devices: full mid-"
                "stream dispatches pad to %d images and discard the pad's "
                "compute (the trailing partial stack ships right-sized); "
                "round --stack to a mesh multiple to avoid the waste",
                stack, n_flat, -(-stack // n_flat) * n_flat,
            )
        fn = pipe.data_parallel(
            make_mesh(n_flat), backend=args.impl, plan=args.plan
        )
    elif stack > 1:  # incl. --shards 1 / 1x1: stacked dispatch, one device
        # donated inputs: each dispatch's staged buffer recycles into its
        # output, so steady state runs without per-batch HBM allocation
        fn = pipe.batched(backend=args.impl, donate=True, plan=args.plan)
    elif n_flat > 1 or n_c is not None:
        mesh = make_mesh_2d(n_r, n_c) if n_c is not None else make_mesh(n_r)
        fn = pipe.sharded(
            mesh, backend=args.impl, halo_mode=args.halo_mode, plan=args.plan
        )
    else:
        # one jit: re-traces only per shape; donation as above
        fn = pipe.jit(backend=args.impl, donate=True, plan=args.plan)
    if stack == 1 and n_flat == 1 and n_c is None or stack > 1 and n_flat == 1:
        import jax

        # async H2D staging: the input upload is already in flight when the
        # dispatch enqueues (sharded/data-parallel callables place their
        # own inputs, so those paths skip it)
        stage = jax.device_put

    t0 = time.perf_counter()
    total_mp = 0.0
    done = 0
    import threading

    from mpi_cuda_imagemanipulation_tpu.engine import Engine, EngineMetrics
    from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
    from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry

    # one registry for the run: engine stage/inflight families plus the
    # per-outcome input counter below; --metrics-out snapshots it as
    # Prometheus text at exit (the offline GET /metrics)
    registry = Registry()
    inputs_total = registry.counter(
        "mcim_batch_inputs_total",
        "Batch inputs by outcome (ok/failed/resumed).",
        labels=("outcome",),
    )
    inputs_total.inc(len(resumed), outcome="resumed")

    # --inflight governs the async engine's dispatch depth (>= 2 overlaps
    # host decode/encode with device compute); --window is the deprecated
    # alias from before the engine existed
    if args.inflight is not None:
        inflight_depth = args.inflight
    elif args.window is not None:
        log.warning("--window is deprecated; use --inflight")
        inflight_depth = args.window
    else:
        inflight_depth = 2
    inflight_depth = max(1, inflight_depth)
    state_lock = threading.Lock()  # guards done/failed across engine workers

    def record_failed(idxs, e) -> None:
        # a failed dispatch/save fails ONLY its own inputs (with a journal
        # line each) — the run continues; the summary exit goes nonzero
        msg = f"{type(e).__name__}: {e}"
        with state_lock:
            for i in idxs:
                failed[i] = msg
        inputs_total.inc(len(idxs), outcome="failed")
        for i in idxs:
            log.error("failed %s: %s", rels[i], msg)
            if journal is not None:
                journal.record_failed(rels[i], _digest(i), msg)

    def save_one(i, out):
        nonlocal done
        if not args.gray_output and out.ndim == 2:
            out = gray_to_rgb(out)
        dst = os.path.join(args.output_dir, rels[i])
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        save_image(dst, out)
        if journal is not None:
            # journaled ONLY here, after the output file exists: a run
            # killed with this batch still in flight re-runs it on
            # --resume — no lost outputs, and no duplicates because the
            # resumed run skips exactly the journaled-ok inputs
            journal.record_ok(rels[i], _digest(i), rels[i])
        inputs_total.inc(outcome="ok")
        with state_lock:
            done += 1

    def on_done(idxs, out, info):
        # engine encode/write worker: a save failure fails only its input
        for k, i in enumerate(idxs):
            try:
                save_one(i, out[k] if stack > 1 else out)
            except Exception as e:
                record_failed([i], e)

    def on_error(idxs, e):
        # device-side failure surfaced at completion (force/D2H)
        record_failed(list(idxs), e)

    engine = Engine(
        inflight=inflight_depth,
        io_threads=max(1, args.io_threads),
        stage=stage,
        metrics=EngineMetrics(registry=registry),
        name="batch",
    )

    # same-shape images accumulate into a stack and ship as one dispatch;
    # a shape change flushes the pending stack (stack == 1: ship per image)
    pending: list[tuple[int, np.ndarray]] = []
    from mpi_cuda_imagemanipulation_tpu.serve.bucketing import pad_stack

    def _ship(idxs, make_input):
        # host-side dispatch failures (incl. armed halo.exchange
        # failpoints) surface at submit time; fail those inputs, keep going.
        # submit blocks while --inflight dispatches are outstanding — the
        # backpressure that keeps decode from racing ahead of the device.
        # Each dispatch is its own trace: build/h2d/enqueue happen under
        # this root on the caller thread, force/encode under it on the
        # engine's threads (context rides the work item)
        root = obs_trace.start_trace(
            "batch.dispatch", n=len(idxs), first=rels[idxs[0]]
        )
        try:
            with root:
                engine.submit(
                    tuple(idxs), make_input, fn,
                    on_done=on_done, on_error=on_error,
                )
        except Exception as e:
            record_failed(idxs, e)

    def flush_pending(final: bool = False):
        nonlocal pending
        if not pending:
            return
        idxs = [i for i, _ in pending]
        if stack > 1:
            imgs = [im for _, im in pending]
            if final and len(imgs) < stack:
                # the TRAILING partial stack ships right-sized: one
                # tail-shaped compile beats padding to --stack and
                # discarding the pad's compute (the data-parallel runner
                # still pads internally, but only to a mesh multiple)
                _ship(idxs, lambda: np.stack(imgs, axis=0))
            else:
                # mid-stream partial (shape-change flush): pad by
                # repeating the last image so every dispatch for a given
                # image shape reuses one compiled batch shape — the shape
                # may recur, and a ragged batch would recompile each time
                # (serve/bucketing.pad_stack — shared with the serving
                # scheduler); padded outputs are dropped in on_done,
                # which iterates idxs only
                _ship(idxs, lambda: pad_stack(imgs, stack))
        else:
            img0 = pending[0][1]
            _ship(idxs, lambda: img0)
        pending = []

    # resume: only un-journaled (or stale/failed) inputs are decoded at all
    work_idx = [i for i in range(len(paths)) if i not in resumed]
    work_paths = [paths[i] for i in work_idx]
    seen: set[int] = set()
    try:
        for j, img, dig in batch_load(
            work_paths,
            n_threads=args.threads,
            on_error="skip",
            with_digests=True,  # hashed on the decode worker, not here
        ):
            i = work_idx[j]
            _digests.setdefault(i, dig)
            # preemption/kill simulation point for the --resume tests: an
            # armed batch.interrupt failpoint aborts the run here, mid-stream
            failpoints.maybe_fail("batch.interrupt", index=i, path=paths[i])
            seen.add(i)
            if pending and (
                len(pending) >= stack or pending[-1][1].shape != img.shape
            ):
                flush_pending()
            pending.append((i, img))
            total_mp += img.shape[0] * img.shape[1] / 1e6
            if stack == 1:
                flush_pending()
        flush_pending(final=True)
    finally:
        # drain every dispatched batch (outputs written, journal lines
        # appended) even when an interrupt/failpoint is propagating: the
        # work that finished must be resumable, the work that didn't must
        # look never-started
        engine.close()
    # decode failures: batch_load skipped them (logged); give them journal
    # lines so --resume re-attempts exactly these
    for j, p in enumerate(work_paths):
        i = work_idx[j]
        if i not in seen and i not in failed:
            failed[i] = "decode failed (skipped)"
            if journal is not None:
                journal.record_failed(rels[i], _digest(i), failed[i])
    wall = time.perf_counter() - t0
    eng = engine.metrics.snapshot()
    # adaptive precision: thumbnail batches should not round to "0.0 MP",
    # large batches should stay in plain decimal (%.3g would go scientific)
    def _fmt(v: float, unit: str) -> str:
        return f"{v:.3g} {unit}" if v < 1 else f"{v:.1f} {unit}"

    mp_s = _fmt(total_mp, "MP")
    rate_s = _fmt(total_mp / wall, "MP/s")
    log.info(
        "processed %d/%d images (%s) in %.2fs (%s end-to-end)%s",
        done, len(paths), mp_s, wall, rate_s,
        f" [{len(resumed)} resumed, {len(failed)} failed]"
        if resumed or failed
        else "",
    )
    if eng["submitted"]:
        log.info("%s", engine.metrics.summary_line())
    if args.show_timing:
        idle = eng["device_idle_frac"]
        print(
            f"batch [{pipe.name}] impl={args.impl}: {done}/{len(paths)} images, "
            f"{mp_s} in {wall:.2f}s ({rate_s} "
            f"end-to-end incl. compile+I/O; inflight {inflight_depth}, "
            f"peak {eng['inflight_peak']}"
            + (
                f", device idle {idle * 100:.0f}%"
                if idle is not None
                else ""
            )
            + ")"
        )
    skipped = [
        paths[i]
        for i in range(len(paths))
        if i not in seen and i not in resumed
    ]
    if args.json_metrics:
        from mpi_cuda_imagemanipulation_tpu.utils.log import emit_json_metrics

        emit_json_metrics(
            {
                "event": "batch",
                "ops": pipe.name,
                "impl": args.impl,
                "inputs": len(paths),
                "processed": done,
                "resumed": len(resumed),
                "skipped": skipped,
                "failed": {rels[i]: msg for i, msg in sorted(failed.items())},
                "journal": journal.path if journal is not None else None,
                "total_mp": total_mp,
                "wall_s": wall,
                "mp_per_s": total_mp / wall if wall > 0 else None,
                "inflight": inflight_depth,
                "io_threads": args.io_threads,
                "engine": eng,
            },
            None if args.json_metrics == "-" else args.json_metrics,
        )
    if args.metrics_out:
        # the offline GET /metrics: one Prometheus text snapshot of the
        # run's registry (engine stage/inflight families + input outcomes)
        with open(args.metrics_out, "w") as f:
            f.write(registry.render())
        log.info("metrics snapshot -> %s", args.metrics_out)
    _export_trace(args, log)
    # partial failure (skipped/failed inputs) is a nonzero exit for
    # scripted callers — distinct from the no-inputs-matched exit (3) above
    return 0 if done + len(resumed) == len(paths) else 1


def _batch_stream(args, paths, rels, resumed, journal, digest_fn, pipe, log) -> int:
    """cmd_batch's streaming lane (--stream-rows): every input runs
    through the tile engine with the output encoded incrementally —
    device row bands hand to the encoder single-copy in tile order, so a
    gigapixel input in a batch directory costs tile memory, not frame
    memory. Journal granularity stays per input (digest-verified), so
    --resume composes exactly as in the whole-image lane."""
    import jax

    from mpi_cuda_imagemanipulation_tpu.engine import Engine, EngineMetrics
    from mpi_cuda_imagemanipulation_tpu.io.stream_codec import (
        open_tile_reader,
        open_tile_writer,
    )
    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_op
    from mpi_cuda_imagemanipulation_tpu.stream import (
        StreamMetrics,
        stream_pipeline,
    )
    from mpi_cuda_imagemanipulation_tpu.stream.tiles import out_channels
    from mpi_cuda_imagemanipulation_tpu.utils.log import emit_json_metrics

    if args.impl not in ("auto", "xla", "mxu"):
        raise ValueError(
            "--stream-rows computes tiles with xla/mxu/auto (the Pallas "
            f"streaming kernels are full-image by design); got {args.impl!r}"
        )
    metrics = StreamMetrics()
    engine = Engine(
        inflight=max(1, args.inflight or 2),
        io_threads=max(1, args.io_threads),
        stage=jax.device_put,
        metrics=EngineMetrics(registry=metrics.registry),
        ordered_done=True,
        name="batch-stream",
    )
    done = 0
    failed: dict[int, str] = {}
    total_mp = 0.0
    t0 = time.perf_counter()
    try:
        for i, p in enumerate(paths):
            if i in resumed:
                continue
            rel = rels[i]
            try:
                reader = open_tile_reader(p)
                ops = pipe.ops
                if not args.gray_output and out_channels(
                    ops, reader.channels
                ) == 1:
                    # keep the batch lane's gray->RGB replication contract
                    ops = (*ops, make_op("gray2rgb"))
                base, ext = os.path.splitext(rel)
                if ext.lower() not in (".png", ".ppm", ".pgm", ".pnm"):
                    log.info(
                        "%s: no incremental encoder for %r; writing .png",
                        rel, ext,
                    )
                    rel = base + ".png"
                dst = os.path.join(args.output_dir, rel)
                os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
                writer = open_tile_writer(
                    dst, reader.height, reader.width,
                    out_channels(ops, reader.channels),
                )
                total_mp += reader.height * reader.width / 1e6
                stream_pipeline(
                    reader, writer, ops,
                    tile_rows=args.stream_rows,
                    impl=args.impl,
                    plan=args.plan,
                    metrics=metrics,
                    engine=engine,
                )
                writer.close()
            except Exception as e:
                failed[i] = f"{type(e).__name__}: {e}"
                log.error("failed %s: %s", rels[i], failed[i])
                if journal is not None:
                    journal.record_failed(rels[i], digest_fn(i), failed[i])
                continue
            if journal is not None:
                journal.record_ok(rels[i], digest_fn(i), rel)
            done += 1
    finally:
        engine.close()
    wall = time.perf_counter() - t0
    log.info(
        "streamed %d/%d inputs (%.1f MP) in %.2fs — peak resident %.1f MiB",
        done, len(paths), total_mp, wall,
        metrics.peak_resident_bytes / 2**20,
    )
    if args.json_metrics:
        emit_json_metrics(
            {
                "event": "batch",
                "mode": "stream",
                "ops": pipe.name,
                "impl": args.impl,
                "stream_rows": args.stream_rows,
                "inputs": len(paths),
                "processed": done,
                "resumed": len(resumed),
                "failed": {rels[i]: m for i, m in sorted(failed.items())},
                "total_mp": total_mp,
                "wall_s": wall,
                "peak_resident_bytes": metrics.peak_resident_bytes,
                "engine": engine.metrics.snapshot(),
            },
            None if args.json_metrics == "-" else args.json_metrics,
        )
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            f.write(metrics.registry.render())
    _export_trace(args, log)
    return 0 if done + len(resumed) == len(paths) else 1


def cmd_stream(args: argparse.Namespace) -> int:
    """Constant-memory streaming: one gigapixel-class image (or a video
    frame sequence) through the tile engine — fixed-shape row bands,
    double-buffered H2D prefetch, seam-stitched halos, ordered
    incremental encode. Bit-exact vs the whole-image golden path; peak
    resident bytes follow --tile-rows/--inflight, not image size."""
    _configure_platform(args.device)
    _arm_failpoints(args)
    _configure_tracing(args)
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
    from mpi_cuda_imagemanipulation_tpu.resilience.journal import BatchJournal
    from mpi_cuda_imagemanipulation_tpu.stream import (
        StreamMetrics,
        resumable_tiles,
        stream_fingerprint,
        stream_pipeline,
        stream_video,
    )
    from mpi_cuda_imagemanipulation_tpu.stream.runner import DEFAULT_TILE_ROWS
    from mpi_cuda_imagemanipulation_tpu.stream.tiles import (
        out_channels,
        plan_tiles,
        validate_stream_ops,
    )
    from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
    from mpi_cuda_imagemanipulation_tpu.utils.log import (
        emit_json_metrics,
        get_logger,
    )

    log = get_logger()
    tile_rows = args.tile_rows or env_registry.get_int(
        "MCIM_STREAM_TILE_ROWS"
    ) or DEFAULT_TILE_ROWS
    inflight = args.inflight or env_registry.get_int(
        "MCIM_STREAM_INFLIGHT"
    ) or 2
    metrics = StreamMetrics()

    # -- video mode ---------------------------------------------------------
    if args.video_frames:
        import glob as globmod

        if not args.output_dir:
            raise ValueError("--video-frames needs --output-dir")
        frames = sorted(
            p for p in globmod.glob(args.video_frames) if os.path.isfile(p)
        )
        if not frames:
            log.error("no frames match %s", args.video_frames)
            return 3
        journal = None
        if not args.no_journal:
            journal = BatchJournal(
                args.journal
                or os.path.join(args.output_dir, ".mcim_stream_journal.jsonl")
            )
        rec = stream_video(
            frames,
            args.output_dir,
            args.ops,
            tile_rows=tile_rows,
            inflight=inflight,
            io_threads=max(1, args.io_threads),
            impl=args.impl,
            plan=args.plan,
            out_ext=args.out_ext,
            metrics=metrics,
            journal=journal,
            resume=args.resume,
        )
        log.info(
            "video: %d/%d frames (%d resumed) in %.2fs (%.1f fps), "
            "peak resident %.1f MiB",
            rec["frames_done"], rec["frames"], rec["frames_resumed"],
            rec["wall_s"], rec["fps"] or 0.0,
            rec["peak_resident_bytes"] / 2**20,
        )
        if args.show_timing:
            print(
                f"video [{args.ops}] impl={args.impl}: "
                f"{rec['frames_done']}/{rec['frames']} frames in "
                f"{rec['wall_s']:.2f}s ({rec['fps'] or 0.0:.1f} fps, "
                f"tile_rows {tile_rows}, inflight {inflight}, peak "
                f"resident {rec['peak_resident_bytes'] / 2**20:.1f} MiB)"
            )
        if args.json_metrics:
            emit_json_metrics(
                {"event": "stream", "mode": "video", "ops": args.ops, **rec},
                None if args.json_metrics == "-" else args.json_metrics,
            )
        if args.metrics_out:
            with open(args.metrics_out, "w") as f:
                f.write(metrics.registry.render())
        _export_trace(args, log)
        return 0

    # -- single-image mode --------------------------------------------------
    if bool(args.input) == bool(args.synthetic):
        raise ValueError("stream needs exactly one of --input/--synthetic")
    if not args.output:
        raise ValueError("stream needs --output")
    if args.synthetic:
        from mpi_cuda_imagemanipulation_tpu.io.stream_codec import (
            SyntheticTileReader,
        )

        dims = [int(v) for v in args.synthetic.lower().split("x")]
        if len(dims) not in (2, 3):
            raise ValueError("--synthetic wants HxW or HxWxC")
        h, w = dims[0], dims[1]
        c = dims[2] if len(dims) == 3 else 3
        reader = SyntheticTileReader(h, w, channels=c, seed=0)
    else:
        from mpi_cuda_imagemanipulation_tpu.io.stream_codec import (
            open_tile_reader,
        )

        reader = open_tile_reader(args.input)

    pipe = Pipeline.parse(args.ops)
    halo = validate_stream_ops(pipe.ops)
    out_c = out_channels(pipe.ops, reader.channels)
    tiles = plan_tiles(reader.height, tile_rows, halo)
    fingerprint = stream_fingerprint(
        pipe.name, reader.height, reader.width, reader.channels,
        tile_rows, args.impl,
    )
    journal = None
    if not args.no_journal:
        journal = BatchJournal(args.journal or args.output + ".journal.jsonl")

    resume_tiles = 0
    out_ext = os.path.splitext(args.output)[1].lower()
    if args.resume:
        if journal is None:
            raise ValueError("--resume needs the journal (drop --no-journal)")
        if out_ext not in (".ppm", ".pgm", ".pnm"):
            raise ValueError(
                "image-mode --resume needs a ppm/pgm output (a PNG "
                "compressor's state does not survive a kill); video-mode "
                "resume works per frame with any container"
            )
        resume_tiles = resumable_tiles(journal, "stream", fingerprint, len(tiles))

    from mpi_cuda_imagemanipulation_tpu.io.stream_codec import (
        PNMTileWriter,
        open_tile_writer,
    )

    if resume_tiles and os.path.exists(args.output):
        writer = PNMTileWriter.resume(
            args.output, reader.height, reader.width, out_c,
            tiles[resume_tiles - 1].out_hi,
        )
    else:
        resume_tiles = 0
        writer = open_tile_writer(
            args.output, reader.height, reader.width, out_c
        )

    root = obs_trace.start_trace(
        "stream", ops=pipe.name, impl=args.impl,
        h=reader.height, w=reader.width, tile_rows=tile_rows,
    )
    t0 = time.perf_counter()
    with root:
        try:
            res = stream_pipeline(
                reader, writer, pipe.ops,
                tile_rows=tile_rows,
                inflight=inflight,
                io_threads=max(1, args.io_threads),
                impl=args.impl,
                plan=args.plan,
                metrics=metrics,
                journal=journal,
                resume_tiles=resume_tiles,
                trace_parent=root.context() if root is not obs_trace.NOOP_SPAN else None,
            )
        except RuntimeError as e:
            # completed tiles are durable + journaled; exit clean so a
            # scripted caller retries with --resume instead of parsing a
            # traceback (cmd_batch's partial-failure discipline). Closing
            # the writer here is what MAKES the prefix durable — rows the
            # journal already claims must not die in a file buffer.
            writer.close()
            log.error("%s", e)
            root.set(error="StreamError")
            _export_trace(args, log)
            return 1
        writer.close()
    wall = time.perf_counter() - t0
    mp = reader.height * reader.width / 1e6
    log.info(
        "streamed %dx%d (%.1f MP) as %d tiles (%d resumed) in %.2fs — "
        "peak resident %.1f MiB vs %.1f MiB whole-image",
        reader.height, reader.width, mp, res.tiles, res.tiles_resumed,
        wall, res.peak_resident_bytes / 2**20,
        reader.height * reader.width * reader.channels / 2**20,
    )
    if args.show_timing:
        eng = res.engine
        idle = eng.get("device_idle_frac")
        print(
            f"stream [{pipe.name}] impl={args.impl}: {mp:.1f} MP in "
            f"{wall:.2f}s ({mp / wall:.1f} MP/s e2e; tile_rows "
            f"{tile_rows}, inflight {inflight}, {res.tiles} tiles, "
            f"{res.compiles} compiles, peak resident "
            f"{res.peak_resident_bytes / 2**20:.2f} MiB"
            + (f", device idle {idle * 100:.0f}%" if idle is not None else "")
            + ")"
        )
    if args.json_metrics:
        emit_json_metrics(
            {
                "event": "stream",
                "mode": "image",
                "ops": pipe.name,
                "impl": args.impl,
                "height": reader.height,
                "width": reader.width,
                "channels": reader.channels,
                "tile_rows": tile_rows,
                "inflight": inflight,
                "halo": halo,
                "mp": mp,
                "mp_per_s": mp / wall if wall > 0 else None,
                **res.as_dict(),
            },
            None if args.json_metrics == "-" else args.json_metrics,
        )
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            f.write(metrics.registry.render())
        log.info("metrics snapshot -> %s", args.metrics_out)
    _export_trace(args, log)
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    """Online serving: warm the shape-bucket compile cache, start the
    micro-batching scheduler, serve HTTP until SIGTERM/SIGINT, then drain
    gracefully — admission stops, queued + in-flight work flushes under
    --drain-deadline-s — and print the metrics summary (the north star's
    "heavy traffic" front door)."""
    if getattr(args, "replicas", 1) > 1:
        # pod mode: same flags, but the process becomes the front-door
        # router over N supervised replica workers (fabric/); the
        # `fabric` subcommand exposes the router-specific knobs
        for name, default in (
            ("heartbeat_s", None), ("stale_s", None),
            ("forward_attempts", None), ("mesh_shards", 0),
            ("slo", None), ("autoscale", False),
            ("min_replicas", None), ("max_replicas", None),
            ("systolic", None),
        ):
            if not hasattr(args, name):
                setattr(args, name, default)
        return cmd_fabric(args)
    _configure_platform(args.device)
    _arm_failpoints(args)
    _configure_tracing(args)
    import signal
    import threading

    from mpi_cuda_imagemanipulation_tpu.serve.bucketing import parse_buckets
    from mpi_cuda_imagemanipulation_tpu.serve.server import (
        ServeConfig,
        Server,
    )
    from mpi_cuda_imagemanipulation_tpu.utils.log import (
        emit_json_metrics,
        get_logger,
    )

    log = get_logger()
    try:
        channels = tuple(
            sorted({int(c) for c in args.channels.split(",") if c.strip()})
        )
    except ValueError:
        raise ValueError(
            f"--channels must be comma-separated ints: {args.channels!r}"
        ) from None
    if not channels or not set(channels) <= {1, 3}:
        raise ValueError(f"--channels entries must be 1 and/or 3, got {channels}")
    cfg = ServeConfig(
        ops=args.ops,
        buckets=parse_buckets(args.buckets),
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        queue_depth=args.queue_depth,
        channels=channels,
        shards=args.shards,
        backend="xla" if args.impl == "auto" else args.impl,
        plan=args.plan,
        default_deadline_ms=args.deadline_ms,
        retry_attempts=args.retry_attempts,
        breaker_threshold=args.breaker_threshold,
        breaker_reset_s=args.breaker_reset_s,
        inflight=args.inflight,
        io_threads=args.io_threads,
    )
    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        log.info(
            "signal %s: graceful drain (deadline %.0fs)",
            signal.Signals(signum).name, args.drain_deadline_s,
        )
        stop_evt.set()

    prev_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)
    }
    srv = Server(cfg, args.host, args.port)
    try:
        srv.start()
        log.info(
            "serving [%s] on %s:%d (buckets %s, max_batch %d, max_delay "
            "%.1fms, queue_depth %d, shards %d) — POST /v1/process, "
            "GET /healthz, GET /stats",
            srv.app.pipe.name, args.host or "0.0.0.0", srv.address[1],
            args.buckets, args.max_batch, args.max_delay_ms,
            args.queue_depth, args.shards,
        )
        stop_evt.wait()
    except KeyboardInterrupt:
        log.info("interrupt: draining and shutting down")
    finally:
        for s, h in prev_handlers.items():
            signal.signal(s, h)
        srv.close(drain=True, deadline_s=args.drain_deadline_s)
        # the SIGTERM drain is a flight-recorder dump trigger: the ring's
        # serving-time facts (hot buckets, breaker/failpoint history)
        # become the shutdown post-mortem (obs/recorder.py)
        from mpi_cuda_imagemanipulation_tpu.obs import recorder as _recorder

        dump_path = _recorder.dump("sigterm_drain", extra={"entry": "serve"})
        if dump_path:
            log.info("recorder dump -> %s", dump_path)
        if args.json_metrics:
            emit_json_metrics(
                {"event": "serve", **srv.app.stats()},
                None if args.json_metrics == "-" else args.json_metrics,
            )
        _export_trace(args, log)
    return 0


def cmd_fabric(args: argparse.Namespace) -> int:
    """Pod-scale serving: front-door router + N supervised replica worker
    processes (fabric/). The router owns --port; replicas bind ephemeral
    ports and register via heartbeat. SIGTERM/SIGINT drains the whole pod
    (replicas flush in-flight work, then the router stops)."""
    _configure_platform(args.device)
    _arm_failpoints(args)
    _configure_tracing(args)
    import signal
    import threading

    from mpi_cuda_imagemanipulation_tpu.fabric.control import (
        default_heartbeat_s,
    )
    from mpi_cuda_imagemanipulation_tpu.fabric.router import RouterConfig
    from mpi_cuda_imagemanipulation_tpu.fabric.supervisor import (
        Fabric,
        FabricConfig,
    )
    from mpi_cuda_imagemanipulation_tpu.graph.systolic import ENV_SYSTOLIC
    from mpi_cuda_imagemanipulation_tpu.serve.bucketing import parse_buckets
    from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
    from mpi_cuda_imagemanipulation_tpu.utils.log import (
        emit_json_metrics,
        get_logger,
    )

    log = get_logger()
    systolic = (
        args.systolic
        if args.systolic is not None
        else env_registry.get_bool(ENV_SYSTOLIC)
    )
    cfg = FabricConfig(
        replicas=args.replicas,
        ops=args.ops,
        buckets=args.buckets,
        channels=args.channels,
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        queue_depth=args.queue_depth,
        impl="xla" if args.impl == "auto" else args.impl,
        plan=getattr(args, "plan", "auto"),
        tune=args.tune,
        tune_arms=args.tune_arms,
        heartbeat_s=args.heartbeat_s,
        router=RouterConfig(
            buckets=parse_buckets(args.buckets),
            stale_s=args.stale_s,
            forward_attempts=args.forward_attempts,
            slo_specs=args.slo,
        ),
        mesh_shards=args.mesh_shards,
        autoscale=args.autoscale,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        systolic=systolic,
        federate=args.federate,
        pod_id=args.pod_id,
    )
    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        log.info(
            "signal %s: draining the fabric",
            signal.Signals(signum).name,
        )
        stop_evt.set()

    prev_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)
    }
    fab = Fabric(cfg)
    try:
        fab.start(args.host, args.port)
        log.info(
            "fabric serving [%s] on %s:%d: router over %d replicas "
            "(buckets %s, heartbeat %.2fs%s) — POST /v1/process, "
            "GET /healthz, GET /stats, GET /metrics",
            args.ops, args.host or "0.0.0.0", fab.router.address[1],
            args.replicas, args.buckets,
            fab.config.heartbeat_s
            if fab.config.heartbeat_s is not None
            else default_heartbeat_s(),
            f", mesh lane {args.mesh_shards} shards"
            if args.mesh_shards
            else "",
        )
        stop_evt.wait()
    except KeyboardInterrupt:
        log.info("interrupt: draining the fabric")
    finally:
        for s, h in prev_handlers.items():
            signal.signal(s, h)
        stats = fab.stats() if fab.supervisor is not None else None
        fab.close(drain=True)
        if args.json_metrics and stats is not None:
            emit_json_metrics(
                {"event": "fabric", **stats},
                None if args.json_metrics == "-" else args.json_metrics,
            )
        _export_trace(args, log)
    return 0


def cmd_federation(args: argparse.Namespace) -> int:
    """Multi-pod federation front door (federation/): a meta-router over
    whole fabric pods. Pods join by heartbeating (`fabric --federate`);
    tenant/spec registrations persist in the durable registry across
    restarts. SIGTERM/SIGINT stops the listener (pods serve on)."""
    _arm_failpoints(args)
    _configure_tracing(args)
    import signal
    import threading

    from mpi_cuda_imagemanipulation_tpu.federation.frontdoor import (
        FrontDoor,
        FrontDoorConfig,
    )
    from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

    log = get_logger()
    door = FrontDoor(
        FrontDoorConfig(
            registry_path=args.registry,
            stale_s=args.stale_s,
            shed_frac=args.shed_frac,
        )
    )
    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        log.info(
            "signal %s: stopping the front door",
            signal.Signals(signum).name,
        )
        stop_evt.set()

    prev_handlers = {
        s: signal.signal(s, _on_signal)
        for s in (signal.SIGTERM, signal.SIGINT)
    }
    try:
        door.start(args.host, args.port)
        log.info(
            "federation front door on %s:%d (registry %s: %d records "
            "rehydrated, %d lines skipped) — pods join via "
            "`fabric --federate http://HOST:%d --pod-id NAME`",
            args.host or "0.0.0.0", door.address[1], door.durable.path,
            door.durable.loaded_records, door.durable.skipped_lines,
            door.address[1],
        )
        stop_evt.wait()
    except KeyboardInterrupt:
        log.info("interrupt: stopping the front door")
    finally:
        for s, h in prev_handlers.items():
            signal.signal(s, h)
        door.close()
        _export_trace(args, log)
    return 0


def cmd_graph(args: argparse.Namespace) -> int:
    """Validate (and optionally run) a pipeline-spec DAG from a file —
    the offline form of the pipeline service's POST surface."""
    from mpi_cuda_imagemanipulation_tpu.graph.spec import SpecError

    try:
        with open(args.spec, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise FileNotFoundError(f"cannot read --spec: {e}") from None
    try:
        from mpi_cuda_imagemanipulation_tpu.graph import (
            compile_graph,
            dag_fingerprint,
            graph_callable,
            parse_spec,
        )

        graph = parse_spec(raw)
    except SpecError as e:
        print(f"spec rejected [{e.code}]: {e}", file=sys.stderr)
        return 2
    program = compile_graph(graph, plan=args.plan, backend=args.impl)
    print(graph.describe())
    print(program.describe())
    print(f"pipeline id: {dag_fingerprint(graph)}")
    if args.validate_only:
        return 0
    if bool(args.input) == bool(args.synthetic):
        raise ValueError("graph needs exactly one of --input/--synthetic")
    _configure_platform(args.device)
    import json as _json
    import time as _time

    import jax
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.io.image import (
        load_image,
        save_image,
        synthetic_image,
    )

    if args.synthetic:
        dims = [int(v) for v in args.synthetic.lower().split("x")]
        if len(dims) not in (2, 3):
            raise ValueError("--synthetic wants HxW or HxWxC")
        img = synthetic_image(
            dims[0], dims[1], channels=dims[2] if len(dims) == 3 else 3,
            seed=0,
        )
    else:
        img = load_image(args.input)
    try:
        graph.check_channels(img.shape[2] if img.ndim == 3 else 1)
    except SpecError as e:
        print(f"request rejected [{e.code}]: {e}", file=sys.stderr)
        return 2
    fn = jax.jit(graph_callable(program, impl=args.impl))
    t0 = _time.perf_counter()
    out = jax.tree_util.tree_map(np.asarray, fn(img))
    wall = _time.perf_counter() - t0
    print(
        f"ran {len(program.steps)} steps in {wall * 1e3:.1f} ms "
        f"(outputs: {sorted(out)})"
    )
    if args.output:
        save_image(args.output, out["image"])
        print(f"image -> {args.output}")
    if args.histogram_out:
        if "histogram" not in out:
            raise ValueError(
                "--histogram-out needs a spec with outputs.histogram"
            )
        with open(args.histogram_out, "w") as f:
            _json.dump([int(v) for v in out["histogram"]], f)
        print(f"histogram -> {args.histogram_out}")
    if args.stats_out:
        if "stats" not in out:
            raise ValueError("--stats-out needs a spec with outputs.stats")
        stats = {
            "count": int(out["stats"]["count"]),
            "min": int(out["stats"]["min"]),
            "max": int(out["stats"]["max"]),
            "mean": round(float(out["stats"]["mean"]), 4),
        }
        with open(args.stats_out, "w") as f:
            _json.dump(stats, f)
        print(f"stats -> {args.stats_out}")
    if args.json_metrics:
        rec = {
            "event": "graph",
            "spec": args.spec,
            "pipeline_id": dag_fingerprint(graph),
            "nodes": len(graph.nodes),
            "segments": program.n_segments,
            "merges": program.n_merges,
            "mode": program.mode,
            "wall_ms": wall * 1e3,
            "outputs": sorted(out),
        }
        payload = _json.dumps(rec, indent=2)
        if args.json_metrics == "-":
            print(payload)
        else:
            with open(args.json_metrics, "w") as f:
                f.write(payload)
    return 0


def cmd_bench(args: argparse.Namespace) -> int:
    _configure_platform(args.device)
    from mpi_cuda_imagemanipulation_tpu.bench_suite import run_suite

    names = args.configs.split(",") if args.configs else None
    run_suite(
        names=names,
        impl=args.impl,
        json_path=args.json_metrics,
        halo_mode=args.halo_mode,
    )
    return 0


def cmd_diff(args: argparse.Namespace) -> int:
    """Bit-exactness / PSNR comparison of two images — the verification
    affordance the reference lacks entirely (its only check is visual
    imshow, kern.cpp:89; PSNR is the BASELINE.json parity criterion)."""
    import numpy as np

    from mpi_cuda_imagemanipulation_tpu.io.image import load_image
    from mpi_cuda_imagemanipulation_tpu.utils.log import emit_json_metrics

    a = np.asarray(load_image(args.a)).astype(np.int64)
    b = np.asarray(load_image(args.b)).astype(np.int64)
    if a.shape != b.shape:
        print(f"shape mismatch: {a.shape} vs {b.shape}")
        if args.json_metrics:
            emit_json_metrics(
                {
                    "event": "diff",
                    "shape_a": list(a.shape),
                    "shape_b": list(b.shape),
                    "identical": False,
                    "error": "shape mismatch",
                },
                None if args.json_metrics == "-" else args.json_metrics,
            )
        return 2
    d = np.abs(a - b)
    ndiff = int(np.count_nonzero(d))
    mse = float((d.astype(np.float64) ** 2).mean())
    psnr = float("inf") if mse == 0 else 10.0 * np.log10(255.0**2 / mse)
    rec = {
        "event": "diff",
        "shape": list(a.shape),
        "max_abs_diff": int(d.max()),
        "differing_pixels": ndiff,
        "total_pixels": int(d.size),
        "mse": mse,
        "psnr_db": psnr,
        "identical": ndiff == 0,
    }
    print(
        f"{'identical' if ndiff == 0 else 'DIFFERENT'}: maxdiff {rec['max_abs_diff']}, "
        f"{ndiff}/{d.size} values differ, PSNR "
        + ("inf" if mse == 0 else f"{psnr:.2f} dB")
    )
    if args.json_metrics:
        emit_json_metrics(
            rec, None if args.json_metrics == "-" else args.json_metrics
        )
    return 0 if ndiff == 0 else 1


def _autotune_info(args: argparse.Namespace) -> int:
    """`autotune info [--online]`: the store's records for --ops — the
    offline sweep entries, and with --online the continuous tuner's
    observations/promotions/quarantines plus which side the newest-wins
    precedence (tune/store.effective_plan_choice) picks."""
    import json as _json

    from mpi_cuda_imagemanipulation_tpu.ops.registry import make_pipeline_ops
    from mpi_cuda_imagemanipulation_tpu.plan.ir import pipeline_fingerprint
    from mpi_cuda_imagemanipulation_tpu.tune.store import (
        effective_plan_choice,
        online_store,
    )
    from mpi_cuda_imagemanipulation_tpu.utils import calibration

    if args.calib_file:
        os.environ["MCIM_CALIB_FILE"] = args.calib_file
    fp = pipeline_fingerprint(make_pipeline_ops(args.ops))
    try:
        kind = calibration.current_device_kind()
    except Exception:
        print("error: no live backend to resolve the device kind")
        return 1
    from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
        STAGE_FALLBACK_REASONS,
        STAGE_ARMS,
    )
    from mpi_cuda_imagemanipulation_tpu.plan.metrics import plan_metrics

    offline = calibration.plan_entry(fp, device_kind=kind)
    report: dict = {
        "store": calibration.calib_path(),
        "device_kind": kind,
        "ops": args.ops,
        "pipeline_fingerprint": fp,
        "offline": {"plan_choice": offline},
        # the per-op-within-stage MXU dimension (round 8): the calibrated
        # stage_arm table plus this process's counted arm landings and
        # closed-vocabulary fallback reasons — a silently-ineligible
        # fleet shows up here, not in a debugger
        "mxu_in_stage": {
            "stage_arms": calibration.stage_arm_entries(kind),
            "ops_by_arm": {
                a: int(plan_metrics.mxu_stage_ops.value(arm=a))
                for a in STAGE_ARMS
                if a != "vpu"
            },
            "fallbacks_by_reason": {
                r: int(plan_metrics.mxu_stage_fallbacks.value(reason=r))
                for r in STAGE_FALLBACK_REASONS
            },
        },
    }
    if args.online:
        windows = online_store.windows(fp, device_kind=kind)
        report["online"] = {
            "promoted": online_store.promoted_entry(fp, device_kind=kind),
            "observations": {
                w: online_store.arm_stats(fp, w, device_kind=kind)
                for w in sorted(windows)
            },
            "audit_tail": online_store.audit_trail()[-10:],
        }
        report["effective"] = {
            # the choice resolve_plan_mode would act on, newest wins;
            # disagreement here is exactly what
            # mcim_tune_stale_overrides_total counts in a serving process
            "plan_choice": effective_plan_choice(fp, device_kind=kind),
        }
    print(_json.dumps(report, indent=2, sort_keys=True, default=str))
    return 0


def cmd_autotune(args: argparse.Namespace) -> int:
    """Sweep candidate block heights on the live backend; record the best.

    Runs with lookups disabled (MCIM_NO_CALIB) so an existing calibration
    cannot steer the sweep it is about to overwrite.
    """
    _configure_platform(args.device)
    if args.action == "info":
        return _autotune_info(args)
    # parse/validate ALL candidates before any expensive measurement: a
    # malformed trailing token must not discard minutes of serialized
    # chip-window work (review finding)
    try:
        candidates = [int(tok) for tok in args.blocks.split(",") if tok.strip()]
    except ValueError:
        raise ValueError(
            f"--blocks must be comma-separated ints: {args.blocks!r}"
        ) from None
    if not candidates:
        raise ValueError("--blocks is empty")
    # the sweep must not leak env mutations: a caller's kill-switch or store
    # path stays exactly as it was on return (review finding)
    saved = {
        k: os.environ.get(k) for k in ("MCIM_CALIB_FILE", "MCIM_NO_CALIB")
    }
    if args.calib_file:
        os.environ["MCIM_CALIB_FILE"] = args.calib_file
    os.environ["MCIM_NO_CALIB"] = "1"
    try:
        import jax

        from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
        from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
            _live_f32_temps,
            _pick_block_h,
            group_ops,
            pipeline_pallas,
        )
        from mpi_cuda_imagemanipulation_tpu.ops.registry import (
            make_pipeline_ops,
        )
        from mpi_cuda_imagemanipulation_tpu.utils import calibration
        from mpi_cuda_imagemanipulation_tpu.utils.log import emit_json_metrics
        from mpi_cuda_imagemanipulation_tpu.utils.timing import (
            device_throughput,
        )

        from mpi_cuda_imagemanipulation_tpu.utils.platform import (
            is_tpu_backend,
        )

        if args.dimension == "plan":
            # the plan sweep times pure-XLA executables — meaningful on
            # any backend, and recorded per device kind, so a CPU record
            # only ever steers CPU runs (no interpret-mode hazard)
            return _autotune_plan(args, make_pipeline_ops(args.ops))
        backend = jax.default_backend()
        if not is_tpu_backend() and not args.allow_interpret:
            # pipeline_pallas defaults to interpret=True off-TPU, so the
            # sweep would time the Pallas INTERPRETER and record a
            # meaningless height that then clamps real runs on this device
            # kind via the min rule (advisor round-3 finding)
            print(
                f"error: refusing to autotune on backend {backend!r} — the "
                "sweep would time Pallas interpret mode and record a "
                "meaningless block height; pass --allow-interpret to "
                "override (CPU tests/dev only)",
                file=sys.stderr,
            )
            return 3
        ops = make_pipeline_ops(args.ops)
        if args.dimension == "backend":
            return _autotune_backend(args, ops)
        # the recorded calibration is applied through min(heuristic, calib),
        # so any candidate above the heuristic cap for this sweep's config
        # could never take effect at run time — measuring it would waste
        # serialized chip time and could "win" a value the min rule then
        # ignores (review finding). Cap = the tightest per-group heuristic.
        swar = args.impl == "swar"
        if swar:
            from mpi_cuda_imagemanipulation_tpu.ops.swar_kernels import (
                _pick_swar_block_h,
                _swar_mode,
                _taps_shift,
                pipeline_swar,
                swar_any_eligible,
                swar_eligible,
            )

            # shape-inclusive eligibility: an ineligible --width would
            # silently sweep the pallas FALLBACK and record its timing as a
            # swar calibration (review finding)
            eligible = [
                op
                for op in ops
                if swar_any_eligible(op, (args.height, args.width))
            ]
            if not eligible:
                print(
                    f"error: no swar-eligible op in --ops {args.ops!r} at "
                    f"{args.height}x{args.width} (need W % 4 == 0; see "
                    "ops/swar_kernels.py eligibility)",
                    file=sys.stderr,
                )
                return 2

            # per-op mode: wide-mode column lanes have a ~3x larger live
            # set, so a narrow-mode cap would admit candidates the wide
            # kernel's VMEM budget can never run (review finding)
            def _mode_of(op):
                if swar_eligible(op):
                    return _swar_mode(_taps_shift(op)[0])
                return "corr2d"

            cap = min(
                _pick_swar_block_h(args.width // 4, op.halo, _mode_of(op))
                for op in eligible
            )
            step = 8  # swar blocks are ext-row multiples of 8, not 32
        else:
            cap = min(
                _pick_block_h(
                    args.width,
                    1,
                    1,
                    stencil.halo if stencil is not None else 0,
                    _live_f32_temps(stencil),
                )
                for _pw, stencil in group_ops(ops)
            )
            step = 32
        if cap not in candidates:
            # the heuristic's own choice is always legal and is the baseline
            # the calibration competes with — measure it even when every
            # --blocks entry sits above the cap (review finding: otherwise a
            # wide-image sweep could skip everything and burn the chip
            # window for nothing)
            candidates.append(cap)
        img = jax.numpy.asarray(
            synthetic_image(args.height, args.width, channels=1, seed=7)
        )
        kind = calibration.current_device_kind()
        results = []
        for bh in candidates:
            if bh < step or bh % step:
                print(f"block {bh}: skipped (must be a multiple of {step}, >={step})")
                continue
            if bh > cap:
                print(f"block {bh}: skipped (above the VMEM heuristic cap {cap})")
                continue
            if swar:
                fn = jax.jit(lambda x, b=bh: pipeline_swar(ops, x, block_h=b))
            else:
                fn = jax.jit(
                    lambda x, b=bh: pipeline_pallas(ops, x, block_h=b)
                )
            try:
                sec = device_throughput(fn, [img])
            except Exception as e:  # Mosaic OOM on too-tall blocks, etc.
                print(f"block {bh}: failed ({str(e)[:120]})")
                continue
            mp_s = args.height * args.width / 1e6 / sec
            results.append((sec, bh, mp_s))
            print(f"block {bh}: {sec * 1e3:.3f} ms/iter  {mp_s:,.0f} MP/s")
        if not results:
            print("error: no candidate block height ran", file=sys.stderr)
            return 1
        sec, best_bh, mp_s = min(results)
        rec = {
            "event": "autotune",
            "device_kind": kind,
            "backend": jax.default_backend(),
            "pipeline": args.ops,
            "impl": args.impl,
            "height": args.height,
            "width": args.width,
            "block_h": best_bh,
            "ms_per_iter": sec * 1e3,
            "mp_per_s": mp_s,
        }
        if args.dry_run:
            print(f"best block {best_bh} (dry run; store not written)")
        else:
            path = calibration.record_block_h(
                kind,
                best_bh,
                impl=args.impl,
                pipeline=args.ops,
                width=args.width,
                mp_per_s=round(mp_s, 1),
            )
            rec["calib_file"] = path
            print(f"best block {best_bh} -> {path} [{kind}]")
        if args.json_metrics:
            emit_json_metrics(
                rec, None if args.json_metrics == "-" else args.json_metrics
            )
        return 0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _autotune_backend(args: argparse.Namespace, ops) -> int:
    """The VPU-vs-MXU autotune dimension (`--dimension backend`): for each
    MXU-eligible stencil family in --ops, measure the VPU streaming
    kernel against the MXU banded and hybrid formulations on the live
    backend and record the winner per (device kind, family, width) in the
    calibration store. `backend='auto'` routes a family to the MXU ONLY
    behind such a recorded win (ops/mxu_kernels.use_mxu_for_stencil), so
    this sweep is what actually cashes the roofline headroom in
    production. Runs under the caller's MCIM_NO_CALIB=1 env, so an
    existing store cannot steer the sweep it is about to overwrite."""
    import jax

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.ops.mxu_kernels import (
        mxu_family,
        pipeline_mxu,
    )
    from mpi_cuda_imagemanipulation_tpu.ops.pallas_kernels import (
        pipeline_pallas,
    )
    from mpi_cuda_imagemanipulation_tpu.utils import calibration
    from mpi_cuda_imagemanipulation_tpu.utils.log import emit_json_metrics
    from mpi_cuda_imagemanipulation_tpu.utils.timing import device_throughput

    fams: dict = {}  # family -> representative stencil op, first wins
    for op in ops:
        fam = mxu_family(op)
        if fam is not None and fam not in fams:
            fams[fam] = op
    if not fams:
        print(
            f"error: no MXU-eligible stencil family in --ops {args.ops!r} "
            "(ops/mxu_kernels.mxu_eligible)",
            file=sys.stderr,
        )
        return 2
    img = jax.numpy.asarray(
        synthetic_image(args.height, args.width, channels=1, seed=7)
    )
    kind = calibration.current_device_kind()
    mp = args.height * args.width / 1e6
    records = []
    for fam, op in fams.items():
        lanes = {
            "vpu": jax.jit(lambda x, o=(op,): pipeline_pallas(o, x)),
            "mxu": jax.jit(
                lambda x, o=(op,): pipeline_mxu(o, x, mode="banded")
            ),
            "hybrid": jax.jit(
                lambda x, o=(op,): pipeline_mxu(o, x, mode="hybrid")
            ),
        }
        timed: dict = {}
        for lane, fn in lanes.items():
            try:
                timed[lane] = device_throughput(fn, [img])
            except Exception as e:  # one lane failing must not kill the sweep
                print(f"{fam}/{lane}: failed ({str(e)[:120]})")
        if not timed:
            print(f"{fam}: no lane ran; skipped")
            continue
        choice = min(timed, key=timed.get)
        lane_mp = {k: round(mp / v, 1) for k, v in timed.items()}
        for lane in ("vpu", "mxu", "hybrid"):
            if lane in timed:
                mark = " <- winner" if lane == choice else ""
                print(
                    f"{fam:10s} {lane:7s} {timed[lane] * 1e3:8.3f} ms/iter"
                    f"  {lane_mp[lane]:>10,.0f} MP/s{mark}"
                )
        rec = {
            "family": fam,
            "op": op.name,
            "choice": choice,
            "width": args.width,
            "mp_per_s": lane_mp,
        }
        if not args.dry_run:
            rec["calib_file"] = calibration.record_backend_choice(
                kind, fam, choice,
                op=op.name, width=args.width, mp_per_s=lane_mp,
            )
        records.append(rec)
    if not records:
        print("error: no family measured", file=sys.stderr)
        return 1
    out = {
        "event": "autotune_backend",
        "device_kind": kind,
        "backend": jax.default_backend(),
        "pipeline": args.ops,
        "height": args.height,
        "width": args.width,
        "families": records,
        "dry_run": bool(args.dry_run),
    }
    if args.dry_run:
        print("dry run; calibration store not written")
    if args.json_metrics:
        emit_json_metrics(
            out, None if args.json_metrics == "-" else args.json_metrics
        )
    return 0


def _autotune_plan(args: argparse.Namespace, ops) -> int:
    """The fused-plan autotune dimension (`--dimension plan`): measure
    the per-op ('off'), pointwise-absorption and fully fused execution
    structures of --ops end-to-end on the live backend and record the
    fastest per (device kind, pipeline fingerprint, width) in the
    calibration store. Every candidate is gated bit-identical to the
    per-op golden output BEFORE timing — a plan that ever diverged would
    be a planner bug, and must never win a record. `plan='auto'` entry
    points (jit/batched/sharded/serving/stream) then route through the
    recorded structure (plan/planner.resolve_plan_mode). Runs under the
    caller's MCIM_NO_CALIB=1 env, so an existing store cannot steer the
    sweep it is about to overwrite."""
    import numpy as np

    import jax

    from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline
    from mpi_cuda_imagemanipulation_tpu.plan import (
        build_plan,
        pipeline_fingerprint,
    )
    from mpi_cuda_imagemanipulation_tpu.serve.padded import accepts_channels
    from mpi_cuda_imagemanipulation_tpu.utils import calibration
    from mpi_cuda_imagemanipulation_tpu.utils.log import emit_json_metrics
    from mpi_cuda_imagemanipulation_tpu.utils.platform import is_tpu_backend
    from mpi_cuda_imagemanipulation_tpu.utils.timing import device_throughput

    pipe = Pipeline(list(ops))
    ch = 3 if accepts_channels(pipe, 3) else 1
    img = jax.numpy.asarray(
        synthetic_image(args.height, args.width, channels=ch, seed=7)
    )
    kind = calibration.current_device_kind()
    mp = args.height * args.width / 1e6
    fp = pipeline_fingerprint(ops)
    modes = ["off", "pointwise", "fused"]
    # the fused-pallas lane joins the sweep only where its kernels
    # compile (real TPU) or the operator explicitly asked for the
    # interpreter (the same guard the block dimension uses) — an
    # interpret-mode timing must never win a plan record
    if is_tpu_backend() or args.allow_interpret:
        modes.append("fused-pallas")
        modes.append("fused-pallas-mxu")
    else:
        print(
            "fused-pallas lane skipped off-TPU (interpret-mode timings "
            "are meaningless; pass --allow-interpret to include it)"
        )
    plans = {m: build_plan(ops, m) for m in modes}
    golden = np.asarray(jax.block_until_ready(pipe.jit(plan="off")(img)))
    timed: dict = {}
    for mode in plans:
        fn = pipe.jit(plan=mode)
        got = np.asarray(jax.block_until_ready(fn(img)))
        if not (got == golden).all():  # pragma: no cover - planner bug
            print(
                f"error: plan mode {mode!r} diverged from the per-op "
                "golden output — refusing to record (planner bug)",
                file=sys.stderr,
            )
            return 1
        timed[mode] = device_throughput(fn, [img])
    choice = min(timed, key=timed.get)
    lane_mp = {k: round(mp / v, 1) for k, v in timed.items()}
    for mode in modes:
        p = plans[mode]
        mark = " <- winner" if mode == choice else ""
        print(
            f"{mode:10s} {timed[mode] * 1e3:8.3f} ms/iter"
            f"  {lane_mp[mode]:>10,.0f} MP/s"
            f"  ({len(p.stages)} stages, {p.hbm_passes} hbm passes){mark}"
        )
    rec = {
        "event": "autotune_plan",
        "device_kind": kind,
        "backend": jax.default_backend(),
        "pipeline": args.ops,
        "pipeline_fp": fp,
        "height": args.height,
        "width": args.width,
        "choice": choice,
        "mp_per_s": lane_mp,
        "stages": {m: len(p.stages) for m, p in plans.items()},
        "dry_run": bool(args.dry_run),
    }
    if args.dry_run:
        print("dry run; calibration store not written")
    else:
        rec["calib_file"] = calibration.record_plan_choice(
            kind, fp, choice,
            ops=args.ops, width=args.width, mp_per_s=lane_mp,
        )
    if args.json_metrics:
        emit_json_metrics(
            rec, None if args.json_metrics == "-" else args.json_metrics
        )
    return 0


def cmd_info(args: argparse.Namespace) -> int:
    _configure_platform(args.device)
    import jax

    from mpi_cuda_imagemanipulation_tpu._version import __version__
    from mpi_cuda_imagemanipulation_tpu.ops.registry import REGISTRY

    print(f"mpi_cuda_imagemanipulation_tpu {__version__}")
    print(f"jax {jax.__version__} backend={jax.default_backend()}")
    print(f"process {jax.process_index()}/{jax.process_count()}")
    print(f"devices: {jax.devices()}")
    print(f"ops: {', '.join(sorted(REGISTRY))}")
    try:
        from mpi_cuda_imagemanipulation_tpu.runtime import codec

        print(f"native codec: {'available' if codec.available() else 'not built'}")
    except Exception:
        print("native codec: not built")
    from mpi_cuda_imagemanipulation_tpu.utils import calibration

    entries = calibration.entries()
    if entries:
        parts = []
        for kind, impls in sorted(entries.items()):
            if not isinstance(impls, dict):
                continue
            for impl, rec in sorted(impls.items()):
                if not isinstance(rec, dict):
                    continue
                if impl == "backend_choice":
                    # the VPU-vs-MXU autotune dimension (family -> choice)
                    parts.extend(
                        f"{kind}/backend:{fam}={ent.get('choice')}"
                        for fam, ent in sorted(rec.items())
                        if isinstance(ent, dict)
                    )
                elif impl == "plan_choice":
                    # the fused-plan dimension (pipeline fp -> build mode)
                    parts.extend(
                        f"{kind}/plan:{fp}={ent.get('choice')}"
                        for fp, ent in sorted(rec.items())
                        if isinstance(ent, dict)
                    )
                else:
                    parts.append(f"{kind}/{impl}: block_h={rec.get('block_h')}")
        print(
            f"autotune calibration ({calibration.calib_path()}): "
            + ", ".join(parts)
        )
    else:
        print("autotune calibration: none (run `mcim-tpu autotune`)")
    return 0


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    cmd = {
        "run": cmd_run,
        "batch": cmd_batch,
        "stream": cmd_stream,
        "serve": cmd_serve,
        "fabric": cmd_fabric,
        "federation": cmd_federation,
        "graph": cmd_graph,
        "bench": cmd_bench,
        "diff": cmd_diff,
        "autotune": cmd_autotune,
        "info": cmd_info,
    }[args.cmd]
    try:
        return cmd(args)
    except (ValueError, FileNotFoundError, NotImplementedError) as e:
        # user-input errors get one clean line, not a traceback
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
