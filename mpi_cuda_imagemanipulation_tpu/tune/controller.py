"""Tune controller — UCB candidate ranking + canary-gated actuation.

Runs in the ROUTER process on its own tick (SLOEngine's ticker shape):
each tick reads the online store's decayed arm statistics for the pod's
pipeline at its hottest width window and emits exactly one decision from
a closed vocabulary:

    insufficient_data   not enough effective samples to rank anything
    hold                ranked, but no candidate clears the gain bar
                        (or a flip is mid-canary — the gate decides)
    propose             deploy a candidate to the canary replica
    promote             gate passed AND the canary measured faster —
                        respawn the whole fleet onto the flip
    rollback            the flip lost: gate breach (quarantine), slower
                        than the incumbent, or produced no measurements
                        before MCIM_TUNE_FLIP_TIMEOUT_S

Every decision flows through `count_decision` (the systolic
count_fallback idiom — unknown members raise, mcim-check enforces the
literal at every call site) and lands in the calibration store's audit
trail. Exploration is optimistic-under-uncertainty for a MINIMIZATION
objective: an arm's score is its decayed mean scaled DOWN by a UCB
bonus, so under-sampled arms look temptingly fast until measured;
unmeasured arms are proposed outright once the incumbent has
MCIM_TUNE_MIN_SAMPLES effective observations.

Actuation is delegated: `deploy(flip)` is the router's canary_deploy,
`on_promote(flip)` / `on_revert(status)` are Fabric hooks that respawn
processes. The controller holds NO sockets or process handles — with a
fake clock, gate and callables it is a pure decision table
(tests/test_tune.py drives every row).

Safety: bit-exactness stays the contract. The canary gate rolls back on
the FIRST shadow-digest mismatch; the router's rollback hook respawns
the stable config before this controller even ticks again, and the tick
then quarantines the arm in the store so it is never proposed again.
The `tune.candidate` failpoint poisons a proposed flip into a
pixel-corrupting one so CI can prove that chain end to end.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time

from mpi_cuda_imagemanipulation_tpu.fabric import canary as canary_mod
from mpi_cuda_imagemanipulation_tpu.resilience.failpoints import (
    FailpointError,
    maybe_fail,
)
from mpi_cuda_imagemanipulation_tpu.tune.store import online_store
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

ENV_TICK_S = "MCIM_TUNE_TICK_S"
ENV_MIN_SAMPLES = "MCIM_TUNE_MIN_SAMPLES"
ENV_EXPLORE_C = "MCIM_TUNE_EXPLORE_C"
ENV_MIN_GAIN = "MCIM_TUNE_MIN_GAIN"
ENV_FLIP_TIMEOUT_S = "MCIM_TUNE_FLIP_TIMEOUT_S"
ENV_CANARY_FRAC = "MCIM_TUNE_CANARY_FRAC"

DECISIONS = ("propose", "hold", "promote", "rollback", "insufficient_data")

# arm vocabulary: "plan:<mode>" — the plan dimension is the one with a
# measured CPU-visible spread (BENCH_HISTORY plan_ab: off 1.5x slower
# than fused at 512^2), so it is the first dimension the controller
# actuates; backend/block_h arms reuse the same machinery when their
# flip argv is wired
_ARM_PREFIX = "plan:"


def count_decision(counter, decision: str) -> None:
    """The one choke point for decision accounting — raises on a member
    outside the closed vocabulary so a typo becomes a loud failure, not
    an unbounded label set (mcim-check: obs-tune-decision-*)."""
    if decision not in DECISIONS:
        raise ValueError(
            f"unknown tune decision {decision!r}; known: {DECISIONS}"
        )
    counter.inc(decision=decision)


def arm_flip(arm: str) -> dict:
    """The deploy payload for an arm: replica argv overriding the pinned
    config (argparse last-wins, the canary_deploy contract)."""
    if arm.startswith(_ARM_PREFIX):
        return {"argv": ["--plan", arm[len(_ARM_PREFIX):]]}
    raise ValueError(f"unknown tune arm {arm!r}")


@dataclasses.dataclass(frozen=True)
class TuneConfig:
    tick_s: float | None = None
    min_samples: float | None = None
    explore_c: float | None = None
    min_gain: float | None = None
    flip_timeout_s: float | None = None
    canary_frac: float | None = None

    def resolved(self) -> "TuneConfig":
        def _f(v, name):
            return float(env_registry.get(name)) if v is None else float(v)

        frac = self.canary_frac
        if frac is None:
            raw = env_registry.get(ENV_CANARY_FRAC)
            frac = float(raw) if raw else None
        return TuneConfig(
            tick_s=_f(self.tick_s, ENV_TICK_S),
            min_samples=_f(self.min_samples, ENV_MIN_SAMPLES),
            explore_c=_f(self.explore_c, ENV_EXPLORE_C),
            min_gain=_f(self.min_gain, ENV_MIN_GAIN),
            flip_timeout_s=_f(self.flip_timeout_s, ENV_FLIP_TIMEOUT_S),
            canary_frac=frac,
        )


class TuneController:
    """One pod's closed-loop tuner. Pure decision logic over an injected
    gate, store and actuation callables — the Fabric wires the real
    ones; tests wire fakes."""

    def __init__(
        self,
        *,
        gate,
        deploy,
        pipe_fp: str,
        current_arm: str,
        arms: tuple[str, ...] | list[str],
        registry,
        on_promote=None,
        on_revert=None,
        store=None,
        config: TuneConfig | None = None,
        clock=time.time,
    ):
        self.gate = gate
        self.deploy = deploy
        self.pipe_fp = pipe_fp
        self.current_arm = current_arm
        self.arms = tuple(arms)
        self.on_promote = on_promote
        self.on_revert = on_revert
        self.store = store or online_store
        self.config = (config or TuneConfig()).resolved()
        self._clock = clock
        self._log = get_logger("tune")
        self.decisions = registry.counter(
            "mcim_tune_decisions_total",
            "Tune controller decisions, by closed-vocabulary member "
            "(propose/hold/promote/rollback/insufficient_data).",
            labels=("decision",),
        )
        self.proposals = registry.counter(
            "mcim_tune_proposals_total",
            "Candidate flips deployed to the canary replica, by arm.",
            labels=("arm",),
        )
        # a tuner flip is lower-stakes than an operator flip (it can
        # always retry), so the pod may scope it to a thinner slice
        if self.config.canary_frac is not None:
            self.gate.config = dataclasses.replace(
                self.gate.config, frac=self.config.canary_frac
            )
        # in-flight proposal state (one at a time; the gate enforces it)
        self.inflight_arm: str | None = None
        self.inflight_flip: dict | None = None
        self.proposed_at: float | None = None
        self.last_decision: str | None = None
        self.last_reason: str | None = None
        self.events: list[dict] = []  # bounded recent-decision ring
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- ticker (SLOEngine shape) ----------------------------------------

    def start(self) -> "TuneController":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="mcim-tune-controller", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                self._log.exception("tune tick failed")
            self._stop.wait(self.config.tick_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- the decision tick ------------------------------------------------

    def tick(self) -> str:
        """One control-loop step; returns the decision made (a DECISIONS
        member — the return value is for tests/status, the counter and
        audit trail are the products)."""
        if self.inflight_arm is not None:
            decision, reason = self._tick_inflight()
        else:
            decision, reason = self._tick_idle()
        self._note(decision, reason)
        return decision

    def _tick_idle(self) -> tuple[str, str]:
        window = self._hottest_window()
        if window is None:
            count_decision(self.decisions, "insufficient_data")
            return "insufficient_data", "no observations yet"
        stats = self.store.arm_stats(self.pipe_fp, window)
        cur = stats.get(self.current_arm)
        cfg = self.config
        if cur is None or cur["n_eff"] < cfg.min_samples:
            count_decision(self.decisions, "insufficient_data")
            return (
                "insufficient_data",
                f"current arm {self.current_arm} has "
                f"{0 if cur is None else cur['n_eff']:.1f}/"
                f"{cfg.min_samples:g} effective samples in window "
                f"{window}",
            )
        candidate, why = self._pick(stats, window)
        if candidate is None:
            count_decision(self.decisions, "hold")
            return "hold", why
        return self._propose(candidate, window, why)

    def _pick(self, stats: dict, window: str) -> tuple[str | None, str]:
        """Rank candidate arms against the incumbent. Unmeasured arms
        explore first; measured ones exploit through an optimistic
        (UCB-style) lower bound on their decayed mean."""
        cfg = self.config
        cur_mean = stats[self.current_arm]["mean"]
        total = 1.0 + sum(s["n_eff"] for s in stats.values())
        best_arm, best_score = None, None
        for arm in self.arms:
            if arm == self.current_arm:
                continue
            if self.store.is_quarantined(self.pipe_fp, arm):
                continue
            s = stats.get(arm)
            if s is None or s["n_eff"] < cfg.min_samples:
                return arm, f"explore: arm {arm} unmeasured in window {window}"
            bonus = cfg.explore_c * math.sqrt(
                math.log(total) / max(s["n_eff"], 1e-9)
            )
            score = s["mean"] * max(0.0, 1.0 - bonus)
            if best_score is None or score < best_score:
                best_arm, best_score = arm, score
        if best_arm is not None and best_score * cfg.min_gain <= cur_mean:
            return (
                best_arm,
                f"exploit: {best_arm} optimistic mean {best_score:.4g}s "
                f"beats {self.current_arm} {cur_mean:.4g}s by >="
                f" {cfg.min_gain:g}x",
            )
        return None, (
            "no candidate clears the gain bar vs "
            f"{self.current_arm} ({cur_mean:.4g}s) in window {window}"
        )

    def _propose(self, arm: str, window: str, why: str) -> tuple[str, str]:
        flip = arm_flip(arm)
        try:
            maybe_fail("tune.candidate", arm=arm, pipe_fp=self.pipe_fp)
        except FailpointError:
            # the poisoned-candidate drill: swap the flip for one that
            # CHANGES PIXELS (ops override), proving the shadow-digest
            # gate catches a wrong-pixels flip before any client sees it
            flip = {"argv": ["--ops", "invert"]}
        try:
            self.deploy(flip)
        except Exception as e:
            count_decision(self.decisions, "hold")
            return "hold", f"deploy of {arm} refused: {e}"
        self.inflight_arm = arm
        self.inflight_flip = flip
        self.proposed_at = self._clock()
        self.proposals.inc(arm=arm)
        count_decision(self.decisions, "propose")
        return "propose", why

    def _tick_inflight(self) -> tuple[str, str]:
        arm = self.inflight_arm
        state = self.gate.state
        if state == canary_mod.CANARY:
            count_decision(self.decisions, "hold")
            return "hold", f"canary of {arm} in flight (gate deciding)"
        if state == canary_mod.PROMOTED:
            return self._tick_promoted(arm)
        # IDLE / ROLLED_BACK: the gate breached (shadow mismatch or burn)
        # and the router's rollback hook already respawned stable — our
        # job is the quarantine + the books
        reason = self.gate.reason or "canary rolled back"
        self.store.quarantine(self.pipe_fp, arm, reason)
        self._clear_inflight()
        count_decision(self.decisions, "rollback")
        return "rollback", f"{arm} breached the gate: {reason}"

    def _tick_promoted(self, arm: str) -> tuple[str, str]:
        """The gate passed (bit-exact, burn under control) — but safe is
        not the same as FASTER. Promote fleet-wide only when the canary's
        own measurements beat the incumbent by min_gain; otherwise revert
        the canary replica to stable (no quarantine: the arm is safe,
        just not a win here — decay may change that)."""
        cfg = self.config
        window = self._hottest_window()
        stats = (
            self.store.arm_stats(self.pipe_fp, window) if window else {}
        )
        cand = stats.get(arm)
        cur = stats.get(self.current_arm)
        if cand is None or cand["n_eff"] < cfg.min_samples:
            age = self._clock() - (self.proposed_at or 0.0)
            if age <= cfg.flip_timeout_s:
                count_decision(self.decisions, "hold")
                return "hold", (
                    f"gate passed {arm}; waiting for canary measurements "
                    f"({0 if cand is None else cand['n_eff']:.1f}/"
                    f"{cfg.min_samples:g})"
                )
            self._revert()
            self._clear_inflight()
            count_decision(self.decisions, "rollback")
            return "rollback", (
                f"{arm} produced no canary measurements within "
                f"{cfg.flip_timeout_s:g}s"
            )
        if cur is None or cand["mean"] * cfg.min_gain <= cur["mean"]:
            flip = dict(self.inflight_flip or {})
            if self.on_promote is not None:
                self.on_promote(flip)
            if arm.startswith(_ARM_PREFIX):
                # the store records the CHOICE (a PLAN_CHOICES member) so
                # effective_plan_choice can compare it with offline records
                self.store.promote(
                    self.pipe_fp, int(window), arm[len(_ARM_PREFIX):]
                )
            old = self.current_arm
            self.current_arm = arm
            self._clear_inflight(reset_gate=True)
            count_decision(self.decisions, "promote")
            return "promote", (
                f"{arm} measured {cand['mean']:.4g}s vs {old} "
                f"{'n/a' if cur is None else format(cur['mean'], '.4g')}s "
                "— fleet respawned onto the flip"
            )
        self._revert()
        self._clear_inflight()
        count_decision(self.decisions, "rollback")
        return "rollback", (
            f"{arm} passed the gate but measured {cand['mean']:.4g}s vs "
            f"{self.current_arm} {cur['mean']:.4g}s (< {cfg.min_gain:g}x "
            "gain) — canary reverted, no quarantine"
        )

    def _revert(self) -> None:
        """Put the canary replica back on the stable config after a
        promote-window loss (the gate never breached, so the router's
        rollback hook never fired — we drive the Fabric's directly)."""
        if self.on_revert is not None:
            try:
                self.on_revert(self.gate.status())
            except Exception:
                self._log.exception("tune revert failed")

    def _clear_inflight(self, reset_gate: bool = False) -> None:
        self.inflight_arm = None
        self.inflight_flip = None
        self.proposed_at = None
        if reset_gate:
            self.gate.reset()

    # -- helpers -----------------------------------------------------------

    def _hottest_window(self) -> str | None:
        windows = self.store.windows(self.pipe_fp)
        if not windows:
            return None
        return max(windows.items(), key=lambda kv: (kv[1], kv[0]))[0]

    def _note(self, decision: str, reason: str) -> None:
        # the mcim_tune_decisions_total count already happened at the
        # decision site (count_decision with the literal member — the
        # closed-vocab rule needs the literal there); this is the books
        self.last_decision = decision
        self.last_reason = reason
        changed = not self.events or (
            self.events[-1]["decision"] != decision
            or self.events[-1].get("arm") != self.inflight_arm
        )
        self.events.append(
            {
                "t": round(self._clock(), 3),
                "decision": decision,
                "reason": reason,
                "arm": self.inflight_arm or self.current_arm,
            }
        )
        del self.events[:-64]
        # every decision lands in the store's audit trail; repeats of the
        # same steady-state decision coalesce in the file via the flush
        # merge cap, but transitions always persist immediately
        self.store.audit(
            decision,
            arm=self.inflight_arm,
            current=self.current_arm,
            reason=reason if changed else None,
            fp=self.pipe_fp,
        )

    def status(self) -> dict:
        """The `/control/tune` and `router.stats()["tune"]` payload."""
        return {
            "current_arm": self.current_arm,
            "arms": list(self.arms),
            "inflight": self.inflight_arm,
            "last_decision": self.last_decision,
            "last_reason": self.last_reason,
            "pipe_fp": self.pipe_fp,
            "events": self.events[-8:],
        }
