"""Continuous autotuning — the fleet consumes its own telemetry.

Offline `mcim-tpu autotune` sweeps (PR 9/13) answer "which config is
fastest" once, on an idle device, and write the answer to the
calibration store. This package closes the loop at serving time:

  * `store` — online observations (dispatch timings from the serve
    scheduler, measured boundary-byte ratios from the cost ledger)
    accumulate under the SAME `(device_kind, pipeline_fingerprint,
    width_window)` keys the offline sweeps use, in bounded reservoirs
    with staleness decay, persisted through the calibration file's
    atomic-rename machinery.
  * `controller` — a UCB-style explore/exploit engine on the router's
    tick that ranks candidate config flips from those observations and
    deploys winners through the PR 12 canary gate: one replica respawns
    with the flip, shadow digests prove bit-exactness, and the flip is
    promoted fleet-wide or rolled back with no human in the loop. One
    digest mismatch quarantines the candidate in the store.
  * `metrics` — the `mcim_tune_*` family, federated to the router like
    `mcim_plan_*` so the fleet view shows the control loop working.

Decisions use a closed vocabulary (`controller.DECISIONS`) through a
single `count_decision` choke point, enforced by mcim-check.
"""
