"""Online-autotuning instrumentation — the `mcim_tune_*` metric family.

One module-level registry, same shape as plan/metrics.py and for the
same reason: observations are recorded from several entry points (the
serve scheduler's completion path, the cost ledger's record path, the
store's precedence resolver) and a per-call registry would fragment
them. A fabric replica's heartbeat delta snapshots include this registry
(serve/server.ServeApp.fleet_registries), so the router's federated
/metrics shows the whole pod's observation flow next to the serving
counters it will eventually steer.

The controller's own decision counters live on the ROUTER registry (the
controller runs in the router process and is handed that registry at
construction) — only the observation/store side lives here, because only
this side runs inside replicas.
"""

from __future__ import annotations

from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry


class TuneMetrics:
    def __init__(self, registry: Registry | None = None):
        self.registry = registry or Registry()
        r = self.registry
        self.observations = r.counter(
            "mcim_tune_observations_total",
            "Online tuning observations ingested, by source (dispatch = "
            "serve-path per-image device seconds; ledger = measured "
            "boundary-byte ratios from the cost ledger).",
            labels=("source",),
        )
        self.stale_overrides = r.counter(
            "mcim_tune_stale_overrides_total",
            "Plan-choice resolutions where the newer of the offline "
            "record and the online promotion overrode the older one "
            "(freshness precedence: newest wins per key).",
        )
        self.quarantined = r.counter(
            "mcim_tune_quarantined_total",
            "Candidate flips quarantined in the calibration store after "
            "a canary breach (shadow-digest mismatch or burn).",
        )
        self.flushes = r.counter(
            "mcim_tune_flushes_total",
            "Online-record merges persisted to the calibration file.",
        )

    def snapshot(self) -> dict:
        return {
            "observations_dispatch": int(
                self.observations.value(source="dispatch")
            ),
            "observations_ledger": int(
                self.observations.value(source="ledger")
            ),
            "stale_overrides": int(self.stale_overrides.value()),
            "quarantined": int(self.quarantined.value()),
            "flushes": int(self.flushes.value()),
        }


tune_metrics = TuneMetrics()
