"""Online observation store — serving telemetry under autotune keys.

Offline sweeps write `device_kinds.<kind>.plan_choice.<fingerprint>`
records; this module accumulates what the fleet measures about itself
at SERVING time under the same `(device_kind, pipeline_fingerprint,
width_window)` keys, in a sibling top-level section of the same
calibration file:

    online.<kind>.obs.<pipe_fp>.<window>.<arm>.samples = [[t, v], ...]
    online.<kind>.io_scale.<plan_fp>.<stage> = {ratio, at}
    online.<kind>.promoted.<pipe_fp> = {choice, width, at}
    online.<kind>.quarantine.<pipe_fp>.<arm> = {reason, at}
    tune_audit = [ {t, decision, ...}, ... ]          (bounded trail)

Three properties keep this safe on the serve path:

  * bounded — reservoirs cap at MCIM_TUNE_RESERVOIR samples per arm
    (newest win) and staleness decay (half-life MCIM_TUNE_STALE_S)
    discounts what survives, so a workload shift re-converges instead
    of being anchored by history;
  * cheap — ingestion appends to process memory; the file is only
    touched by a rate-limited merge (MCIM_TUNE_FLUSH_S) that re-reads,
    unions by timestamp and atomically rewrites, so N replicas sharing
    one store converge instead of clobbering each other;
  * off by default — persistence requires MCIM_TUNE=1 and respects
    MCIM_NO_CALIB like every other calibration consumer. In-memory
    ingestion always runs (it is just a deque append) so a single
    process can still introspect itself.

Width windows are power-of-two anchors (`1 << (w.bit_length()-1)`): the
factor-of-two rule the offline store applies at lookup time, applied
here at RECORD time so observations at 500 and 512 wide share a bucket.

Freshness precedence (`effective_plan_choice`): when an offline
`plan_choice` record and an online `promoted` record disagree for the
same key, the newer `recorded_at`/`at` stamp wins and
`mcim_tune_stale_overrides_total` counts the override — BENCH_HISTORY
becomes a trail, not the decision input.
"""

from __future__ import annotations

import math
import threading
import time

from mpi_cuda_imagemanipulation_tpu.tune.metrics import tune_metrics
from mpi_cuda_imagemanipulation_tpu.utils import calibration
from mpi_cuda_imagemanipulation_tpu.utils import env as env_registry

_ENV_TUNE = "MCIM_TUNE"
_ENV_STALE_S = "MCIM_TUNE_STALE_S"
_ENV_RESERVOIR = "MCIM_TUNE_RESERVOIR"
_ENV_FLUSH_S = "MCIM_TUNE_FLUSH_S"

_ONLINE_KEY = "online"
_AUDIT_KEY = "tune_audit"
_AUDIT_CAP = 512


def width_window(width: int) -> str:
    """Power-of-two anchor bucketing a width into its factor-of-two
    window (500 and 512 -> "256"; the offline lookup rule, applied at
    record time)."""
    w = max(1, int(width))
    return str(1 << (w.bit_length() - 1))


def _now() -> float:
    return time.time()


def _device_kind() -> str | None:
    try:
        return calibration.current_device_kind()
    except Exception:
        return None


class OnlineStore:
    """Process-local reservoir of online observations + the merge/flush
    protocol against the shared calibration file.

    All public record_* methods are lock-protected and never raise on
    the happy path contract the serve scheduler needs: a broken store
    file or missing backend must degrade to "no observation", not a
    failed dispatch (callers still wrap in try/except as belt and
    braces)."""

    def __init__(self, clock=None):
        self._clock = clock or _now
        self._lock = threading.Lock()
        # obs[(kind, pipe_fp, window, arm)] = list[[t, v]]
        self._obs: dict[tuple, list] = {}
        # io[(kind, plan_fp, stage)] = (ratio, t)
        self._io: dict[tuple, tuple] = {}
        # promoted[(kind, pipe_fp)] = {"choice", "width", "at"}
        self._promoted: dict[tuple, dict] = {}
        # quarantine[(kind, pipe_fp, arm)] = {"reason", "at"}
        self._quarantine: dict[tuple, dict] = {}
        self._audit_pending: list[dict] = []
        self._last_t: dict[tuple, float] = {}
        self._dirty = False
        self._last_flush = 0.0
        self._kind: str | None = None

    # -- config ----------------------------------------------------------

    @staticmethod
    def enabled() -> bool:
        """Persistence armed? (MCIM_TUNE=1 and calibration not disabled.)"""
        return env_registry.get_bool(_ENV_TUNE) and not env_registry.get(
            "MCIM_NO_CALIB"
        )

    @staticmethod
    def _stale_s() -> float:
        v = env_registry.get_float(_ENV_STALE_S)
        return v if v and v > 0 else 900.0

    @staticmethod
    def _reservoir() -> int:
        v = env_registry.get_int(_ENV_RESERVOIR)
        return v if v and v > 0 else 64

    @staticmethod
    def _flush_s() -> float:
        v = env_registry.get_float(_ENV_FLUSH_S)
        return v if v is not None and v >= 0 else 1.0

    def _resolve_kind(self) -> str | None:
        with self._lock:
            if self._kind is not None:
                return self._kind
        kind = _device_kind()  # may initialize the backend: not under lock
        with self._lock:
            if self._kind is None and kind is not None:
                self._kind = kind
            return self._kind or kind

    # -- ingestion -------------------------------------------------------

    def record_dispatch(
        self, pipe_fp: str, width: int, arm: str, device_s: float
    ) -> None:
        """One serve-path observation: per-image device seconds under
        `arm` for (pipeline fingerprint, width window)."""
        kind = self._resolve_kind()
        if kind is None or not pipe_fp or device_s <= 0:
            return
        key = (kind, pipe_fp, width_window(width), str(arm))
        t = round(self._clock(), 3)
        with self._lock:
            # strictly increasing per key: the flush merge unions by
            # (t, v), so two sub-millisecond dispatches with equal cost
            # must not collapse into one observation
            last = self._last_t.get(key)
            if last is not None and t <= last:
                t = round(last + 0.001, 3)
            self._last_t[key] = t
            samples = self._obs.setdefault(key, [])
            samples.append([t, float(device_s)])
            cap = self._reservoir()
            if len(samples) > cap:
                del samples[: len(samples) - cap]
            self._dirty = True
        tune_metrics.observations.inc(source="dispatch")
        self.flush()

    def record_io_scale(self, plan_fp: str, stage: str, ratio: float) -> None:
        """One measured boundary-bytes/modeled-bytes ratio from the cost
        ledger, persisted so OTHER processes (and future builds in this
        one) can correct the analytical byte model."""
        kind = self._resolve_kind()
        if kind is None or not plan_fp or not ratio or ratio <= 0:
            return
        with self._lock:
            self._io[(kind, str(plan_fp), str(stage))] = (
                float(ratio),
                round(self._clock(), 3),
            )
            self._dirty = True
        tune_metrics.observations.inc(source="ledger")
        self.flush()

    def promote(self, pipe_fp: str, width: int, choice: str) -> None:
        """Record a fleet-wide promotion (the controller's promote
        decision) — the online side of the newest-wins precedence pair.

        `choice` is the closed plan vocabulary (`promoted_entry` already
        gates reads on it; raising at the write catches the typo'd arm
        at the choke point instead of silently banking a promotion no
        resolver will ever honour — fused-pallas-mxu joins the set via
        calibration.PLAN_CHOICES, nothing store-side to widen)."""
        if choice not in calibration.PLAN_CHOICES:
            raise ValueError(
                f"unknown plan choice {choice!r}; known: "
                f"{calibration.PLAN_CHOICES}"
            )
        kind = self._resolve_kind()
        if kind is None:
            return
        with self._lock:
            self._promoted[(kind, pipe_fp)] = {
                "choice": choice,
                "width": int(width),
                "at": round(self._clock(), 3),
            }
            self._dirty = True
        self.flush(force=True)

    def quarantine(self, pipe_fp: str, arm: str, reason: str) -> None:
        """Ban a candidate arm for this (kind, fingerprint) after a
        canary breach; the controller never proposes it again."""
        kind = self._resolve_kind()
        if kind is None:
            return
        with self._lock:
            self._quarantine[(kind, pipe_fp, str(arm))] = {
                "reason": str(reason)[:200],
                "at": round(self._clock(), 3),
            }
            self._dirty = True
        tune_metrics.quarantined.inc()
        self.flush(force=True)

    def audit(self, decision: str, **fields) -> None:
        """Append one decision to the store's audit trail (bounded at
        _AUDIT_CAP entries in the file; merged on flush)."""
        entry = {"t": round(self._clock(), 3), "decision": decision}
        entry.update({k: v for k, v in fields.items() if v is not None})
        with self._lock:
            self._audit_pending.append(entry)
            if len(self._audit_pending) > _AUDIT_CAP:
                del self._audit_pending[: -_AUDIT_CAP]
            self._dirty = True

    # -- queries ---------------------------------------------------------

    def arm_stats(
        self, pipe_fp: str, window: str, device_kind: str | None = None
    ) -> dict:
        """{arm: {"mean", "n_eff", "n", "newest"}} merging this process's
        reservoirs with the persisted store (other replicas' flushes),
        staleness-decayed: weight = 0.5 ** (age / stale_s)."""
        kind = device_kind or self._resolve_kind()
        if kind is None:
            return {}
        now = self._clock()
        stale_s = self._stale_s()
        merged: dict[str, dict] = {}
        for arm, samples in self._all_samples(kind, pipe_fp, window).items():
            wsum = vsum = 0.0
            n = 0
            newest = 0.0
            for t, v in samples:
                age = max(0.0, now - t)
                if age > 8 * stale_s:
                    continue
                w = 0.5 ** (age / stale_s)
                wsum += w
                vsum += w * v
                n += 1
                newest = max(newest, t)
            if wsum > 0:
                merged[arm] = {
                    "mean": vsum / wsum,
                    "n_eff": wsum,
                    "n": n,
                    "newest": newest,
                }
        return merged

    def windows(self, pipe_fp: str, device_kind: str | None = None) -> dict:
        """{window: total_sample_count} for a fingerprint — the
        controller tunes the hottest window (workload-mix adaptive)."""
        kind = device_kind or self._resolve_kind()
        if kind is None:
            return {}
        out: dict[str, int] = {}
        seen: set[tuple] = set()
        with self._lock:
            mem = dict(self._obs)
        for (k, fp, window, arm), samples in mem.items():
            if k == kind and fp == pipe_fp:
                out[window] = out.get(window, 0) + len(samples)
                seen.add((window, arm))
        obs = self._persisted_kind(kind).get("obs", {})
        table = obs.get(pipe_fp, {}) if isinstance(obs, dict) else {}
        if isinstance(table, dict):
            for window, arms in table.items():
                if not isinstance(arms, dict):
                    continue
                for arm, rec in arms.items():
                    if (window, arm) in seen:
                        continue  # counted from memory already
                    samples = (
                        rec.get("samples") if isinstance(rec, dict) else None
                    )
                    if isinstance(samples, list):
                        out[window] = out.get(window, 0) + len(samples)
        return out

    def is_quarantined(
        self, pipe_fp: str, arm: str, device_kind: str | None = None
    ) -> bool:
        kind = device_kind or self._resolve_kind()
        if kind is None:
            return False
        with self._lock:
            if (kind, pipe_fp, arm) in self._quarantine:
                return True
        q = self._persisted_kind(kind).get("quarantine", {})
        table = q.get(pipe_fp) if isinstance(q, dict) else None
        return isinstance(table, dict) and arm in table

    def io_scale(
        self, plan_fp: str, stage: str, device_kind: str | None = None
    ) -> float | None:
        """Persisted measured/modeled boundary-byte ratio for a plan
        stage, or None. The cross-process generalization of the cost
        ledger's in-memory drift(): plan/pallas_exec and graph/compile
        fall back to this when the live ledger has no record (fresh
        process, record made by a replica)."""
        if env_registry.get("MCIM_NO_CALIB"):
            return None
        kind = device_kind or self._resolve_kind()
        if kind is None:
            return None
        with self._lock:
            ent = self._io.get((kind, plan_fp, stage))
        if ent is not None:
            return ent[0]
        table = self._persisted_kind(kind).get("io_scale", {})
        rec = table.get(plan_fp) if isinstance(table, dict) else None
        ent = rec.get(stage) if isinstance(rec, dict) else None
        if isinstance(ent, dict):
            ratio = ent.get("ratio")
            if isinstance(ratio, (int, float)) and ratio > 0:
                return float(ratio)
        return None

    def promoted_entry(
        self,
        pipe_fp: str,
        device_kind: str | None = None,
        width: int | None = None,
    ) -> dict | None:
        """The online promoted record for (fingerprint, kind), width-window
        filtered like the offline lookup."""
        kind = device_kind or self._resolve_kind()
        if kind is None:
            return None
        with self._lock:
            ent = self._promoted.get((kind, pipe_fp))
        if ent is None:
            table = self._persisted_kind(kind).get("promoted", {})
            ent = table.get(pipe_fp) if isinstance(table, dict) else None
        if not isinstance(ent, dict):
            return None
        if ent.get("choice") not in calibration.PLAN_CHOICES:
            return None
        rec_w = ent.get("width")
        if (
            width is not None
            and isinstance(rec_w, (int, float))
            and rec_w > 0
            and not (rec_w / 2 <= width <= rec_w * 2)
        ):
            return None
        return ent

    # -- persistence -----------------------------------------------------

    def flush(self, force: bool = False) -> str | None:
        """Merge this process's pending records into the calibration file
        (read, union, atomic rewrite). Rate-limited; no-op unless armed
        (MCIM_TUNE=1) or forced by a test."""
        if not force and not self.enabled():
            return None
        now = self._clock()
        with self._lock:
            if not self._dirty and not force:
                return None
            if not force and now - self._last_flush < self._flush_s():
                return None
            obs = dict(self._obs)
            io = dict(self._io)
            promoted = dict(self._promoted)
            quarantine = dict(self._quarantine)
            audit = list(self._audit_pending)
            self._audit_pending = []
            self._dirty = False
            self._last_flush = now
        try:
            data = calibration.raw_store()
            self._merge(data, obs, io, promoted, quarantine, audit, now)
            path = calibration.write_raw_store(data)
        except Exception:
            # persistence must never take down serving; records stay in
            # memory and the next flush retries
            with self._lock:
                self._audit_pending = audit + self._audit_pending
                self._dirty = True
            return None
        tune_metrics.flushes.inc()
        return path

    def _merge(self, data, obs, io, promoted, quarantine, audit, now):
        stale_s = self._stale_s()
        cap = self._reservoir()
        online = data.setdefault(_ONLINE_KEY, {})
        if not isinstance(online, dict):
            online = data[_ONLINE_KEY] = {}
        for (kind, fp, window, arm), samples in obs.items():
            rec = self._online_leaf(online, kind, "obs", fp, window, arm)
            merged = {
                (round(t, 3), v): None
                for t, v in self._file_samples(rec)
                if now - t <= 8 * stale_s
            }
            for t, v in samples:
                merged[(round(t, 3), float(v))] = None
            keep = sorted(merged, key=lambda tv: tv[0])[-cap:]
            rec["samples"] = [[t, v] for t, v in keep]
        for (kind, fp, stage), (ratio, t) in io.items():
            rec = self._online_leaf(online, kind, "io_scale", fp, stage)
            if not isinstance(rec.get("at"), (int, float)) or rec["at"] <= t:
                rec["ratio"] = round(ratio, 4)
                rec["at"] = t
        for (kind, fp), ent in promoted.items():
            table = self._online_leaf(online, kind, "promoted")
            old = table.get(fp)
            if (
                not isinstance(old, dict)
                or not isinstance(old.get("at"), (int, float))
                or old["at"] <= ent["at"]
            ):
                table[fp] = dict(ent)
        for (kind, fp, arm), ent in quarantine.items():
            table = self._online_leaf(online, kind, "quarantine", fp)
            table.setdefault(arm, dict(ent))
        if audit:
            trail = data.setdefault(_AUDIT_KEY, [])
            if not isinstance(trail, list):
                trail = data[_AUDIT_KEY] = []
            trail.extend(audit)
            trail.sort(key=lambda e: e.get("t", 0))
            del trail[:-_AUDIT_CAP]

    @staticmethod
    def _online_leaf(online: dict, kind: str, *path: str) -> dict:
        node = online.setdefault(kind, {})
        if not isinstance(node, dict):
            node = online[kind] = {}
        for p in path:
            nxt = node.setdefault(p, {})
            if not isinstance(nxt, dict):
                nxt = node[p] = {}
            node = nxt
        return node

    @staticmethod
    def _file_samples(rec) -> list:
        samples = rec.get("samples") if isinstance(rec, dict) else None
        out = []
        if isinstance(samples, list):
            for s in samples:
                if (
                    isinstance(s, (list, tuple))
                    and len(s) == 2
                    and isinstance(s[0], (int, float))
                    and isinstance(s[1], (int, float))
                ):
                    out.append((float(s[0]), float(s[1])))
        return out

    def _persisted_kind(self, kind: str) -> dict:
        online = calibration._load().get(_ONLINE_KEY)
        if not isinstance(online, dict):
            return {}
        rec = online.get(kind)
        return rec if isinstance(rec, dict) else {}

    def _all_samples(self, kind: str, pipe_fp: str, window: str) -> dict:
        """{arm: [(t, v), ...]} unioned across memory and file."""
        out: dict[str, list] = {}
        obs = self._persisted_kind(kind).get("obs", {})
        table = obs.get(pipe_fp, {}) if isinstance(obs, dict) else {}
        arms = table.get(window, {}) if isinstance(table, dict) else {}
        if isinstance(arms, dict):
            for arm, rec in arms.items():
                out[arm] = self._file_samples(rec)
        with self._lock:
            for (k, fp, win, arm), samples in self._obs.items():
                if k == kind and fp == pipe_fp and win == window:
                    seen = {(round(t, 3), v) for t, v in out.get(arm, [])}
                    merged = list(out.get(arm, []))
                    for t, v in samples:
                        if (round(t, 3), v) not in seen:
                            merged.append((t, v))
                    out[arm] = merged
        return out

    def audit_trail(self) -> list:
        """The persisted audit trail plus unflushed pending entries."""
        trail = calibration._load().get(_AUDIT_KEY)
        out = list(trail) if isinstance(trail, list) else []
        with self._lock:
            out.extend(self._audit_pending)
        return out

    def reset(self) -> None:
        """Drop all process-local state (tests)."""
        with self._lock:
            self._obs.clear()
            self._io.clear()
            self._last_t.clear()
            self._promoted.clear()
            self._quarantine.clear()
            self._audit_pending = []
            self._dirty = False
            self._last_flush = 0.0
            self._kind = None


online_store = OnlineStore()


def effective_plan_choice(
    pipe_fp: str | None,
    device_kind: str | None = None,
    width: int | None = None,
) -> str | None:
    """Newest-wins plan choice across the offline record and the online
    promotion for one key.

    Both sides are width-window filtered first; a missing `recorded_at`
    (legacy offline entry) sorts as oldest. When both exist and
    DISAGREE, the loser is by definition stale —
    `mcim_tune_stale_overrides_total` counts the override so a fleet
    whose offline sweeps have been lapped by live measurement is visible
    in the exposition."""
    if pipe_fp is None or env_registry.get("MCIM_NO_CALIB"):
        return None
    if device_kind is None:
        try:
            device_kind = calibration.current_device_kind()
        except Exception:
            return None
    offline = calibration.plan_entry(
        pipe_fp, device_kind=device_kind, width=width
    )
    online = online_store.promoted_entry(
        pipe_fp, device_kind=device_kind, width=width
    )
    if offline is None and online is None:
        return None
    if online is None:
        return offline.get("choice")
    if offline is None:
        return online.get("choice")
    off_t = offline.get("recorded_at")
    off_t = float(off_t) if isinstance(off_t, (int, float)) else 0.0
    on_t = online.get("at")
    on_t = float(on_t) if isinstance(on_t, (int, float)) else 0.0
    newer, older = (
        (online, offline) if on_t >= off_t else (offline, online)
    )
    if newer.get("choice") != older.get("choice"):
        tune_metrics.stale_overrides.inc()
    return newer.get("choice")


def persisted_io_scale(plan_fp: str | None, stage: str) -> float | None:
    """Module-level convenience over online_store.io_scale — the drop-in
    fallback for cost_ledger.drift() callers. Returns the decay-free
    persisted ratio clamped to the ledger's [0.25, 4.0] sanity band, or
    None."""
    if plan_fp is None:
        return None
    try:
        ratio = online_store.io_scale(str(plan_fp), stage)
    except Exception:
        return None
    if ratio is None or not math.isfinite(ratio):
        return None
    return min(4.0, max(0.25, float(ratio)))
