"""Constant-footprint streaming tile engine (docs/design.md "Streaming
tile engine"): gigapixel images and video as row-band streams through
the async engine — fixed-shape tiles, seam-stitched halos
(parallel/halo host strips), incremental decode/encode
(io/stream_codec), problem size decoupled from memory footprint."""

from mpi_cuda_imagemanipulation_tpu.stream.metrics import StreamMetrics
from mpi_cuda_imagemanipulation_tpu.stream.runner import (
    DEFAULT_TILE_ROWS,
    StreamResult,
    resumable_tiles,
    stream_fingerprint,
    stream_pipeline,
)
from mpi_cuda_imagemanipulation_tpu.stream.tiles import (
    StreamabilityError,
    plan_tiles,
    validate_stream_ops,
)
from mpi_cuda_imagemanipulation_tpu.stream.video import stream_video

__all__ = [
    "DEFAULT_TILE_ROWS",
    "StreamMetrics",
    "StreamResult",
    "StreamabilityError",
    "plan_tiles",
    "resumable_tiles",
    "stream_fingerprint",
    "stream_pipeline",
    "stream_video",
    "validate_stream_ops",
]
