"""Stream instrumentation — tile counters, stage latencies, and the
resident-bytes accounting the constant-memory claim rests on.

`mcim_stream_resident_bytes` tracks the bytes of pixel data the stream
runner is holding host-side RIGHT NOW (decoded bands, seam carries,
assembled tiles in flight, completed bands awaiting their ordered
write); `mcim_stream_peak_resident_bytes` is its high-water mark. The
acceptance property — and the tier-1 assertion — is that the peak is a
function of (tile_rows, inflight, chain halo) and FLAT in image height:
processing a 20x larger image must not move it. Device-side residency
is bounded by the same knobs (inflight tiles of fixed shape); the gauge
measures the host because that is where the old whole-image paths
actually died first.

Shares a Registry with the engine's `mcim_engine_*` families so one
`--metrics-out` snapshot carries both."""

from __future__ import annotations

import threading

from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry

STAGES = ("read", "stitch", "write")


class StreamMetrics:
    def __init__(self, registry: Registry | None = None):
        self.registry = registry or Registry()
        r = self.registry
        self._lock = threading.Lock()
        self._resident = 0
        self.tiles = r.counter(
            "mcim_stream_tiles_total",
            "Stream tiles by outcome (ok/failed/resumed).",
            labels=("outcome",),
        )
        self.rows = r.counter(
            "mcim_stream_rows_total", "Output rows emitted by the stream."
        )
        self.frames = r.counter(
            "mcim_stream_frames_total",
            "Video frames by outcome (ok/failed/resumed).",
            labels=("outcome",),
        )
        self.stage = r.histogram(
            "mcim_stream_stage_seconds",
            "Host-side stream stage latency (read/stitch/write).",
            labels=("stage",),
        )
        self.resident = r.gauge(
            "mcim_stream_resident_bytes",
            "Host-resident pixel bytes held by the stream runner now.",
        )
        self.resident_peak = r.gauge(
            "mcim_stream_peak_resident_bytes",
            "High-water host-resident pixel bytes — the constant-memory "
            "acceptance gauge (flat in image size).",
        )

    # -- residency accounting ----------------------------------------------

    def track(self, nbytes: int) -> None:
        with self._lock:
            self._resident += int(nbytes)
            self.resident.set(self._resident)
            self.resident_peak.set_max(self._resident)

    def untrack(self, nbytes: int) -> None:
        with self._lock:
            self._resident = max(0, self._resident - int(nbytes))
            self.resident.set(self._resident)

    @property
    def peak_resident_bytes(self) -> int:
        return int(self.resident_peak.value())

    def on_stage(
        self, stage: str, seconds: float, exemplar: str | None = None
    ) -> None:
        # exemplar: the stream's trace id joins a stage-latency spike in
        # the exposition to its tile span chain (obs/metrics.py)
        self.stage.observe(seconds, stage=stage, exemplar=exemplar)

    def snapshot(self) -> dict:
        return {
            "tiles_ok": int(self.tiles.value(outcome="ok")),
            "tiles_failed": int(self.tiles.value(outcome="failed")),
            "tiles_resumed": int(self.tiles.value(outcome="resumed")),
            "rows": int(self.rows.value()),
            "resident_bytes": int(self.resident.value()),
            "peak_resident_bytes": self.peak_resident_bytes,
        }
