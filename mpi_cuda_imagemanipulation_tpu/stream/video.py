"""Video mode — frame sequences through the same engine steady state.

A video is a stream of same-shape frames; the per-frame steady state
(decode → temporal combine → tiled spatial chain → incremental encode)
is exactly the overlap workload the async engine was built for, so the
frame loop keeps ONE ordered engine alive across frames and only the
per-frame writers rotate.

Temporal ops (ops/temporal.py) lead the chain and read from bounded
frame-history rings — one ring per temporal op, each capped at that
op's window, so an hour of video holds `sum(window)` frames, never the
stream. Spatial ops then run through the tile runner per frame
(frames taller than the tile budget stream in bands like any image).

Resume reuses the batch journal discipline verbatim: one record per
FRAME, trusted only when the input digest matches, written only after
the frame's output is durable. Skipped frames are still DECODED on
resume — the temporal rings need their pixels — but pay no compute or
encode; the log says so, because "resume re-reads k frames" is a
latency the operator should see, not discover.

LIVE sessions (`VideoSessionHost` + `stream_video_session`): the same
temporal rings, held as per-session replica state behind the fabric
front door (fabric/session.py routes). The router owns stickiness and
the replayable journal tail; this module owns the ring arithmetic on
the replica and the ordered-stream client. The replay protocol is
strict on sequence numbers — a frame that is not exactly `last_seq + 1`
is either an idempotent duplicate (skipped) or a protocol gap
(rejected), never silently pushed, because a ring with a missing frame
produces plausible-but-wrong pixels forever after.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

import numpy as np

from mpi_cuda_imagemanipulation_tpu.engine import Engine, EngineMetrics
from mpi_cuda_imagemanipulation_tpu.io.image import load_image
from mpi_cuda_imagemanipulation_tpu.io.stream_codec import (
    ArrayTileReader,
    open_tile_writer,
)
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.ops.temporal import TemporalOp, split_temporal
from mpi_cuda_imagemanipulation_tpu.stream.metrics import StreamMetrics
from mpi_cuda_imagemanipulation_tpu.stream.runner import (
    DEFAULT_TILE_ROWS,
    stream_pipeline,
)
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger


def parse_video_ops(spec: str):
    """(temporal_ops, spatial_ops) from one pipeline string. The spatial
    part goes through Pipeline.parse — same registry, same validation —
    and may be empty (a pure temporal pipeline like `framediff`)."""
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline

    temporal, rest = split_temporal(spec)
    spatial = Pipeline.parse(rest).ops if rest else ()
    return temporal, spatial


class FrameRings:
    """One bounded history ring per temporal op, chained: op k's ring
    holds op k-1's outputs. `push` advances all rings for one frame and
    returns the final temporal output. Memory = sum of windows, ever."""

    def __init__(self, temporal: tuple[TemporalOp, ...],
                 metrics: StreamMetrics | None = None):
        self.temporal = temporal
        self._rings: list[deque] = [
            deque(maxlen=op.window) for op in temporal
        ]
        self._metrics = metrics

    def push(self, frame: np.ndarray) -> np.ndarray:
        x = frame
        for op, ring in zip(self.temporal, self._rings):
            if self._metrics is not None:
                if len(ring) == ring.maxlen:
                    self._metrics.untrack(ring[0].nbytes)
                self._metrics.track(x.nbytes)
            ring.append(x)
            x = op(ring)
        return x

    def sizes(self) -> list[int]:
        return [len(r) for r in self._rings]


def stream_video(
    frame_paths,
    output_dir: str | os.PathLike,
    ops_spec: str,
    *,
    tile_rows: int = DEFAULT_TILE_ROWS,
    inflight: int = 2,
    io_threads: int = 2,
    impl: str = "xla",
    plan: str = "auto",
    out_ext: str = ".png",
    metrics: StreamMetrics | None = None,
    journal=None,
    resume: bool = False,
) -> dict:
    """Process an ordered frame sequence; returns the summary record.

    Output frames land in `output_dir` under each input's basename with
    `out_ext`. Frames must share one shape (the compiled tile chain and
    the temporal rings both require it) — a mismatched frame fails the
    run with the offending path named."""
    log = get_logger()
    metrics = metrics or StreamMetrics()
    temporal, spatial = parse_video_ops(ops_spec)
    frame_paths = [str(p) for p in frame_paths]
    if not frame_paths:
        raise ValueError("no video frames to process")
    os.makedirs(output_dir, exist_ok=True)

    prior = journal.load() if (journal is not None and resume) else {}
    rings = FrameRings(temporal, metrics)

    import jax

    engine = Engine(
        inflight=inflight,
        io_threads=io_threads,
        stage=jax.device_put,
        metrics=EngineMetrics(registry=metrics.registry),
        ordered_done=True,
        name="stream-video",
    )
    shape = None
    fn_cache = None  # shared across frames: one compile for the stream
    frames_done = 0
    frames_resumed = 0
    t0 = time.perf_counter()
    root = obs_trace.start_trace(
        "stream.video", frames=len(frame_paths), ops=ops_spec
    )
    try:
        with root:
            for k, path in enumerate(frame_paths):
                rel = os.path.basename(path)
                from mpi_cuda_imagemanipulation_tpu.resilience.journal import (
                    content_digest,
                )

                digest = content_digest(path)
                frame = np.asarray(load_image(path))
                if shape is None:
                    shape = frame.shape
                elif frame.shape != shape:
                    raise ValueError(
                        f"frame {path} has shape {frame.shape}; the "
                        f"stream is {shape} (video frames must match)"
                    )
                # temporal rings ALWAYS advance — a resumed frame's
                # pixels still feed its successors' history
                tframe = rings.push(frame)
                rec = prior.get(rel)
                if (
                    rec
                    and rec.get("status") == "ok"
                    and rec.get("digest") == digest
                ):
                    frames_resumed += 1
                    metrics.frames.inc(outcome="resumed")
                    continue
                out_name = os.path.splitext(rel)[0] + out_ext
                out_path = os.path.join(output_dir, out_name)
                c = tframe.shape[2] if tframe.ndim == 3 else 1
                from mpi_cuda_imagemanipulation_tpu.stream.tiles import (
                    out_channels,
                )

                writer = open_tile_writer(
                    out_path, tframe.shape[0], tframe.shape[1],
                    out_channels(spatial, c),
                )
                if fn_cache is None:
                    from mpi_cuda_imagemanipulation_tpu.stream.tiles import (
                        TileFnCache,
                    )

                    fn_cache = TileFnCache(
                        tuple(spatial),
                        global_h=tframe.shape[0],
                        global_w=tframe.shape[1],
                        impl=impl,
                        plan=plan,
                    )
                try:
                    stream_pipeline(
                        ArrayTileReader(tframe),
                        writer,
                        spatial,
                        tile_rows=min(tile_rows, tframe.shape[0]),
                        impl=impl,
                        metrics=metrics,
                        engine=engine,  # shared: one steady state
                        trace_parent=root.context(),
                        fn_cache=fn_cache,  # shared: one compile
                    )
                    writer.close()
                except Exception:
                    metrics.frames.inc(outcome="failed")
                    if journal is not None:
                        journal.record_failed(rel, digest, "frame failed")
                    raise
                if journal is not None:
                    journal.record_ok(rel, digest, out_name)
                metrics.frames.inc(outcome="ok")
                frames_done += 1
    finally:
        engine.close()
    wall = time.perf_counter() - t0
    if frames_resumed:
        log.info(
            "video resume: %d frames re-decoded for temporal history, "
            "0 recomputed", frames_resumed,
        )
    return {
        "frames": len(frame_paths),
        "frames_done": frames_done,
        "frames_resumed": frames_resumed,
        "temporal": [op.name for op in temporal],
        "ring_sizes": rings.sizes(),
        "wall_s": wall,
        "fps": frames_done / wall if wall > 0 else None,
        "peak_resident_bytes": metrics.peak_resident_bytes,
        "engine": engine.metrics.snapshot(),
    }


# --------------------------------------------------------------------------
# live sessions — per-session rings on a replica + the front-door client
# --------------------------------------------------------------------------


class SessionGapError(ValueError):
    """A live frame broke sequence contiguity — the rings cannot absorb
    it without lying. The HTTP layer maps this to 409 so the router
    rebinds with a proper journal-tail replay instead of serving
    corrupt temporal state."""


class _LiveSession:
    """One session's replica-side state: the temporal rings plus the
    sequence cursor the replay protocol is checked against."""

    def __init__(self, ops_spec: str):
        temporal, rest = split_temporal(ops_spec)
        self.ops_spec = ops_spec
        self.temporal = temporal
        self.rest = rest
        self.rings = FrameRings(temporal)
        self.last_seq = -1
        self.frames = 0
        self.lock = threading.Lock()
        self.last_active = time.monotonic()


class VideoSessionHost:
    """The replica side of live video sessions (fabric/session.py).

    Holds the digest-keyed temporal frame rings per session id and the
    spatial jit per ops spec (shared across sessions — two streams with
    one pipeline pay one compile). `process_frame` is the whole
    protocol: reset rebuilds from scratch (failover replay), a replayed
    frame pushes rings but skips compute+encode (the router discards
    the output anyway), duplicates are idempotent no-ops, and gaps
    raise `SessionGapError` — the bit-exactness of a resumed stream
    rests on this strictness."""

    def __init__(self, *, registry=None, max_sessions: int = 256):
        self.max_sessions = max_sessions
        self._lock = threading.Lock()
        self._sessions: dict[str, _LiveSession] = {}
        self._spatial: dict[str, object] = {}  # rest spec -> jit fn
        self.evicted = 0
        if registry is not None:
            self._m_frames = registry.counter(
                "mcim_stream_session_frames_total",
                "Live-session frames on this replica by outcome "
                "(live/replay/skipped).",
                labels=("outcome",),
            )
            registry.gauge(
                "mcim_stream_sessions_live",
                "Live video sessions holding rings on this replica.",
                fn=lambda: float(len(self._sessions)),
            )
        else:
            self._m_frames = None

    def _count(self, outcome: str) -> None:
        if self._m_frames is not None:
            self._m_frames.inc(outcome=outcome)

    def _get(self, sid: str, ops_spec: str, *, reset: bool) -> _LiveSession:
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is not None and not reset and sess.ops_spec == ops_spec:
                return sess
            if (
                len(self._sessions) >= self.max_sessions
                and sid not in self._sessions
            ):
                victim = min(
                    self._sessions.items(),
                    key=lambda kv: kv[1].last_active,
                )[0]
                del self._sessions[victim]
                self.evicted += 1
            sess = self._sessions[sid] = _LiveSession(ops_spec)
            return sess

    def _spatial_fn(self, sess: _LiveSession):
        if not sess.rest:
            return None
        fn = self._spatial.get(sess.rest)
        if fn is None:
            from mpi_cuda_imagemanipulation_tpu.models.pipeline import (
                Pipeline,
            )

            fn = self._spatial[sess.rest] = Pipeline.parse(sess.rest).jit()
        return fn

    def process_frame(
        self,
        sid: str,
        ops_spec: str,
        seq: int,
        frame: np.ndarray,
        *,
        replay: bool = False,
        reset: bool = False,
    ) -> np.ndarray | None:
        """Advance one session by one frame; returns the processed frame
        for live traffic, None for replayed/duplicate frames."""
        sess = self._get(sid, ops_spec, reset=reset)
        with sess.lock:
            sess.last_active = time.monotonic()
            if reset:
                # failover replay starts here: whatever rings an earlier
                # binding left behind are history that no longer matches
                # the router's journal tail
                sess.rings = FrameRings(sess.temporal)
                sess.last_seq = seq - 1
            if seq <= sess.last_seq:
                self._count("skipped")
                return None  # idempotent duplicate (replay overlap)
            if seq != sess.last_seq + 1:
                raise SessionGapError(
                    f"session {sid}: frame {seq} after {sess.last_seq} — "
                    "rings need a contiguous replay, not a gap"
                )
            out = sess.rings.push(np.asarray(frame))
            sess.last_seq = seq
            sess.frames += 1
            if replay:
                self._count("replay")
                return None
            fn = self._spatial_fn(sess)
            self._count("live")
            return np.asarray(fn(out)) if fn is not None else out

    def stats(self) -> dict:
        with self._lock:
            return {
                "sessions": len(self._sessions),
                "evicted": self.evicted,
                "by_id": {
                    sid: {
                        "ops": s.ops_spec,
                        "last_seq": s.last_seq,
                        "frames": s.frames,
                        "ring_sizes": s.rings.sizes(),
                    }
                    for sid, s in self._sessions.items()
                },
            }


def post_session_frame(
    url: str,
    session_id: str,
    ops_spec: str,
    seq: int,
    blob,
    *,
    timeout_s: float = 60.0,
) -> dict:
    """One live frame to a fabric front door; returns {code, body,
    replica, seq}. Transport errors surface as code 599 (the caller's
    retry policy decides, same contract as loadgen.http_post_image)."""
    import urllib.error
    import urllib.request

    from mpi_cuda_imagemanipulation_tpu.fabric import session as fsession

    req = urllib.request.Request(
        f"{url.rstrip('/')}{fsession.SESSION_PATH_PREFIX}"
        f"{session_id}/frame",
        data=blob,
        headers={
            "Content-Type": "application/octet-stream",
            fsession.HDR_OPS: ops_spec,
            fsession.HDR_SEQ: str(seq),
        },
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return {
                "code": resp.status,
                "body": resp.read(),
                "replica": resp.headers.get("X-Fabric-Replica", ""),
                "seq": seq,
            }
    except urllib.error.HTTPError as e:
        return {
            "code": e.code,
            "body": e.read(),
            "replica": e.headers.get("X-Fabric-Replica", ""),
            "seq": seq,
        }
    except Exception:
        return {"code": 599, "body": b"", "replica": "", "seq": seq}


def stream_video_session(
    frames,
    url: str,
    ops_spec: str,
    *,
    session_id: str,
    start_seq: int = 0,
    timeout_s: float = 60.0,
    retries: int = 3,
    retry_delay_s: float = 0.5,
    on_frame=None,
) -> dict:
    """Drive an ordered frame sequence through a fabric front door as ONE
    live session. `frames` are uint8 arrays (or paths, loaded in order);
    each is PNG-encoded and posted with its sequence number. A shed/
    transport answer retries the SAME seq after a short delay — an
    ordered stream must not skip — so a mid-stream replica death costs
    latency, never frames. Returns the summary with decoded outputs."""
    from mpi_cuda_imagemanipulation_tpu.io.image import (
        decode_image_bytes,
        encode_image_bytes,
    )

    outputs = []
    replicas = []
    retried = 0
    for seq, frame in enumerate(frames, start=start_seq):
        if isinstance(frame, (str, os.PathLike)):
            frame = np.asarray(load_image(frame))
        blob = encode_image_bytes(np.asarray(frame))
        r = None
        for attempt in range(retries + 1):
            r = post_session_frame(
                url, session_id, ops_spec, seq, blob, timeout_s=timeout_s
            )
            if r["code"] == 200:
                break
            retried += 1
            time.sleep(retry_delay_s * (attempt + 1))
        if r is None or r["code"] != 200:
            raise RuntimeError(
                f"session {session_id}: frame {seq} failed with "
                f"{r['code'] if r else 'n/a'} after {retries + 1} attempts"
            )
        out = decode_image_bytes(r["body"])
        outputs.append(out)
        replicas.append(r["replica"])
        if on_frame is not None:
            on_frame(seq, out, r)
    return {
        "session_id": session_id,
        "frames": len(outputs),
        "outputs": outputs,
        "replicas": replicas,
        "retried": retried,
    }
