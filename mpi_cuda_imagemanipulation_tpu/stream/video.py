"""Video mode — frame sequences through the same engine steady state.

A video is a stream of same-shape frames; the per-frame steady state
(decode → temporal combine → tiled spatial chain → incremental encode)
is exactly the overlap workload the async engine was built for, so the
frame loop keeps ONE ordered engine alive across frames and only the
per-frame writers rotate.

Temporal ops (ops/temporal.py) lead the chain and read from bounded
frame-history rings — one ring per temporal op, each capped at that
op's window, so an hour of video holds `sum(window)` frames, never the
stream. Spatial ops then run through the tile runner per frame
(frames taller than the tile budget stream in bands like any image).

Resume reuses the batch journal discipline verbatim: one record per
FRAME, trusted only when the input digest matches, written only after
the frame's output is durable. Skipped frames are still DECODED on
resume — the temporal rings need their pixels — but pay no compute or
encode; the log says so, because "resume re-reads k frames" is a
latency the operator should see, not discover.
"""

from __future__ import annotations

import os
import time
from collections import deque

import numpy as np

from mpi_cuda_imagemanipulation_tpu.engine import Engine, EngineMetrics
from mpi_cuda_imagemanipulation_tpu.io.image import load_image
from mpi_cuda_imagemanipulation_tpu.io.stream_codec import (
    ArrayTileReader,
    open_tile_writer,
)
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.ops.temporal import TemporalOp, split_temporal
from mpi_cuda_imagemanipulation_tpu.stream.metrics import StreamMetrics
from mpi_cuda_imagemanipulation_tpu.stream.runner import (
    DEFAULT_TILE_ROWS,
    stream_pipeline,
)
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger


def parse_video_ops(spec: str):
    """(temporal_ops, spatial_ops) from one pipeline string. The spatial
    part goes through Pipeline.parse — same registry, same validation —
    and may be empty (a pure temporal pipeline like `framediff`)."""
    from mpi_cuda_imagemanipulation_tpu.models.pipeline import Pipeline

    temporal, rest = split_temporal(spec)
    spatial = Pipeline.parse(rest).ops if rest else ()
    return temporal, spatial


class FrameRings:
    """One bounded history ring per temporal op, chained: op k's ring
    holds op k-1's outputs. `push` advances all rings for one frame and
    returns the final temporal output. Memory = sum of windows, ever."""

    def __init__(self, temporal: tuple[TemporalOp, ...],
                 metrics: StreamMetrics | None = None):
        self.temporal = temporal
        self._rings: list[deque] = [
            deque(maxlen=op.window) for op in temporal
        ]
        self._metrics = metrics

    def push(self, frame: np.ndarray) -> np.ndarray:
        x = frame
        for op, ring in zip(self.temporal, self._rings):
            if self._metrics is not None:
                if len(ring) == ring.maxlen:
                    self._metrics.untrack(ring[0].nbytes)
                self._metrics.track(x.nbytes)
            ring.append(x)
            x = op(ring)
        return x

    def sizes(self) -> list[int]:
        return [len(r) for r in self._rings]


def stream_video(
    frame_paths,
    output_dir: str | os.PathLike,
    ops_spec: str,
    *,
    tile_rows: int = DEFAULT_TILE_ROWS,
    inflight: int = 2,
    io_threads: int = 2,
    impl: str = "xla",
    plan: str = "auto",
    out_ext: str = ".png",
    metrics: StreamMetrics | None = None,
    journal=None,
    resume: bool = False,
) -> dict:
    """Process an ordered frame sequence; returns the summary record.

    Output frames land in `output_dir` under each input's basename with
    `out_ext`. Frames must share one shape (the compiled tile chain and
    the temporal rings both require it) — a mismatched frame fails the
    run with the offending path named."""
    log = get_logger()
    metrics = metrics or StreamMetrics()
    temporal, spatial = parse_video_ops(ops_spec)
    frame_paths = [str(p) for p in frame_paths]
    if not frame_paths:
        raise ValueError("no video frames to process")
    os.makedirs(output_dir, exist_ok=True)

    prior = journal.load() if (journal is not None and resume) else {}
    rings = FrameRings(temporal, metrics)

    import jax

    engine = Engine(
        inflight=inflight,
        io_threads=io_threads,
        stage=jax.device_put,
        metrics=EngineMetrics(registry=metrics.registry),
        ordered_done=True,
        name="stream-video",
    )
    shape = None
    fn_cache = None  # shared across frames: one compile for the stream
    frames_done = 0
    frames_resumed = 0
    t0 = time.perf_counter()
    root = obs_trace.start_trace(
        "stream.video", frames=len(frame_paths), ops=ops_spec
    )
    try:
        with root:
            for k, path in enumerate(frame_paths):
                rel = os.path.basename(path)
                from mpi_cuda_imagemanipulation_tpu.resilience.journal import (
                    content_digest,
                )

                digest = content_digest(path)
                frame = np.asarray(load_image(path))
                if shape is None:
                    shape = frame.shape
                elif frame.shape != shape:
                    raise ValueError(
                        f"frame {path} has shape {frame.shape}; the "
                        f"stream is {shape} (video frames must match)"
                    )
                # temporal rings ALWAYS advance — a resumed frame's
                # pixels still feed its successors' history
                tframe = rings.push(frame)
                rec = prior.get(rel)
                if (
                    rec
                    and rec.get("status") == "ok"
                    and rec.get("digest") == digest
                ):
                    frames_resumed += 1
                    metrics.frames.inc(outcome="resumed")
                    continue
                out_name = os.path.splitext(rel)[0] + out_ext
                out_path = os.path.join(output_dir, out_name)
                c = tframe.shape[2] if tframe.ndim == 3 else 1
                from mpi_cuda_imagemanipulation_tpu.stream.tiles import (
                    out_channels,
                )

                writer = open_tile_writer(
                    out_path, tframe.shape[0], tframe.shape[1],
                    out_channels(spatial, c),
                )
                if fn_cache is None:
                    from mpi_cuda_imagemanipulation_tpu.stream.tiles import (
                        TileFnCache,
                    )

                    fn_cache = TileFnCache(
                        tuple(spatial),
                        global_h=tframe.shape[0],
                        global_w=tframe.shape[1],
                        impl=impl,
                        plan=plan,
                    )
                try:
                    stream_pipeline(
                        ArrayTileReader(tframe),
                        writer,
                        spatial,
                        tile_rows=min(tile_rows, tframe.shape[0]),
                        impl=impl,
                        metrics=metrics,
                        engine=engine,  # shared: one steady state
                        trace_parent=root.context(),
                        fn_cache=fn_cache,  # shared: one compile
                    )
                    writer.close()
                except Exception:
                    metrics.frames.inc(outcome="failed")
                    if journal is not None:
                        journal.record_failed(rel, digest, "frame failed")
                    raise
                if journal is not None:
                    journal.record_ok(rel, digest, out_name)
                metrics.frames.inc(outcome="ok")
                frames_done += 1
    finally:
        engine.close()
    wall = time.perf_counter() - t0
    if frames_resumed:
        log.info(
            "video resume: %d frames re-decoded for temporal history, "
            "0 recomputed", frames_resumed,
        )
    return {
        "frames": len(frame_paths),
        "frames_done": frames_done,
        "frames_resumed": frames_resumed,
        "temporal": [op.name for op in temporal],
        "ring_sizes": rings.sizes(),
        "wall_s": wall,
        "fps": frames_done / wall if wall > 0 else None,
        "peak_resident_bytes": metrics.peak_resident_bytes,
        "engine": engine.metrics.snapshot(),
    }
