"""Tile geometry + the compiled per-tile op chain.

The streaming engine decomposes an (H, W[, C]) image into fixed-height
row bands and runs the SAME op chain every other backend runs — but on a
band extended with `chain_halo` real neighbour rows per interior seam,
so the band's output is bit-identical to the corresponding rows of the
whole-image golden result. The machinery is the sharded runner's
(parallel/api.py `_stencil_on_ext`), generalized from device boundaries
to tile boundaries:

  * each stencil op consumes `op.halo` rows of context from every
    interior side of the band and PADS (pad2d, the op's own edge mode)
    at sides that are the true image boundary — a chain of ops walks
    the extension down exactly as `ops.spec.chain_halo` sizes it;
  * `finalize` runs at GLOBAL row offsets (y0 is a traced scalar), so
    `edge_mode='interior'` masks (the reference guard) see image
    coordinates, not band coordinates — the same trick that removes the
    reference's per-slice seams removes ours;
  * only shape-preserving ops stream: pointwise + stencil families.
    Geometric ops re-index globally and global-statistics ops need a
    full-image pass; both are rejected loudly (`StreamabilityError`).

Compile cost is bounded by construction, not by image size: every
middle band shares one (shape, lead, tail) signature, so an arbitrarily
tall image compiles at most four variants (first / middle / last /
short-last) per chain. `y0` rides as a traced argument precisely so the
band index never recompiles anything.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from mpi_cuda_imagemanipulation_tpu.ops.spec import (
    U8,
    GeometricOp,
    GlobalOp,
    Op,
    PointwiseOp,
    StencilOp,
    chain_halo,
    exact_f32,
)

STREAM_IMPLS = ("auto", "xla", "mxu")


class StreamabilityError(ValueError):
    """The op chain cannot run as a row stream."""


def validate_stream_ops(ops: tuple[Op, ...]) -> int:
    """Reject non-streamable ops; return the chain halo (seam size)."""
    for op in ops:
        if isinstance(op, GeometricOp):
            raise StreamabilityError(
                f"op {op.name!r} re-indexes the image globally and cannot "
                "run as a row stream (geometric ops need the whole frame)"
            )
        if isinstance(op, GlobalOp):
            raise StreamabilityError(
                f"op {op.name!r} depends on a full-image statistic and "
                "cannot run as a single-pass row stream"
            )
        if not isinstance(op, (PointwiseOp, StencilOp)):
            raise StreamabilityError(f"op {op.name!r} is not streamable")
    return chain_halo(ops)


def out_channels(ops: tuple[Op, ...], in_channels: int) -> int:
    """Channel count after the chain (grayscale 3->1, gray2rgb 1->3)."""
    chan = in_channels
    for op in ops:
        if op.in_channels and chan != op.in_channels:
            raise ValueError(
                f"op {op.name!r} expects {op.in_channels} channels, "
                f"stream carries {chan}"
            )
        if op.out_channels:
            chan = op.out_channels
    return chan


@dataclass(frozen=True)
class TileSpec:
    """One band of the decomposition, in global row coordinates."""

    index: int
    out_lo: int  # first output row this tile produces
    out_hi: int  # one past the last
    lead: int  # context rows included above out_lo (0 at the image top)
    tail: int  # context rows included below out_hi (0 at the bottom)

    @property
    def ext_lo(self) -> int:
        return self.out_lo - self.lead

    @property
    def ext_hi(self) -> int:
        return self.out_hi + self.tail

    @property
    def out_rows(self) -> int:
        return self.out_hi - self.out_lo


def plan_tiles(height: int, tile_rows: int, halo: int) -> list[TileSpec]:
    """Decompose `height` rows into bands of `tile_rows`, each extended
    by `halo` rows of real context at interior seams. `tile_rows` must
    cover the chain halo: a seam strip comes from exactly one neighbour
    band (the Casper single-strip reuse), so halo > tile_rows would need
    multi-band carries — raise and let the caller pick a bigger tile."""
    if tile_rows < 1:
        raise ValueError(f"tile_rows must be >= 1, got {tile_rows}")
    if height < 1:
        raise ValueError(f"height must be >= 1, got {height}")
    if halo > tile_rows:
        raise StreamabilityError(
            f"tile_rows={tile_rows} is smaller than the chain halo "
            f"{halo}; a seam would span multiple bands — raise "
            f"--tile-rows to at least {halo}"
        )
    n = math.ceil(height / tile_rows)
    bounds = [
        (k * tile_rows, min(height, (k + 1) * tile_rows)) for k in range(n)
    ]
    # a short last band (< halo rows) would hand its predecessor a
    # partial seam strip; merge it into the predecessor instead — the
    # merged band is at most tile_rows + halo <= 2*tile_rows tall, so
    # the memory bound only gains a constant
    if len(bounds) > 1 and bounds[-1][1] - bounds[-1][0] < halo:
        lo, _ = bounds[-2]
        bounds[-2] = (lo, height)
        bounds.pop()
    tiles = []
    for k, (lo, hi) in enumerate(bounds):
        tiles.append(
            TileSpec(
                index=k,
                out_lo=lo,
                out_hi=hi,
                lead=min(halo, lo),
                tail=min(halo, height - hi),
            )
        )
    return tiles


# --------------------------------------------------------------------------
# The compiled per-tile chain
# --------------------------------------------------------------------------


def _acc_fn(op: StencilOp, impl: str, width: int):
    """Per-stencil accumulator routing — graduated to the shared
    plan-executor helper (plan/exec.stencil_acc_fn) so the stream, plan
    and sharded fused paths make identical per-op backend decisions."""
    from mpi_cuda_imagemanipulation_tpu.plan.exec import stencil_acc_fn

    return stencil_acc_fn(op, impl, width)


def make_tile_fn(
    ops: tuple[Op, ...],
    *,
    lead: int,
    tail: int,
    global_h: int,
    global_w: int,
    impl: str = "xla",
    plan=None,
):
    """A jitted ``f(ext_u8, y_ext0) -> out_u8`` for tiles with this
    (lead, tail) context signature. ``ext`` covers global rows
    [y_ext0, y_ext0 + ext.rows); the result covers
    [y_ext0 + lead, y_ext0 + ext.rows - tail). One closure serves every
    band with the same signature — `y_ext0` is traced, so only the four
    edge-position variants (and the short last band) ever retrace.

    `plan` (a built plan.ir.Plan, default per-op) stages the walk: each
    fused stage runs as one pass via the shared stage walker
    (plan/exec.walk_stage), with the context budget threaded ACROSS
    stages so seam consumption is identical to the per-op walk — the
    seam strips themselves are already per-chain (`chain_halo`), so the
    plan changes in-tile structure, never tile geometry."""
    if impl not in STREAM_IMPLS:
        raise ValueError(f"unknown stream impl {impl!r}; known: {STREAM_IMPLS}")
    if plan is None:
        from mpi_cuda_imagemanipulation_tpu.plan import build_plan

        plan = build_plan(ops, "off")
    from mpi_cuda_imagemanipulation_tpu.plan.exec import walk_stage

    acc_fns = {
        id(op): _acc_fn(op, impl, global_w)
        for op in ops
        if isinstance(op, StencilOp)
    }

    def run(ext: jnp.ndarray, y_ext0: jnp.ndarray) -> jnp.ndarray:
        cur = ext
        lead_rem, tail_rem = lead, tail
        y_lo = y_ext0
        for stage in plan.stages:
            # validate_stream_ops rejected geometric/global ops up front,
            # so every stage is a fused pointwise/stencil run
            f, y_lo, lead_rem, tail_rem = walk_stage(
                stage.ops,
                exact_f32(cur),
                y_lo=y_lo,
                lead_rem=lead_rem,
                tail_rem=tail_rem,
                global_h=global_h,
                global_w=global_w,
                acc_fns=acc_fns,
            )
            cur = f.astype(U8)
        return cur

    return jax.jit(run)


class TileFnCache:
    """The per-run compile cache: one jitted closure per (lead, tail)
    signature (jit itself keys on the band shape). At most four entries
    for any image height — the bounded-compile guarantee. `plan` is the
    fusion-planner knob (a PLAN_MODES string), resolved once here so
    every band variant shares one stage structure."""

    def __init__(self, ops, *, global_h, global_w, impl, plan="auto"):
        from mpi_cuda_imagemanipulation_tpu.plan import (
            build_plan,
            resolve_plan_mode,
        )

        self.ops = ops
        self.global_h = global_h
        self.global_w = global_w
        self.impl = impl
        # the stream computes with XLA/MXU accumulators only (no Pallas),
        # so resolution follows the pure-XLA convention at the stream's
        # width; 'auto' therefore defaults to fused here. A resolved
        # 'fused-pallas' keeps the identical stage partition but walks it
        # with the same XLA executor — tile seams thread their (lead,
        # tail) budget across stages on the host-tiled path, which the
        # static-block megakernel does not model (plan/pallas_exec
        # eligibility matrix)
        self.plan_mode = resolve_plan_mode(
            ops, plan, backend="xla" if impl == "auto" else impl,
            width=global_w,
        )
        self.plan = build_plan(ops, self.plan_mode)
        self._fns: dict[tuple[int, int], object] = {}

    def _modeled_bytes(self, lead: int, tail: int, args) -> float:
        """Boundary model for one tile executable: the u8 extended band
        in (+ the traced y0 scalar), the u8 output band out — seam
        context rides the input read, nothing else crosses no matter how
        the plan staged the walk (the cost ledger checks this against
        memory_analysis per compiled variant)."""
        ext = args[0]
        in_px = 1
        for d in ext.shape:
            in_px *= int(d)
        ch_in = ext.shape[2] if len(ext.shape) == 3 else 1
        ch_out = out_channels(self.ops, ch_in)
        out_rows = ext.shape[0] - lead - tail
        out_px = out_rows * ext.shape[1] * ch_out
        return float(in_px + out_px + 4)  # + the i32 y0 scalar

    def fn(self, spec: TileSpec):
        key = (spec.lead, spec.tail)
        f = self._fns.get(key)
        if f is None:
            from mpi_cuda_imagemanipulation_tpu.obs import cost as obs_cost

            jitted = make_tile_fn(
                self.ops,
                lead=spec.lead,
                tail=spec.tail,
                global_h=self.global_h,
                global_w=self.global_w,
                impl=self.impl,
                plan=self.plan,
            )
            # cost attribution rides the insertion (obs/cost): the first
            # call per variant compiles AOT with the live band shapes —
            # the one compile jit would have paid anyway — and the
            # ledger keys the record by the plan fingerprint + signature
            f = self._fns[key] = obs_cost.wrap_cache_fn(
                "stream",
                f"{self.plan.fingerprint}:l{spec.lead}t{spec.tail}",
                jitted,
                modeled_fn=lambda args, lt=key: self._modeled_bytes(
                    lt[0], lt[1], args
                ),
            )
        return f
