"""The constant-footprint streaming tile runner.

One pass over the image, problem size decoupled from memory footprint:

    reader thread       caller thread                engine completion   encode pool
    ─────────────       ─────────────                ─────────────────   ───────────
    read band k+2 ──►┐
    (bounded queue,  ├─ stitch seam strips → ext_k
     2 bands ahead) ─┘  submit: H2D stage + enqueue ─► force D2H in ───► ordered
                          ▲ blocks at `inflight`       submission        write_rows
                          │ outstanding (backpressure) order             → journal ok

Reads are single-pass: every row is decoded ONCE. Tile k's extension is
assembled from the seam strips of its neighbours — the previous band's
tail strip is carried forward host-side (parallel/halo.host_edge_strips,
the ppermute edge-strip logic generalized to tile boundaries) and the
next band, already read for prefetch, donates its head — so interior
seams cost one `chain_halo` strip copy instead of a re-read (the Casper
reuse). With `inflight >= 2` the H2D upload of tile k+1 is staged while
tile k computes and tile k-1 encodes: the double-buffered steady state
the async engine was built for, now fed by a stream instead of a file
list.

Failure model: a tile that fails at dispatch/force/encode fails the
STREAM (one output file), but every completed tile was already written
and journaled, so `--resume` restarts at the first missing tile — the
journal trusts a tile record only when its config fingerprint matches
(ops/shape/tile_rows/impl), mirroring cmd_batch's digest rule. The
`stream.tile` and `stream.stitch` failpoints inject exactly these
faults for the tier-1 recovery tests.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from mpi_cuda_imagemanipulation_tpu.engine import Engine, EngineMetrics
from mpi_cuda_imagemanipulation_tpu.io.stream_codec import TileReader, TileWriter
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.parallel.halo import (
    host_edge_strips,
    stitch_tile,
)
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.stream.metrics import StreamMetrics
from mpi_cuda_imagemanipulation_tpu.stream.tiles import (
    TileFnCache,
    plan_tiles,
    validate_stream_ops,
)
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

DEFAULT_TILE_ROWS = 512


def stream_fingerprint(
    ops_name: str, height: int, width: int, channels: int,
    tile_rows: int, impl: str,
) -> str:
    """The journal 'digest' for stream tiles: a resumed run must be the
    SAME decomposition of the same computation, or every prior tile is
    distrusted (cmd_batch's edited-input rule, applied to config)."""
    import hashlib

    key = f"{ops_name}|{height}x{width}x{channels}|T{tile_rows}|{impl}"
    return hashlib.sha256(key.encode()).hexdigest()


@dataclass
class StreamResult:
    tiles: int
    tiles_done: int
    tiles_resumed: int
    rows: int
    wall_s: float
    peak_resident_bytes: int
    engine: dict
    compiles: int

    def as_dict(self) -> dict:
        return {
            "tiles": self.tiles,
            "tiles_done": self.tiles_done,
            "tiles_resumed": self.tiles_resumed,
            "rows": self.rows,
            "wall_s": self.wall_s,
            "peak_resident_bytes": self.peak_resident_bytes,
            "compiles": self.compiles,
            "engine": self.engine,
        }


def stream_pipeline(
    reader: TileReader,
    writer: TileWriter,
    ops,
    *,
    tile_rows: int = DEFAULT_TILE_ROWS,
    inflight: int = 2,
    io_threads: int = 2,
    impl: str = "xla",
    plan: str = "auto",
    metrics: StreamMetrics | None = None,
    engine: Engine | None = None,
    journal=None,
    journal_key: str = "stream",
    resume_tiles: int = 0,
    trace_parent=None,
    fn_cache: TileFnCache | None = None,
) -> StreamResult:
    """Run `ops` over `reader`'s rows into `writer`, holding O(tile_rows)
    pixels host-side regardless of image height. Bit-identical to the
    whole-image golden path for every streamable chain (stream/tiles.py).

    `engine=None` creates a private ordered engine and closes it;
    passing a shared one (video mode) flushes instead, so consecutive
    frames ride one steady state. `fn_cache` likewise shares the
    compiled tile closures across same-shape runs (video frames compile
    ONCE for the whole stream). `resume_tiles` skips that many leading
    tiles — the caller has verified (journal + output state) they are
    already durable."""
    log = get_logger()
    metrics = metrics or StreamMetrics()
    halo = validate_stream_ops(tuple(ops))
    H, W = reader.height, reader.width
    tiles = plan_tiles(H, tile_rows, halo)
    fingerprint = stream_fingerprint(
        ",".join(op.name for op in ops), H, W, reader.channels,
        tile_rows, impl,
    )
    if fn_cache is not None and (
        fn_cache.global_h != H or fn_cache.global_w != W
        or fn_cache.impl != impl
    ):
        raise ValueError(
            "shared fn_cache was built for "
            f"{fn_cache.global_h}x{fn_cache.global_w}/{fn_cache.impl}, "
            f"stream is {H}x{W}/{impl}"
        )
    # `plan` stages the per-tile chain through the fusion planner
    # (stream/tiles.TileFnCache): fused stages do one pass per stencil
    # group inside each tile. Seam geometry is untouched (strips are
    # already per-chain), and output stays bit-identical across modes,
    # so the resume fingerprint deliberately excludes the plan.
    cache = fn_cache or TileFnCache(
        tuple(ops), global_h=H, global_w=W, impl=impl, plan=plan
    )

    own_engine = engine is None
    if own_engine:
        import jax

        engine = Engine(
            inflight=inflight,
            io_threads=io_threads,
            stage=jax.device_put,
            metrics=EngineMetrics(registry=metrics.registry),
            ordered_done=True,
            name="stream",
        )

    root_ctx = trace_parent
    if root_ctx is None:
        cur = obs_trace.current_context()
        root_ctx = cur if cur is not None else None

    errors: list[tuple[int, BaseException]] = []
    done = {"n": 0}
    # host bytes of each in-flight tile's assembled extension: tracked
    # from stitch until the tile resolves (bounded by `inflight`)
    ext_bytes: dict[int, int] = {}

    def on_done(key, host, info):
        spec = tiles[key]
        host = np.asarray(host)
        metrics.track(host.nbytes)
        t0 = time.perf_counter()
        try:
            with obs_trace.span("stream.write", tile=key):
                writer.write_rows(host)
        finally:
            metrics.untrack(host.nbytes)
            metrics.untrack(ext_bytes.pop(key, 0))
            metrics.on_stage(
                "write", time.perf_counter() - t0,
                exemplar=obs_trace.current_trace_id() or None,
            )
        if journal is not None:
            # flush first: the ok record claims these rows survive a kill
            writer.flush()
            journal.record_ok(
                f"{journal_key}#tile{key}", fingerprint, f"rows{spec.out_lo}"
            )
        metrics.tiles.inc(outcome="ok")
        metrics.rows.inc(spec.out_rows)
        done["n"] += 1

    def on_error(key, exc):
        metrics.untrack(ext_bytes.pop(key, 0))
        metrics.tiles.inc(outcome="failed")
        errors.append((key, exc))
        if journal is not None:
            journal.record_failed(
                f"{journal_key}#tile{key}", fingerprint,
                f"{type(exc).__name__}: {exc}",
            )
        log.error("stream tile %s failed: %s", key, exc)

    # -- resume fast-forward ------------------------------------------------
    resume_tiles = min(resume_tiles, len(tiles))
    prev_tail: np.ndarray | None = None
    start = resume_tiles
    if resume_tiles:
        skipped_rows = tiles[resume_tiles - 1].out_hi
        if start < len(tiles) and tiles[start].lead:
            reader.skip_rows(skipped_rows - halo)
            prev_tail = reader.read_rows(halo)
        else:
            reader.skip_rows(skipped_rows)
        metrics.tiles.inc(resume_tiles, outcome="resumed")
        metrics.rows.inc(skipped_rows)
        log.info(
            "stream resume: %d/%d tiles (%d rows) already durable",
            resume_tiles, len(tiles), skipped_rows,
        )

    # -- decode prefetch thread --------------------------------------------
    # bands are read AHEAD of the submit loop on their own thread through
    # a bounded queue (2 bands — the decode double-buffer), so read
    # latency overlaps tile compute instead of serializing the stream;
    # backpressure composes: a full queue stalls the reader, a full
    # engine stalls the submitter, and both bounds are constants
    import queue as _queue
    import threading

    band_q: _queue.Queue = _queue.Queue(maxsize=2)
    stop_reading = threading.Event()

    def _produce():
        try:
            for j in range(start, len(tiles)):
                t0 = time.perf_counter()
                with obs_trace.span(
                    "stream.prefetch", parent=root_ctx, tile=j
                ):
                    b = reader.read_rows(tiles[j].out_rows)
                metrics.on_stage("read", time.perf_counter() - t0)
                metrics.track(b.nbytes)
                while not stop_reading.is_set():
                    try:
                        band_q.put((j, b), timeout=0.1)
                        break
                    except _queue.Full:
                        continue
                if stop_reading.is_set():
                    metrics.untrack(b.nbytes)
                    return
            band_q.put((None, None))
        except BaseException as e:  # surfaced to the submit loop
            band_q.put((None, e))

    producer = threading.Thread(
        target=_produce, name="mcim-stream-read", daemon=True
    )

    def _next_band() -> np.ndarray | None:
        j, b = band_q.get()
        if j is None:
            if isinstance(b, BaseException):
                raise b
            return None
        return b

    t_start = time.perf_counter()
    band: np.ndarray | None = None
    try:
        producer.start()
        if start < len(tiles):
            band = _next_band()
        if prev_tail is not None:
            metrics.track(prev_tail.nbytes)

        for i in range(start, len(tiles)):
            if errors:
                break  # a failed tile fails the stream; stop feeding it
            spec = tiles[i]
            nxt = _next_band() if i + 1 < len(tiles) else None

            t0 = time.perf_counter()
            with obs_trace.span("stream.stitch", parent=root_ctx, tile=i):
                failpoints.maybe_fail("stream.stitch", tile=i)
                head = nxt[: spec.tail] if spec.tail else None
                ext = stitch_tile(
                    prev_tail if spec.lead else None, band, head
                )
            metrics.on_stage("stitch", time.perf_counter() - t0)
            metrics.track(ext.nbytes)
            ext_bytes[i] = ext.nbytes

            # carry the seam strip for tile i+1 BEFORE the band is dropped
            new_tail = None
            if i + 1 < len(tiles) and tiles[i + 1].lead:
                new_tail = host_edge_strips(band, halo)[1]
                metrics.track(new_tail.nbytes)
            metrics.untrack(band.nbytes)
            if prev_tail is not None:
                metrics.untrack(prev_tail.nbytes)
            prev_tail, band = new_tail, nxt

            fn = cache.fn(spec)
            with obs_trace.span(
                "stream.tile", parent=root_ctx, tile=i,
                rows=spec.out_rows,
            ) as tspan:
                try:
                    failpoints.maybe_fail("stream.tile", tile=i)
                    engine.submit(
                        i,
                        lambda e=ext, y=spec.ext_lo: (e, np.int32(y)),
                        lambda x, f=fn: f(*x),
                        on_done=on_done,
                        on_error=on_error,
                    )
                except Exception as e:
                    tspan.set(error=type(e).__name__)
                    on_error(i, e)
                    break

    finally:
        stop_reading.set()
        while True:  # unblock a producer stuck on a full queue
            try:
                band_q.get_nowait()
            except _queue.Empty:
                break
        if producer.is_alive():
            producer.join(timeout=10.0)
        if own_engine:
            engine.close()
        else:
            engine.flush()
        reader.close()
    wall = time.perf_counter() - t_start

    if errors:
        k, exc = errors[0]
        raise RuntimeError(
            f"stream failed at tile {k} "
            f"({done['n'] + resume_tiles}/{len(tiles)} tiles durable; "
            f"re-run with --resume): {exc}"
        ) from exc

    return StreamResult(
        tiles=len(tiles),
        tiles_done=done["n"],
        tiles_resumed=resume_tiles,
        rows=H,
        wall_s=wall,
        peak_resident_bytes=metrics.peak_resident_bytes,
        engine=engine.metrics.snapshot(),
        compiles=len(cache._fns),
    )


def resumable_tiles(journal, journal_key: str, fingerprint: str, n_tiles: int) -> int:
    """The longest PREFIX of tiles journaled ok under `fingerprint` — a
    stream output is sequential, so only a contiguous prefix is durable
    (a lone ok tile after a gap is unreachable and re-run)."""
    if journal is None:
        return 0
    records = journal.load()
    k = 0
    while k < n_tiles:
        rec = records.get(f"{journal_key}#tile{k}")
        if not (
            rec
            and rec.get("status") == "ok"
            and rec.get("digest") == fingerprint
        ):
            break
        k += 1
    return k
