"""Shape buckets + stack padding — shared by the serving scheduler and the
batch CLI.

A jitted executable is keyed on its input shapes, so an online service that
compiled one executable per request shape would trace on every novel image.
Instead requests are padded *up* to a small configured set of (rows, cols)
buckets and the batch dimension is padded up to a small set of batch sizes,
so the whole reachable shape space is a finite grid that `serve/cache.py`
pre-compiles at startup. `serve/padded.py` makes the padding bit-invisible.

The same helpers serve `cli.py:cmd_batch`: a mid-stream partial stack (shape
change flush) pads to the compiled stack size with `pad_stack` so the shape's
executable is reused, while the trailing partial stack ships right-sized
(one extra compile beats discarding the pad's compute at the tail).
"""

from __future__ import annotations

import numpy as np

# Default row/col bucket sizes (each bucket is square unless the spec says
# RxC): covers thumbnails through 4K-ish rows; `serve --buckets` overrides.
DEFAULT_BUCKETS = ((512, 512), (1024, 1024), (2048, 2048), (4096, 4096))


def parse_buckets(spec: str) -> tuple[tuple[int, int], ...]:
    """Parse a CLI bucket spec: 'N' entries are square NxN buckets, 'RxC'
    entries are explicit. '512,1024x2048' -> ((512, 512), (1024, 2048)),
    sorted by area so `pick_bucket` prefers the cheapest fit."""
    out: list[tuple[int, int]] = []
    for tok in spec.split(","):
        tok = tok.strip().lower()
        if not tok:
            continue
        try:
            if "x" in tok:
                r, _, c = tok.partition("x")
                bh, bw = int(r), int(c)
            else:
                bh = bw = int(tok)
        except ValueError:
            raise ValueError(
                f"invalid bucket {tok!r}: expected N (square) or RxC"
            ) from None
        if bh < 1 or bw < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {tok!r}")
        out.append((bh, bw))
    if not out:
        raise ValueError(f"empty bucket spec {spec!r}")
    return tuple(sorted(set(out), key=lambda b: (b[0] * b[1], b)))


def pick_bucket(
    height: int, width: int, buckets: tuple[tuple[int, int], ...]
) -> tuple[int, int] | None:
    """The smallest-area bucket that fits (height, width), or None when the
    image exceeds every bucket (the caller sheds with a 'too large' status
    instead of compiling an unbounded shape)."""
    for bh, bw in buckets:  # sorted by area in parse_buckets
        if height <= bh and width <= bw:
            return (bh, bw)
    return None


def batch_buckets(max_batch: int, shards: int = 1) -> tuple[int, ...]:
    """The compiled batch sizes: shards * powers of two up to max_batch,
    plus max_batch itself. Every entry is a multiple of `shards` so the
    data-parallel sharding over the mesh's batch axis always divides."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if shards < 1:
        raise ValueError(f"shards must be >= 1, got {shards}")
    if max_batch % shards:
        raise ValueError(
            f"max_batch ({max_batch}) must be a multiple of shards ({shards})"
        )
    sizes = set()
    n = shards
    while n < max_batch:
        sizes.add(n)
        n *= 2
    sizes.add(max_batch)
    return tuple(sorted(sizes))


def pick_batch_bucket(n: int, buckets: tuple[int, ...]) -> int:
    """The smallest compiled batch size >= n (buckets sorted ascending)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds the largest compiled size {buckets[-1]}")


def pad_to_bucket(img: np.ndarray, bucket_h: int, bucket_w: int) -> np.ndarray:
    """Zero-pad an image at the bottom/right up to the bucket shape. The
    pad content is arbitrary by design: serve/padded.py reconstructs each
    op's true border extension from the true shape, so padded outputs are
    bit-identical to the unpadded run and the pad region is never read."""
    h, w = img.shape[:2]
    if h > bucket_h or w > bucket_w:
        raise ValueError(
            f"image {img.shape} exceeds bucket ({bucket_h}, {bucket_w})"
        )
    if (h, w) == (bucket_h, bucket_w):
        return img
    pad = [(0, bucket_h - h), (0, bucket_w - w)] + [(0, 0)] * (img.ndim - 2)
    return np.pad(img, pad)


def pad_stack(imgs: list[np.ndarray], n_target: int) -> np.ndarray:
    """Stack same-shape images, padding to `n_target` by repeating the last
    image so every dispatch reuses one compiled batch shape (a ragged batch
    would force a recompile — the very overhead stacking amortises). The
    caller drops the padded outputs (it knows its own real count)."""
    if not imgs:
        raise ValueError("pad_stack needs at least one image")
    if len(imgs) > n_target:
        raise ValueError(f"{len(imgs)} images exceed the target stack {n_target}")
    imgs = list(imgs) + [imgs[-1]] * (n_target - len(imgs))
    return np.stack(imgs, axis=0)
