r"""Serving metrics — queue depth, batch occupancy, latency percentiles.

Since the obs/ fabric landed this class is a thin recording facade over
an `obs.Registry`: every quantity lives in ONE named metric family
(`mcim_serve_*`, docs/design.md "Observability" naming scheme), the
Prometheus `GET /metrics` exposition renders the same objects, and
`snapshot()` — the `/stats` payload and shutdown report — is a *view*
over the registry, so the two endpoints cannot drift. Latency
percentiles come from the histograms' bounded reservoirs via
`utils.timing.percentiles` — the same quantile definition the bench
suite uses, so offline and online reports are comparable; the reservoir
keeps the most recent `sample_cap` observations (a serving process must
not grow memory with request count — admission control bounds the queue,
this bounds the accounting).

Per-request timeline (all device-synchronised wall clocks):

    submit --queue_wait--> dispatch --[batch device time]--> done
      \__________________ e2e latency _________________________/
"""

from __future__ import annotations

import threading

from mpi_cuda_imagemanipulation_tpu.obs.metrics import Registry
from mpi_cuda_imagemanipulation_tpu.resilience import (
    deadline as deadline_mod,
)

PERCENTILES = (50, 95, 99)

# terminal request statuses, the label set of mcim_serve_requests_total
STATUSES = (
    "ok", "overloaded", "rejected", "deadline_expired", "error",
    "quarantined",
)


class ServeMetrics:
    def __init__(self, registry: Registry | None = None,
                 sample_cap: int = 65536):
        self.registry = registry or Registry()
        r = self.registry
        # one lock serialises multi-metric updates (e.g. queue depth +
        # its peak) so snapshots never see a torn pair
        self._lock = threading.Lock()
        self._submitted = r.counter(
            "mcim_serve_submitted_total", "Requests submitted for admission."
        )
        self._requests = r.counter(
            "mcim_serve_requests_total",
            "Requests resolved, by terminal status.",
            labels=("status",),
        )
        self._retries = r.counter(
            "mcim_serve_retries_total",
            "Dispatch attempts re-run by the retry executor.",
        )
        self._qos_shed = r.counter(
            "mcim_serve_qos_shed_total",
            "Sheds caused by a QoS class hitting its queue fraction "
            "before the full depth (low classes shed first; "
            "graph/tenancy ladder).",
            labels=("qos",),
        )
        self._degraded = r.counter(
            "mcim_serve_degraded_total",
            "Requests served via the golden fallback (breaker open).",
        )
        self._dispatches = r.counter(
            "mcim_serve_dispatches_total", "Micro-batch dispatches."
        )
        self._batch_slots = r.counter(
            "mcim_serve_batch_slots_total",
            "Compiled batch slots dispatched (incl. pad).",
        )
        self._batch_real = r.counter(
            "mcim_serve_batch_real_total", "Real requests dispatched."
        )
        self._queued = r.gauge(
            "mcim_serve_queue_depth", "Current admission-queue depth."
        )
        self._queued_peak = r.gauge(
            "mcim_serve_queue_depth_peak",
            "High-water admission-queue depth.",
        )
        self._queue_wait = r.histogram(
            "mcim_serve_queue_wait_seconds",
            "Admission-to-dispatch wait per request.",
            sample_cap=sample_cap,
        )
        self._device = r.histogram(
            "mcim_serve_device_seconds",
            "Device time per micro-batch dispatch.",
            sample_cap=sample_cap,
        )
        self._e2e = r.histogram(
            "mcim_serve_e2e_latency_seconds",
            "Submit-to-done latency per completed request.",
            sample_cap=sample_cap,
        )
        # the per-tier deadline-expiry counter (resilience/deadline.py):
        # shared by this process's HTTP edge ("replica"), queue-pop
        # expiry ("scheduler") and graph dispatch ("graph") — the
        # registry dedups, so each subsystem just asks for it
        self.deadline_tiers = deadline_mod.expired_counter(r)

    # -- registry-backed readers (back-compat attribute surface) -----------

    @property
    def submitted(self) -> int:
        return int(self._submitted.value())

    @property
    def completed(self) -> int:
        return int(self._requests.value(status="ok"))

    @property
    def retries(self) -> int:
        return int(self._retries.value())

    @property
    def queued(self) -> int:
        return int(self._queued.value())

    # -- recording ---------------------------------------------------------

    def on_submit(self) -> None:
        self._submitted.inc()

    def on_admit(self) -> None:
        with self._lock:
            self._queued.inc()
            self._queued_peak.set_max(self._queued.value())

    def on_shed(self, qos: str = "") -> None:
        """`qos` names the admission class when the shed happened at a
        class fraction BELOW the full queue depth (QoS-first shedding);
        "" is the plain full-queue shed."""
        self._requests.inc(status="overloaded")
        if qos:
            self._qos_shed.inc(qos=qos)

    def on_reject(self) -> None:
        self._requests.inc(status="rejected")

    def on_deadline_at_submit(self) -> None:
        """A request whose propagated budget was already dead at submit:
        resolved deadline_expired without ever being admitted (so no
        queue-depth bookkeeping, unlike `on_deadline`)."""
        self._requests.inc(status="deadline_expired")
        deadline_mod.count_expired(self.deadline_tiers, "scheduler")

    def on_deadline(self, queue_wait_s: float, trace_id: str = "") -> None:
        with self._lock:
            self._requests.inc(status="deadline_expired")
            self._queued.dec()
        # the queue-pop expiry is the LAST link of the propagated
        # deadline chain — same per-tier family the door/router use
        deadline_mod.count_expired(self.deadline_tiers, "scheduler")
        self._queue_wait.observe(queue_wait_s, exemplar=trace_id or None)

    def on_dispatch(
        self, n_real: int, n_slots: int, device_s: float,
        trace_id: str = "",
    ) -> None:
        self._dispatches.inc()
        self._batch_real.inc(n_real)
        self._batch_slots.inc(n_slots)
        self._device.observe(device_s, exemplar=trace_id or None)

    def on_complete(
        self, queue_wait_s: float, e2e_s: float, trace_id: str = ""
    ) -> None:
        """`trace_id` rides as the latency histograms' exemplar: a p99
        spike in the (federated) exposition then names the trace that
        caused it instead of an anonymous bucket count."""
        with self._lock:
            self._requests.inc(status="ok")
            self._queued.dec()
        self._queue_wait.observe(queue_wait_s, exemplar=trace_id or None)
        self._e2e.observe(e2e_s, exemplar=trace_id or None)

    def on_error(self, n: int = 1) -> None:
        with self._lock:
            self._requests.inc(n, status="error")
            self._queued.dec(n)

    def on_retry(self) -> None:
        self._retries.inc()

    def on_quarantine(self, n: int = 1) -> None:
        with self._lock:
            self._requests.inc(n, status="quarantined")
            self._queued.dec(n)

    def on_degraded(self, n: int = 1) -> None:
        # the request ALSO counts through on_complete (it succeeded); this
        # only tags how many went via the fallback path
        self._degraded.inc(n)

    # -- reporting ---------------------------------------------------------

    def e2e_exemplar(self, q: float = 99) -> dict | None:
        """The e2e-latency exemplar nearest the q-th percentile — the
        trace id loadgen/bench reports print next to the outlier
        percentile (obs/metrics.Histogram.exemplar_for_quantile)."""
        ex = self._e2e.exemplar_for_quantile(q)
        if ex is None:
            return None
        return {"trace_id": ex[0], "value_s": ex[1]}

    def snapshot(self) -> dict:
        dispatches = int(self._dispatches.value())
        batch_real = int(self._batch_real.value())
        batch_slots = int(self._batch_slots.value())
        return {
            "submitted": int(self._submitted.value()),
            "completed": int(self._requests.value(status="ok")),
            "shed_overloaded": int(self._requests.value(status="overloaded")),
            "rejected": int(self._requests.value(status="rejected")),
            "deadline_expired": int(
                self._requests.value(status="deadline_expired")
            ),
            "errors": int(self._requests.value(status="error")),
            "retries": int(self._retries.value()),
            "quarantined": int(self._requests.value(status="quarantined")),
            "degraded": int(self._degraded.value()),
            "queued": int(self._queued.value()),
            "queued_peak": int(self._queued_peak.value()),
            "dispatches": dispatches,
            "mean_batch_occupancy": (
                batch_real / dispatches if dispatches else None
            ),
            "batch_fill_frac": (
                batch_real / batch_slots if batch_slots else None
            ),
            "queue_wait": self._queue_wait.percentiles_ms(PERCENTILES),
            "device_per_dispatch": self._device.percentiles_ms(PERCENTILES),
            "e2e_latency": self._e2e.percentiles_ms(PERCENTILES),
        }

    def summary_line(self) -> str:
        s = self.snapshot()
        lat = s["e2e_latency"] or {}
        occ = s["mean_batch_occupancy"]
        return (
            f"served {s['completed']}/{s['submitted']} "
            f"(shed {s['shed_overloaded']}, rejected {s['rejected']}, "
            f"deadline {s['deadline_expired']}, errors {s['errors']}, "
            f"retries {s['retries']}, quarantined {s['quarantined']}, "
            f"degraded {s['degraded']}) in "
            f"{s['dispatches']} dispatches"
            + (f" (mean occupancy {occ:.2f})" if occ else "")
            + (
                f"; e2e p50/p95/p99 = {lat['p50_ms']:.1f}/"
                f"{lat['p95_ms']:.1f}/{lat['p99_ms']:.1f} ms"
                if lat
                else ""
            )
        )
