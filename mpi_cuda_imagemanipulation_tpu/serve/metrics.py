r"""Serving metrics — queue depth, batch occupancy, latency percentiles.

Counters + bounded reservoirs behind one lock; `snapshot()` is the /stats
payload and `summary_line()` the shutdown report. Latency percentiles come
from `utils.timing.percentiles` — the same quantile definition the bench
suite uses, so offline and online reports are comparable. Sample
reservoirs keep the most recent `sample_cap` observations (a serving
process must not grow memory with request count — admission control
bounds the queue, this bounds the accounting).

Per-request timeline (all device-synchronised wall clocks):

    submit --queue_wait--> dispatch --[batch device time]--> done
      \__________________ e2e latency _________________________/
"""

from __future__ import annotations

import threading
from collections import deque

from mpi_cuda_imagemanipulation_tpu.utils.timing import percentiles

PERCENTILES = (50, 95, 99)


class ServeMetrics:
    def __init__(self, sample_cap: int = 65536):
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.shed_overloaded = 0
        self.rejected = 0  # malformed / too-large / too-small requests
        self.deadline_expired = 0
        self.errors = 0
        self.retries = 0  # dispatch attempts re-run by the retry executor
        self.quarantined = 0  # poison requests failed solo after bisection
        self.degraded = 0  # requests served via the golden fallback
        self.dispatches = 0
        self.batch_slots = 0  # compiled slots dispatched (incl. pad)
        self.batch_real = 0  # real requests dispatched
        self.queued = 0  # current admission-queue depth (gauge)
        self.queued_peak = 0
        self.queue_wait_s: deque = deque(maxlen=sample_cap)
        self.device_s: deque = deque(maxlen=sample_cap)  # per dispatch
        self.e2e_s: deque = deque(maxlen=sample_cap)

    # -- recording ---------------------------------------------------------

    def on_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def on_admit(self) -> None:
        with self._lock:
            self.queued += 1
            self.queued_peak = max(self.queued_peak, self.queued)

    def on_shed(self) -> None:
        with self._lock:
            self.shed_overloaded += 1

    def on_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def on_deadline(self, queue_wait_s: float) -> None:
        with self._lock:
            self.deadline_expired += 1
            self.queued -= 1
            self.queue_wait_s.append(queue_wait_s)

    def on_dispatch(self, n_real: int, n_slots: int, device_s: float) -> None:
        with self._lock:
            self.dispatches += 1
            self.batch_real += n_real
            self.batch_slots += n_slots
            self.device_s.append(device_s)

    def on_complete(self, queue_wait_s: float, e2e_s: float) -> None:
        with self._lock:
            self.completed += 1
            self.queued -= 1
            self.queue_wait_s.append(queue_wait_s)
            self.e2e_s.append(e2e_s)

    def on_error(self, n: int = 1) -> None:
        with self._lock:
            self.errors += n
            self.queued -= n

    def on_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def on_quarantine(self, n: int = 1) -> None:
        with self._lock:
            self.quarantined += n
            self.queued -= n

    def on_degraded(self, n: int = 1) -> None:
        # the request ALSO counts through on_complete (it succeeded); this
        # only tags how many went via the fallback path
        with self._lock:
            self.degraded += n

    # -- reporting ---------------------------------------------------------

    @staticmethod
    def _pcts(samples) -> dict[str, float] | None:
        if not samples:
            return None
        got = percentiles(samples, PERCENTILES)
        return {f"p{int(q)}_ms": got[q] * 1e3 for q in PERCENTILES}

    def snapshot(self) -> dict:
        with self._lock:
            mean_occupancy = (
                self.batch_real / self.dispatches if self.dispatches else None
            )
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed_overloaded": self.shed_overloaded,
                "rejected": self.rejected,
                "deadline_expired": self.deadline_expired,
                "errors": self.errors,
                "retries": self.retries,
                "quarantined": self.quarantined,
                "degraded": self.degraded,
                "queued": self.queued,
                "queued_peak": self.queued_peak,
                "dispatches": self.dispatches,
                "mean_batch_occupancy": mean_occupancy,
                "batch_fill_frac": (
                    self.batch_real / self.batch_slots if self.batch_slots else None
                ),
                "queue_wait": self._pcts(self.queue_wait_s),
                "device_per_dispatch": self._pcts(self.device_s),
                "e2e_latency": self._pcts(self.e2e_s),
            }

    def summary_line(self) -> str:
        s = self.snapshot()
        lat = s["e2e_latency"] or {}
        occ = s["mean_batch_occupancy"]
        return (
            f"served {s['completed']}/{s['submitted']} "
            f"(shed {s['shed_overloaded']}, rejected {s['rejected']}, "
            f"deadline {s['deadline_expired']}, errors {s['errors']}, "
            f"retries {s['retries']}, quarantined {s['quarantined']}, "
            f"degraded {s['degraded']}) in "
            f"{s['dispatches']} dispatches"
            + (f" (mean occupancy {occ:.2f})" if occ else "")
            + (
                f"; e2e p50/p95/p99 = {lat['p50_ms']:.1f}/"
                f"{lat['p95_ms']:.1f}/{lat['p99_ms']:.1f} ms"
                if lat
                else ""
            )
        )
