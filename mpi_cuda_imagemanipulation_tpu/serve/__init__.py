"""Online serving subsystem — the framework's front door for live traffic.

Every other entry point (`run`/`batch`/`bench`) is offline: a fixed file
list, then exit. `serve/` turns the same compiled throughput machinery into
an online service:

  * `scheduler.py`  — micro-batching scheduler: a bounded admission queue
                      feeding coalesced same-bucket stacked dispatches under
                      a max_batch / max_delay_ms policy.
  * `bucketing.py`  — shape buckets + stack padding (shared with the batch
                      CLI's partial-stack handling).
  * `padded.py`     — the bucket-padded executor: requests padded up to a
                      bucket shape compute BIT-IDENTICAL outputs to the
                      per-request golden path (dynamic true-shape extension
                      + masks), so bucketing is purely an execution detail.
  * `cache.py`      — shape-bucket compile cache, pre-warmed at startup so
                      no user request ever pays a jit trace.
  * `metrics.py`    — queue depth, batch occupancy, queue-wait/device time,
                      p50/p95/p99 end-to-end latency — a facade over the
                      app's obs/ registry (`/stats` is a view over it,
                      `GET /metrics` the Prometheus exposition of it).
  * `server.py`     — stdlib ThreadingHTTPServer front end (POST
                      /v1/process, GET /healthz, GET /stats, GET
                      /metrics) plus the in-process `Client` used by
                      tests and the load generator, and the
                      context-manager `Server` that guarantees
                      socket/scheduler release on every exit.
  * `loadgen.py`    — open-loop offered-load sweep (bench_suite lane),
                      with a fault_rate knob for availability runs and
                      per-request trace ids for tail attribution.

Fault tolerance (PR 3, resilience/): dispatch runs under a retrying
executor with per-bucket circuit breakers, poison requests quarantine solo
instead of failing their micro-batch, open breakers degrade traffic to the
golden per-request path, and /healthz reports the health state machine
(starting/serving/degraded/draining/stopped).
"""

from mpi_cuda_imagemanipulation_tpu.serve.scheduler import (  # noqa: F401
    STATUS_DEADLINE,
    STATUS_OK,
    STATUS_OVERLOADED,
    STATUS_QUARANTINED,
    DeadlineExceeded,
    Overloaded,
    Quarantined,
    RequestRejected,
    ServeError,
)
from mpi_cuda_imagemanipulation_tpu.serve.server import (  # noqa: F401
    Client,
    ServeApp,
    ServeConfig,
    Server,
)
