"""Micro-batching scheduler: bounded admission, same-bucket coalescing.

One thread owns the device: it pulls admitted requests out of per-bucket
FIFO queues and ships them as stacked dispatches through the pre-warmed
compile cache. Dispatch policy (the classic micro-batching tradeoff):

  * a bucket with `max_batch` waiting requests dispatches immediately
    (full stack — best amortisation);
  * otherwise the bucket whose OLDEST request has waited `max_delay_ms`
    dispatches with whatever it has (bounded added latency);
  * the scheduler sleeps exactly until the nearest such deadline — no
    polling.

Admission control happens at submit time, on the caller's thread:

  * malformed requests (wrong channel count, dims above every bucket or
    below the pipeline's reflect bound) are REJECTED outright;
  * beyond `queue_depth` total queued requests the scheduler SHEDS with the
    distinct `overloaded` status — callers get an immediate, explicit
    signal (the HTTP front end maps it to 429) instead of unbounded
    buffering, which under sustained overload is just an OOM with extra
    steps;
  * admitted requests carry an optional deadline; ones that expire while
    queued are answered `deadline_expired` at pop time and never waste a
    device slot.

Bit-exactness note: a dispatch pads each image to the bucket and the stack
to a compiled batch size (serve/bucketing), runs the serving executable
(serve/padded — true shapes ride along), then crops each response back to
its true shape. The pad slots repeat the last image and are dropped.

Fault tolerance (resilience/): each dispatch runs under a retrying
executor (exponential backoff + jitter) behind a per-bucket circuit
breaker. A batch that still fails after retries is bisected — every
member re-dispatched solo — so one poison request is quarantined with the
distinct `quarantined` status instead of failing its whole micro-batch.
While a bucket's breaker is open its traffic runs the golden per-request
fallback (bit-identical, just slower) and the health state machine reports
`degraded`; half-open probes restore the fast path when it recovers.

Async execution (engine/): the scheduler thread only ENQUEUES dispatches
(JAX async dispatch returns immediately) and moves on to coalescing the
next micro-batch, keeping `inflight` batches outstanding; the engine's
completion thread drains results in submission order (D2H) and its worker
pool crops + resolves responses. The serial alternative — `np.asarray`
inside the dispatch loop — left the device idle during every crop/resolve
and capped the pipeline at one batch in flight. Failure composition is
unchanged: enqueue-time errors (incl. the `serve.dispatch` failpoint)
retry exactly as before on the scheduler thread; completion-time errors
(D2H, the `engine.complete` failpoint) re-run the batch through the
synchronous retry unit and fall through to the same bisect/quarantine/
breaker machinery.

Group lanes (graph/ DAG dispatch): `submit_group` admits traffic whose
coalescing unit is an opaque lane key instead of a spatial bucket — for
graphs, (dag fingerprint, true shape) — so same-program same-shape
requests stack into one vmapped dispatch and stop jitting per request.
Lane members are never spatially padded (stencil border extension at a
pad seam would change values); only the batch dimension pads. Everything
else — queue depth, QoS ladder, aged-bucket pops, retry, per-lane
breaker, bisect/quarantine, the async engine — is the same machinery.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from mpi_cuda_imagemanipulation_tpu.engine import Engine, EngineMetrics
from mpi_cuda_imagemanipulation_tpu.obs import recorder as flight_recorder
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.resilience.breaker import (
    CLOSED,
    BreakerBoard,
)
from mpi_cuda_imagemanipulation_tpu.resilience.health import (
    DEGRADED,
    SERVING,
    HealthState,
)
from mpi_cuda_imagemanipulation_tpu.resilience.retry import (
    RetryPolicy,
    call_with_retry,
)
from mpi_cuda_imagemanipulation_tpu.serve import bucketing
from mpi_cuda_imagemanipulation_tpu.serve.cache import CompileCache
from mpi_cuda_imagemanipulation_tpu.serve.metrics import ServeMetrics
from mpi_cuda_imagemanipulation_tpu.utils.log import get_logger

STATUS_OK = "ok"
STATUS_OVERLOADED = "overloaded"
STATUS_REJECTED = "rejected"
STATUS_DEADLINE = "deadline_expired"
STATUS_ERROR = "error"
STATUS_SHUTDOWN = "shutdown"
STATUS_QUARANTINED = "quarantined"


class ServeError(Exception):
    status = STATUS_ERROR


class Overloaded(ServeError):
    """Shed by admission control: queue at --queue-depth."""

    status = STATUS_OVERLOADED


class RequestRejected(ServeError):
    """Malformed request: bad channels, or dims outside the servable range."""

    status = STATUS_REJECTED


class DeadlineExceeded(ServeError):
    status = STATUS_DEADLINE


class Quarantined(ServeError):
    """A poison request: it failed alone (after batch bisection + retries),
    so the failure is attributed to this request, not its batch-mates."""

    status = STATUS_QUARANTINED


@dataclasses.dataclass
class GroupSpec:
    """A coalescing lane for non-chain traffic (graph/ DAG dispatch).

    The lane key replaces the spatial bucket as the coalescing unit: a
    producer keys it on everything that must match for two requests to
    share one compiled dispatch — for graphs that is (dag fingerprint,
    TRUE shape), so members are value-identical under batching and there
    is never any spatial padding (stencil border extension at a pad seam
    would change values; only the batch dimension pads, repeat-last,
    dropped on the completion slice).

      key       opaque hashable lane id; also the breaker key, so a
                poisoned lane degrades without touching chain buckets
      get_fn    nb -> callable(imgs[nb, ...]) returning a result pytree
                (called on the dispatch thread; expected to hit the
                producer's own compile cache)
      fallback  img -> result pytree — the golden per-request path this
                lane degrades to while its breaker is open (bit-exact
                with the batched path by construction)
    """

    key: tuple
    get_fn: object
    fallback: object = None


@dataclasses.dataclass
class Request:
    img: np.ndarray
    true_h: int
    true_w: int
    # (bucket_h, bucket_w, channels) for chain traffic; an opaque
    # GroupSpec.key for group-lane traffic (graph/ DAG dispatch)
    bucket: tuple
    t_submit: float
    deadline: float | None  # absolute monotonic seconds, or None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    status: str = STATUS_OK
    # chain responses are cropped u8 arrays; group-lane responses are the
    # producer's result pytree sliced per member
    result: object = None
    error: str | None = None
    group: GroupSpec | None = None
    t_dispatch: float | None = None
    t_done: float | None = None
    # -- observability (obs/trace.py): the request's root span + id -------
    # trace is the live root Span handle (the shared no-op when tracing is
    # disarmed or this request sampled out); trace_id is "" then — the
    # join key for log lines, /metrics outliers and X-Trace-Id headers
    trace: object = obs_trace.NOOP_SPAN
    trace_id: str = ""
    coalesce_span: object = obs_trace.NOOP_SPAN

    def trace_ctx(self) -> obs_trace.SpanContext:
        return self.trace.context()

    def wait(self, timeout: float | None = None) -> np.ndarray:
        """Block for the response; raise the status-matching ServeError on
        anything but success."""
        if not self.done.wait(timeout):
            raise TimeoutError("request still in flight")
        if self.status == STATUS_OK:
            assert self.result is not None
            return self.result
        exc = {
            STATUS_OVERLOADED: Overloaded,
            STATUS_REJECTED: RequestRejected,
            STATUS_DEADLINE: DeadlineExceeded,
            STATUS_QUARANTINED: Quarantined,
        }.get(self.status, ServeError)
        raise exc(self.error or self.status)


class MicroBatchScheduler:
    def __init__(
        self,
        cache: CompileCache,
        *,
        max_batch: int,
        max_delay_ms: float,
        queue_depth: int,
        metrics: ServeMetrics | None = None,
        clock=time.monotonic,
        retry_policy: RetryPolicy | None = None,
        breakers: BreakerBoard | None = None,
        health: HealthState | None = None,
        fallback=None,
        retry_seed: int = 0,
        inflight: int = 2,
        io_threads: int = 4,
    ):
        if max_batch > max(cache.batch_buckets):
            raise ValueError(
                f"max_batch {max_batch} exceeds the largest compiled batch "
                f"bucket {max(cache.batch_buckets)}"
            )
        self.cache = cache
        self.max_batch = max_batch
        self.max_delay_s = max_delay_ms / 1e3
        self.queue_depth = queue_depth
        self.metrics = metrics or ServeMetrics()
        self.min_dim = _min_dim(cache)
        # -- fault tolerance (resilience/): retry + breaker + fallback ------
        self.retry_policy = retry_policy or RetryPolicy()
        self.breakers = breakers or BreakerBoard()
        self.health = health  # None: no state machine attached (tests)
        # fallback(img: np.ndarray) -> np.ndarray — the golden per-request
        # path a bucket degrades to while its breaker is open
        self.fallback = fallback
        self._retry_rng = random.Random(retry_seed)
        self._clock = clock
        # bucket width -> (pipeline_fp, "plan:<mode>") memo for the online
        # tuning observation (tune/store) recorded per dispatch
        self._tune_keys: dict = {}
        # -- async execution engine (engine/): bounded in-flight dispatch --
        self._inflight = max(1, inflight)
        self._io_threads = max(1, io_threads)
        self.engine: Engine | None = None
        self._cond = threading.Condition()
        # bucket/lane key -> FIFO of Requests; OrderedDict so the
        # aged-bucket scan is deterministic under equal deadlines
        self._pending: OrderedDict[tuple, deque] = OrderedDict()
        self._queued = 0
        self._running = False
        self._thread: threading.Thread | None = None
        self._log = get_logger()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._cond:
            if self._running:
                return
            self._running = True
        if self.engine is None or self.engine.closed:
            # the engine shares the serving registry, so /metrics exposes
            # serve + engine families in one scrape (no second island)
            self.engine = Engine(
                inflight=self._inflight,
                io_threads=self._io_threads,
                metrics=EngineMetrics(registry=self.metrics.registry),
                name="serve",
            )
        self._thread = threading.Thread(
            target=self._loop, name="mcim-serve-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self, *, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the dispatch loop. `drain=True` ships everything already
        admitted first; `drain=False` answers queued requests `shutdown`.
        In-flight engine batches complete either way (they already own
        device work — finishing them is strictly cheaper than dropping)."""
        with self._cond:
            if not self._running:
                return
            self._running = False
            self._drain_on_stop = drain
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        if self.engine is not None:
            self.engine.close(timeout)

    def queue_fill_frac(self) -> float:
        """Current admission-queue fill fraction — the load signal the
        graph service's QoS ladder shares with chain admission."""
        with self._cond:
            return self._queued / max(1, self.queue_depth)

    # -- admission ---------------------------------------------------------

    def submit(
        self,
        img: np.ndarray,
        *,
        deadline_ms: float | None = None,
        trace_id: str | None = None,
        qos: str = "interactive",
    ) -> Request:
        """Admit one image; returns a Request whose `.wait()` yields the
        response. Never blocks: over-depth submissions fail immediately
        with `overloaded` (the Request is returned already-resolved, so
        open-loop callers can fire-and-collect). `trace_id` adopts an
        upstream distributed-trace id (the fabric router's X-Trace-Id
        hop) instead of minting one here.

        `qos` is the tenant's admission class (graph/tenancy.QOS_CLASSES
        — the pipeline-service ladder, honored here for chain traffic
        too): a non-interactive class admits only while the queue is
        below its fraction of `queue_depth`, so under load the LOW
        classes shed first and interactive keeps the full depth (the
        default preserves the historical single-class behavior)."""
        now = self._clock()
        self.metrics.on_submit()
        img = np.asarray(img)
        req = Request(
            img=img,
            true_h=img.shape[0] if img.ndim >= 2 else 0,
            true_w=img.shape[1] if img.ndim >= 2 else 0,
            bucket=(0, 0, 0),
            t_submit=now,
            deadline=now + deadline_ms / 1e3 if deadline_ms is not None else None,
        )
        # root span: one trace per request, made HERE (the only sampling
        # decision on this request's path — everything downstream anchors
        # to it or no-ops; an adopted upstream id overrides the decision)
        root = obs_trace.start_trace(
            "serve.request", trace_id=trace_id, h=req.true_h, w=req.true_w
        )
        req.trace = root
        req.trace_id = root.trace_id
        enq = obs_trace.span("serve.enqueue", parent=root.context())
        if deadline_ms is not None and deadline_ms <= 0.0:
            # a propagated budget already dead on arrival (the HTTP edge
            # forwards the wire remainder, floored at 0): resolve it
            # without queue admission — the pop-time check would only
            # discover the same verdict after a pointless wait. Counted
            # as a resolution (never on_admit'd, so no queue-gauge
            # bookkeeping like metrics.on_deadline does).
            self.metrics.on_deadline_at_submit()
            enq.end()
            return self._resolve(
                req, STATUS_DEADLINE, "expired before admission"
            )
        problem = self._validate(img)
        if problem is not None:
            self.metrics.on_reject()
            enq.end()
            return self._resolve(req, STATUS_REJECTED, problem)
        ch = img.shape[2] if img.ndim == 3 else 1
        bh, bw = bucketing.pick_bucket(
            img.shape[0], img.shape[1], self.cache.buckets
        )
        req.bucket = (bh, bw, ch)
        enq.set(bucket=f"{bh}x{bw}x{ch}")
        return self._admit_queued(req, qos, enq)

    def submit_group(
        self,
        img: np.ndarray,
        group: GroupSpec,
        *,
        deadline_ms: float | None = None,
        trace_id: str | None = None,
        qos: str = "interactive",
    ) -> Request:
        """Admit one ALREADY-VALIDATED image into an opaque coalescing
        lane (graph/ DAG dispatch — the producer has run its own
        validation and tenant admission before calling this). Shares the
        chain path's queue depth, QoS ladder, dispatch loop, retry/
        breaker/bisect machinery and engine; differs only in the
        coalescing key (the GroupSpec's lane id instead of a spatial
        bucket) and in `.wait()` yielding the lane's result pytree
        sliced per member instead of a cropped array."""
        now = self._clock()
        self.metrics.on_submit()
        img = np.asarray(img)
        req = Request(
            img=img,
            true_h=img.shape[0] if img.ndim >= 2 else 0,
            true_w=img.shape[1] if img.ndim >= 2 else 0,
            bucket=group.key,
            t_submit=now,
            deadline=(
                now + deadline_ms / 1e3 if deadline_ms is not None else None
            ),
            group=group,
        )
        root = obs_trace.start_trace(
            "serve.request", trace_id=trace_id, h=req.true_h, w=req.true_w
        )
        req.trace = root
        req.trace_id = root.trace_id
        enq = obs_trace.span("serve.enqueue", parent=root.context())
        enq.set(bucket=str(group.key))
        return self._admit_queued(req, qos, enq)

    def _admit_queued(self, req: Request, qos: str, enq) -> Request:
        """Shared admission tail (chain + group lanes): depth check under
        the lock, enqueue + notify, open the coalesce span."""
        limit = self._qos_depth(qos)
        with self._cond:
            if not self._running:
                enq.end()
                return self._resolve(req, STATUS_SHUTDOWN, "scheduler stopped")
            if self._queued >= limit:
                self.metrics.on_shed(
                    qos=qos if limit < self.queue_depth else ""
                )
                enq.end()
                return self._resolve(
                    req,
                    STATUS_OVERLOADED,
                    f"queue at capacity ({limit} of {self.queue_depth} "
                    f"for qos={qos})"
                    if limit < self.queue_depth
                    else f"queue at capacity ({self.queue_depth})",
                )
            self._pending.setdefault(req.bucket, deque()).append(req)
            self._queued += 1
            self.metrics.on_admit()
            self._cond.notify_all()
        enq.end()
        # the coalesce span is opened on the caller's thread and ended on
        # the scheduler thread when the batch pops — its duration IS the
        # micro-batching queue wait on the timeline
        req.coalesce_span = obs_trace.span(
            "serve.coalesce", parent=req.trace.context()
        )
        return req

    def _qos_depth(self, qos: str) -> int:
        """The queue depth this admission class may fill: interactive
        (and any unknown label — never punish a typo with data loss)
        keeps the full depth; lower classes stop at their fraction of
        it, so as the queue grows past the shed threshold the low-QoS
        tenants shed FIRST (graph/tenancy.qos_admit_frac)."""
        if qos in (None, "", "interactive"):
            return self.queue_depth
        from mpi_cuda_imagemanipulation_tpu.graph.tenancy import (
            QOS_CLASSES,
            qos_admit_frac,
        )

        if qos not in QOS_CLASSES:
            return self.queue_depth
        return max(1, int(self.queue_depth * qos_admit_frac(qos)))

    def _validate(self, img: np.ndarray) -> str | None:
        if img.dtype != np.uint8 or img.ndim not in (2, 3):
            return f"expected a (H, W[, C]) uint8 image, got {img.dtype} ndim={img.ndim}"
        ch = img.shape[2] if img.ndim == 3 else 1
        if ch not in self.cache.channels:
            return (
                f"{ch}-channel images are not served (configured: "
                f"{self.cache.channels})"
            )
        h, w = img.shape[:2]
        if min(h, w) < self.min_dim:
            return (
                f"image {h}x{w} is below the pipeline's minimum servable "
                f"dimension {self.min_dim} (stencil border extension)"
            )
        if bucketing.pick_bucket(h, w, self.cache.buckets) is None:
            big = self.cache.buckets[-1]
            return f"image {h}x{w} exceeds the largest bucket {big[0]}x{big[1]}"
        return None

    @staticmethod
    def _resolve(req: Request, status: str, error: str | None) -> Request:
        req.status = status
        req.error = error
        req.t_done = time.monotonic()
        req.coalesce_span.end()
        req.trace.set(status=status)
        req.trace.end()
        req.done.set()
        return req

    # -- dispatch loop -----------------------------------------------------

    def _loop(self) -> None:
        try:
            self._loop_body()
        finally:
            # every dispatched batch must resolve before the loop thread
            # dies — stop()'s join is the caller's completion barrier
            if self.engine is not None:
                self.engine.flush()

    def _loop_body(self) -> None:
        while True:
            batch: list[Request] | None = None
            with self._cond:
                while True:
                    if not self._running:
                        break
                    batch = self._pop_dispatchable()
                    if batch is not None:
                        break
                    self._cond.wait(timeout=self._sleep_s())
                if not self._running and batch is None:
                    leftovers: list[Request] = []
                    for q in self._pending.values():
                        leftovers.extend(q)
                        self._queued -= len(q)
                    self._pending.clear()
                    drain = getattr(self, "_drain_on_stop", True)
                    if not drain:
                        for r in leftovers:
                            self.metrics.on_error()
                            self._resolve(r, STATUS_SHUTDOWN, "server stopped")
                        return
                    # drain: ship what was admitted, bucket by bucket
                    for r in leftovers:
                        self._pending.setdefault(r.bucket, deque()).append(r)
                        self._queued += 1
                    if not self._pending:
                        return
                    key = next(iter(self._pending))
                    batch = self._pop_bucket(key)
            if batch:
                self._dispatch(batch)
            with self._cond:
                if not self._running and not self._pending:
                    return

    def _sleep_s(self) -> float | None:
        """Seconds until the oldest queued request hits max_delay (None =
        sleep until notified). Called under the lock."""
        heads = [q[0].t_submit for q in self._pending.values() if q]
        if not heads:
            return None
        due = min(heads) + self.max_delay_s
        return max(due - self._clock(), 0.0)

    def _pop_dispatchable(self) -> list[Request] | None:
        """Under the lock: a full bucket, else the most-overdue aged bucket."""
        now = self._clock()
        aged_key = None
        aged_t = None
        for key, q in self._pending.items():
            if not q:
                continue
            if len(q) >= self.max_batch:
                return self._pop_bucket(key)
            if now - q[0].t_submit >= self.max_delay_s and (
                aged_t is None or q[0].t_submit < aged_t
            ):
                aged_key, aged_t = key, q[0].t_submit
        if aged_key is not None:
            return self._pop_bucket(aged_key)
        return None

    def _pop_bucket(self, key: tuple[int, int, int]) -> list[Request]:
        q = self._pending[key]
        batch = [q.popleft() for _ in range(min(len(q), self.max_batch))]
        if not q:
            del self._pending[key]
        self._queued -= len(batch)
        return batch

    @staticmethod
    def _trace_parent(live: list[Request]) -> obs_trace.SpanContext | None:
        """The batch's trace anchor: the calling thread's active span if
        any, else the first sampled member's root. A batch mixes traced
        and untraced requests — the span rides the first traced one, the
        rest get their own membership events."""
        cur = obs_trace.current_context()
        if cur is not None and cur.sampled:
            return cur
        for r in live:
            ctx = r.trace_ctx()
            if ctx.sampled:
                return ctx
        return None

    def _dispatch(self, batch: list[Request]) -> None:
        now = self._clock()
        live: list[Request] = []
        for r in batch:
            r.coalesce_span.end()  # popped: the micro-batching wait is over
            if r.deadline is not None and now > r.deadline:
                self.metrics.on_deadline(now - r.t_submit, r.trace_id)
                self._resolve(r, STATUS_DEADLINE, "expired while queued")
            else:
                live.append(r)
        if not live:
            return
        bucket = live[0].bucket
        breaker = self.breakers.get(bucket)
        if not breaker.allow():
            # breaker open (and no half-open probe slot): golden fallback
            with obs_trace.span(
                "serve.degraded", parent=self._trace_parent(live),
                bucket=str(bucket), n=len(live),
            ):
                self._dispatch_degraded(live)
            return
        with obs_trace.span(
            "serve.dispatch", parent=self._trace_parent(live),
            bucket=str(bucket), n=len(live),
        ) as dspan:
            if len(live) > 1 and dspan is not obs_trace.NOOP_SPAN:
                # batch-mates of the anchoring trace stay joinable by id
                dspan.set(
                    batch_traces=[r.trace_id for r in live if r.trace_id]
                )
            if self.engine is None:
                # engine not started (direct-driven tests): serial fallback
                self._dispatch_sync(live, bucket, breaker)
                return
            # async fast path: enqueue only — the engine's completion
            # thread forces + resolves while this thread coalesces the next
            # batch. Enqueue-time failures (incl. the serve.dispatch
            # failpoint) are host-side and retry here, exactly like the
            # serial path did.
            try:
                call_with_retry(
                    lambda: self._enqueue_batch(live),
                    policy=self.retry_policy,
                    rng=self._retry_rng,
                    on_retry=lambda a, e, d: self._note_retry(
                        bucket, a, e, d, live=live
                    ),
                )
            except Exception as e:
                self._fail_batch(live, bucket, breaker, e)

    def _dispatch_sync(self, live, bucket, breaker) -> None:
        """The serial dispatch unit (pre-engine behavior): force inline."""
        try:
            out, nb, device_s = call_with_retry(
                lambda: self._run_batch(live),
                policy=self.retry_policy,
                rng=self._retry_rng,
                on_retry=lambda a, e, d: self._note_retry(
                    bucket, a, e, d, live=live
                ),
            )
        except Exception as e:  # retries exhausted: fail the path, not the loop
            self._fail_batch(live, bucket, breaker, e)
            return
        breaker.on_success()
        self._update_health()
        self._complete(live, out, nb, device_s)

    def _fail_batch(self, live, bucket, breaker, e) -> None:
        """Retries exhausted for a whole batch: feed the breaker, then
        quarantine (solo) or bisect (grouped)."""
        breaker.on_failure()
        if breaker.state != CLOSED:
            # breaker transition/holding state is an event on the trace —
            # a p99 outlier pulled up by id shows WHY it degraded
            for r in live:
                obs_trace.event(
                    "breaker.not_closed", parent=r.trace_ctx(),
                    bucket=str(bucket), state=breaker.state,
                )
            # breaker-open is a flight-recorder dump trigger: the ring
            # (recent dispatches, failpoint hits, warnings) explains
            # which bucket was hot when the path failed (rate-limited)
            flight_recorder.dump(
                "breaker_open",
                extra={"scope": "serve", "bucket": str(bucket)},
            )
        self._update_health()
        self._log.warning(
            "dispatch failed after %d attempts for bucket %s: %s",
            self.retry_policy.max_attempts, bucket, e,
        )
        if len(live) == 1:
            self.metrics.on_quarantine()
            obs_trace.event(
                "serve.quarantine", parent=live[0].trace_ctx(),
                error=type(e).__name__,
            )
            flight_recorder.dump(
                "quarantine",
                extra={"bucket": str(bucket), "error": type(e).__name__},
            )
            self._resolve(
                live[0], STATUS_QUARANTINED, f"{type(e).__name__}: {e}"
            )
        else:
            # poison isolation: re-dispatch every member solo so one bad
            # request cannot fail its batch-mates
            self._bisect_solo(live)

    def _prepare_batch(self, live: list[Request]):
        """(fn, host inputs, batch bucket) for one dispatch attempt."""
        nb = bucketing.pick_batch_bucket(len(live), self.cache.batch_buckets)
        group = live[0].group
        if group is not None:
            # group lane: the key IS the true shape, so members stack
            # as-is — no spatial padding (stencil border extension at a
            # pad seam would change values); only the batch dimension
            # pads, repeat-last, dropped on the completion slice
            fn = group.get_fn(nb)
            imgs = np.stack(
                [r.img for r in live] + [live[-1].img] * (nb - len(live))
            )
            return fn, (imgs,), nb
        bh, bw, ch = live[0].bucket
        fn = self.cache.get(bh, bw, ch, nb)
        imgs = bucketing.pad_stack(
            [bucketing.pad_to_bucket(r.img, bh, bw) for r in live], nb
        )
        th = np.asarray(
            [r.true_h for r in live] + [live[-1].true_h] * (nb - len(live)),
            dtype=np.int32,
        )
        tw = np.asarray(
            [r.true_w for r in live] + [live[-1].true_w] * (nb - len(live)),
            dtype=np.int32,
        )
        return fn, (imgs, th, tw), nb

    def _enqueue_batch(self, live: list[Request]) -> None:
        """One async dispatch attempt: build + enqueue, never force."""
        failpoints.maybe_fail("serve.dispatch", requests=live)
        fn, inputs, nb = self._prepare_batch(live)
        now = self._clock()
        for r in live:
            r.t_dispatch = now
        assert self.engine is not None
        self.engine.submit(
            (tuple(live), nb),
            lambda: inputs,
            lambda a: fn(*a),  # async enqueue: returns un-forced device out
            on_done=self._on_engine_done,
            on_error=self._on_engine_error,
        )

    def _on_engine_done(self, key, out, info) -> None:
        """Engine worker pool: the batch's host result landed — crop and
        resolve each member, report breaker success."""
        live, nb = key
        live = list(live)
        breaker = self.breakers.get(live[0].bucket)
        breaker.on_success()
        self._update_health()
        # group-lane results are pytrees (the engine's device_get already
        # forced them leaf-wise); chain results normalise to one ndarray
        host = out if live[0].group is not None else np.asarray(out)
        self._complete(live, host, nb, info.get("force_s", 0.0))

    def _on_engine_error(self, key, exc) -> None:
        """Completion-stage failure (D2H / engine.complete failpoint): the
        async fast path lost this batch's result after a clean enqueue.
        Re-run it through the synchronous retry unit on this (engine
        completion) thread — the scheduler thread keeps coalescing and the
        engine keeps draining behind us; exhaustion falls through to the
        same bisect/quarantine/breaker machinery as always."""
        live, nb = key
        live = list(live)
        bucket = live[0].bucket
        breaker = self.breakers.get(bucket)
        # the lost async attempt
        self._note_retry(bucket, 1, exc, 0.0, live=live)
        try:
            out, nb2, device_s = call_with_retry(
                lambda: self._run_batch(live),
                policy=self.retry_policy,
                rng=self._retry_rng,
                on_retry=lambda a, e, d: self._note_retry(
                    bucket, a, e, d, live=live
                ),
            )
        except Exception as e:
            self._fail_batch(live, bucket, breaker, e)
            return
        breaker.on_success()
        self._update_health()
        self._complete(live, out, nb2, device_s)

    def _run_batch(self, live: list[Request]):
        """One synchronous padded-executor dispatch attempt (the retry
        unit for the serial path, bisection, and completion-failure
        re-runs)."""
        parent = obs_trace.current_context()
        with obs_trace.span(
            "serve.attempt",
            parent=parent if parent else self._trace_parent(live),
            n=len(live),
        ):
            failpoints.maybe_fail("serve.dispatch", requests=live)
            fn, inputs, nb = self._prepare_batch(live)
            now = self._clock()
            for r in live:
                r.t_dispatch = now
            t0 = self._clock()
            out = _force_host(fn(*inputs))  # forces completion + transfer
            # completion-stage failpoint fires on the sync path too, so an
            # `always`-armed site drives the full quarantine pipeline
            failpoints.maybe_fail("engine.complete", requests=live)
            return out, nb, self._clock() - t0

    def _complete(self, live, out, nb, device_s) -> None:
        batch_tid = next((r.trace_id for r in live if r.trace_id), "")
        self.metrics.on_dispatch(len(live), nb, device_s, batch_tid)
        group = live[0].group
        if group is None:
            self._note_tune_observation(live[0].bucket, len(live), device_s)
        # flight recorder: per-dispatch bucket summaries are the "which
        # bucket was hot" evidence a post-mortem dump aggregates
        flight_recorder.note(
            "dispatch",
            bucket=(
                str(live[0].bucket) if group is not None
                else "{}x{}x{}".format(*live[0].bucket)
            ),
            n=len(live),
            device_ms=device_s * 1e3,
        )
        t_done = self._clock()
        for k, r in enumerate(live):
            if group is not None:
                # lane members ran at their true shape: slice, don't crop
                r.result = _tree_index(out, k)
            else:
                r.result = out[k, : r.true_h, : r.true_w, ...]
            r.t_done = t_done
            r.status = STATUS_OK
            self.metrics.on_complete(
                (r.t_dispatch or r.t_submit) - r.t_submit,
                t_done - r.t_submit,
                r.trace_id,
            )
            r.trace.set(status=STATUS_OK)
            r.trace.end()
            r.done.set()

    def _note_tune_observation(self, bucket, n, device_s) -> None:
        """Feed the online autotuning store one per-image device-seconds
        sample for this dispatch, keyed (pipeline fingerprint, bucket
        width, resolved-plan arm). Memoized per bucket width — resolving
        the serving plan is cached in the CompileCache but the arm string
        need not be rebuilt per dispatch. Never allowed to fail a
        completed dispatch: the observation is advisory."""
        try:
            bh, bw, ch = bucket
            key = self._tune_keys.get(bw)
            if key is None:
                from mpi_cuda_imagemanipulation_tpu.plan.ir import (
                    pipeline_fingerprint,
                )
                from mpi_cuda_imagemanipulation_tpu.serve.padded import (
                    resolve_serving_plan,
                )

                built = resolve_serving_plan(
                    self.cache.pipe, self.cache.plan, self.cache.backend, bw
                )
                arm = "plan:" + ("off" if built is None else built.mode)
                key = (pipeline_fingerprint(self.cache.pipe.ops), arm)
                self._tune_keys[bw] = key
            pipe_fp, arm = key
            from mpi_cuda_imagemanipulation_tpu.tune.store import (
                online_store,
            )

            online_store.record_dispatch(
                pipe_fp, bw, arm, device_s / max(n, 1)
            )
        except Exception:
            # the dispatch already succeeded; a tuning-store hiccup (no
            # backend, corrupt file, unexpected plan shape) must not
            # surface as a serving error
            pass

    def _note_retry(self, bucket, attempt, exc, delay_s, live=()) -> None:
        self.metrics.on_retry()
        for r in live:
            # retry attempts are events on the request's trace, so a p99
            # outlier pulled up by id shows its whole recovery history
            obs_trace.event(
                "serve.retry", parent=r.trace_ctx(), attempt=attempt,
                error=type(exc).__name__, backoff_ms=delay_s * 1e3,
            )
        self._log.info(
            "retrying bucket %s after %s (attempt %d, backoff %.1fms)",
            bucket, type(exc).__name__, attempt, delay_s * 1e3,
        )

    def _bisect_solo(self, live: list[Request]) -> None:
        """Failed-batch isolation: each member gets its own retried solo
        dispatch. Survivors complete normally; the poison fails alone with
        the distinct `quarantined` status."""
        bucket = live[0].bucket
        breaker = self.breakers.get(bucket)
        for r in live:
            with obs_trace.span(
                "serve.bisect", parent=r.trace_ctx(), bucket=str(bucket)
            ):
                try:
                    out, nb, device_s = call_with_retry(
                        lambda r=r: self._run_batch([r]),
                        policy=self.retry_policy,
                        rng=self._retry_rng,
                        on_retry=lambda a, e, d, r=r: self._note_retry(
                            bucket, a, e, d, live=(r,)
                        ),
                    )
                except Exception as e:
                    self.metrics.on_quarantine()
                    obs_trace.event(
                        "serve.quarantine", parent=r.trace_ctx(),
                        error=type(e).__name__,
                    )
                    flight_recorder.dump(
                        "quarantine",
                        extra={
                            "bucket": str(bucket),
                            "error": type(e).__name__,
                        },
                    )
                    self._resolve(
                        r, STATUS_QUARANTINED, f"{type(e).__name__}: {e}"
                    )
                    continue
            # the path works without the poison: healthy signal
            breaker.on_success()
            self._complete([r], out, nb, device_s)
        self._update_health()

    def _dispatch_degraded(self, live: list[Request]) -> None:
        """Open-breaker path: serve each request through the golden
        per-request fallback (bit-identical output, no micro-batching).
        Group lanes bring their own fallback (the producer's solo
        dispatch); chain buckets use the scheduler-wide one."""
        group = live[0].group
        fallback = group.fallback if group is not None else self.fallback
        if fallback is None:
            self.metrics.on_error(len(live))
            for r in live:
                self._resolve(
                    r, STATUS_ERROR,
                    f"circuit open for bucket {r.bucket} and no fallback",
                )
            return
        for r in live:
            r.t_dispatch = self._clock()
            try:
                out = _force_host(fallback(r.img))
            except Exception as e:
                self.metrics.on_quarantine()
                self._resolve(
                    r, STATUS_QUARANTINED, f"{type(e).__name__}: {e}"
                )
                continue
            t_done = self._clock()
            r.result = out
            r.t_done = t_done
            r.status = STATUS_OK
            self.metrics.on_degraded()
            self.metrics.on_complete(
                r.t_dispatch - r.t_submit, t_done - r.t_submit, r.trace_id
            )
            r.trace.set(status=STATUS_OK, degraded=True)
            r.trace.end()
            r.done.set()

    def _update_health(self) -> None:
        """Drive the serving <-> degraded edge off the breaker board."""
        if self.health is None:
            return
        state = self.health.state
        if state == SERVING and self.breakers.any_open():
            self._log.warning("dispatch breaker open: health -> degraded")
            self.health.to(DEGRADED)
        elif state == DEGRADED and not self.breakers.any_open():
            self._log.info("breakers recovered: health -> serving")
            self.health.to(SERVING)


def _min_dim(cache: CompileCache) -> int:
    from mpi_cuda_imagemanipulation_tpu.serve.padded import min_true_dim

    return min_true_dim(cache.pipe)


def _force_host(out):
    """Force a device result to host, structure-preserving: chain
    dispatches return one stacked array, group lanes a result pytree."""
    if isinstance(out, dict):
        return {k: _force_host(v) for k, v in out.items()}
    if isinstance(out, (list, tuple)):
        return type(out)(_force_host(v) for v in out)
    return np.asarray(out)


def _tree_index(out, k: int):
    """Slice member k out of a stacked result pytree (group lanes):
    every leaf loses its batch dimension, the structure is preserved."""
    if isinstance(out, dict):
        return {key: _tree_index(v, k) for key, v in out.items()}
    if isinstance(out, (list, tuple)):
        return type(out)(_tree_index(v, k) for v in out)
    return np.asarray(out)[k]
