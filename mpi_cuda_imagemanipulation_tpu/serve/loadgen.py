"""Open-loop load generator — throughput vs latency under offered load.

Open-loop means arrivals are scheduled by the offered rate alone, never
gated on completions (a closed loop self-throttles and hides queueing
collapse — the coordinated-omission trap). `Client.submit` is non-blocking
by construction, so one thread fires requests on the arrival clock and the
handles are collected afterwards; shed requests resolve instantly and count
against goodput.

`sweep()` is the bench_suite `serve_loadgen` lane: per offered rate it
reports achieved throughput, p50/p95/p99 end-to-end latency, mean batch
occupancy and shed fraction — the saturation curve that sizes
`--max-batch`/`--queue-depth` for a deployment.

`fault_rate` arms the `serve.dispatch` failpoint for the sweep so the lane
also reports AVAILABILITY under injected transient faults: success %,
shed %, retried %, quarantined — the numbers that size `--retry-attempts`
and the breaker knobs the way the latency curve sizes the batching ones.

The CHURN mode (`churn_run`, the `fabric_loadgen` lane) drives the pod
fabric over real HTTP instead: the same open-loop arrival clock fires
`POST /v1/process` at the front-door router through a worker pool, a
replica is SIGKILLed mid-sweep, and the record reports ok% / retried%
(router rerouting, from the X-Fabric-Attempts response header) / p99 for
the BEFORE, DURING and AFTER phases — availability under churn as three
numbers, not an anecdote.

The router's `503 + Retry-After` is an explicit SHED ("come back
later"), not unavailability: it gets its own shed/shed_frac columns and
`ok_accepted_frac` reports goodput over the load the pod actually
accepted. Without the distinction, the elastic lanes would misread the
autoscaler intentionally shedding during a scale-up as the pod being
down — the opposite of what is happening.

With tracing armed (obs/trace.py, e.g. MCIM_TRACE_SAMPLE=1) every request
carries a trace id and each per-rate record names its slowest completions
(`slowest_traces`) and failures (`failed_traces`) by id — the p99 outlier
is pulled up by id in the `--trace-out` file, not found by eyeballing.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from mpi_cuda_imagemanipulation_tpu.io.image import synthetic_image
from mpi_cuda_imagemanipulation_tpu.obs import trace as obs_trace
from mpi_cuda_imagemanipulation_tpu.resilience import failpoints
from mpi_cuda_imagemanipulation_tpu.serve.server import Client, ServeApp
from mpi_cuda_imagemanipulation_tpu.utils.timing import percentiles

PERCENTILES = (50, 95, 99)


def mixed_shapes(
    buckets, n: int, *, channels: int = 3, seed: int = 0, min_dim: int = 8
) -> list[np.ndarray]:
    """Deterministic request mix: for each bucket, one exact-fit image plus
    off-bucket sizes that exercise the padding path."""
    rng = np.random.default_rng(seed)
    shapes: list[tuple[int, int]] = []
    for bh, bw in buckets:
        shapes.append((bh, bw))
        shapes.append((max(min_dim, bh - 7), max(min_dim, bw - 13)))
        shapes.append((max(min_dim, (bh * 3) // 4), max(min_dim, (bw * 2) // 3)))
    out = []
    for i in range(n):
        h, w = shapes[int(rng.integers(len(shapes)))]
        out.append(
            synthetic_image(h, w, channels=channels, seed=int(rng.integers(1 << 31)))
        )
    return out


def run_offered_load(
    client: Client,
    images: list[np.ndarray],
    offered_rps: float,
    duration_s: float,
    *,
    clock=time.monotonic,
    sleep=time.sleep,
) -> dict:
    """Fire requests open-loop at `offered_rps` for `duration_s`; block for
    stragglers; return the per-rate record."""
    period = 1.0 / offered_rps
    t0 = clock()
    handles = []
    i = 0
    while True:
        due = t0 + i * period
        now = clock()
        if due - t0 >= duration_s:
            break
        if due > now:
            sleep(due - now)
        handles.append(client.submit(images[i % len(images)]))
        i += 1
    for h in handles:
        h.done.wait()
    wall = clock() - t0
    ok = [h for h in handles if h.status == "ok"]
    shed = sum(1 for h in handles if h.status == "overloaded")
    quarantined = sum(1 for h in handles if h.status == "quarantined")
    lat = [h.t_done - h.t_submit for h in ok]
    n = len(handles)
    rec = {
        "offered_rps": offered_rps,
        "submitted": n,
        "completed": len(ok),
        "shed": shed,
        "shed_frac": shed / n if n else 0.0,
        "quarantined": quarantined,
        # availability: the fraction of offered load that got a good
        # answer (shed is an explicit no, quarantined/error a failure)
        "ok_frac": len(ok) / n if n else 0.0,
        "achieved_rps": len(ok) / wall if wall > 0 else 0.0,
        "wall_s": wall,
    }
    if lat:
        p = percentiles(lat, PERCENTILES)
        rec.update({f"e2e_p{int(q)}_ms": p[q] * 1e3 for q in PERCENTILES})
        # tail attribution (obs/trace.py): when tracing is armed each
        # request carried a trace id — record the slowest completions so
        # a p99 outlier can be pulled up BY ID in the --trace-out file
        # instead of eyeballing the whole timeline. Under sampled
        # tracing with tail keep, ids that actually RESOLVE in the
        # export (sampled-in or tail-promoted) rank ahead of
        # provisional ids the tracer dropped — a slow-trace column full
        # of unresolvable ids is the old blind spot in a new shape.
        slowest = sorted(
            (h for h in ok if h.trace_id),
            key=lambda h: (
                not obs_trace.trace_kept(h.trace_id),
                -(h.t_done - h.t_submit),
            ),
        )[:3]
        if slowest:
            rec["slowest_traces"] = [
                {
                    "trace_id": h.trace_id,
                    "e2e_ms": (h.t_done - h.t_submit) * 1e3,
                    "kept": obs_trace.trace_kept(h.trace_id),
                }
                for h in slowest
            ]
        failed_ids = [
            {"trace_id": h.trace_id, "status": h.status}
            for h in handles
            if h.trace_id and h.status not in ("ok", "overloaded")
        ]
        if failed_ids:
            rec["failed_traces"] = failed_ids[:10]
    return rec


# --------------------------------------------------------------------------
# HTTP loadgen + availability-under-churn (the fabric front door)
# --------------------------------------------------------------------------


def encode_blob(img: np.ndarray) -> memoryview:
    """Single-copy request blob: the PNG encoder writes into ONE buffer
    (`io.image.encode_image_into`) and the HTTP client posts a view of
    it — the full byte string is never duplicated. The streamed outputs'
    incremental encoder (io/stream_codec.PNGTileWriter over a BytesIO)
    hands its buffer through the same path, so a stream-produced frame
    costs one resident copy end to end."""
    import io as _io

    from mpi_cuda_imagemanipulation_tpu.io.image import encode_image_into

    buf = _io.BytesIO()
    encode_image_into(img, buf)
    return buf.getbuffer()


def http_post_image(
    url: str,
    blob: bytes | bytearray | memoryview,
    *,
    timeout_s: float = 30.0,
    headers: dict | None = None,
) -> dict:
    """One `POST /v1/process` against a front door (router or replica).
    `blob` is any bytes-like body (memoryviews from `encode_blob` / the
    incremental stream encoder post without a defensive copy). Returns
    {code, body, attempts, replica, trace_id, retry_after, e2e_s};
    transport errors surface as code 599 so open-loop accounting never
    raises. `retry_after` carries the server's Retry-After header — the
    router's explicit shed-and-retry-later signal, which the accounting
    layer must keep distinct from real unavailability. `headers` adds
    request headers — the multi-tenant lanes ride tenant + pipeline
    identity (X-MCIM-Tenant / X-MCIM-Pipeline) through here."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url.rstrip("/") + "/v1/process",
        data=blob,
        headers={
            "Content-Type": "application/octet-stream",
            **(headers or {}),
        },
        method="POST",
    )
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            body = resp.read()
            code = resp.status
            hdrs = resp.headers
    except urllib.error.HTTPError as e:
        body = e.read()
        code = e.code
        hdrs = e.headers
    except Exception:
        # connection refused/reset mid-churn: a transport-level failure,
        # distinct from any server-sent status
        return {
            "code": 599, "body": b"", "attempts": 1, "replica": "",
            "trace_id": "", "retry_after": "",
            "e2e_s": time.monotonic() - t0,
        }
    return {
        "code": code,
        "body": body,
        "attempts": int(hdrs.get("X-Fabric-Attempts", "1") or 1),
        "replica": hdrs.get("X-Fabric-Replica", ""),
        "trace_id": hdrs.get("X-Trace-Id", ""),
        "retry_after": hdrs.get("Retry-After", ""),
        "e2e_s": time.monotonic() - t0,
    }


def http_run_offered_load(
    url: str,
    blobs: list[bytes | bytearray | memoryview],
    offered_rps: float,
    duration_s: float,
    *,
    timeout_s: float = 30.0,
    max_workers: int = 32,
    clock=time.monotonic,
    sleep=time.sleep,
    headers: dict | None = None,
    deadline_ms: float | None = None,
) -> dict:
    """The open-loop driver over HTTP: arrivals on the offered clock via a
    worker pool, collection afterwards (same discipline as
    `run_offered_load` — completions never gate arrivals). Returns the
    phase record plus `results`: [(blob_index, response dict), ...] so the
    caller can verify successes bit-exactly against golden outputs.
    `headers` rides every request (e.g. the X-MCIM-Deadline-Ms budget the
    chaos lane sets); `deadline_ms` additionally feeds the summary's
    goodput-within-deadline column."""
    from concurrent.futures import ThreadPoolExecutor

    from mpi_cuda_imagemanipulation_tpu.resilience import (
        deadline as deadline_mod,
    )

    if deadline_ms is not None:
        headers = {
            **(headers or {}),
            deadline_mod.HEADER: f"{deadline_ms:.1f}",
        }
    period = 1.0 / offered_rps
    futures = []
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        t0 = clock()
        i = 0
        while True:
            due = t0 + i * period
            now = clock()
            if due - t0 >= duration_s:
                break
            if due > now:
                sleep(due - now)
            k = i % len(blobs)
            futures.append(
                (k, pool.submit(http_post_image, url, blobs[k],
                                timeout_s=timeout_s, headers=headers))
            )
            i += 1
        results = [(k, f.result()) for k, f in futures]
        wall = clock() - t0
    rec = summarize_http_results(
        results, wall, offered_rps, deadline_ms=deadline_ms
    )
    rec["results"] = results
    return rec


def summarize_http_results(
    results: list[tuple[int, dict]], wall: float, offered_rps: float,
    *, deadline_ms: float | None = None,
) -> dict:
    """The shared HTTP open-loop accounting: one phase/lane record from
    [(blob_index, response dict), ...]. A 503 WITH Retry-After is an
    explicit shed — "come back later", the intended behavior under
    quota/QoS/elastic pressure — and must not be folded into
    unavailability (the 599/bare-503 failure class): a lane that counts
    intentional shedding as downtime would misread admission control
    doing its job as the pod losing traffic. A 504 is a deadline miss
    (`deadline_expired`) — its own class, NOT unavailability: the stack
    refusing doomed work is the deadline chain doing its job. `accepted`
    is the offered load the pod actually took on; `ok_accepted_frac` is
    goodput over it (the elastic/tenant acceptance criteria gate on it
    at 100%). With `deadline_ms` set, `ok_in_deadline` / `goodput_rps`
    count only the 200s that ALSO landed within the client's budget —
    the chaos/elastic lanes' real goodput."""
    ok = [r for _, r in results if r["code"] == 200]
    retried = sum(1 for _, r in results if r["attempts"] > 1)
    shed = sum(
        1
        for _, r in results
        if r["code"] == 503 and r.get("retry_after")
    )
    overloaded = sum(1 for _, r in results if r["code"] == 429)
    deadline_expired = sum(1 for _, r in results if r["code"] == 504)
    n = len(results)
    # a deadline-expired request was REFUSED (the stack declined doomed
    # work), not taken on — it leaves `accepted` like a shed does
    accepted = n - shed - overloaded - deadline_expired
    lat = [r["e2e_s"] for r in ok]
    ok_in_deadline = (
        sum(1 for r in ok if r["e2e_s"] * 1e3 <= deadline_ms)
        if deadline_ms is not None
        else len(ok)
    )
    rec = {
        "offered_rps": offered_rps,
        "submitted": n,
        "ok": len(ok),
        "ok_frac": len(ok) / n if n else 0.0,
        "accepted": accepted,
        "ok_accepted_frac": len(ok) / accepted if accepted else 1.0,
        "retried": retried,
        "retried_frac": retried / n if n else 0.0,
        "shed": shed,
        "shed_frac": shed / n if n else 0.0,
        "deadline_expired": deadline_expired,
        "ok_in_deadline": ok_in_deadline,
        "goodput_rps": ok_in_deadline / wall if wall > 0 else 0.0,
        "unavailable": sum(
            1
            for _, r in results
            if r["code"] == 599
            or (r["code"] == 503 and not r.get("retry_after"))
        ),
        "overloaded": overloaded,
        "achieved_rps": len(ok) / wall if wall > 0 else 0.0,
        "wall_s": wall,
    }
    if lat:
        p = percentiles(lat, PERCENTILES)
        rec.update({f"e2e_p{int(q)}_ms": p[q] * 1e3 for q in PERCENTILES})
    return rec


def multi_tenant_run(
    url: str,
    lanes: list[dict],
    offered_rps: float,
    duration_s: float,
    *,
    timeout_s: float = 30.0,
    max_workers: int = 32,
    clock=time.monotonic,
    sleep=time.sleep,
) -> dict:
    """The multi-tenant offered-load mix: ONE open-loop arrival clock at
    `offered_rps` total, arrivals round-robined across the tenant lanes,
    per-tenant accounting out. Each lane is

        {"tenant": <id>, "blobs": [...], "headers": {...}}

    — `headers` carries the lane's identity (X-MCIM-Tenant, and
    X-MCIM-Pipeline for graph lanes), so each tenant's quota window and
    QoS class act on exactly its slice of the offered load. Returns
    {tenant: phase record} with the shared shed-vs-unavailable
    accounting per tenant (ok% / shed% / p99 are the lane's columns —
    the numbers that show low-QoS tenants shedding FIRST while the
    interactive tenant's goodput holds)."""
    from concurrent.futures import ThreadPoolExecutor

    period = 1.0 / offered_rps
    futures: list[tuple[str, int, object]] = []
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        t0 = clock()
        i = 0
        while True:
            due = t0 + i * period
            now = clock()
            if due - t0 >= duration_s:
                break
            if due > now:
                sleep(due - now)
            lane = lanes[i % len(lanes)]
            blobs = lane["blobs"]
            k = (i // len(lanes)) % len(blobs)
            futures.append(
                (
                    lane["tenant"],
                    k,
                    pool.submit(
                        http_post_image, url, blobs[k],
                        timeout_s=timeout_s,
                        headers=lane.get("headers"),
                    ),
                )
            )
            i += 1
        by_tenant: dict[str, list[tuple[int, dict]]] = {
            lane["tenant"]: [] for lane in lanes
        }
        for tenant, k, f in futures:
            by_tenant[tenant].append((k, f.result()))
        wall = clock() - t0
    share = offered_rps / len(lanes)
    return {
        tenant: summarize_http_results(results, wall, share)
        for tenant, results in by_tenant.items()
    }


def churn_run(
    url: str,
    blobs: list[bytes],
    *,
    offered_rps: float,
    phase_s: float,
    kill,
    before_after=None,
    timeout_s: float = 30.0,
) -> dict:
    """Availability under churn, in three measured phases:

        before   steady state, every replica up
        during   `kill()` fires at the phase midpoint (SIGKILL one
                 replica) while the offered load keeps arriving — the
                 in-flight forwards to the dead replica must resolve via
                 router rerouting, not hang or error
        after    `before_after()` (e.g. wait for the supervisor restart
                 to rejoin) runs first, then steady state again

    Each phase reports ok% / retried% / p99; `results` ride along for
    bit-exactness checks. The per-phase numbers ARE the acceptance
    criterion: during-phase ok_frac stays 1.0 when rerouting works."""
    phases: dict[str, dict] = {}
    phases["before"] = http_run_offered_load(
        url, blobs, offered_rps, phase_s, timeout_s=timeout_s
    )
    killer = threading.Timer(phase_s / 2.0, kill)
    killer.start()
    try:
        phases["during"] = http_run_offered_load(
            url, blobs, offered_rps, phase_s, timeout_s=timeout_s
        )
    finally:
        killer.cancel()  # no-op if it already fired
        killer.join()
    if before_after is not None:
        before_after()
    phases["after"] = http_run_offered_load(
        url, blobs, offered_rps, phase_s, timeout_s=timeout_s
    )
    return phases


def sweep(
    app: ServeApp,
    *,
    offered_rps: tuple[float, ...],
    duration_s: float = 2.0,
    n_images: int = 64,
    channels: int = 3,
    seed: int = 7,
    fault_rate: float = 0.0,
    fault_seed: int = 7,
) -> list[dict]:
    """The offered-load sweep over a STARTED app. Dispatch metrics (batch
    occupancy, retries) are read as per-rate deltas of the app-wide
    counters. `fault_rate > 0` arms the `serve.dispatch` failpoint for the
    whole sweep (cleared on exit), so the lane measures availability under
    injected transient dispatch failures."""
    from mpi_cuda_imagemanipulation_tpu.serve.padded import min_true_dim

    client = Client(app)
    images = mixed_shapes(
        app.cache.buckets,
        n_images,
        channels=channels,
        seed=seed,
        min_dim=min_true_dim(app.pipe),
    )
    if fault_rate > 0.0:
        failpoints.configure(
            f"serve.dispatch={fault_rate}", seed=fault_seed
        )
    records = []
    try:
        for rps in offered_rps:
            before = app.metrics.snapshot()
            rec = run_offered_load(client, images, rps, duration_s)
            after = app.metrics.snapshot()
            d_real = (after["dispatches"] or 0) - (before["dispatches"] or 0)
            if d_real:
                done = after["completed"] - before["completed"]
                rec["mean_batch_occupancy"] = done / d_real
            rec["dispatches"] = d_real
            rec["retried"] = after["retries"] - before["retries"]
            rec["retried_frac"] = (
                rec["retried"] / rec["submitted"] if rec["submitted"] else 0.0
            )
            rec["degraded"] = after["degraded"] - before["degraded"]
            # the p99's exemplar trace id (histogram bucket exemplars) —
            # printed next to the percentile in the lane table, so the
            # outlier links to its --trace-out spans without eyeballing
            ex = app.metrics.e2e_exemplar(99)
            if ex is not None:
                rec["p99_exemplar"] = ex
            if fault_rate > 0.0:
                rec["fault_rate"] = fault_rate
            records.append(rec)
    finally:
        if fault_rate > 0.0:
            failpoints.clear()
    return records
